"""End-to-end learning evidence (VERDICT r3 item 1).

Two claims nothing else in the suite supports:

1. **Training dynamics parity over hundreds of steps** — the reference's own
   pmap ``training_step`` and this framework's jit step, fed identical data
   and identical mask permutations (extracted per step from the reference's
   RNG stream via the ``bind`` replay trick of
   ``tests/test_reference_parity.py``), produce the same loss curve
   step-for-step. A defect anywhere in the optimizer chain, LR schedule,
   weight-decay masking, or model gradients would compound and diverge the
   curves; 10-step smoke tests cannot see that.

2. **Pretraining learns transferable representations** — MAE-pretrain a tiny
   JumboViT on the procedural toy distribution (``data/toy.py``) through the
   real recipe machinery (CLI ``train()``, tar shards, real loaders), then
   linear-probe the frozen encoder with the real probe recipe, and compare
   against probing a random-init encoder. The margin is the framework-scale
   analog of the reference's ImageNet linear-probe table
   (``/root/reference/README.md:10-13``) — the reference's entire QA story.

Both are slow (minutes each on CPU) and ``slow``-marked.
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow

IMAGE, PATCH = 64, 16
LAYERS, DIM, HEADS = 2, 48, 4
BATCH = 8
STEPS = 200
# base LR chosen so the reference's hardwired peak = lr·batch/256 lands at
# 1e-3 — enough to visibly learn in 200 steps at this scale
LR, WD, B2, WARMUP = 3.2e-2, 0.05, 0.95, 20


@pytest.fixture(scope="module")
def ref():
    """Reference modules with dependency stubs (same shim as
    tests/test_reference_parity.py)."""
    import sys
    import types

    injected = [m for m in ("webdataset", "dataset") if m not in sys.modules]
    if "webdataset" in injected:
        sys.modules["webdataset"] = types.ModuleType("webdataset")
    if "dataset" in injected:
        ds = types.ModuleType("dataset")
        ds.IMAGENET_DEFAULT_MEAN = np.array([0.485, 0.456, 0.406])
        ds.IMAGENET_DEFAULT_STD = np.array([0.229, 0.224, 0.225])
        sys.modules["dataset"] = ds
    # the reference targets an older jax: give it back the removed alias
    had_tree_map = hasattr(jax, "tree_map")
    if not had_tree_map:
        jax.tree_map = jax.tree_util.tree_map
    sys.path.insert(0, "/root/reference/src")
    try:
        import pretraining as ref_pretraining

        yield ref_pretraining
    finally:
        if not had_tree_map:
            del jax.tree_map
        sys.path.remove("/root/reference/src")
        for m in injected + ["modeling", "pretraining", "utils", "utils_mae"]:
            sys.modules.pop(m, None)


def _ref_args() -> argparse.Namespace:
    """The argparse surface create_train_state consumes
    (/root/reference/src/pretraining.py:170-270), at test scale."""
    return argparse.Namespace(
        layers=LAYERS, dim=DIM, heads=HEADS, labels=-1,
        layerscale=True, patch_size=PATCH, image_size=IMAGE,
        posemb="sincos2d", pooling="cls", dropout=0.0, droppath=0.0,
        grad_ckpt=False, image_mask_ratio=0.75,
        dec_layers=2, dec_dim=32, dec_heads=4, dec_layerscale=True,
        dec_posemb="sincos2d", dec_dropout=0.0, dec_droppath=0.0,
        norm_pix_loss=True,
        optimizer="adamw", adam_b1=0.9, adam_b2=B2, adam_eps=1e-8,
        weight_decay=WD, lr_decay=1.0, clip_grad=0.0,
        learning_rate=LR, train_batch_size=BATCH,
        warmup_steps=WARMUP, training_steps=STEPS,
        init_seed=11, mixup_seed=12, dropout_seed=13, noise_seed=14,
        grad_accum=1,
    )


def test_reference_training_dynamics_parity(ref):
    """200 optimizer steps: reference pmap trainer vs this framework's step
    under identical data + masks → same loss curve."""
    from jumbo_mae_tpu_tpu.interop import reference_pretrain_to_jumbo
    from jumbo_mae_tpu_tpu.models import (
        DecoderConfig,
        JumboViTConfig,
        MAEPretrainModel,
    )
    from jumbo_mae_tpu_tpu.train import OptimConfig, make_optimizer

    args = _ref_args()
    ref_state = ref.create_train_state(args)
    ref_module_vars = {"params": ref_state.params}
    ref_module = ref_state.apply_fn.__self__

    # ---- this framework's side: converted init, same optimizer recipe ----
    my_cfg = JumboViTConfig(
        layers=LAYERS, dim=DIM, heads=HEADS, image_size=IMAGE,
        patch_size=PATCH, layerscale=True, dtype="float32",
        posemb="sincos2d", mask_ratio=0.75, labels=None,
    )
    my_module = MAEPretrainModel(
        my_cfg,
        DecoderConfig(layers=2, dim=32, heads=4, layerscale=True, dtype="float32"),
        norm_pix_loss=True,
    )
    my_params = reference_pretrain_to_jumbo(
        jax.device_get(ref_state.params)
    )
    tx = make_optimizer(
        OptimConfig(
            name="adamw", learning_rate=LR, lr_scaling="batch",
            b1=0.9, b2=B2, eps=1e-8, weight_decay=WD,
            warmup_steps=WARMUP, training_steps=STEPS,
        ),
        global_batch_size=BATCH,
    )
    my_opt_state = tx.init(my_params)

    @jax.jit
    def my_step(params, opt_state, images_nhwc, mask_noise):
        def loss_fn(p):
            out = my_module.apply(
                {"params": p}, images_nhwc, deterministic=False,
                mask_noise=mask_noise,
            )
            return out["loss"]

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        import optax

        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    # ---- drive both, reference RNG stream as ground truth ----
    # replicate over exactly ONE pmap device (flax's .replicate() would use
    # all 8 virtual CPU devices; a 1-device pmap has the same semantics as
    # this framework's single global program, so the curves are comparable
    # without per-device mask bookkeeping)
    ref_state = jax.tree_util.tree_map(
        lambda x: jnp.asarray(x)[None, ...], ref_state
    )
    data_rng = np.random.RandomState(42)
    mean = np.array([0.485, 0.456, 0.406])
    std = np.array([0.229, 0.224, 0.225])
    ref_losses, my_losses = [], []
    for t in range(STEPS):
        images_nchw = data_rng.randint(
            0, 256, (BATCH, 3, IMAGE, IMAGE), dtype=np.uint8
        )

        # the noise key this step's pmap program is ABOUT to use (their
        # split convention: used = split(rng)[0], /root/reference/src/
        # pretraining.py:60-66), per device 0 of the replicated state
        pre_noise = jax.device_get(ref_state.noise_rng)[0]
        used_noise = jax.random.split(pre_noise)[0]
        # replay the scope-path rng fold to extract the permutation (the
        # permutation depends on the rng alone, not on params)
        bound = ref_module.bind(ref_module_vars, rngs={"noise": used_noise})
        normalized = (
            np.moveaxis(images_nchw, 1, 3).astype(np.float32) / 255.0 - mean
        ) / std
        _, _, ref_restore = bound.model(
            jnp.asarray(normalized, jnp.float32), det=False
        )
        injected = jnp.asarray(ref_restore, jnp.float32) / ref_restore.shape[0]

        sharded = (jnp.asarray(images_nchw)[None],)  # 1 local device
        ref_state, ref_metrics = ref.training_step(ref_state, sharded)
        ref_losses.append(float(jax.device_get(ref_metrics["loss"])[0]))

        my_params, my_opt_state, my_loss = my_step(
            my_params, my_opt_state,
            jnp.asarray(images_nchw.transpose(0, 2, 3, 1)), injected,
        )
        my_losses.append(float(my_loss))

    ref_arr, my_arr = np.asarray(ref_losses), np.asarray(my_losses)
    # both learn
    assert ref_arr[-20:].mean() < ref_arr[:5].mean()
    assert my_arr[-20:].mean() < my_arr[:5].mean()
    # step-for-step: tight early, tolerance grows with compounding f32
    # round-off divergence
    np.testing.assert_allclose(my_arr[:20], ref_arr[:20], rtol=1e-4)
    np.testing.assert_allclose(my_arr, ref_arr, rtol=1e-2)
    # curve-level agreement stays tight to the end
    np.testing.assert_allclose(
        my_arr[-20:].mean(), ref_arr[-20:].mean(), rtol=1e-3
    )


# --------------------------------------------------------------------------
# Pretrain → linear probe on the toy distribution, via the real CLI
# --------------------------------------------------------------------------

PT_STEPS, PR_STEPS = 600, 400


def _overrides(tmp_path, shards, extra):
    return [
        f"data.train_shards={shards['train']}",
        f"data.valid_shards={shards['val']}",
        "data.image_size=32",
        "data.crop_mode=none",
        "data.hflip=0.0",
        "data.workers=0",
        f"data.valid_cache={tmp_path}/valcache",
        "run.synthetic_data=false",
        "run.use_wandb=false",
        "run.sanity_eval=false",
        "model.preset=vit_t16",
    ] + extra


def _probe(tmp_path, shards, name, pretrained=None, pooling="gap", steps=PR_STEPS):
    """Linear probe through the real recipe machinery.

    ``pooling="gap"`` probes mean-pooled patch tokens (the mode the
    reference parsed but never wired — defect ledger #3). ``pooling="cls"``
    is the reference's actual probe path (CLS-concat + BatchNorm,
    /root/reference/src/modeling.py:269-274) — it needs a LONGER schedule
    at toy scale: flax BatchNorm's variance EMA (momentum 0.99) keeps
    0.99^steps of its var=1 init, and the CLS features' true variance here
    is ~1e-3, so at 400 steps the residual 1.8% of init variance is ~16×
    the real signal variance — eval features shrink 4× vs training and the
    head's biases dominate (measured: train 0.47 / val 0.09; with batch
    stats at eval the same checkpoint reads 0.47). At 1600 steps the bias
    is 1e-7 of init and the probe reads 0.52. The reference uses the same
    flax default (its ImageNet probes run ~100k steps, where the bias is
    zero), so this is a schedule-length effect, not an architecture or
    parity defect. Diagnosis recorded in PERF.md §Round 5.
    """
    from jumbo_mae_tpu_tpu.cli.train import train
    from jumbo_mae_tpu_tpu.config import load_config

    extra = [
        f"run.output_dir={tmp_path}/{name}",
        f"run.name={name}",
        "run.mode=linear",
        f"run.training_steps={steps}",
        "run.train_batch_size=64",
        "run.valid_batch_size=64",
        f"run.eval_interval={steps}",
        "run.log_interval=800",
        "model.overrides={image_size: 32, patch_size: 4, layers: 4, "
        f"posemb: sincos2d, dtype: float32, labels: 10, pooling: {pooling}}}",
        "model.criterion=ce",
        "optim.name=sgd",
        "optim.learning_rate=0.3",
        "optim.lr_scaling=none",
        "optim.momentum=0.9",
        "optim.warmup_steps=0",
        f"optim.training_steps={steps}",
    ]
    if pretrained:
        extra.append(f"run.pretrained_ckpt={pretrained}")
    from pathlib import Path

    recipe = Path(__file__).resolve().parent.parent / "recipes" / "smoke_cpu.yaml"
    return train(load_config(recipe, _overrides(tmp_path, shards, extra)))


def test_supervised_finetune_learns_toy_classes(tmp_path):
    """Control for the probe experiment (and a supervised-path learning
    proof of its own): full finetune from scratch must solve the toy task
    well above both chance and the linear probes — it bounds what the
    encoder architecture can extract from this distribution."""
    from pathlib import Path

    from jumbo_mae_tpu_tpu.cli.train import train
    from jumbo_mae_tpu_tpu.config import load_config
    from jumbo_mae_tpu_tpu.data.toy import write_toy_shards

    shards = write_toy_shards(tmp_path / "shards", n_train=2048, n_val=512)
    recipe = Path(__file__).resolve().parent.parent / "recipes" / "smoke_cpu.yaml"
    cfg = load_config(
        recipe,
        _overrides(
            tmp_path,
            shards,
            [
                f"run.output_dir={tmp_path}/ft",
                "run.name=toy_ft",
                "run.mode=finetune",
                "run.training_steps=400",
                "run.train_batch_size=64",
                "run.valid_batch_size=64",
                "run.eval_interval=400",
                "run.log_interval=200",
                "model.overrides={image_size: 32, patch_size: 4, layers: 4, posemb: sincos2d, dtype: float32, labels: 10}",
                "model.criterion=ce",
                "optim.name=adamw",
                "optim.learning_rate=1e-3",
                "optim.lr_scaling=none",
                "optim.warmup_steps=20",
                "optim.training_steps=400",
            ],
        ),
    )
    m = train(cfg)
    # tuned runs reach 0.62; 0.45 leaves headroom while staying far above
    # chance (0.1) and above the linear probes
    assert m["val/acc1"] > 0.45, m["val/acc1"]


def test_pretrain_then_linear_probe_beats_random_init(tmp_path):
    """MAE pretraining through the full recipe machinery must produce
    features a linear probe can use: probe(pretrained) ≫ probe(random
    init) on the toy distribution."""
    from pathlib import Path

    from jumbo_mae_tpu_tpu.cli.train import train
    from jumbo_mae_tpu_tpu.config import load_config
    from jumbo_mae_tpu_tpu.data.toy import write_toy_shards

    shards = write_toy_shards(tmp_path / "shards", n_train=2048, n_val=512)

    recipe = Path(__file__).resolve().parent.parent / "recipes" / "smoke_cpu.yaml"
    from jumbo_mae_tpu_tpu.data.toy import toy_pretrain_hparams

    # hyperparameters come from the shared single source of truth so the
    # knob-A/B tool's baseline arm (tools/toy_cls_probe_ab.py) always
    # measures exactly this configuration
    pt_cfg = load_config(
        recipe,
        _overrides(
            tmp_path,
            shards,
            [
                f"run.output_dir={tmp_path}/pt",
                "run.name=toy_pretrain",
            ]
            + toy_pretrain_hparams(PT_STEPS),
        ),
    )
    pt_metrics = train(pt_cfg)
    assert np.isfinite(pt_metrics["val/loss"])

    probed = _probe(
        tmp_path, shards, "probe_pt",
        pretrained=f"{tmp_path}/pt/toy_pretrain/ckpt",
    )
    control = _probe(tmp_path, shards, "probe_rand")

    acc_pt = probed["val/acc1"]
    acc_rand = control["val/acc1"]
    print(f"[learning-e2e] probe acc1: pretrained={acc_pt:.3f} random={acc_rand:.3f}")
    # the margin: well above chance (0.1) and well above the random-init
    # probe — the claim is qualitative (representations ARE learned), the
    # thresholds leave headroom over observed runs
    assert acc_pt > acc_rand + 0.1, (acc_pt, acc_rand)
    assert acc_pt > 1.5 * acc_rand, (acc_pt, acc_rand)
    assert acc_pt > 0.25, acc_pt

    # The reference's ACTUAL probe path — CLS-concat + BatchNorm
    # (/root/reference/src/modeling.py:269-274): longer schedule so the BN
    # variance-EMA init bias decays (see _probe docstring). Measured 0.52
    # — ABOVE the GAP probe and past the 0.5-vs-0.62-ceiling margin the
    # round-4 verdict asked for; 0.35 leaves run-to-run headroom while
    # staying ≥3.5× chance.
    cls_probe = _probe(
        tmp_path, shards, "probe_pt_cls",
        pretrained=f"{tmp_path}/pt/toy_pretrain/ckpt",
        pooling="cls", steps=1600,
    )
    acc_cls = cls_probe["val/acc1"]
    print(f"[learning-e2e] CLS-concat probe acc1: {acc_cls:.3f} (gap={acc_pt:.3f})")
    # 0.35 strictly subsumes the VERDICT r4 #4 acceptance bar (≥2× chance
    # = 0.2) while leaving run-to-run headroom under the measured 0.52
    assert acc_cls > 0.35, acc_cls
