"""Converter tests: exact round-trip + torch-semantics equivalence."""

import jax
import numpy as np
import pytest

from jumbo_mae_tpu_tpu.interop import flax_to_torch_state, torch_to_flax_params
from jumbo_mae_tpu_tpu.models import ClassificationModel, preset


@pytest.fixture(scope="module")
def tiny_variables():
    enc = preset(
        "vit_t16",
        labels=10,
        image_size=32,
        patch_size=4,
        layerscale=True,
        posemb="learnable",
        batch_norm=True,
        linear_probing=True,
        dtype="float32",
    )
    model = ClassificationModel(enc)
    variables = model.init(
        {"params": jax.random.key(0)},
        np.zeros((1, 32, 32, 3), np.uint8),
        np.zeros((1,), np.int32),
    )
    return enc, model, variables


def test_roundtrip_exact(tiny_variables):
    enc_cfg, _, variables = tiny_variables
    params = variables["params"]
    torch_state = flax_to_torch_state(params, variables.get("batch_stats"))
    back = torch_to_flax_params(torch_state, heads=enc_cfg.heads)
    stats = back.pop("__batch_stats__")

    flat_orig = jax.tree_util.tree_flatten_with_path(params["model"])[0]
    flat_back = jax.tree_util.tree_flatten_with_path(back)[0]
    assert len(flat_orig) == len(flat_back)
    orig = {tuple(getattr(k, "key", k) for k in p): v for p, v in flat_orig}
    conv = {tuple(getattr(k, "key", k) for k in p): v for p, v in flat_back}
    assert orig.keys() == conv.keys()
    for key in orig:
        np.testing.assert_array_equal(np.asarray(orig[key]), np.asarray(conv[key]), err_msg=str(key))
    np.testing.assert_array_equal(
        stats["head"]["bn"]["mean"],
        np.asarray(variables["batch_stats"]["model"]["head"]["bn"]["mean"]),
    )


def test_no_jumbo_params_dropped(tiny_variables):
    """Every leaf of the flax tree must appear in the torch dict — the
    reference's converters silently dropped cls_tokens/jumbo_mlp/norm3."""
    _, _, variables = tiny_variables
    torch_state = flax_to_torch_state(variables["params"], variables["batch_stats"])
    n_leaves = len(jax.tree_util.tree_leaves(variables["params"]))
    # fused qkv merges 6 leaves (3 kernels + 3 biases) into 2 per block
    n_blocks = sum(1 for k in torch_state if k.endswith("attn.qkv.weight"))
    assert len(torch_state) == n_leaves - 4 * n_blocks + 2  # +2 bn running stats


def test_qkv_fusion_matches_torch_linear(tiny_variables):
    """The fused qkv must reproduce the flax DenseGeneral projection under
    torch's F.linear convention."""
    torch = pytest.importorskip("torch")
    enc_cfg, _, variables = tiny_variables
    blk = variables["params"]["model"]["block_0"]["attn"]
    state = flax_to_torch_state(variables["params"])

    x = np.random.default_rng(0).normal(size=(5, enc_cfg.dim)).astype(np.float32)
    # flax: x @ kernel(D,H,hd) + bias
    q_flax = np.einsum("nd,dhk->nhk", x, np.asarray(blk["q"]["kernel"])) + np.asarray(
        blk["q"]["bias"]
    )
    w = torch.from_numpy(state["blocks.0.attn.qkv.weight"].copy())
    b = torch.from_numpy(state["blocks.0.attn.qkv.bias"].copy())
    qkv = torch.nn.functional.linear(torch.from_numpy(x), w, b).numpy()
    q_torch = qkv[:, : enc_cfg.dim].reshape(5, enc_cfg.heads, enc_cfg.dim // enc_cfg.heads)
    np.testing.assert_allclose(q_flax, q_torch, rtol=1e-5, atol=1e-6)


def test_patch_embed_conv_semantics(tiny_variables):
    """Converted patch-embed weight must match under torch conv2d."""
    torch = pytest.importorskip("torch")
    _, _, variables = tiny_variables
    state = flax_to_torch_state(variables["params"])
    k = np.asarray(variables["params"]["model"]["embed"]["proj"]["kernel"])  # (p,p,3,D)
    x = np.random.default_rng(1).normal(size=(1, 8, 8, 3)).astype(np.float32)
    # flax VALID conv, stride=p: one output position per patch
    p = k.shape[0]
    flax_out = np.einsum("bhwc,hwcd->bd", x[:, :p, :p, :], k)
    w = torch.from_numpy(state["patch_embed.proj.weight"].copy())
    t_out = torch.nn.functional.conv2d(
        torch.from_numpy(x.transpose(0, 3, 1, 2)), w, stride=p
    ).numpy()
    np.testing.assert_allclose(flax_out[0], t_out[0, :, 0, 0], rtol=1e-4, atol=1e-5)
