"""Converter tests: exact round-trip + torch-semantics equivalence."""

from pathlib import Path

import jax
import numpy as np
import pytest

from jumbo_mae_tpu_tpu.interop import flax_to_torch_state, torch_to_flax_params
from jumbo_mae_tpu_tpu.models import ClassificationModel, preset


@pytest.fixture(scope="module")
def tiny_variables():
    enc = preset(
        "vit_t16",
        labels=10,
        image_size=32,
        patch_size=4,
        layerscale=True,
        posemb="learnable",
        batch_norm=True,
        linear_probing=True,
        dtype="float32",
    )
    model = ClassificationModel(enc)
    variables = model.init(
        {"params": jax.random.key(0)},
        np.zeros((1, 32, 32, 3), np.uint8),
        np.zeros((1,), np.int32),
    )
    return enc, model, variables


def test_roundtrip_exact(tiny_variables):
    enc_cfg, _, variables = tiny_variables
    params = variables["params"]
    torch_state = flax_to_torch_state(params, variables.get("batch_stats"))
    back = torch_to_flax_params(torch_state, heads=enc_cfg.heads)
    stats = back.pop("__batch_stats__")

    flat_orig = jax.tree_util.tree_flatten_with_path(params["model"])[0]
    flat_back = jax.tree_util.tree_flatten_with_path(back)[0]
    assert len(flat_orig) == len(flat_back)
    orig = {tuple(getattr(k, "key", k) for k in p): v for p, v in flat_orig}
    conv = {tuple(getattr(k, "key", k) for k in p): v for p, v in flat_back}
    assert orig.keys() == conv.keys()
    for key in orig:
        np.testing.assert_array_equal(np.asarray(orig[key]), np.asarray(conv[key]), err_msg=str(key))
    np.testing.assert_array_equal(
        stats["head"]["bn"]["mean"],
        np.asarray(variables["batch_stats"]["model"]["head"]["bn"]["mean"]),
    )


def test_no_jumbo_params_dropped(tiny_variables):
    """Every leaf of the flax tree must appear in the torch dict — the
    reference's converters silently dropped cls_tokens/jumbo_mlp/norm3."""
    _, _, variables = tiny_variables
    torch_state = flax_to_torch_state(variables["params"], variables["batch_stats"])
    n_leaves = len(jax.tree_util.tree_leaves(variables["params"]))
    # fused qkv merges 6 leaves (3 kernels + 3 biases) into 2 per block
    n_blocks = sum(1 for k in torch_state if k.endswith("attn.qkv.weight"))
    assert len(torch_state) == n_leaves - 4 * n_blocks + 2  # +2 bn running stats


def test_qkv_fusion_matches_torch_linear(tiny_variables):
    """The fused qkv must reproduce the flax DenseGeneral projection under
    torch's F.linear convention."""
    torch = pytest.importorskip("torch")
    enc_cfg, _, variables = tiny_variables
    blk = variables["params"]["model"]["block_0"]["attn"]
    state = flax_to_torch_state(variables["params"])

    x = np.random.default_rng(0).normal(size=(5, enc_cfg.dim)).astype(np.float32)
    # flax: x @ kernel(D,H,hd) + bias
    q_flax = np.einsum("nd,dhk->nhk", x, np.asarray(blk["q"]["kernel"])) + np.asarray(
        blk["q"]["bias"]
    )
    w = torch.from_numpy(state["blocks.0.attn.qkv.weight"].copy())
    b = torch.from_numpy(state["blocks.0.attn.qkv.bias"].copy())
    qkv = torch.nn.functional.linear(torch.from_numpy(x), w, b).numpy()
    q_torch = qkv[:, : enc_cfg.dim].reshape(5, enc_cfg.heads, enc_cfg.dim // enc_cfg.heads)
    np.testing.assert_allclose(q_flax, q_torch, rtol=1e-5, atol=1e-6)


def test_patch_embed_conv_semantics(tiny_variables):
    """Converted patch-embed weight must match under torch conv2d."""
    torch = pytest.importorskip("torch")
    _, _, variables = tiny_variables
    state = flax_to_torch_state(variables["params"])
    k = np.asarray(variables["params"]["model"]["embed"]["proj"]["kernel"])  # (p,p,3,D)
    x = np.random.default_rng(1).normal(size=(1, 8, 8, 3)).astype(np.float32)
    # flax VALID conv, stride=p: one output position per patch
    p = k.shape[0]
    flax_out = np.einsum("bhwc,hwcd->bd", x[:, :p, :p, :], k)
    w = torch.from_numpy(state["patch_embed.proj.weight"].copy())
    t_out = torch.nn.functional.conv2d(
        torch.from_numpy(x.transpose(0, 3, 1, 2)), w, stride=p
    ).numpy()
    np.testing.assert_allclose(flax_out[0], t_out[0, :, 0, 0], rtol=1e-4, atol=1e-5)


# --------------------------------------------------------------------------
# timm-hub import (--from-timm), with a stubbed hub — no network in tests
# --------------------------------------------------------------------------


def _plain_vit_state(dim=64, heads=4, blocks=2, grid=4, labels=10, seed=0):
    """A timm-layout plain-ViT state dict (single cls_token, CLS slot baked
    into pos_embed) sized for preset('vit_t16', image_size=32, patch_size=8)."""
    rng = np.random.default_rng(seed)

    def r(*shape):
        return rng.standard_normal(shape).astype(np.float32)

    sd = {
        "cls_token": r(1, 1, dim),
        "pos_embed": r(1, 1 + grid * grid, dim),
        "patch_embed.proj.weight": r(dim, 3, 8, 8),
        "patch_embed.proj.bias": r(dim),
        "norm.weight": r(dim),
        "norm.bias": r(dim),
        "head.weight": r(labels, dim),
        "head.bias": r(labels),
    }
    for i in range(blocks):
        p = f"blocks.{i}."
        sd |= {
            p + "norm1.weight": r(dim),
            p + "norm1.bias": r(dim),
            p + "attn.qkv.weight": r(3 * dim, dim),
            p + "attn.qkv.bias": r(3 * dim),
            p + "attn.proj.weight": r(dim, dim),
            p + "attn.proj.bias": r(dim),
            p + "norm2.weight": r(dim),
            p + "norm2.bias": r(dim),
            p + "mlp.fc1.weight": r(4 * dim, dim),
            p + "mlp.fc1.bias": r(4 * dim),
            p + "mlp.fc2.weight": r(dim, 4 * dim),
            p + "mlp.fc2.bias": r(dim),
        }
    return sd


def test_timm_adapter_folds_cls_posemb_and_tiles():
    from jumbo_mae_tpu_tpu.interop import timm_plain_vit_to_jumbo_state

    sd = _plain_vit_state()
    out = timm_plain_vit_to_jumbo_state(sd, num_cls_tokens=3)
    want_cls = sd["cls_token"] + sd["pos_embed"][:, :1, :]
    assert out["cls_tokens"].shape == (1, 3, 64)
    for k in range(3):
        np.testing.assert_array_equal(out["cls_tokens"][:, k], want_cls[:, 0])
    np.testing.assert_array_equal(out["pos_embed"], sd["pos_embed"][:, 1:, :])
    assert "cls_token" not in out
    assert not any(k.startswith("jumbo_mlp") for k in out)


def test_timm_import_end_to_end_warm_start(tmp_path, monkeypatch):
    """Stubbed timm hub → CLI to-flax --from-timm → msgpack → warm start into
    a real jumbo model: pretrained leaves load, the jumbo MLP (which has no
    timm source) keeps its fresh init."""
    import sys
    import types

    import torch

    sd_np = _plain_vit_state()

    class _StubModel:
        def state_dict(self):
            return {k: torch.from_numpy(v) for k, v in sd_np.items()}

    stub = types.ModuleType("timm")
    stub.create_model = lambda name, pretrained=True, **kw: _StubModel()
    monkeypatch.setitem(sys.modules, "timm", stub)

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))
    try:
        import convert_checkpoint
    finally:
        sys.path.pop(0)
    dst = tmp_path / "timm.msgpack"
    convert_checkpoint.main(
        ["to-flax", "vit_tiny_stub", str(dst), "--heads", "4", "--from-timm"]
    )

    from jumbo_mae_tpu_tpu.models import ClassificationModel
    from jumbo_mae_tpu_tpu.train.checkpoint import load_pretrained_params

    enc = preset(
        "vit_t16",
        labels=10,
        image_size=32,
        patch_size=8,
        posemb="learnable",
        dtype="float32",
    )
    model = ClassificationModel(enc)
    init = model.init(
        {"params": jax.random.key(0)},
        np.zeros((1, 32, 32, 3), np.uint8),
        np.zeros((1,), np.int32),
    )["params"]
    merged = load_pretrained_params(str(dst), init, verbose=False)

    got = merged["model"]
    want_cls = np.tile(
        sd_np["cls_token"] + sd_np["pos_embed"][:, :1, :], (1, 3, 1)
    )
    np.testing.assert_array_equal(np.asarray(got["cls_tokens"]), want_cls)
    np.testing.assert_array_equal(
        np.asarray(got["embed"]["proj"]["kernel"]),
        sd_np["patch_embed.proj.weight"].transpose(2, 3, 1, 0),
    )
    np.testing.assert_array_equal(
        np.asarray(got["embed"]["pos_embed"]),
        sd_np["pos_embed"][0, 1:, :].reshape(4, 4, 64),
    )
    # plain head (L, D) → jumbo head (L, K*D): K copies at 1/K, so logits
    # match the plain model while the CLS slots still agree
    want_head = np.tile(sd_np["head.weight"] / 3.0, (1, 3)).T
    np.testing.assert_allclose(
        np.asarray(got["head"]["fc"]["kernel"]), want_head, rtol=1e-6
    )
    # the jumbo MLP has no timm counterpart — fresh init preserved
    np.testing.assert_array_equal(
        np.asarray(got["jumbo_mlp"]["fc1"]["kernel"]),
        np.asarray(init["model"]["jumbo_mlp"]["fc1"]["kernel"]),
    )


def test_timm_adapter_gap_model_without_cls_token():
    """GAP-pooled timm models (class_token=False) have no cls_token and no
    CLS slot in pos_embed — the adapter must pass the grid through and omit
    cls_tokens (fresh init on warm start), not crash."""
    from jumbo_mae_tpu_tpu.interop import timm_plain_vit_to_jumbo_state

    sd = _plain_vit_state()
    del sd["cls_token"]
    sd["pos_embed"] = sd["pos_embed"][:, 1:, :]  # (1, 16, 64) — no CLS slot
    out = timm_plain_vit_to_jumbo_state(sd, num_cls_tokens=3)
    np.testing.assert_array_equal(out["pos_embed"], sd["pos_embed"])
    assert "cls_tokens" not in out and "cls_token" not in out
    # and the downstream converter tolerates the absent cls_tokens
    tree = torch_to_flax_params(out, heads=4)
    assert "cls_tokens" not in tree
    assert "block_0" in tree and "embed" in tree
