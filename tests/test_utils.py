"""Tests for meters, MFU math, and the metric logger."""

import json

import numpy as np

from jumbo_mae_tpu_tpu.models import preset
from jumbo_mae_tpu_tpu.models.config import DecoderConfig
from jumbo_mae_tpu_tpu.utils import (
    AverageMeter,
    MetricLogger,
    StepTimer,
    classify_flops_per_image,
    encoder_flops_per_image,
    mfu_report,
    pretrain_flops_per_image,
)


def test_average_meter_means_and_latest():
    m = AverageMeter()
    m.update({"loss": 1.0, "learning_rate": 0.1})
    m.update({"loss": 3.0, "learning_rate": 0.2})
    out = m.summary("train/")
    assert out["train/loss"] == 2.0
    assert out["train/learning_rate"] == 0.2
    assert m.summary() == {}  # buffer cleared


def test_average_meter_accepts_arrays():
    m = AverageMeter()
    m.update({"loss": np.float32(2.5)})
    assert m.summary()["loss"] == 2.5


def test_flops_masked_encoder_cheaper():
    cfg = preset("vit_b16", mask_ratio=0.75, labels=None)
    masked = encoder_flops_per_image(cfg, masked=True)
    full = encoder_flops_per_image(cfg, masked=False)
    assert masked < 0.5 * full  # 75% masking cuts well over half the FLOPs
    assert masked > 0


def test_pretrain_flops_vs_known_scale():
    """ViT-L/16 MAE fwd+bwd should land in the right order of magnitude
    (~100 GFLOPs/image: ViT-L full fwd is ~62 GFLOPs; masked enc + 8×512
    decoder fwd ≈ 33 GFLOPs, ×3 for training)."""
    enc = preset("vit_l16", mask_ratio=0.75, labels=None)
    dec = DecoderConfig(layers=8, dim=512, heads=16)
    flops = pretrain_flops_per_image(enc, dec, training=True)
    assert 5e10 < flops < 3e11


def test_classify_flops_includes_head():
    with_head = classify_flops_per_image(preset("vit_b16", labels=1000))
    without = classify_flops_per_image(preset("vit_b16", labels=None))
    assert with_head > without


def test_mfu_report_math():
    r = mfu_report(1e12, 100.0, peak_tflops=200.0)
    assert np.isclose(r.achieved_tflops, 100.0)
    assert np.isclose(r.mfu, 0.5)


def test_metric_logger_jsonl(tmp_path):
    logger = MetricLogger(tmp_path, name="t", config={"a": 1}, use_wandb=False)
    logger.log({"loss": 1.5}, step=3)
    logger.log({"loss": 2.5}, step=4)
    logger.close()
    lines = (tmp_path / "t-metrics.jsonl").read_text().strip().splitlines()
    assert len(lines) == 2
    rec = json.loads(lines[0])
    assert rec["step"] == 3 and rec["loss"] == 1.5
    assert json.loads((tmp_path / "t-config.json").read_text()) == {"a": 1}


def test_metric_logger_wandb_plumbing(tmp_path, monkeypatch):
    """project/entity/tags/resume-id reach wandb.init; logs are forwarded."""
    import sys
    import types

    calls = {}

    class _Run:
        def log(self, metrics, step=None):
            calls.setdefault("logged", []).append((dict(metrics), step))

        def finish(self):
            calls["finished"] = True

    def _init(**kw):
        calls["init"] = kw
        return _Run()

    stub = types.ModuleType("wandb")
    stub.init = _init
    monkeypatch.setitem(sys.modules, "wandb", stub)

    logger = MetricLogger(
        tmp_path,
        name="t",
        config={"a": 1},
        wandb_project="proj",
        wandb_entity="team",
        wandb_tags=("vit", "mae"),
        wandb_id="run-123",
    )
    logger.log({"loss": 1.0}, step=1)
    logger.close()

    assert calls["init"] == {
        "name": "t",
        "config": {"a": 1},
        "project": "proj",
        "entity": "team",
        "tags": ["vit", "mae"],
        "id": "run-123",
        "resume": "allow",
    }
    assert calls["logged"] == [({"loss": 1.0}, 1)]
    assert calls["finished"]


def test_metric_logger_wandb_absent_falls_back(tmp_path, monkeypatch):
    import builtins
    import sys

    monkeypatch.delitem(sys.modules, "wandb", raising=False)
    real_import = builtins.__import__

    def no_wandb(name, *a, **k):
        if name == "wandb":
            raise ImportError("no wandb")
        return real_import(name, *a, **k)

    monkeypatch.setattr(builtins, "__import__", no_wandb)
    logger = MetricLogger(tmp_path, name="fb", use_wandb=True)
    logger.log({"x": 1.0}, step=1)
    logger.close()
    assert (tmp_path / "fb-metrics.jsonl").exists()


def test_metric_logger_disabled(tmp_path):
    logger = MetricLogger(tmp_path, name="off", enabled=False)
    logger.log({"x": 1})
    logger.close()
    assert not (tmp_path / "off-metrics.jsonl").exists()


def test_step_timer():
    t = StepTimer(warmup_steps=1)
    for _ in range(5):
        t.tick()
    assert t.steps_per_sec is not None and t.steps_per_sec > 0


def test_param_summary():
    """Startup parameter table (parity: the reference's module.tabulate
    pre-flight print): per-subtree rows + an exact total."""
    import numpy as np

    from jumbo_mae_tpu_tpu.utils import param_summary

    params = {
        "encoder": {
            "block_0": {"kernel": np.zeros((4, 8), np.float32)},
            "block_1": {"kernel": np.zeros((4, 8), np.float32)},
        },
        "head": {"kernel": np.zeros((8, 10), np.float32), "bias": np.zeros(10)},
    }
    out = param_summary(params)
    assert "encoder/block_0" in out and "head" in out
    assert "total" in out and "154" in out  # 32 + 32 + 80 + 10


def test_detect_peak_tflops_device_kind_spellings(monkeypatch):
    """PJRT spells the e-variants 'lite' ('TPU v5 lite'); an unmatched kind
    must fall back to the caller's default (bench.py passes 0.0 to disable
    its plausibility guard rather than guess)."""
    import jax

    from jumbo_mae_tpu_tpu.utils.mfu import detect_peak_tflops

    class _Dev:
        def __init__(self, kind):
            self.device_kind = kind

    cases = {
        "TPU v5 lite": 197.0,
        "TPU v5e": 197.0,
        "TPU v5p": 459.0,
        "TPU v6 lite": 918.0,
        "TPU v4": 275.0,
        "weird accelerator": 0.0,  # falls back to the default
    }
    for kind, want in cases.items():
        monkeypatch.setattr(jax, "devices", lambda k=kind: [_Dev(k)])
        assert detect_peak_tflops(default=0.0) == want, kind
