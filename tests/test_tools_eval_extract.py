"""run.eval_only mode + tools/extract_features.py (beyond-reference
capabilities: the reference evaluates only inline in its train loop and has
no feature-export path)."""

import sys
from pathlib import Path

import numpy as np
import pytest

from jumbo_mae_tpu_tpu.config import load_config

REPO = Path(__file__).resolve().parent.parent
RECIPES = REPO / "recipes"
sys.path.insert(0, str(REPO / "tools"))


def _smoke_overrides(out, extra=()):
    return [
        f"run.output_dir={out}",
        "run.training_steps=4",
        "run.eval_interval=4",
        "run.log_interval=4",
        "run.sanity_eval=false",
        # big steps so 4 of them move the weights measurably — the
        # fresh-init negative control below needs trained != init
        "optim.learning_rate=3e-2",
        "optim.warmup_steps=0",
        *extra,
    ]


@pytest.mark.slow
def test_eval_only_restores_and_matches_training_eval(tmp_path):
    """Train 4 steps (checkpoint saved at the end), then run eval_only with
    run.resume=true: it must restore the trained weights and reproduce the
    training run's final val/loss exactly (same weights, same eval stream,
    no training steps in between)."""
    from jumbo_mae_tpu_tpu.cli.train import train

    out = tmp_path / "run"
    trained = train(load_config(RECIPES / "smoke_cpu.yaml", _smoke_overrides(out)))
    assert "val/loss" in trained

    evaled = train(
        load_config(
            RECIPES / "smoke_cpu.yaml",
            _smoke_overrides(out, ["run.eval_only=true", "run.resume=true"]),
        )
    )
    assert set(evaled) == {"val/loss"}
    assert evaled["val/loss"] == pytest.approx(trained["val/loss"], rel=1e-6)

    # fresh-init eval (no restore) must differ — proves the restore mattered
    fresh = train(
        load_config(
            RECIPES / "smoke_cpu.yaml",
            _smoke_overrides(tmp_path / "fresh", ["run.eval_only=true"]),
        )
    )
    assert fresh["val/loss"] != pytest.approx(trained["val/loss"], rel=1e-4)


@pytest.mark.slow
def test_eval_only_linear_mode_grafts_batch_stats(tmp_path):
    """Linear-probe eval_only: restore_eval must graft BatchNorm
    batch_stats (not just params) — acc/loss reproduce the training run's
    final eval exactly."""
    from jumbo_mae_tpu_tpu.cli.train import train

    out = tmp_path / "lin"
    extra = ["run.mode=linear", "model.overrides.labels=10"]
    trained = train(
        load_config(RECIPES / "smoke_cpu.yaml", _smoke_overrides(out, extra))
    )
    assert "val/acc1" in trained

    evaled = train(
        load_config(
            RECIPES / "smoke_cpu.yaml",
            _smoke_overrides(
                out, extra + ["run.eval_only=true", "run.resume=true"]
            ),
        )
    )
    for key in ("val/loss", "val/acc1", "val/acc5"):
        assert evaled[key] == pytest.approx(trained[key], rel=1e-6), key


@pytest.mark.slow
def test_eval_only_model_mismatch_raises(tmp_path):
    """eval_only+resume with a DIFFERENT model than the checkpoint's must
    raise a readable mismatch error, not push RestoreArgs leaves into jit
    (regression: Orbax partial_restore fills missing paths with the item's
    own RestoreArgs objects)."""
    from jumbo_mae_tpu_tpu.cli.train import train

    out = tmp_path / "run"
    train(load_config(RECIPES / "smoke_cpu.yaml", _smoke_overrides(out)))

    with pytest.raises(ValueError, match="does not match the checkpoint"):
        train(
            load_config(
                RECIPES / "smoke_cpu.yaml",
                _smoke_overrides(
                    out,
                    [
                        "run.eval_only=true",
                        "run.resume=true",
                        # classify-mode tree ('model' root) vs the saved
                        # pretrain tree ('encoder' root)
                        "run.mode=linear",
                        "model.overrides.labels=10",
                    ],
                ),
            )
        )


@pytest.mark.slow
def test_eval_only_which_best(tmp_path):
    """run.eval_which=best restores the metric-BEST slot even when a later
    (worse) 'last' checkpoint exists; a missing best slot and a config
    where the knob would be silently dropped both raise."""
    from jumbo_mae_tpu_tpu.cli.train import train

    out = tmp_path / "run"
    trained = train(load_config(RECIPES / "smoke_cpu.yaml", _smoke_overrides(out)))

    # resume 2 more steps at an absurd LR: val loss blows up, so 'best'
    # stays at step 4 while 'last' advances to step 6 — the two slots now
    # hold DIFFERENT weights, making the assertions below discriminating
    worse = train(
        load_config(
            RECIPES / "smoke_cpu.yaml",
            _smoke_overrides(
                out,
                [
                    "run.resume=true",
                    "run.training_steps=6",
                    "run.eval_interval=6",
                    "run.log_interval=6",
                    "optim.learning_rate=100.0",
                ],
            ),
        )
    )
    assert not worse["val/loss"] == pytest.approx(trained["val/loss"], rel=1e-4)

    best = train(
        load_config(
            RECIPES / "smoke_cpu.yaml",
            _smoke_overrides(
                out,
                ["run.eval_only=true", "run.resume=true", "run.eval_which=best"],
            ),
        )
    )
    assert best["val/loss"] == pytest.approx(trained["val/loss"], rel=1e-6)

    last = train(
        load_config(
            RECIPES / "smoke_cpu.yaml",
            _smoke_overrides(
                out,
                ["run.eval_only=true", "run.resume=true", "run.eval_which=last"],
            ),
        )
    )
    assert not last["val/loss"] == pytest.approx(best["val/loss"], rel=1e-4)

    # an entirely absent best slot raises the slot-specific error (the
    # last-present/best-absent split can't arise from the CLI: any eval
    # that saves also promotes a first best)
    with pytest.raises(FileNotFoundError, match="'best'"):
        train(
            load_config(
                RECIPES / "smoke_cpu.yaml",
                _smoke_overrides(
                    tmp_path / "empty",
                    [
                        "run.eval_only=true",
                        "run.resume=true",
                        "run.eval_which=best",
                    ],
                ),
            )
        )

    with pytest.raises(ValueError, match="eval_which"):
        train(
            load_config(
                RECIPES / "smoke_cpu.yaml",
                _smoke_overrides(out, ["run.eval_only=true", "run.eval_which=bogus"]),
            )
        )

    # the knob must not be silently ignored outside eval_only+resume
    with pytest.raises(ValueError, match="silently"):
        train(
            load_config(
                RECIPES / "smoke_cpu.yaml",
                _smoke_overrides(out, ["run.eval_which=best"]),
            )
        )


def test_eval_only_resume_without_checkpoint_raises(tmp_path):
    """An explicit run.resume=true that can't be satisfied must raise, not
    silently evaluate a random init (regression)."""
    from jumbo_mae_tpu_tpu.cli.train import train

    cfg = load_config(
        RECIPES / "smoke_cpu.yaml",
        _smoke_overrides(
            tmp_path / "nothing_here",
            ["run.eval_only=true", "run.resume=true"],
        ),
    )
    with pytest.raises(FileNotFoundError, match="no 'last' checkpoint"):
        train(cfg)


def test_eval_only_requires_valid_data(tmp_path):
    from jumbo_mae_tpu_tpu.cli.train import train

    cfg = load_config(
        RECIPES / "smoke_cpu.yaml",
        _smoke_overrides(
            tmp_path, ["run.eval_only=true", "run.synthetic_data=false"]
        ),
    )
    with pytest.raises(ValueError, match="eval_only"):
        train(cfg)


def test_reconstruct_grid(tmp_path):
    """tools/reconstruct.py writes the 4-panel grid; --ckpt (whole-tree
    merge, decoder included) changes the rendered reconstruction."""
    import jax
    from PIL import Image

    from reconstruct import main as reconstruct_main
    from jumbo_mae_tpu_tpu.cli.train import build_model
    from jumbo_mae_tpu_tpu.train.checkpoint import export_params_msgpack

    base = [
        str(RECIPES / "smoke_cpu.yaml"),
        "--n",
        "2",
        "--set",
        "run.synthetic_data=true",
    ]
    out1 = reconstruct_main(base + ["--out", str(tmp_path / "a.png")])
    cfg = load_config(RECIPES / "smoke_cpu.yaml")
    img = Image.open(out1)
    pad, size, panels = 2, cfg.data.image_size, 4
    assert img.size == (panels * (size + pad) - pad, 2 * (size + pad) - pad)

    # a differently-seeded full pretrain tree must change the rendering
    model, _, _ = build_model(cfg)
    rng = jax.random.PRNGKey(999)
    variables = model.init(
        {"params": rng, "noise": rng, "dropout": rng},
        np.zeros((1, size, size, 3), np.uint8),
    )
    ckpt = tmp_path / "tree.msgpack"
    export_params_msgpack(variables["params"], str(ckpt))
    out2 = reconstruct_main(
        base + ["--out", str(tmp_path / "b.png"), "--ckpt", str(ckpt)]
    )
    a = np.asarray(Image.open(out1), np.int16)
    b = np.asarray(Image.open(out2), np.int16)
    assert a.shape == b.shape
    assert np.abs(a - b).max() > 0  # reconstruction panel differs
    # originals panel (col 0) is identical — same data stream
    np.testing.assert_array_equal(a[:, :size], b[:, :size])

    # an unrelated tree must refuse, not render random-init noise
    import flax.linen as fnn

    junk = fnn.Dense(5).init(rng, np.zeros((1, 2), np.float32))["params"]
    junk_path = tmp_path / "junk_tree.msgpack"
    export_params_msgpack(junk, str(junk_path))
    with pytest.raises(SystemExit, match="0 params"):
        reconstruct_main(
            base + ["--out", str(tmp_path / "junk.png"), "--ckpt", str(junk_path)]
        )
    assert not (tmp_path / "junk.png").exists()


def test_reconstruct_from_image_files(tmp_path):
    """--images bypasses the data pipeline: arbitrary files are resized +
    center-cropped to the model input and rendered."""
    from PIL import Image

    from reconstruct import main as reconstruct_main

    rng = np.random.default_rng(0)
    files = []
    for i, shape in enumerate([(60, 80, 3), (100, 40, 3)]):
        f = tmp_path / f"im{i}.png"
        Image.fromarray(rng.integers(0, 256, shape, dtype=np.uint8)).save(f)
        files.append(str(f))

    out = reconstruct_main(
        [
            str(RECIPES / "smoke_cpu.yaml"),
            "--out",
            str(tmp_path / "user.png"),
            "--images",
            *files,
        ]
    )
    cfg = load_config(RECIPES / "smoke_cpu.yaml")
    size, pad = cfg.data.image_size, 2
    assert Image.open(out).size == (4 * (size + pad) - pad, 2 * (size + pad) - pad)


def test_knn_probe_separates_clusters(tmp_path):
    """kNN probe: near-perfect on well-separated gaussian clusters, chance
    on shuffled labels; CLI prints the JSON metric line."""
    from knn_probe import knn_predict, main as knn_main

    rng = np.random.default_rng(0)
    classes, per, dim = 5, 40, 16
    centers = rng.standard_normal((classes, dim)) * 4.0

    def make(n_per, seed):
        r = np.random.default_rng(seed)
        feats = np.concatenate(
            [centers[c] + r.standard_normal((n_per, dim)) for c in range(classes)]
        )
        labels = np.repeat(np.arange(classes), n_per)
        return feats.astype(np.float32), labels

    train_f, train_l = make(per, 1)
    query_f, query_l = make(10, 2)
    preds = knn_predict(train_f, train_l, query_f, k=10)
    assert (preds == query_l).mean() > 0.9

    shuffled = train_l.copy()
    np.random.default_rng(3).shuffle(shuffled)
    chance = (knn_predict(train_f, shuffled, query_f, k=10) == query_l).mean()
    assert chance < 0.5

    np.savez(tmp_path / "train.npz", features=train_f, labels=train_l)
    np.savez(tmp_path / "val.npz", features=query_f, labels=query_l)
    acc = knn_main([str(tmp_path / "train.npz"), str(tmp_path / "val.npz")])
    assert acc > 0.9


def test_knn_probe_rejects_empty_train_and_bad_k():
    """An empty reference set (or k < 1) must die loudly — the silent
    failure mode was class-0 predictions for every query (ADVICE r5)."""
    from knn_probe import knn_predict

    feats = np.ones((4, 8), np.float32)
    labels = np.zeros((4,), np.int64)
    query = np.ones((3, 8), np.float32)
    with pytest.raises(SystemExit, match="empty"):
        knn_predict(np.zeros((0, 8), np.float32), np.zeros((0,), int), query)
    with pytest.raises(SystemExit, match="k must be"):
        knn_predict(feats, labels, query, k=0)


def test_extract_features_pools_and_ckpt_restore(tmp_path):
    """Shapes per pool mode; determinism; --ckpt actually changes the
    features (pretrain-tree 'encoder' subtree mapped onto the bare
    encoder)."""
    import jax

    from extract_features import main as extract_main
    from jumbo_mae_tpu_tpu.models import MAEPretrainModel, preset
    from jumbo_mae_tpu_tpu.models.config import DecoderConfig
    from jumbo_mae_tpu_tpu.train.checkpoint import export_params_msgpack

    base = [
        str(RECIPES / "smoke_cpu.yaml"),
        "--set",
        "run.synthetic_data=true",
        "run.valid_batch_size=8",
    ]

    cls = np.load(
        extract_main(base + ["--out", str(tmp_path / "cls.npz"), "--pool", "cls"])
    )
    gap = np.load(
        extract_main(base + ["--out", str(tmp_path / "gap.npz"), "--pool", "gap"])
    )
    cfg = load_config(RECIPES / "smoke_cpu.yaml")
    enc = preset(
        cfg.model.preset,
        **{**cfg.model.overrides, "labels": None, "mask_ratio": None},
    )
    k, d = enc.num_cls_tokens, enc.dim
    assert cls["features"].shape == (32, k * d)
    assert gap["features"].shape == (32, d)
    assert np.isfinite(cls["features"]).all()

    # determinism: same invocation → identical bytes
    cls2 = np.load(
        extract_main(base + ["--out", str(tmp_path / "cls2.npz"), "--pool", "cls"])
    )
    np.testing.assert_array_equal(cls["features"], cls2["features"])

    # a classify recipe with model.overrides.labels must not collide with
    # the tool's forced headless config (regression: keyword collision)
    lab = np.load(
        extract_main(
            base
            + ["model.overrides.labels=10", "--out", str(tmp_path / "lab.npz")]
        )
    )
    assert lab["features"].shape == cls["features"].shape

    # --ckpt: export a differently-seeded pretrain tree and restore it
    enc_mae = enc.replace(mask_ratio=0.75)
    mae = MAEPretrainModel(enc_mae, DecoderConfig(layers=1, dim=32, heads=4))
    rng = jax.random.PRNGKey(123)
    variables = mae.init(
        {"params": rng, "noise": rng, "dropout": rng},
        np.zeros((1, cfg.data.image_size, cfg.data.image_size, 3), np.uint8),
    )
    ckpt_path = tmp_path / "pretrain.msgpack"
    export_params_msgpack(variables["params"], str(ckpt_path))

    warm = np.load(
        extract_main(
            base
            + ["--out", str(tmp_path / "warm.npz"), "--pool", "cls", "--ckpt", str(ckpt_path)]
        )
    )
    assert warm["features"].shape == cls["features"].shape
    assert not np.allclose(warm["features"], cls["features"])

    # an unrelated tree (wrong preset/shapes) must refuse to write rather
    # than silently export random-init features
    import flax.linen as fnn

    junk = fnn.Dense(7).init(rng, np.zeros((1, 3), np.float32))["params"]
    junk_path = tmp_path / "junk.msgpack"
    export_params_msgpack(junk, str(junk_path))
    with pytest.raises(SystemExit, match="0 params"):
        extract_main(
            base
            + ["--out", str(tmp_path / "junk.npz"), "--ckpt", str(junk_path)]
        )
    assert not (tmp_path / "junk.npz").exists()

    # a BARE encoder tree (no 'encoder'/'model' nesting) must load too —
    # and land on the same features as the nested pretrain tree it came from
    bare_path = tmp_path / "bare.msgpack"
    export_params_msgpack(variables["params"]["encoder"], str(bare_path))
    bare = np.load(
        extract_main(
            base
            + ["--out", str(tmp_path / "bare.npz"), "--pool", "cls", "--ckpt", str(bare_path)]
        )
    )
    np.testing.assert_array_equal(bare["features"], warm["features"])
