#!/usr/bin/env bash
# Fan a training run out to every worker of a TPU pod slice.
#
# Role parity with the reference's worked multi-host workflow
# (/root/reference/README.md:97-113): there, each recipe is a bash script
# and the user hand-runs
#   gcloud compute tpus tpu-vm ssh $TPU_NAME --worker=all \
#     --command="... screen -dmL bash $CONFIG_FILE"
# Here the recipe is a YAML + `--set` overrides and multi-host process
# coordination is `cli.train --distributed` (jax.distributed.initialize);
# this script owns the gcloud fan-out, detached launch, and log retrieval.
#
# Usage:
#   scripts/launch_pod.sh launch recipes/pretrain_vit_l16.yaml \
#       [--set run.name=l16-800ep ...]          # extra args pass through
#   scripts/launch_pod.sh setup                 # bootstrap every worker
#   scripts/launch_pod.sh status                # screen session per worker
#   scripts/launch_pod.sh tail                  # last log lines per worker
#   scripts/launch_pod.sh kill                  # stop the run everywhere
#
# Environment:
#   TPU_NAME   (required) TPU VM / pod slice name
#   TPU_ZONE   (default us-central2-b)
#   TPU_PROJECT  optional gcloud project override
#   REMOTE_DIR (default ~/jumbo_mae_tpu_tpu) repo checkout on the workers
#   SESSION    (default mae) screen session name
set -euo pipefail

ZONE="${TPU_ZONE:-us-central2-b}"
REMOTE_DIR="${REMOTE_DIR:-\$HOME/jumbo_mae_tpu_tpu}"
SESSION="${SESSION:-mae}"

usage() { sed -n '2,27p' "$0" | sed 's/^# \{0,1\}//'; exit 1; }

[ $# -ge 1 ] || usage
CMD="$1"; shift

: "${TPU_NAME:?set TPU_NAME to the pod slice name}"

GCLOUD=(gcloud compute tpus tpu-vm ssh "$TPU_NAME" --zone="$ZONE" --worker=all)
if [ -n "${TPU_PROJECT:-}" ]; then
  GCLOUD+=(--project="$TPU_PROJECT")
fi

run_everywhere() { "${GCLOUD[@]}" --command="$1"; }

case "$CMD" in
  setup)
    run_everywhere "cd $REMOTE_DIR && bash scripts/setup.sh"
    ;;
  launch)
    [ $# -ge 1 ] || { echo "launch needs a recipe path" >&2; exit 1; }
    RECIPE="$1"; shift
    # Remaining args (e.g. --set k=v) pass through to cli.train verbatim.
    EXTRA=""
    for a in "$@"; do EXTRA+=" $(printf '%q' "$a")"; done
    # screen -dmL: detached + logged (screenlog.0 in $REMOTE_DIR), so the
    # ssh fan-out returns immediately and `tail` can read progress — same
    # detachment pattern as the reference's workflow.
    run_everywhere "cd $REMOTE_DIR && screen -dmL -S $SESSION \
python3 -m jumbo_mae_tpu_tpu.cli.train --config $(printf '%q' "$RECIPE") \
--distributed$EXTRA"
    echo "launched '$SESSION' on all workers of $TPU_NAME"
    echo "follow with: $0 tail    stop with: $0 kill"
    ;;
  status)
    run_everywhere "screen -ls || true"
    ;;
  tail)
    run_everywhere "tail -n 20 $REMOTE_DIR/screenlog.0 2>/dev/null || echo '(no log yet)'"
    ;;
  kill)
    run_everywhere "screen -S $SESSION -X quit || true"
    ;;
  *)
    usage
    ;;
esac
