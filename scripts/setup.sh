#!/usr/bin/env bash
# Fresh TPU-VM bootstrap for jumbo_mae_tpu_tpu.
#
# Role parity with the reference's env script
# (/root/reference/scripts/setup.sh:15-34), rebuilt for this framework's
# stack: jax[tpu] instead of jax+libtpu-from-releases-page, opencv (SIMD
# JPEG decode in the data workers) instead of Pillow-SIMD, orbax instead of
# nothing, and an optional native build for the C++ tar reader.
#
# Run on each worker VM of the pod slice, e.g.:
#   gcloud compute tpus tpu-vm ssh $TPU_NAME --worker=all \
#     --command="bash jumbo_mae_tpu_tpu/scripts/setup.sh"
set -euo pipefail

# 1. Python deps. jax[tpu] pulls the matching libtpu; pin jax>=0.8 for the
#    sharding APIs the runtime uses (jax.sharding.set_mesh, shard_map vma).
python3 -m pip install -U pip
python3 -m pip install -U "jax[tpu]>=0.8" \
  -f https://storage.googleapis.com/jax-releases/libtpu_releases.html
python3 -m pip install -U flax optax chex einops numpy pillow orbax-checkpoint pyyaml

# 2. Fast image decode for the host-side data workers (cv2 uses SIMD
#    libjpeg-turbo wheels; data/decode.py falls back to PIL when absent).
python3 -m pip install -U opencv-python-headless

# 3. Optional extras: wandb metrics sink (utils/logging.py falls back to
#    JSONL without it), pytest for the test suite.
python3 -m pip install -U wandb pytest || true

# 4. Native tar reader (data/native.py; pure-Python tario is the fallback,
#    so this step is optional but recommended for >10GbE shard streaming).
REPO_DIR="$(cd "$(dirname "$0")/.." && pwd)"
if command -v c++ >/dev/null 2>&1; then
  c++ -O2 -shared -fPIC -o "$REPO_DIR/native/libtario.so" "$REPO_DIR/native/tario.cc"
  echo "built native/libtario.so"
else
  echo "no C++ compiler found; skipping native reader (python fallback active)"
fi

# 5. Install the package itself (editable, so recipes resolve relative paths).
python3 -m pip install -e "$REPO_DIR"

python3 - <<'EOF'
import jax
print("jax", jax.__version__, "devices:", jax.devices())
EOF
