"""Gated train→serve weights publisher + artifact verification.

The serving tier already polls a ``--swap-watch`` directory and hot-swaps
whatever lands there (``serve/replicaset.py``'s ``WeightSwapController``
runs restore → parity → canary → promote). Nothing ever *fed* that
directory from training. This module closes the loop:

- :class:`CheckpointPublisher` registers on the :class:`~jumbo_mae_tpu_tpu.
  train.engine.RunEngine` checkpoint hook. Every interval checkpoint that
  passes the gates — a finite-loss window since the last save, no sentinel
  rollback since the last save, at least ``min_interval_steps`` since the
  last publish, and (optionally) an eval metric above/below a floor — is
  exported as an inference-ready artifact into the watch directory.
- Export is **int8 PTQ at publish time** (``infer/quant.py``): the serving
  tier's HBM-bandwidth-bound shapes want int8 anyway, so quantize once on
  the training host instead of on every replica restore. ``quant="none"``
  ships f32.
- Transport is **delta against the last published tree**: only leaves whose
  (quantized) bytes changed ride in the payload; the manifest records every
  leaf's digest and whether it lives in this artifact or the base, plus the
  base's name and tree fingerprint, forming a resolvable chain. A full tree
  is forced every ``full_every`` publishes so chains stay bounded.
- Commit is **atomic**: everything is staged in a dot-prefixed tmp dir
  (invisible to the watcher, which skips dotted names), fsync'd, then
  ``os.replace``'d into place + :func:`~jumbo_mae_tpu_tpu.obs.journal.
  fsync_dir` — a torn export can never present a partial artifact.
- The manifest carries a **parity fingerprint** (sha256 over every leaf's
  digest): :func:`verify_artifact` / :func:`resolve_chain` recompute it
  before any bytes reach a live model, so a poisoned or torn artifact is
  quarantined at the watcher, not discovered by the parity gate after a
  restore. The ``publish.export`` fault site injects exactly those
  corruptions for the chaos suite.
- Publish device-time is billed to a dedicated ``publish`` tenant through
  :class:`~jumbo_mae_tpu_tpu.serve.costmeter.CostMeter`, so continuous
  deployment shows up in the chargeback (``tools/cost_doctor.py``), not as
  noise.

Artifact layout (one directory per publish, names sort in publish order)::

    <publish_dir>/publish-000007/
        manifest.json       # schema, step, leaf digests, chain link, gates
        weights.msgpack     # flax msgpack: {path: {kind, q, scale} | {kind, v}}

Offline verification lives in ``tools/publish_doctor.py``.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import re
import shutil
import time
from pathlib import Path
from types import SimpleNamespace

import numpy as np

from jumbo_mae_tpu_tpu.faults import fault_point
from jumbo_mae_tpu_tpu.obs.journal import fsync_dir
from jumbo_mae_tpu_tpu.obs.metrics import get_registry

SCHEMA = 1
MANIFEST = "manifest.json"
PAYLOAD = "weights.msgpack"
_NAME_RE = re.compile(r"^publish-(\d{6})$")


class PublishIntegrityError(RuntimeError):
    """An artifact failed verification: torn write, corrupted payload,
    fingerprint mismatch, or a broken/cyclic delta chain. The watcher
    quarantines on this — it must never crash the serving process."""


# --------------------------------------------------------------- tree codec


def _flatten(node, prefix: str, out: dict) -> None:
    from jumbo_mae_tpu_tpu.infer.quant import QuantizedTensor

    if node is None:
        return
    if isinstance(node, QuantizedTensor):
        out[prefix] = node
    elif isinstance(node, dict):
        for k in sorted(node):
            _flatten(node[k], f"{prefix}/{k}" if prefix else str(k), out)
    else:
        out[prefix] = np.asarray(node)


def _unflatten(leaves: dict) -> dict:
    tree: dict = {}
    for path, leaf in leaves.items():
        parts = path.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = leaf
    return tree


def _encode_leaf(leaf) -> dict:
    from jumbo_mae_tpu_tpu.infer.quant import QuantizedTensor

    if isinstance(leaf, QuantizedTensor):
        return {
            "kind": "q8",
            "q": np.asarray(leaf.q),
            "scale": np.asarray(leaf.scale),
        }
    return {"kind": "raw", "v": np.asarray(leaf)}


def _decode_leaf(entry: dict, dtype: str):
    if entry["kind"] == "q8":
        q = np.asarray(entry["q"], np.float32)
        return (q * np.asarray(entry["scale"], np.float32)).astype(dtype)
    return np.asarray(entry["v"])


def _leaf_digest(entry: dict) -> str:
    h = hashlib.sha256()
    h.update(entry["kind"].encode())
    for part in ("q", "scale", "v"):
        arr = entry.get(part)
        if arr is not None:
            arr = np.ascontiguousarray(arr)
            h.update(f"|{part}:{arr.dtype}:{arr.shape}|".encode())
            h.update(arr.tobytes())
    return h.hexdigest()


def tree_fingerprint(digests: dict) -> str:
    """The parity fingerprint: sha256 over every leaf's ``path:digest``
    line, sorted — identical trees fingerprint identically regardless of
    which chain link physically carries each leaf."""
    h = hashlib.sha256()
    for path in sorted(digests):
        h.update(f"{path}:{digests[path]}\n".encode())
    return h.hexdigest()


# ----------------------------------------------------------------- read side


def is_publish_artifact(path) -> bool:
    """A directory containing a publish manifest (vs a raw checkpoint)."""
    p = Path(path)
    return p.is_dir() and (p / MANIFEST).is_file()


def load_manifest(path) -> dict:
    p = Path(path) / MANIFEST
    try:
        m = json.loads(p.read_text())
    except (OSError, ValueError) as e:
        raise PublishIntegrityError(f"{path}: unreadable manifest ({e})") from e
    if m.get("schema") != SCHEMA or m.get("kind") != "jumbo-publish":
        raise PublishIntegrityError(
            f"{path}: not a publish artifact (schema={m.get('schema')!r})"
        )
    return m


def verify_artifact(path) -> tuple[dict, dict]:
    """Verify ONE chain link: payload sha256/size match the manifest, every
    payload leaf's recomputed digest matches its manifest row. Returns
    ``(manifest, payload_leaves)``; raises :class:`PublishIntegrityError`
    on any mismatch — before any byte can reach a live model."""
    from flax import serialization

    p = Path(path)
    m = load_manifest(p)
    pay = p / m["payload"]["file"]
    try:
        raw = pay.read_bytes()
    except OSError as e:
        raise PublishIntegrityError(f"{path}: unreadable payload ({e})") from e
    if len(raw) != int(m["payload"]["nbytes"]):
        raise PublishIntegrityError(
            f"{path}: torn payload ({len(raw)} bytes, manifest says "
            f"{m['payload']['nbytes']})"
        )
    sha = hashlib.sha256(raw).hexdigest()
    if sha != m["payload"]["sha256"]:
        raise PublishIntegrityError(
            f"{path}: payload sha256 mismatch (corrupted artifact)"
        )
    try:
        leaves = serialization.msgpack_restore(raw)
    except Exception as e:  # noqa: BLE001 - any decode failure is integrity
        raise PublishIntegrityError(f"{path}: undecodable payload ({e})") from e
    rows = m["leaves"]
    for lp, entry in leaves.items():
        row = rows.get(lp)
        if row is None or row["where"] != "payload":
            raise PublishIntegrityError(f"{path}: stray payload leaf {lp!r}")
        if _leaf_digest(entry) != row["digest"]:
            raise PublishIntegrityError(
                f"{path}: leaf {lp!r} digest mismatch (corrupted artifact)"
            )
    missing = [
        lp for lp, row in rows.items()
        if row["where"] == "payload" and lp not in leaves
    ]
    if missing:
        raise PublishIntegrityError(
            f"{path}: payload missing manifest leaves {missing[:3]}"
        )
    return m, leaves


def resolve_chain(path, *, max_depth: int = 64) -> tuple[dict, dict | None, dict]:
    """Resolve a (possibly delta) artifact to a full dequantized tree.

    Walks base links, verifying every visited link and the recorded base
    fingerprints, then recomputes the resolved tree's fingerprint against
    the head manifest — a leaf silently swapped anywhere in the chain fails
    here. Returns ``(params, batch_stats_or_None, head_manifest)`` with f32
    leaves ready for :class:`WeightSwapController`'s restore path.
    """
    head = Path(path)
    m, leaves = verify_artifact(head)
    need = {lp for lp, row in m["leaves"].items() if row["where"] == "base"}
    cur, cur_dir = m, head
    depth = 0
    while need:
        base = cur.get("base")
        if not base:
            raise PublishIntegrityError(
                f"{cur_dir}: {len(need)} leaves unresolved and no base link"
            )
        depth += 1
        if depth > max_depth:
            raise PublishIntegrityError(
                f"{path}: delta chain deeper than {max_depth} (cycle?)"
            )
        bdir = head.parent / base["name"]
        if not bdir.is_dir():
            raise PublishIntegrityError(
                f"{cur_dir}: base {base['name']!r} is missing (broken chain)"
            )
        bm, bleaves = verify_artifact(bdir)
        if bm["fingerprint"] != base["fingerprint"]:
            raise PublishIntegrityError(
                f"{cur_dir}: base {base['name']!r} fingerprint mismatch "
                "(chain link was replaced)"
            )
        for lp in list(need):
            if lp in bleaves:
                leaves[lp] = bleaves[lp]
                need.discard(lp)
        cur, cur_dir = bm, bdir
    digests = {lp: _leaf_digest(entry) for lp, entry in leaves.items()}
    fp = tree_fingerprint(digests)
    if fp != m["fingerprint"]:
        raise PublishIntegrityError(
            f"{path}: resolved tree fingerprint {fp[:12]} != manifest "
            f"{m['fingerprint'][:12]}"
        )
    for lp, row in m["leaves"].items():
        if digests.get(lp) != row["digest"]:
            raise PublishIntegrityError(
                f"{path}: resolved leaf {lp!r} digest mismatch"
            )
    decoded = {
        lp: _decode_leaf(entry, m["leaves"][lp]["dtype"])
        for lp, entry in leaves.items()
    }
    tree = _unflatten(decoded)
    return tree.get("params", {}), tree.get("batch_stats"), m


def latest_artifact(publish_dir) -> Path | None:
    """The newest ``publish-NNNNNN`` entry, or None. Dot-prefixed staging
    dirs are invisible by construction."""
    d = Path(publish_dir)
    if not d.is_dir():
        return None
    names = sorted(n for n in os.listdir(d) if _NAME_RE.match(n))
    return d / names[-1] if names else None


# ---------------------------------------------------------------- write side


class CheckpointPublisher:
    """The train-side publish component (see module docstring).

    Register on a :class:`RunEngine` via :meth:`register` *after* the
    checkpoint saver so the save has landed when the publish hook runs.
    Export failures (including injected ``publish.export`` faults) journal
    ``publish_failed`` and never propagate — continuous deployment must not
    be able to kill training.
    """

    def __init__(
        self,
        publish_dir,
        *,
        quant: str = "int8",
        min_interval_steps: int = 0,
        full_every: int = 8,
        metric_key: str = "",
        metric_floor: float = 0.0,
        metric_sense: str = "below",
        emit=None,
        registry=None,
        clock=time.perf_counter,
    ):
        if quant not in ("int8", "none"):
            raise ValueError(f"publish quant must be int8|none, got {quant!r}")
        if metric_sense not in ("above", "below"):
            raise ValueError(
                f"publish metric sense must be above|below, got {metric_sense!r}"
            )
        self.publish_dir = Path(publish_dir)
        self.quant = quant
        self.min_interval_steps = int(min_interval_steps)
        self.full_every = max(1, int(full_every))
        self.metric_key = metric_key
        self.metric_floor = float(metric_floor)
        self.metric_sense = metric_sense
        self._emit = emit
        self._clock = clock
        reg = registry if registry is not None else get_registry()
        self._m_published = reg.counter(
            "publish_total", "artifacts published to the swap-watch dir"
        )
        self._m_failed = reg.counter(
            "publish_failed_total", "publish exports that failed"
        )
        self._m_rejected = reg.counter(
            "publish_gate_rejections_total",
            "checkpoints the publish gates rejected",
            labels=("reason",),
        )
        self._g_bytes = reg.gauge(
            "publish_bytes", "payload bytes of the last published artifact"
        )
        self._g_delta = reg.gauge(
            "publish_delta_fraction",
            "fraction of leaves shipped (vs riding the base) last publish",
        )
        self._g_seconds = reg.gauge(
            "publish_seconds", "wall seconds of the last publish export"
        )
        # the publish tenant: export wall-time billed through the costmeter
        # so continuous deployment appears in the chargeback by name
        self._meter = None
        if emit is not None:
            from jumbo_mae_tpu_tpu.serve.admission import TenantSpec
            from jumbo_mae_tpu_tpu.serve.costmeter import CostMeter

            self._meter = CostMeter(
                (TenantSpec(name="publish", tclass="batch"),),
                tracer=SimpleNamespace(event=emit),
                registry=reg,
            )
        self._bad_since_ckpt = 0
        self._rollback_since_ckpt = False
        self._last_published_step: int | None = None
        # resume the chain across restarts: the newest valid on-disk
        # artifact is the delta base and names the next sequence number
        self._seq = 0
        self._base: tuple[str, str, dict] | None = None  # (name, fp, digests)
        prev = latest_artifact(self.publish_dir)
        if prev is not None:
            try:
                pm = load_manifest(prev)
                self._seq = int(_NAME_RE.match(prev.name).group(1)) + 1
                self._base = (
                    prev.name,
                    pm["fingerprint"],
                    {lp: row["digest"] for lp, row in pm["leaves"].items()},
                )
            except PublishIntegrityError:
                self._seq = int(_NAME_RE.match(prev.name).group(1)) + 1

    # -- engine hooks ----------------------------------------------------
    def register(self, engine) -> None:
        engine.on_log_window(self._note_window)
        engine.on_rollback(self._note_rollback)
        engine.on_checkpoint(self._on_checkpoint)

    def _note_window(self, eng, win) -> None:
        self._bad_since_ckpt += len(getattr(win, "bad_steps", ()))

    def _note_rollback(self, eng, step, win):
        self._rollback_since_ckpt = True
        return None  # the restore hook owns the resumed step

    def _on_checkpoint(self, eng, cev) -> None:
        if cev.reason != "interval":
            return  # preemption save: never stand between SIGTERM and exit
        bad, rolled = self._bad_since_ckpt, self._rollback_since_ckpt
        self._bad_since_ckpt = 0
        self._rollback_since_ckpt = False
        reason = self._gate(cev.step, cev.metrics, bad, rolled)
        if reason is not None:
            self._m_rejected.labels(reason).inc()
            if self._emit is not None:
                self._emit("publish_skipped", step=cev.step, reason=reason)
            return
        try:
            self.publish(
                cev.step,
                eng.state.params,
                batch_stats=getattr(eng.state, "batch_stats", None),
                metrics=cev.metrics,
            )
        except Exception as e:  # noqa: BLE001 - publish must not kill training
            self._m_failed.inc()
            if self._emit is not None:
                self._emit(
                    "publish_failed",
                    step=cev.step,
                    error=f"{type(e).__name__}: {e}",
                )
            print(f"[publish] WARNING: export failed at step {cev.step}: {e}")

    def _gate(self, step, metrics, bad, rolled) -> str | None:
        """None = publish; otherwise the rejection reason."""
        if bad:
            return "bad_steps"
        if rolled:
            return "rollback"
        if (
            self._last_published_step is not None
            and step - self._last_published_step < self.min_interval_steps
        ):
            return "min_interval"
        if self.metric_key:
            val = (metrics or {}).get(self.metric_key)
            if val is None:
                return "metric_missing"
            val = float(val)
            if not math.isfinite(val):
                return "metric_nonfinite"
            ok = (
                val >= self.metric_floor
                if self.metric_sense == "above"
                else val <= self.metric_floor
            )
            if not ok:
                return "metric_floor"
        return None

    # -- the export ------------------------------------------------------
    def publish(self, step, params, *, batch_stats=None, metrics=None) -> Path:
        """Export one artifact (gates already passed). Raises on failure;
        :meth:`_on_checkpoint` converts that into ``publish_failed``."""
        import jax
        from flax import serialization

        t0 = self._clock()
        host = jax.device_get(serialization.to_state_dict(params))
        quant_report = None
        if self.quant == "int8":
            from jumbo_mae_tpu_tpu.infer.quant import quantize_params

            host, quant_report = quantize_params(host)
        flat: dict = {}
        _flatten(host, "params", flat)
        if batch_stats is not None:
            _flatten(
                jax.device_get(serialization.to_state_dict(batch_stats)),
                "batch_stats",
                flat,
            )
        entries = {lp: _encode_leaf(leaf) for lp, leaf in flat.items()}
        digests = {lp: _leaf_digest(e) for lp, e in entries.items()}
        fingerprint = tree_fingerprint(digests)

        # delta vs the last published tree; forced full every full_every
        # publishes (and whenever the base is missing a needed leaf)
        base = None
        in_payload = set(entries)
        if self._base is not None and self._seq % self.full_every != 0:
            bname, bfp, bdig = self._base
            carried = {
                lp for lp in entries
                if bdig.get(lp) == digests[lp]
            }
            if carried:
                in_payload = set(entries) - carried
                base = {"name": bname, "fingerprint": bfp}

        name = f"publish-{self._seq:06d}"
        payload_tree = {lp: entries[lp] for lp in sorted(in_payload)}
        payload = serialization.msgpack_serialize(payload_tree)
        sha = hashlib.sha256(payload).hexdigest()
        # chaos site: corrupt() poisons the committed bytes AFTER the
        # manifest digests are sealed (the watcher must catch it); raise
        # models a torn export (staging dir cleaned up, nothing ships)
        payload = fault_point("publish.export", key=str(step), data=payload)
        manifest = {
            "schema": SCHEMA,
            "kind": "jumbo-publish",
            "name": name,
            "step": int(step),
            "quant": self.quant,
            "fingerprint": fingerprint,
            "base": base,
            "payload": {"file": PAYLOAD, "sha256": sha, "nbytes": len(payload)},
            "leaves": {
                lp: {
                    "digest": digests[lp],
                    "kind": entries[lp]["kind"],
                    "shape": list(np.asarray(flat[lp].q if entries[lp]["kind"] == "q8" else flat[lp]).shape),
                    "dtype": "float32"
                    if entries[lp]["kind"] == "q8"
                    else str(np.asarray(flat[lp]).dtype),
                    "where": "payload" if lp in in_payload else "base",
                }
                for lp in sorted(entries)
            },
            "delta_fraction": round(len(in_payload) / max(len(entries), 1), 4),
            "quant_report": quant_report,
            "gate": {
                "metric_key": self.metric_key or None,
                "metrics": {k: v for k, v in (metrics or {}).items()},
            },
        }

        self.publish_dir.mkdir(parents=True, exist_ok=True)
        tmp = self.publish_dir / f".tmp-{name}"
        final = self.publish_dir / name
        if tmp.exists():
            shutil.rmtree(tmp)
        tmp.mkdir()
        try:
            for fname, data in (
                (PAYLOAD, payload),
                (MANIFEST, json.dumps(manifest, indent=1).encode()),
            ):
                fp = tmp / fname
                with open(fp, "wb") as f:
                    f.write(data)
                    f.flush()
                    os.fsync(f.fileno())
            fsync_dir(tmp)
            os.replace(tmp, final)  # atomic: the watcher sees all or nothing
            fsync_dir(self.publish_dir)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise

        dt = self._clock() - t0
        self._seq += 1
        self._base = (name, fingerprint, digests)
        self._last_published_step = int(step)
        self._m_published.inc()
        self._g_bytes.set(float(len(payload)))
        self._g_delta.set(manifest["delta_fraction"])
        self._g_seconds.set(dt)
        if self._meter is not None:
            self._meter.observe_batch(
                run_s=dt,
                traces=[
                    SimpleNamespace(
                        tenant="publish",
                        tclass="batch",
                        task="publish",
                        bucket=1,
                        tokens=None,
                        pad_fraction=0.0,
                    )
                ],
                batch=1,
            )
            self._meter.flush()
        if self._emit is not None:
            self._emit(
                "publish",
                step=int(step),
                name=name,
                fingerprint=fingerprint,
                leaves=len(entries),
                delta_leaves=len(in_payload),
                delta_fraction=manifest["delta_fraction"],
                bytes=len(payload),
                seconds=round(dt, 3),
                quant=self.quant,
                base=base["name"] if base else None,
            )
        print(
            f"[publish] {name} @ step {step}: {len(in_payload)}/{len(entries)} "
            f"leaves, {len(payload)} bytes, {self.quant}, "
            f"{'delta vs ' + base['name'] if base else 'full tree'}"
        )
        return final
