"""Perfmodel-driven autoscaler: SLO burn + queue state → replica count.

Replica count was a hand-picked ``--replicas`` constant; this closes the
loop. A reconcile tick reads three signals and computes a target:

- **demand** — an EWMA of the pool's arrival rate plus the backlog
  amortized over one reconcile interval (a queue that grew is demand the
  current capacity already failed to serve);
- **capacity** — requests/s one replica sustains. Preferably the roofline
  prediction (:func:`roofline_capacity`, ``obs/perfmodel``) for the
  serving bucket — available before any traffic, so the very first flash
  crowd is scaled on *predicted* capacity, not on a cold observation —
  with a live served-rate estimate as fallback/refinement;
- **SLO burn** — :meth:`SLOTracker.worst_burn`; burning budget faster
  than it accrues (or an open replica breaker) forces a step up even
  when the demand model disagrees — the model is a lower bound, reality
  outranks it.

``target = ceil(demand * headroom / capacity)`` clamped to
``[min_replicas, max_replicas]``. Asymmetric actuation: scale **up**
immediately (shedding interactive traffic is the expensive failure),
scale **down** one step at a time and only after ``down_hold`` ticks of
sustained low demand (flapping a replica away during a lull kills the
next burst). Actuation goes through :meth:`ReplicaSet.scale_to`, which
drains before removal — scale-down never kills in-flight work.

Every decision that changes the pool journals an ``autoscale`` event with
the inputs that drove it; `serve_autoscale_*` metrics expose the same
live. ``tick()`` is public and the clock injectable — tests drive the
reconcile deterministically without the daemon thread.
"""

from __future__ import annotations

import math
import threading
import time

from jumbo_mae_tpu_tpu.obs.metrics import get_registry


def roofline_capacity(
    flops_per_item: float,
    bytes_per_item: float,
    chip=None,
    *,
    utilization: float = 0.5,
) -> float:
    """Requests/s one replica sustains, from the roofline model: the
    paper's chip-speed envelope derated by ``utilization`` (a serving
    replica also pays host transfer, dispatch, and coalescing gaps — half
    the roofline is the honest default until measured)."""
    from jumbo_mae_tpu_tpu.obs.perfmodel import detect_chip, roofline

    spec = chip if chip is not None else detect_chip()
    pred = roofline(flops_per_item, bytes_per_item, spec)
    return pred.throughput_per_sec * float(utilization)


class Autoscaler:
    """Reconcile loop sizing a :class:`ReplicaSet` between
    ``min_replicas`` and ``max_replicas``.

    ``capacity_fn()`` returns predicted requests/s per replica (wire it
    to :func:`roofline_capacity`); without one, only the live estimate is
    used. ``slo`` is an :class:`SLOTracker` (or ``None``). ``start=False``
    skips the daemon thread — tests call :meth:`tick` directly.
    """

    def __init__(
        self,
        replicaset,
        *,
        min_replicas: int = 1,
        max_replicas: int = 4,
        interval_s: float = 1.0,
        slo=None,
        capacity_fn=None,
        headroom: float = 1.2,
        burn_max: float = 1.0,
        down_hold: int = 3,
        drain_timeout_s: float = 10.0,
        tracer=None,
        registry=None,
        clock=time.monotonic,
        start: bool = True,
    ):
        if not 1 <= min_replicas <= max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"[{min_replicas}, {max_replicas}]"
            )
        self.rs = replicaset
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.interval_s = float(interval_s)
        self.slo = slo
        self._capacity_fn = capacity_fn
        self.headroom = float(headroom)
        self.burn_max = float(burn_max)
        self.down_hold = int(down_hold)
        self.drain_timeout_s = float(drain_timeout_s)
        self._tracer = tracer
        self._clock = clock
        reg = registry if registry is not None else get_registry()
        self._m_target = reg.gauge(
            "serve_autoscale_target",
            "replica count the autoscaler last decided on",
        )
        self._m_events = reg.counter(
            "serve_autoscale_events_total",
            "pool resizes actuated, by direction (up|down)",
            labels=("direction",),
        )
        self._m_demand = reg.gauge(
            "serve_autoscale_demand",
            "estimated demand (req/s) at the last reconcile tick",
        )
        self._m_capacity = reg.gauge(
            "serve_autoscale_capacity",
            "estimated per-replica capacity (req/s) at the last tick",
        )
        self._last_t: float | None = None
        self._last_submitted: int | None = None
        self._last_served: int | None = None
        self._rate_ewma = 0.0
        self._live_capacity: float | None = None
        self._down_ticks = 0
        self.events: list[dict] = []
        self._closed = False
        self._thread = None
        if start:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="autoscaler"
            )
            self._thread.start()

    # ------------------------------------------------------------ signals

    def _observe(self, stats: dict, now: float) -> tuple[float, float]:
        """Update rate EWMAs from pool counters; returns
        (demand req/s, per-replica capacity req/s)."""
        submitted = stats["requests_submitted"]
        served = sum(r["served"] for r in stats["replicas"].values())
        if self._last_t is not None:
            dt = max(now - self._last_t, 1e-6)
            rate = max(submitted - self._last_submitted, 0) / dt
            self._rate_ewma = 0.4 * rate + 0.6 * self._rate_ewma
            healthy = max(stats["healthy"], 1)
            served_rate = max(served - self._last_served, 0) / dt / healthy
            # the live estimate only *raises* confidence while busy: an
            # idle pool serves 0/s because nothing arrived, not because
            # it can't
            if served_rate > 0:
                self._live_capacity = (
                    served_rate
                    if self._live_capacity is None
                    else max(self._live_capacity * 0.7, served_rate)
                )
        self._last_t = now
        self._last_submitted = submitted
        self._last_served = served
        backlog_rate = stats["queue_depth"] / max(self.interval_s, 1e-6)
        demand = self._rate_ewma + backlog_rate
        predicted = None
        if self._capacity_fn is not None:
            try:
                predicted = float(self._capacity_fn())
            except Exception:  # noqa: BLE001 — a broken model must not stop reconciles
                predicted = None
        candidates = [
            c for c in (predicted, self._live_capacity) if c and c > 0
        ]
        capacity = max(candidates) if candidates else 1.0
        return demand, capacity

    # ---------------------------------------------------------- reconcile

    def tick(self, now: float | None = None) -> dict:
        """One reconcile: read signals, decide, actuate. Returns the
        decision dict (also journaled when the pool changed)."""
        now = self._clock() if now is None else now
        stats = self.rs.stats()
        current = len(stats["replicas"])
        demand, capacity = self._observe(stats, now)
        self._m_demand.set(demand)
        self._m_capacity.set(capacity)
        burn = self.slo.worst_burn() if self.slo is not None else 0.0
        want = math.ceil(demand * self.headroom / capacity) if demand > 0 else 0
        reason = "demand"
        if burn > self.burn_max or stats["breaker_open"]:
            # budget burning or quorum lost: the demand model is wrong or
            # capacity is degraded — step up past whatever it says
            want = max(want, current + 1)
            reason = "burn" if burn > self.burn_max else "breaker"
        target = min(max(want, self.min_replicas), self.max_replicas)
        decision = {
            "t": round(now, 3),
            "current": current,
            "target": target,
            "demand_rps": round(demand, 3),
            "capacity_rps": round(capacity, 3),
            "burn": round(burn, 3),
            "queue_depth": stats["queue_depth"],
            "occupancy": stats.get("batch_occupancy", 0.0),
            "reason": reason,
        }
        self._m_target.set(target)
        if target > current:
            self._down_ticks = 0
            self._actuate(target, "up", decision)
        elif target < current:
            # sustained-low gate, then one step at a time: a drain is
            # cheap to repeat next tick, a killed burst is not
            self._down_ticks += 1
            if self._down_ticks >= self.down_hold and burn <= self.burn_max:
                self._actuate(current - 1, "down", decision)
                self._down_ticks = 0
        else:
            self._down_ticks = 0
        return decision

    def _actuate(self, target: int, direction: str, decision: dict) -> None:
        report = self.rs.scale_to(
            target, drain_timeout_s=self.drain_timeout_s
        )
        decision["scaled_from"] = report["from"]
        decision["scaled_to"] = report["to"]
        if report["to"] == report["from"]:
            return  # nothing moved (slot not removable yet) — retry next tick
        self._m_events.labels(direction).inc()
        self.events.append(decision)
        if self._tracer is not None:
            self._tracer.event("autoscale", direction=direction, **decision)

    def close(self, timeout_s: float = 5.0) -> None:
        self._closed = True
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _loop(self) -> None:
        while not self._closed:
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — a bad tick must not kill the loop
                pass
            time.sleep(self.interval_s)
