"""Weighted multi-tenant admission: token-bucket quotas + priority classes.

The pool's existing shedding is blind: ``max_queue`` sheds whoever submits
next, so one scraping tenant flooding the queue starves the interactive
tenant behind it. Admission makes shedding *weighted*: every request names
a tenant, every tenant has a priority class, and under pressure the low
classes shed first —

- ``interactive`` — latency-sensitive user traffic; sheds only at full
  pressure (and jumps the dispatch queue in the continuous scheduler);
- ``batch`` — throughput traffic; sheds when the pool is clearly loaded;
- ``scavenger`` — best-effort backfill; sheds at the first sign of load.

Three independent shed reasons, all subclasses of the pool's
:class:`~jumbo_mae_tpu_tpu.infer.batching.QueueFullError` so existing
callers' shed handling works unchanged:

- **quota** (:class:`TenantQuotaError`): the tenant's own token bucket is
  empty — it exceeded its contracted rate, regardless of pool load;
- **pressure** (:class:`TenantPressureError`): the pool-wide pressure
  signal (queue depth / max_queue, supplied by the scheduler) crossed the
  class's shed threshold — the pool is protecting higher classes;
- **budget** (:class:`TenantBudgetError`): the tenant spent its
  ``budget=`` device-seconds over its accounting window (per the attached
  :class:`~jumbo_mae_tpu_tpu.serve.costmeter.CostMeter`), so it degrades
  to *scavenger-class* pressure sensitivity — it sheds at half load like
  any other best-effort tenant, but is never shed at zero pressure: a
  budget bounds a tenant's claim on contended capacity, it is not a hard
  kill switch.

Token buckets refill continuously at ``rate`` tokens/s up to ``burst``;
a tenant with no rate is unmetered (class pressure still applies). The
clock is injectable for deterministic tests.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from jumbo_mae_tpu_tpu.infer.batching import QueueFullError
from jumbo_mae_tpu_tpu.obs import lockwatch
from jumbo_mae_tpu_tpu.obs.metrics import get_registry

# priority order: index 0 sheds last, jumps the queue first
CLASSES = ("interactive", "batch", "scavenger")

# pool pressure (0..1) at which each class starts shedding: scavenger
# gives way at half load, batch at heavy load, interactive only when the
# queue is actually full (pressure >= 1.0 is the old max_queue shed)
CLASS_SHED_PRESSURE = {"interactive": 1.0, "batch": 0.85, "scavenger": 0.5}

# scheduler score bonus per class (scheduler.py): a waiting interactive
# request outweighs an equally-old batch request
CLASS_WEIGHT = {"interactive": 1.0, "batch": 0.35, "scavenger": 0.0}


class TenantQuotaError(QueueFullError):
    """The tenant's token bucket is empty — over its contracted rate."""


class TenantPressureError(QueueFullError):
    """Pool pressure crossed this tenant's class shed threshold."""


class TenantBudgetError(QueueFullError):
    """The tenant exhausted its device-second budget and the pool is
    contended — shed at scavenger-class pressure until the window rolls."""


@dataclass(frozen=True)
class TenantSpec:
    """One tenant's contract: priority class + optional rate limit +
    optional device-second budget."""

    name: str
    tclass: str = "batch"
    rate: float | None = None     # tokens (requests) per second
    burst: float | None = None    # bucket capacity; defaults to max(rate, 1)
    budget: float | None = None   # device-seconds per accounting window
    budget_window_s: float | None = None  # window length; meter default if None

    def __post_init__(self):
        if self.tclass not in CLASSES:
            raise ValueError(
                f"unknown tenant class {self.tclass!r} for {self.name!r}; "
                f"expected one of {CLASSES}"
            )
        if self.rate is not None and self.rate <= 0:
            raise ValueError(f"tenant {self.name!r} rate must be > 0")
        if self.budget is not None and self.budget <= 0:
            raise ValueError(f"tenant {self.name!r} budget must be > 0")
        if self.budget_window_s is not None and self.budget_window_s <= 0:
            raise ValueError(f"tenant {self.name!r} window must be > 0")


def parse_tenants(spec: str) -> list[TenantSpec]:
    """Parse the ``--tenants`` flag:
    ``"web=interactive:rate=50:burst=100,scrape=batch:rate=5:budget=2"``.

    Each comma-separated entry is
    ``name=class[:rate=N][:burst=N][:budget=D][:window=W]`` — ``budget``
    is device-seconds per accounting window, ``window`` its length in
    seconds (the cost meter's default window when omitted); class must be
    one of :data:`CLASSES`. Typos fail loudly — a silent default would
    quietly demote a tenant to ``batch``.
    """
    tenants: list[TenantSpec] = []
    seen: set[str] = set()
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        if "=" not in entry:
            raise ValueError(
                f"bad tenant entry {entry!r}; expected name=class[:rate=N]"
            )
        name, _, rest = entry.partition("=")
        name = name.strip()
        if not name:
            raise ValueError(f"empty tenant name in {entry!r}")
        if name in seen:
            raise ValueError(f"duplicate tenant {name!r}")
        seen.add(name)
        parts = rest.split(":")
        tclass = parts[0].strip()
        rate = burst = budget = window = None
        for opt in parts[1:]:
            key, _, val = opt.partition("=")
            key = key.strip()
            if key == "rate":
                rate = float(val)
            elif key == "burst":
                burst = float(val)
            elif key == "budget":
                budget = float(val)
            elif key == "window":
                window = float(val)
            else:
                raise ValueError(
                    f"unknown tenant option {key!r} in {entry!r} "
                    f"(rate, burst, budget, window)"
                )
        tenants.append(TenantSpec(name, tclass, rate, burst, budget, window))
    if not tenants:
        raise ValueError(f"empty tenant spec {spec!r}")
    return tenants


class _Bucket:
    """One tenant's token bucket; caller holds the admission lock."""

    __slots__ = ("rate", "capacity", "tokens", "t")

    def __init__(self, rate: float, burst: float | None, now: float):
        self.rate = float(rate)
        self.capacity = float(burst) if burst is not None else max(rate, 1.0)
        self.tokens = self.capacity
        self.t = now

    def take(self, now: float) -> bool:
        self.tokens = min(
            self.capacity, self.tokens + (now - self.t) * self.rate
        )
        self.t = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class AdmissionController:
    """Admit-or-shed gate in front of the scheduler.

    ``pressure_fn`` is a zero-arg callable returning the pool's current
    pressure in [0, 1] (the scheduler supplies pending-depth /
    max_queue); without one, only quotas apply. Unknown tenants are
    admitted with the default ``batch`` class and no quota — admission
    shapes traffic, it is not an auth layer.
    """

    def __init__(
        self,
        tenants,
        *,
        pressure_fn=None,
        meter=None,
        registry=None,
        clock=time.monotonic,
    ):
        self._clock = clock
        self._pressure_fn = pressure_fn
        self._meter = meter
        self._specs = {t.name: t for t in tenants}
        self._lock = lockwatch.lock("serve.admission")
        now = clock()
        self._buckets = {
            t.name: _Bucket(t.rate, t.burst, now)
            for t in tenants
            if t.rate is not None
        }
        self._default = TenantSpec("_default", "batch")
        reg = registry if registry is not None else get_registry()
        self._m_admitted = reg.counter(
            "serve_admit_total",
            "requests admitted past the tenant gate",
            labels=("tenant", "class"),
        )
        self._m_shed = reg.counter(
            "serve_admit_shed_total",
            "requests shed at admission by reason (quota|pressure|budget)",
            labels=("tenant", "class", "reason"),
        )
        self._m_pressure = reg.gauge(
            "serve_admit_pressure",
            "pool pressure sampled at the last admission decision",
        )
        self._m_budget_left = reg.gauge(
            "serve_tenant_budget_remaining",
            "device-seconds left in the tenant's budget window (budgeted tenants)",
            labels=("tenant", "class"),
        )
        # eager children: every configured tenant is scrapeable (at zero)
        # from construction, not from its first admit/shed event
        for sp in self._specs.values():
            self._m_admitted.labels(sp.name, sp.tclass)
            for reason in ("quota", "pressure", "budget"):
                self._m_shed.labels(sp.name, sp.tclass, reason)
            if sp.budget is not None:
                self._m_budget_left.labels(sp.name, sp.tclass).set(sp.budget)
        # shed bookkeeping for stats()/tests, by (tenant, reason)
        self._admitted_n: dict[str, int] = {}
        self._shed_n: dict[tuple[str, str], int] = {}

    def set_pressure_fn(self, fn) -> None:
        """Late-bind the pool pressure probe — the scheduler that supplies
        it usually takes this controller as a constructor argument."""
        self._pressure_fn = fn

    def set_meter(self, meter) -> None:
        """Late-bind the cost meter that prices ``budget=`` tenants — it
        is usually built after the controller, next to the replica set."""
        self._meter = meter

    def spec(self, tenant: str | None) -> TenantSpec:
        if tenant is None:
            return self._default
        return self._specs.get(tenant, TenantSpec(tenant, "batch"))

    def pressure(self) -> float:
        if self._pressure_fn is None:
            return 0.0
        try:
            return max(0.0, float(self._pressure_fn()))
        except Exception:  # noqa: BLE001 — a broken probe must not shed traffic
            return 0.0

    def admit(self, tenant: str | None) -> TenantSpec:
        """Gate one request; returns the tenant's spec (class for the
        trace row and the scheduler score) or raises a typed shed.

        Pressure is checked before quota: under load, a low class sheds
        even with tokens in the bank — the whole point is protecting the
        higher classes' capacity. A budgeted tenant that spent its window
        degrades to scavenger-class pressure sensitivity (never a shed at
        zero pressure — budgets bound contention, they don't kill).
        """
        sp = self.spec(tenant)
        pressure = self.pressure()
        self._m_pressure.set(pressure)
        if pressure >= CLASS_SHED_PRESSURE[sp.tclass]:
            self._shed(sp, "pressure")
            raise TenantPressureError(
                f"tenant {sp.name!r} ({sp.tclass}) shed at pressure "
                f"{pressure:.2f} >= {CLASS_SHED_PRESSURE[sp.tclass]}"
            )
        if sp.budget is not None and self._meter is not None:
            window = sp.budget_window_s
            used = self._meter.window_usage(sp.name, window)
            self._m_budget_left.labels(sp.name, sp.tclass).set(
                max(0.0, sp.budget - used)
            )
            if (
                used >= sp.budget
                and pressure >= CLASS_SHED_PRESSURE["scavenger"]
            ):
                self._shed(sp, "budget")
                raise TenantBudgetError(
                    f"tenant {sp.name!r} over budget "
                    f"({used:.3f}s >= {sp.budget:g}s device-time per window) "
                    f"at pressure {pressure:.2f}"
                )
        bucket = self._buckets.get(sp.name)
        if bucket is not None:
            with self._lock:
                ok = bucket.take(self._clock())
            if not ok:
                self._shed(sp, "quota")
                raise TenantQuotaError(
                    f"tenant {sp.name!r} over quota "
                    f"({bucket.rate:g} req/s, burst {bucket.capacity:g})"
                )
        self._m_admitted.labels(sp.name, sp.tclass).inc()
        with self._lock:
            self._admitted_n[sp.name] = self._admitted_n.get(sp.name, 0) + 1
        return sp

    def admit_wait(
        self, tenant: str | None, timeout_s: float = 30.0
    ) -> TenantSpec:
        """Blocking :meth:`admit` for throughput-class clients (batch
        jobs): a shed is backpressure, not an answer, so retry with
        backoff until admitted or ``timeout_s`` passes — then re-raise
        the last typed shed for the caller's error accounting. Never use
        this on an interactive path (it holds the calling thread)."""
        deadline = self._clock() + timeout_s
        delay = 0.02
        while True:
            try:
                return self.admit(tenant)
            except QueueFullError:
                if self._clock() >= deadline:
                    raise
                time.sleep(min(delay, max(0.0, deadline - self._clock())))
                delay = min(delay * 2, 0.5)

    def _shed(self, sp: TenantSpec, reason: str) -> None:
        self._m_shed.labels(sp.name, sp.tclass, reason).inc()
        with self._lock:
            key = (sp.name, reason)
            self._shed_n[key] = self._shed_n.get(key, 0) + 1

    def stats(self) -> dict:
        with self._lock:
            admitted = dict(self._admitted_n)
            shed = {f"{t}:{r}": n for (t, r), n in self._shed_n.items()}
        return {"admitted": admitted, "shed": shed}
