"""Tenant cost metering: who consumed the capacity, and how much was pad.

The traffic-shaping tier can say *that* the fleet is saturated and *who*
got shed; this module says *who consumed the device*. On every dispatched
batch the replica set calls :meth:`CostMeter.observe_batch` with the
measured wall-time and the request traces it just served. The meter looks
up the executable's :class:`~jumbo_mae_tpu_tpu.obs.costmodel.ProgramCost`
for that ``(task, bucket)``, splits the whole batch cost pro-rata across
the occupied rows, and accumulates per-tenant ledgers.

Attribution model — conservation first:

- every occupied row is billed ``run_s / rows`` device-seconds and
  ``exec_flops / rows`` FLOPs, so per-tenant sums reconcile *exactly*
  with the batch-level measurements (``sum device_s == sum run_s``,
  ``sum flops == exec_flops × batches``);
- padding is an attribution *within* that total, not on top of it: a
  batch dispatched at pad fraction ``p`` moves ``run_s × p`` of its bill
  into each dispatching tenant's ``waste`` account (split equally across
  the traces in the batch), so the chargeback report can show how much of
  a tenant's bill bought padding rather than work.

Three read paths hang off the ledgers: ``serve_tenant_*{tenant,class}``
counters/gauges (scrapeable), ``device_ms``/``cost_flops`` columns stamped
onto each access-log row (per-request), and periodic ``tenant_usage``
journal events (offline chargeback via ``tools/cost_doctor.py``). The
admission gate consults :meth:`CostMeter.window_usage` for ``budget=``
enforcement: over-budget tenants degrade to scavenger-class shedding.

The meter never raises on the hot path: a missing cost table bills
device-time only, and a meter-internal error must not kill a flush.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Iterable

import time

from jumbo_mae_tpu_tpu.obs import lockwatch

_TENANT_LABELS = ("tenant", "class")


def default_cost_fn(engine, task: str, bucket: int):
    """Resolve analytic cost from a real engine's published cost table."""
    from jumbo_mae_tpu_tpu.obs.costmodel import lookup_cost

    return lookup_cost(getattr(engine, "cost_reports", None), task, bucket)


class _Ledger:
    """One tenant's running bill."""

    __slots__ = (
        "tclass",
        "requests",
        "batches",
        "device_s",
        "flops",
        "bytes_accessed",
        "waste_device_s",
        "waste_flops",
        "window",
    )

    def __init__(self, tclass: str):
        self.tclass = tclass
        self.requests = 0
        self.batches = 0
        self.device_s = 0.0
        self.flops = 0.0
        self.bytes_accessed = 0.0
        self.waste_device_s = 0.0
        self.waste_flops = 0.0
        # (timestamp, device_s) samples for budget-window accounting
        self.window: deque[tuple[float, float]] = deque()


def _cost_field(cost, name: str) -> float:
    if cost is None:
        return 0.0
    if isinstance(cost, dict):
        val = cost.get(name, 0.0)
    else:
        val = getattr(cost, name, 0.0)
    try:
        return max(0.0, float(val or 0.0))
    except (TypeError, ValueError):
        return 0.0


class CostMeter:
    """Per-tenant usage ledger fed by the replica set's flush loop.

    ``tenants`` seeds the ledger (and eagerly registers metric children)
    for every configured tenant; unknown tenants appearing at dispatch
    time get ledgers on demand. ``cost_fn(engine, task, bucket)`` resolves
    the analytic per-execution cost (``ProgramCost`` or a plain dict with
    ``flops``/``bytes_accessed``); ``None`` engines or lookups bill
    device-time only. ``chip`` prices device-seconds against a roofline
    :class:`~jumbo_mae_tpu_tpu.obs.perfmodel.ChipSpec` in snapshots.
    """

    def __init__(
        self,
        tenants: Iterable[Any] = (),
        *,
        cost_fn: Callable[[Any, str, int], Any] | None = default_cost_fn,
        chip=None,
        tracer=None,
        registry=None,
        window_s: float = 60.0,
        journal_interval_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if chip is None:
            from jumbo_mae_tpu_tpu.obs.perfmodel import detect_chip

            try:
                chip = detect_chip()
            except Exception:  # noqa: BLE001 - pricing is best-effort
                chip = None
        self._cost_fn = cost_fn
        self._chip = chip
        self._tracer = tracer
        self._window_s = float(window_s)
        self._journal_interval_s = float(journal_interval_s)
        self._clock = clock
        self._lock = lockwatch.lock("serve.costmeter")
        self._ledgers: dict[str, _Ledger] = {}
        self._budgets: dict[str, tuple[float, float]] = {}
        # batch-level totals the conservation tests reconcile against
        self.total_batches = 0
        self.total_device_s = 0.0
        self.total_flops = 0.0
        self._t_journal = clock()

        if registry is None:
            from jumbo_mae_tpu_tpu.obs.metrics import get_registry

            registry = get_registry()
        reg = registry
        self._m_requests = reg.counter(
            "serve_tenant_requests_total",
            "requests served (reached a device batch) per tenant",
            labels=_TENANT_LABELS,
        )
        self._m_device_s = reg.counter(
            "serve_tenant_device_seconds_total",
            "device wall-seconds attributed to the tenant, pro-rata per occupied row",
            labels=_TENANT_LABELS,
        )
        self._m_flops = reg.counter(
            "serve_tenant_flops_total",
            "executable FLOPs attributed to the tenant, pro-rata per occupied row",
            labels=_TENANT_LABELS,
        )
        self._m_waste_s = reg.counter(
            "serve_tenant_waste_device_seconds_total",
            "share of the tenant's device-seconds that bought bucket padding",
            labels=_TENANT_LABELS,
        )
        self._m_share = reg.gauge(
            "serve_tenant_capacity_share",
            "tenant's fraction of metered device-seconds over the budget window",
            labels=_TENANT_LABELS,
        )
        for spec in tenants:
            name = getattr(spec, "name", str(spec))
            self._ledger(name, getattr(spec, "tclass", "batch"))
            budget = getattr(spec, "budget", None)
            if budget is not None:
                win = getattr(spec, "budget_window_s", None) or self._window_s
                self._budgets[name] = (float(budget), float(win))

    # -- ledger plumbing ---------------------------------------------------

    def _ledger(self, tenant: str, tclass: str | None) -> _Ledger:
        led = self._ledgers.get(tenant)
        if led is None:
            led = _Ledger(tclass or "batch")
            self._ledgers[tenant] = led
            labels = (tenant, led.tclass)
            # eager children: the tenant is scrapeable from first sight
            self._m_requests.labels(*labels)
            self._m_device_s.labels(*labels)
            self._m_flops.labels(*labels)
            self._m_waste_s.labels(*labels)
            self._m_share.labels(*labels)
        return led

    def _prune(self, led: _Ledger, now: float, window: float) -> float:
        cutoff = now - window
        win = led.window
        while win and win[0][0] < cutoff:
            win.popleft()
        return sum(s for _, s in win)

    # -- hot path ----------------------------------------------------------

    def observe_batch(
        self, *, run_s: float, traces, batch: int, engine=None
    ) -> None:
        """Attribute one flushed batch. Called by ``ReplicaSet._flush``
        after a successful run, before per-request finish — so the stamped
        ``device_s``/``cost_flops`` land on every access-log row."""
        try:
            self._observe(run_s=run_s, traces=traces, batch=batch, engine=engine)
        except Exception:  # noqa: BLE001 - metering must never kill a flush
            pass

    def _observe(self, *, run_s: float, traces, batch: int, engine) -> None:
        traces = [tr for tr in traces if tr is not None]
        if not traces:
            return
        n = max(int(batch), len(traces), 1)
        run_s = max(0.0, float(run_s))
        lead = traces[0]
        task = getattr(lead, "task", None) or "predict"
        bucket = getattr(lead, "bucket", None) or n
        pad = getattr(lead, "pad_fraction", None)
        if pad is None:
            pad = max(0.0, (int(bucket) - n) / int(bucket)) if bucket else 0.0
        pad = min(1.0, max(0.0, float(pad)))

        cost = None
        if self._cost_fn is not None:
            try:
                cost = self._cost_fn(engine, task, int(bucket))
            except Exception:  # noqa: BLE001 - cost lookup is best-effort
                cost = None
        exec_flops = _cost_field(cost, "flops")
        exec_bytes = _cost_field(cost, "bytes_accessed")

        # token-packed groups carry per-trace token counts: a 896px request
        # in the pack did ~49x the work of a 224px one, so uniform per-row
        # split would cross-subsidize. Token-pro-rata shares preserve the
        # conservation law (per-trace sums still equal batch totals).
        tok = [float(getattr(tr, "tokens", None) or 0) for tr in traces]
        tok_total = sum(tok)
        token_weighted = tok_total > 0 and all(t > 0 for t in tok)

        row_s = run_s / n
        row_flops = exec_flops / n
        row_bytes = exec_bytes / n
        waste_s_per_trace = run_s * pad / len(traces)
        waste_flops_per_trace = exec_flops * pad / len(traces)
        now = self._clock()

        with self._lock:
            self.total_batches += 1
            self.total_device_s += run_s
            self.total_flops += exec_flops
            seen: set[str] = set()
            for j, tr in enumerate(traces):
                if token_weighted:
                    share = tok[j] / tok_total
                    row_s = run_s * share
                    row_flops = exec_flops * share
                    row_bytes = exec_bytes * share
                    waste_s_per_trace = run_s * pad * share
                    waste_flops_per_trace = exec_flops * pad * share
                tr.device_s = row_s
                tr.cost_flops = row_flops if row_flops > 0.0 else None
                tenant = getattr(tr, "tenant", None) or "_default"
                led = self._ledger(tenant, getattr(tr, "tclass", None))
                led.requests += 1
                if tenant not in seen:
                    seen.add(tenant)
                    led.batches += 1
                led.device_s += row_s
                led.flops += row_flops
                led.bytes_accessed += row_bytes
                led.waste_device_s += waste_s_per_trace
                led.waste_flops += waste_flops_per_trace
                led.window.append((now, row_s))
                labels = (tenant, led.tclass)
                self._m_requests.labels(*labels).inc()
                self._m_device_s.labels(*labels).inc(row_s)
                if row_flops:
                    self._m_flops.labels(*labels).inc(row_flops)
                if waste_s_per_trace:
                    self._m_waste_s.labels(*labels).inc(waste_s_per_trace)
            self._update_shares(now)
        self._maybe_journal(now)

    def _update_shares(self, now: float) -> None:
        usage = {
            t: self._prune(led, now, self._window_s)
            for t, led in self._ledgers.items()
        }
        total = sum(usage.values())
        for tenant, win_s in usage.items():
            led = self._ledgers[tenant]
            share = win_s / total if total > 0.0 else 0.0
            self._m_share.labels(tenant, led.tclass).set(share)

    # -- budget + reporting ------------------------------------------------

    def window_usage(self, tenant: str, window_s: float | None = None) -> float:
        """Device-seconds the tenant consumed over the trailing window."""
        with self._lock:
            led = self._ledgers.get(tenant)
            if led is None:
                return 0.0
            return self._prune(led, self._clock(), window_s or self._window_s)

    def budget_for(self, tenant: str) -> tuple[float, float] | None:
        """(device-seconds, window-seconds) budget, if one is configured."""
        return self._budgets.get(tenant)

    def over_budget(self, tenant: str) -> bool:
        budget = self._budgets.get(tenant)
        if budget is None:
            return False
        limit, window = budget
        return self.window_usage(tenant, window) >= limit

    def snapshot(self) -> dict:
        """Ledger totals for reports: per-tenant bill + batch-level sums."""
        now = self._clock()
        with self._lock:
            tenants = {}
            win_usage = {
                t: self._prune(led, now, self._window_s)
                for t, led in self._ledgers.items()
            }
            win_total = sum(win_usage.values())
            for tenant, led in self._ledgers.items():
                budget = self._budgets.get(tenant)
                row = {
                    "class": led.tclass,
                    "requests": led.requests,
                    "device_s": led.device_s,
                    "flops": led.flops,
                    "bytes_accessed": led.bytes_accessed,
                    "waste_device_s": led.waste_device_s,
                    "waste_flops": led.waste_flops,
                    "window_device_s": win_usage[tenant],
                    "share": win_usage[tenant] / win_total if win_total else 0.0,
                }
                if budget is not None:
                    limit, window = budget
                    used = self._prune(led, now, window)
                    row["budget_device_s"] = limit
                    row["budget_window_s"] = window
                    row["budget_used_s"] = used
                    row["over_budget"] = used >= limit
                tenants[tenant] = row
            out = {
                "tenants": tenants,
                "total_batches": self.total_batches,
                "total_device_s": self.total_device_s,
                "total_flops": self.total_flops,
            }
        if self._chip is not None:
            out["chip"] = getattr(self._chip, "name", str(self._chip))
            peak = getattr(self._chip, "peak_tflops", 0.0) or 0.0
            if peak and out["total_device_s"] > 0.0:
                # achieved fraction of what the chip could have delivered
                # over the metered device-time
                out["roofline_utilization"] = out["total_flops"] / (
                    out["total_device_s"] * peak * 1e12
                )
        return out

    def _maybe_journal(self, now: float) -> None:
        if self._tracer is None:
            return
        if now - self._t_journal < self._journal_interval_s:
            return
        self._t_journal = now
        self._journal()

    def _journal(self) -> None:
        if self._tracer is None:
            return
        snap = self.snapshot()
        for tenant, row in snap["tenants"].items():
            fields = {
                "tenant": tenant,
                "class": row["class"],
                "requests": row["requests"],
                "device_s": round(row["device_s"], 6),
                "flops": row["flops"],
                "waste_device_s": round(row["waste_device_s"], 6),
                "window_device_s": round(row["window_device_s"], 6),
                "share": round(row["share"], 4),
            }
            if "budget_device_s" in row:
                fields["budget_device_s"] = row["budget_device_s"]
                fields["over_budget"] = row["over_budget"]
            try:
                self._tracer.event("tenant_usage", **fields)
            except Exception:  # noqa: BLE001 - journaling is best-effort
                return

    def flush(self) -> None:
        """Force a final ``tenant_usage`` emission (shutdown path)."""
        self._journal()
