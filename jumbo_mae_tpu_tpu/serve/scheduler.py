"""Continuous batching: late arrivals join pending batches until cutoff.

The per-replica :class:`MicroBatcher` loop is FIFO-per-flush: the first
request opens a batch window, the window closes, the batch runs — and a
request arriving 1 ms after the close waits out a whole new window. Worse,
the pool router splits concurrent arrivals *across* replicas, so each
replica flushes a half-empty batch and the pad fraction burns MXU cycles
(`infer_batch_occupancy` tells the story).

The :class:`ContinuousScheduler` centralizes coalescing: one dispatcher
thread owns per-``(task, shape-bucket)`` accumulators; every arrival joins
its bucket's *pending* batch — including one already waiting to dispatch —
up to a deadline-aware cutoff. A batch becomes *ready* when it fills or
its cutoff passes; among ready batches the dispatcher picks the highest

    score = occupancy + oldest_wait / max_delay + max(class weight)

so full batches go first, no waiter starves (age grows without bound),
and interactive tenants outrank batch/scavenger at equal fill. Within the
dispatched batch, slots go to the highest class first (the admit-queue
jump): when a batch is over-full, the *low*-class overflow waits for the
next one. Dispatch hands the whole group to
:meth:`ReplicaSet.submit_group`, which lands it on ONE replica as one
flush — the occupancy the scheduler assembled is the occupancy the
replica runs.

Exactly-once: after ``submit`` enqueues an entry, only the dispatcher
thread touches it, and each entry leaves exactly one way — expired
(deadline), failed (dispatch error / shutdown), or chained to the backend
future that resolves it. The backend owns each trace once dispatch is
called (`submit_group`'s contract); the scheduler finishes traces only
for entries that never reached dispatch.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future

import numpy as np

from jumbo_mae_tpu_tpu.faults.inject import fault_point
from jumbo_mae_tpu_tpu.infer.batching import (
    DeadlineExceededError,
    OccupancyWindow,
    QueueFullError,
    ShutdownError,
)
from jumbo_mae_tpu_tpu.infer.bucketing import floor_bucket  # noqa: F401 — re-export
from jumbo_mae_tpu_tpu.obs import lockwatch
from jumbo_mae_tpu_tpu.obs.metrics import RATIO_BUCKETS, get_registry
from jumbo_mae_tpu_tpu.serve.admission import CLASSES, CLASS_WEIGHT

_STOP = object()

# a deadline-carrying entry must dispatch this fraction of max_delay
# before the deadline itself, or compute time eats the remaining budget
_DEADLINE_MARGIN = 0.25


class _Entry:
    __slots__ = (
        "image", "fut", "tr", "tenant", "tclass", "deadline",
        "meta", "task", "t_submit", "tokens",
    )

    def __init__(
        self, image, fut, tr, tenant, tclass, deadline, meta, task, now,
        tokens=None,
    ):
        self.image = image
        self.fut = fut
        self.tr = tr
        self.tenant = tenant
        self.tclass = tclass
        self.deadline = deadline   # absolute time.monotonic() instant | None
        self.meta = meta
        self.task = task
        self.t_submit = now
        self.tokens = tokens       # packed mode: patch+CLS token count


class ContinuousScheduler:
    """Cross-request batch assembler in front of a dispatch backend.

    ``dispatch(items)`` receives ``[(image, deadline, meta, tr), ...]``
    and returns one backend future per item —
    :meth:`ReplicaSet.submit_group` is the production backend; tests pass
    a stub. ``admission`` is an optional
    :class:`~jumbo_mae_tpu_tpu.serve.admission.AdmissionController`;
    when the scheduler builds its own pressure signal
    (pending / ``max_queue``), wire ``admission.pressure_fn`` to
    :meth:`pressure`. ``clock`` must be ``time.monotonic``-like (absolute
    deadlines are compared against it).

    ``packed=True`` switches the accumulators from per-``(task, shape)``
    to ONE token accumulator: mixed resolutions and encoder-sharing tasks
    coalesce together, a batch fills when its *token* sum reaches
    ``token_budget`` (``seq_len_fn(image) -> tokens`` prices each entry),
    and the dispatch backend is expected to serve the group through the
    engine's token-packed path (``predict_packed``). Entries carry their
    token count on their trace (``tr.tokens``) so the costmeter bills
    device time token-pro-rata instead of per-row.
    """

    def __init__(
        self,
        dispatch,
        *,
        max_batch: int = 32,
        max_delay_ms: float = 5.0,
        max_queue: int | None = None,
        admission=None,
        tracer=None,
        task: str = "",
        registry=None,
        clock=time.monotonic,
        packed: bool = False,
        token_budget: int | None = None,
        seq_len_fn=None,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if packed:
            if seq_len_fn is None:
                raise ValueError(
                    "packed=True needs seq_len_fn (e.g. lambda img: "
                    "engine.seq_len(img.shape[0])) to price entries in tokens"
                )
            if not token_budget or token_budget < 1:
                raise ValueError(
                    f"packed=True needs a positive token_budget, got "
                    f"{token_budget}"
                )
        self.packed = bool(packed)
        self.token_budget = int(token_budget) if token_budget else None
        self._seq_len_fn = seq_len_fn
        self._dispatch = dispatch
        self.max_batch = int(max_batch)
        self.max_delay = float(max_delay_ms) / 1000.0
        self.max_queue = max_queue
        self.admission = admission
        self._tracer = tracer
        self.task = task
        self._clock = clock
        reg = registry if registry is not None else get_registry()
        self._m_batches = reg.counter(
            "serve_sched_batches_total",
            "batches dispatched by the continuous scheduler, by trigger "
            "(full|cutoff|aligned|close)",
            labels=("reason",),
        )
        self._m_occupancy = reg.histogram(
            "serve_sched_batch_occupancy",
            "dispatched batch size / max_batch (continuous scheduler)",
            buckets=RATIO_BUCKETS,
        )
        self._m_depth = reg.gauge(
            "serve_sched_queue_depth",
            "requests pending in scheduler accumulators",
        )
        self._m_jumps = reg.counter(
            "serve_sched_priority_jumps_total",
            "dispatch slots a higher class took ahead of an earlier-"
            "arrived lower-class request",
        )
        self._occ = OccupancyWindow(self.max_batch)
        self._depth = 0
        self._depth_lock = lockwatch.lock("serve.sched.depth")
        self._dispatched = 0
        self._expired = 0
        self._closed = False
        self._drain = True
        self._wake: queue.SimpleQueue = queue.SimpleQueue()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="continuous-scheduler"
        )
        self._thread.start()

    # ------------------------------------------------------------- client

    def submit(
        self,
        image,
        *,
        task: str | None = None,
        deadline_ms: float | None = None,
        meta=None,
        tenant: str | None = None,
    ) -> Future:
        """Admit one request into its (task, shape) accumulator; returns a
        future. Sheds typed: tenant-weighted
        (:class:`TenantQuotaError` / :class:`TenantPressureError` /
        :class:`TenantBudgetError`) when an admission controller is
        attached, plus the hard :class:`QueueFullError` backstop at
        ``max_queue``. Typed sheds stamp the subclass name into the
        access-log row's ``err`` column so offline doctors can split
        quota vs pressure vs budget sheds."""
        sp = None
        tclass = None
        if self.admission is not None:
            sp = self.admission.spec(tenant)
            tclass = sp.tclass
        tr = (
            self._tracer.begin(
                task=task if task is not None else self.task,
                deadline_ms=deadline_ms,
                tenant=tenant,
                tclass=tclass,
            )
            if self._tracer is not None
            else None
        )
        arr = np.asarray(image)
        tokens = None
        try:
            fault_point("serve.submit")
            if self._closed:
                raise ShutdownError("ContinuousScheduler is closed")
            if self._seq_len_fn is not None:
                # price the entry in tokens up front — a misaligned or
                # oversized request sheds here, typed, not on the dispatcher.
                # A seq_len_fn without packed mode still stamps tr.tokens so
                # the costmeter can bill image-bucketed traffic pro-rata too
                tokens = int(self._seq_len_fn(arr))
                if self.packed and tokens > self.token_budget:
                    raise ValueError(
                        f"request needs {tokens} tokens > token_budget="
                        f"{self.token_budget} — raise the budget or resize"
                    )
            if self.admission is not None:
                self.admission.admit(tenant)
            with self._depth_lock:
                if self.max_queue is not None and self._depth >= self.max_queue:
                    raise QueueFullError(
                        f"scheduler queue full ({self._depth}/{self.max_queue})"
                    )
                self._depth += 1
        except BaseException as e:  # noqa: BLE001 — classify, trace, re-raise
            if tr is not None:
                if isinstance(e, QueueFullError):
                    # subclass name (quota/pressure/budget) rides in err;
                    # a bare QueueFullError shed stays unannotated
                    shed_kind = (
                        type(e).__name__
                        if type(e) is not QueueFullError
                        else None
                    )
                    self._tracer.finish(tr, "shed", error=shed_kind)
                elif isinstance(e, ShutdownError) or self._closed:
                    self._tracer.finish(tr, "shutdown")
                else:
                    self._tracer.finish(
                        tr, "aborted", error=f"{type(e).__name__}: {e}"
                    )
            raise
        fut: Future = Future()
        if tr is not None:
            fut.rid = tr.rid
        now = self._clock()
        deadline = (
            None if deadline_ms is None else now + float(deadline_ms) / 1000.0
        )
        entry = _Entry(
            arr, fut, tr, tenant, tclass, deadline, meta,
            task if task is not None else self.task, now, tokens,
        )
        if tr is not None and tokens is not None:
            tr.tokens = tokens
        self._wake.put(entry)
        return fut

    def pressure(self) -> float:
        """Pending depth / max_queue in [0, ~]: the admission
        controller's pool-pressure signal. Unbounded queue → always 0."""
        if not self.max_queue:
            return 0.0
        with self._depth_lock:
            return self._depth / self.max_queue

    def stats(self) -> dict:
        with self._depth_lock:
            depth = self._depth
        occ = self._occ.snapshot()
        return {
            "queue_depth": depth,
            "pressure": self.pressure(),
            "dispatched": self._dispatched,
            "expired": self._expired,
            "batch_occupancy": occ["ewma"],
            "window_batch_occupancy": occ["window_mean"],
            "batches": occ["batches"],
        }

    def close(self, drain: bool = True, timeout_s: float = 10.0) -> None:
        """Stop the dispatcher and resolve every undispatched entry:
        ``drain=True`` fails them with :class:`ShutdownError`;
        ``drain=False`` dispatches the leftovers first."""
        if self._closed:
            return
        self._drain = drain
        self._closed = True
        self._wake.put(_STOP)
        self._thread.join(timeout=timeout_s)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # --------------------------------------------------------- dispatcher

    def _cutoff(self, entry: _Entry) -> float:
        cut = entry.t_submit + self.max_delay
        if entry.deadline is not None:
            cut = min(cut, entry.deadline - _DEADLINE_MARGIN * self.max_delay)
        return cut

    def _key(self, entry: _Entry) -> tuple:
        """Accumulator key: per-(task, shape) bucketed, ONE shared token
        accumulator packed — mixing resolutions and encoder-sharing tasks
        is the whole point of the packed dispatch."""
        if self.packed:
            return ("__packed__",)
        return (entry.task, entry.image.shape)

    def _loop(self) -> None:
        # all accumulator state lives on this thread — no locks
        buckets: dict[tuple, list[_Entry]] = {}
        while True:
            timeout = self._next_wait(buckets)
            try:
                item = self._wake.get(timeout=timeout)
            except queue.Empty:
                item = None
            if item is _STOP:
                self._shutdown(buckets)
                return
            if item is not None:
                buckets.setdefault(self._key(item), []).append(item)
                # opportunistic drain: pull everything already queued so a
                # burst lands in its accumulators in one pass
                while True:
                    try:
                        nxt = self._wake.get_nowait()
                    except queue.Empty:
                        break
                    if nxt is _STOP:
                        self._shutdown(buckets)
                        return
                    buckets.setdefault(self._key(nxt), []).append(nxt)
            self._expire(buckets)
            self._dispatch_ready(buckets)
            self._m_depth.set(sum(len(v) for v in buckets.values()))

    def _next_wait(self, buckets) -> float:
        """Sleep until the earliest pending cutoff (bounded), or idle."""
        if not any(buckets.values()):
            return 0.25
        now = self._clock()
        earliest = min(
            self._cutoff(e) for v in buckets.values() for e in v
        )
        return max(min(earliest - now, 0.25), 0.0005)

    def _expire(self, buckets) -> None:
        """Fail entries whose deadline already passed while pending —
        they must not occupy dispatch slots."""
        now = self._clock()
        for key, entries in buckets.items():
            keep = []
            for e in entries:
                if e.deadline is not None and now > e.deadline:
                    self._expired += 1
                    self._dec(1)
                    if e.tr is not None:
                        self._tracer.finish(e.tr, "deadline")
                    e.fut.set_exception(
                        DeadlineExceededError(
                            "request deadline passed in scheduler accumulator"
                        )
                    )
                else:
                    keep.append(e)
            buckets[key] = keep

    def _dispatch_ready(self, buckets) -> None:
        while True:
            now = self._clock()
            best_key, best_score, best_reason = None, None, None
            for key, entries in buckets.items():
                if not entries:
                    continue
                if self.packed:
                    tok = sum(e.tokens or 0 for e in entries)
                    full = (
                        tok >= self.token_budget
                        or len(entries) >= self.max_batch
                    )
                    occ = min(tok / self.token_budget, 1.0)
                else:
                    full = len(entries) >= self.max_batch
                    occ = min(len(entries) / self.max_batch, 1.0)
                past_cutoff = any(self._cutoff(e) <= now for e in entries)
                if not (full or past_cutoff):
                    continue
                oldest = max(now - e.t_submit for e in entries)
                weight = max(
                    CLASS_WEIGHT.get(e.tclass, CLASS_WEIGHT["batch"])
                    if e.tclass is not None
                    else CLASS_WEIGHT["batch"]
                    for e in entries
                )
                score = occ + oldest / self.max_delay + weight
                if best_score is None or score > best_score:
                    best_key, best_score = key, score
                    best_reason = "full" if full else "cutoff"
            if best_key is None:
                return
            self._dispatch_bucket(buckets, best_key, best_reason)

    def _take_batch(
        self, entries: list[_Entry], reason: str
    ) -> tuple[list[_Entry], str]:
        """Pull up to max_batch entries, highest class first (FIFO within
        a class) — the over-full case is where priority jumps the queue.

        A cutoff-triggered partial batch is **bucket-aligned** when it
        can be: the engine pads every flush to a power-of-2 bucket, so
        dispatching 11 entries computes 16 rows while dispatching 8 and
        holding the 3 youngest (still inside their own cutoffs, now
        seeding the next batch) computes 8 — same latency for the due
        entries, zero pad. Alignment never holds a due entry back: if
        more entries are past cutoff than the floor bucket holds, the
        whole accumulator flushes padded.
        """
        if self.packed:
            return self._take_packed(entries, reason)
        n = min(len(entries), self.max_batch)
        if reason == "cutoff" and len(entries) < self.max_batch:
            now = self._clock()
            due = sum(1 for e in entries if self._cutoff(e) <= now)
            fb = floor_bucket(len(entries), self.max_batch)
            if fb < len(entries) and due <= fb:
                n = fb
                reason = "aligned"
        if n == len(entries):
            batch = list(entries)
            entries.clear()
            return batch, reason
        rank = {c: i for i, c in enumerate(CLASSES)}
        now = self._clock()
        # over-full: the highest class takes the slots (the queue jump).
        # aligned hold-back: due entries go first regardless of class —
        # alignment must never hold back an entry whose budget is spent
        due_first = reason == "aligned"
        order = sorted(
            range(len(entries)),
            key=lambda i: (
                (0 if self._cutoff(entries[i]) <= now else 1)
                if due_first
                else 0,
                rank.get(entries[i].tclass, rank["batch"]),
                entries[i].t_submit,
            ),
        )
        chosen = set(order[:n])
        # a jump = a chosen entry that arrived after an unchosen one
        arrival_cut = sorted(range(len(entries)))[:n]
        jumps = len(chosen - set(arrival_cut))
        if jumps:
            self._m_jumps.inc(jumps)
        batch = [entries[i] for i in sorted(chosen)]
        entries[:] = [e for i, e in enumerate(entries) if i not in chosen]
        return batch, reason

    def _take_packed(
        self, entries: list[_Entry], reason: str
    ) -> tuple[list[_Entry], str]:
        """Fill the token budget greedily in priority order: due entries
        first (a cutoff flush must carry everyone whose delay budget is
        spent), then class rank, then arrival. An entry that would
        overflow the remaining budget is SKIPPED, not a wall — smaller
        entries behind it may still top up the rung (that remainder is
        pure pad otherwise). Starvation is bounded: the head of the order
        is always taken, so a skipped large request reaches the head and
        ships first in a later dispatch; each skip-over also counts into
        ``serve_sched_priority_jumps_total``."""
        now = self._clock()
        rank = {c: i for i, c in enumerate(CLASSES)}
        order = sorted(
            range(len(entries)),
            key=lambda i: (
                0 if self._cutoff(entries[i]) <= now else 1,
                rank.get(entries[i].tclass, rank["batch"]),
                entries[i].t_submit,
            ),
        )
        chosen: list[int] = []
        tok = 0
        for i in order:
            if chosen and len(chosen) >= self.max_batch:
                break
            t = entries[i].tokens or 0
            if chosen and tok + t > self.token_budget:
                continue  # skim: later, smaller entries may still fit
            chosen.append(i)
            tok += t
        chosen_set = set(chosen)
        jumps = len(chosen_set - set(range(len(chosen))))
        if jumps:
            self._m_jumps.inc(jumps)
        batch = [entries[i] for i in sorted(chosen_set)]
        entries[:] = [e for i, e in enumerate(entries) if i not in chosen_set]
        return batch, reason

    def _dispatch_bucket(self, buckets, key, reason: str) -> None:
        batch, reason = self._take_batch(buckets[key], reason)
        if not batch:
            return
        self._dec(len(batch))
        self._m_batches.labels(reason).inc()
        if self.packed:
            self._m_occupancy.observe(
                min(sum(e.tokens or 0 for e in batch) / self.token_budget, 1.0)
            )
        else:
            self._m_occupancy.observe(len(batch) / self.max_batch)
        self._occ.observe(len(batch))
        self._dispatched += len(batch)
        items = [(e.image, e.deadline, e.meta, e.tr) for e in batch]
        try:
            backend_futs = self._dispatch(items)
        except BaseException as e:  # noqa: BLE001 — backend finished the traces; we fail the futures
            for entry in batch:
                entry.fut.set_exception(e)
            return
        for entry, bfut in zip(batch, backend_futs):
            bfut.add_done_callback(self._chain(entry.fut))

    @staticmethod
    def _chain(caller_fut: Future):
        def copy(bfut: Future) -> None:
            exc = bfut.exception()
            if exc is not None:
                caller_fut.set_exception(exc)
            else:
                caller_fut.set_result(bfut.result())

        return copy

    def _dec(self, k: int) -> None:
        with self._depth_lock:
            self._depth -= k

    def _shutdown(self, buckets) -> None:
        # sweep racers enqueued behind the stop sentinel
        while True:
            try:
                item = self._wake.get_nowait()
            except queue.Empty:
                break
            if item is _STOP:
                continue
            buckets.setdefault(self._key(item), []).append(item)
        if not self._drain:
            # graceful: flush what we have, then stop
            for key in list(buckets):
                while buckets[key]:
                    self._dispatch_bucket(buckets, key, "close")
            return
        for entries in buckets.values():
            for e in entries:
                self._dec(1)
                if e.tr is not None:
                    self._tracer.finish(e.tr, "shutdown")
                e.fut.set_exception(
                    ShutdownError("ContinuousScheduler closed")
                )
            entries.clear()
