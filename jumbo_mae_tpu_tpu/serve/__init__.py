"""Traffic-shaping tier: the scheduling brain between request submission
and the replicated serving pool.

Four cooperating components (ROADMAP open item 2 — the gap between
"survives crashes" and "serves millions of users"):

- :mod:`~jumbo_mae_tpu_tpu.serve.admission` — per-tenant token-bucket
  quotas and priority classes (``interactive`` > ``batch`` >
  ``scavenger``): under pressure, low-priority tenants shed *first*;
  budgeted tenants degrade to scavenger-class shedding once their
  device-second budget is spent.
- :mod:`~jumbo_mae_tpu_tpu.serve.scheduler` — continuous batching:
  per-(task, shape-bucket) accumulators admit late arrivals into
  partially-filled pending batches up to a deadline-aware cutoff, and the
  next batch is picked by occupancy + oldest-waiter age + priority class.
- :mod:`~jumbo_mae_tpu_tpu.serve.autoscaler` — a reconcile loop turning
  SLO burn rate, queue depth/occupancy, and roofline capacity estimates
  (``obs/perfmodel``) into a target replica count, actuated through
  :meth:`ReplicaSet.scale_to` (scale-down drains; never kills in-flight
  work).
- :mod:`~jumbo_mae_tpu_tpu.serve.costmeter` — per-tenant usage metering:
  every dispatched batch's wall-time and executable FLOPs are split
  pro-rata across occupied rows into per-tenant ledgers (pad waste
  attributed separately), feeding ``serve_tenant_*`` metrics, per-row
  ``device_ms``/``cost_flops`` access-log columns, ``tenant_usage``
  journal events, and the admission gate's ``budget=`` enforcement.
- :mod:`~jumbo_mae_tpu_tpu.serve.publisher` — continuous deployment:
  the gated train→serve weights publisher (int8/delta artifacts with a
  verifiable manifest chain into the ``--swap-watch`` directory) and the
  verification/resolution helpers the swap watcher and
  ``tools/publish_doctor.py`` share.
"""

from jumbo_mae_tpu_tpu.serve.admission import (
    CLASSES,
    AdmissionController,
    TenantBudgetError,
    TenantPressureError,
    TenantQuotaError,
    TenantSpec,
    parse_tenants,
)
from jumbo_mae_tpu_tpu.serve.autoscaler import Autoscaler, roofline_capacity
from jumbo_mae_tpu_tpu.serve.costmeter import CostMeter, default_cost_fn
from jumbo_mae_tpu_tpu.serve.publisher import (
    CheckpointPublisher,
    PublishIntegrityError,
    is_publish_artifact,
    latest_artifact,
    resolve_chain,
    verify_artifact,
)
from jumbo_mae_tpu_tpu.serve.scheduler import ContinuousScheduler

__all__ = [
    "CLASSES",
    "AdmissionController",
    "Autoscaler",
    "CheckpointPublisher",
    "ContinuousScheduler",
    "CostMeter",
    "PublishIntegrityError",
    "is_publish_artifact",
    "latest_artifact",
    "resolve_chain",
    "verify_artifact",
    "TenantBudgetError",
    "TenantPressureError",
    "TenantQuotaError",
    "TenantSpec",
    "default_cost_fn",
    "parse_tenants",
    "roofline_capacity",
]
