"""Resumable, exactly-once offline batch inference over tar shards.

Submodules:

- ``job``      — :class:`JobSpec` / :class:`BatchJobRunner`, the
  lease-fenced shard-parallel executor
- ``leases``   — :class:`LeaseTable`, journaled leases with expiry/steal
  and write fencing
- ``partfile`` — framed torn-tail-tolerant part files and the
  deterministic manifest
"""

from jumbo_mae_tpu_tpu.batch.job import (
    BatchJobRunner,
    JobSpec,
    default_decode,
    part_stem,
)
from jumbo_mae_tpu_tpu.batch.leases import LeaseTable
from jumbo_mae_tpu_tpu.batch.partfile import (
    file_sha256,
    iter_records,
    read_manifest,
    scan_part,
    write_manifest,
)

__all__ = [
    "BatchJobRunner",
    "JobSpec",
    "LeaseTable",
    "default_decode",
    "file_sha256",
    "iter_records",
    "part_stem",
    "read_manifest",
    "scan_part",
    "write_manifest",
]
