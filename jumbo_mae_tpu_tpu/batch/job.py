"""Resumable offline-inference jobs: shard leases → replica tier → parts.

A :class:`JobSpec` names tar shards, a task, and an output directory; the
:class:`BatchJobRunner` streams every sample of every shard through a
``ContinuousScheduler``-shaped submit function as a budget-capped
``batch``-class tenant and writes one durable part file per shard
(`batch/partfile.py`). The job is **killable at any instruction** and a
restart produces bit-identical output to a fault-free run:

- shards are claimed via journaled leases with expiry/steal
  (`batch/leases.py`) — a worker killed mid-shard (the ``batch.worker``
  fault site, or a whole SIGKILL'd process) just stops renewing, and a
  surviving worker steals the shard after ``lease_s``;
- per-shard progress is the count of durable frames in the ``.partial``
  file — the restarted worker truncates the torn tail, re-streams the
  shard, and skips exactly the written prefix (``iter_tar_samples`` resumes
  deterministically), so no sample is ever duplicated or dropped;
- shard completion atomically renames ``.partial`` → ``.part``
  (fsync + ``fsync_dir``); job completion writes the deterministic
  manifest. Both are journaled (``job_shard_done`` / ``job_complete``)
  alongside lease grants (``job_lease``) and progress cursors
  (``job_cursor``) for ``tools/batch_doctor.py``.

The runner takes the submit callable instead of building the serving stack
itself, so tests drive it with a deterministic stub and ``cli/batch.py``
drives it with the real scheduler + admission + replica pool.
"""

from __future__ import annotations

import hashlib
import os
import re
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable

import numpy as np

from jumbo_mae_tpu_tpu.batch.leases import LeaseTable
from jumbo_mae_tpu_tpu.batch.partfile import (
    append_record,
    encode_record,
    file_sha256,
    finalize_part,
    read_manifest,
    scan_part,
    write_manifest,
)
from jumbo_mae_tpu_tpu.data.tario import QUARANTINE, RetryPolicy, iter_tar_samples
from jumbo_mae_tpu_tpu.faults.inject import fault_point
from jumbo_mae_tpu_tpu.infer.batching import QueueFullError, ShutdownError
from jumbo_mae_tpu_tpu.infer.replicaset import PoolUnhealthyError
from jumbo_mae_tpu_tpu.obs.journal import RunJournal
from jumbo_mae_tpu_tpu.obs.metrics import get_registry


class _WorkerKilled(BaseException):
    """The ``batch.worker`` fault fired: this worker is dead. It must NOT
    release its lease — recovering the shard is the steal path's job."""


class _Fenced(Exception):
    """The worker's lease was stolen mid-shard (it renewed too late); it
    must stop writing immediately — the thief owns the partial file now."""


def part_stem(url: str) -> str:
    """Deterministic, filesystem-safe part name for one shard URL: the
    basename plus a short URL hash (two shards named ``data.tar`` in
    different directories must not collide)."""
    name = url.rsplit("/", 1)[-1] or "shard"
    if name.endswith(".tar"):
        name = name[:-4]
    name = re.sub(r"[^A-Za-z0-9._-]", "_", name)
    h = hashlib.sha256(url.encode("utf-8")).hexdigest()[:8]
    return f"{name}-{h}"


def default_decode(sample: dict, width: int = 256) -> np.ndarray:
    """Payload → fixed-shape uint8 vector (first member by sorted ext,
    zero-padded/truncated to ``width``). Fixed shape on purpose: the
    scheduler buckets by ``(task, shape)`` and the pool stacks batches.
    Real deployments pass a proper image decoder to the runner."""
    for ext in sorted(k for k in sample if not k.startswith("__")):
        raw = np.frombuffer(sample[ext][:width], dtype=np.uint8)
        if raw.size < width:
            raw = np.concatenate([raw, np.zeros(width - raw.size, np.uint8)])
        return raw
    return np.zeros(width, np.uint8)


@dataclass(frozen=True)
class JobSpec:
    """One offline inference job: shard list × task × output dir."""

    shards: tuple[str, ...]
    output_dir: str
    task: str = "features"
    tenant: str = "batch"
    workers: int = 2
    submit_window: int = 8       # samples in flight per worker
    lease_s: float = 30.0
    cursor_every: int = 32       # journal a job_cursor every N samples
    deadline_ms: float | None = None
    result_timeout_s: float = 60.0
    submit_timeout_s: float = 30.0  # budget for shed/heal retries per sample
    retry: RetryPolicy = field(default_factory=RetryPolicy)

    def __post_init__(self):
        if not self.shards:
            raise ValueError("JobSpec needs at least one shard")
        if self.workers < 1:
            raise ValueError(f"workers must be >= 1, got {self.workers}")
        if len(set(self.shards)) != len(self.shards):
            raise ValueError("duplicate shard URLs in JobSpec")
        object.__setattr__(self, "shards", tuple(self.shards))


class BatchJobRunner:
    """Shard-parallel, lease-fenced, resumable job executor.

    ``submit(image, *, task=, deadline_ms=, meta=, tenant=) -> Future`` is
    the :meth:`ContinuousScheduler.submit` shape; typed sheds
    (:class:`QueueFullError` subclasses — quota/pressure/budget) and a
    healing pool (:class:`PoolUnhealthyError`) are retried with backoff
    inside the per-sample submit budget, because a batch job's contract is
    throughput, not latency.
    """

    def __init__(
        self,
        spec: JobSpec,
        submit: Callable,
        *,
        decode: Callable[[dict], np.ndarray] | None = None,
        registry=None,
        journal: RunJournal | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.spec = spec
        self._submit = submit
        self._decode = decode or default_decode
        self._clock = clock
        self.out = Path(spec.output_dir)
        self.parts_dir = self.out / "parts"
        self.parts_dir.mkdir(parents=True, exist_ok=True)
        self.manifest_path = self.out / "manifest.json"
        self.journal = journal or RunJournal(self.out / "journal")
        self._stop = threading.Event()
        self._errors: list[str] = []
        self._err_lock = threading.Lock()
        self._done: dict[str, dict] = {}   # shard -> {"part","samples","sha256"} | {"quarantined": True}
        self._done_lock = threading.Lock()
        self._steal_seen = 0

        reg = registry if registry is not None else get_registry()
        self._m_shards = reg.gauge(
            "batch_job_shards",
            "job work units by lease state (pending|leased|done)",
            labels=("state",),
        )
        self._m_samples = reg.counter(
            "batch_samples_processed_total",
            "samples computed and durably written by this job run",
        )
        self._m_resumed = reg.counter(
            "batch_samples_resumed_total",
            "samples skipped on (re)claim because a prior run already "
            "wrote them durably",
        )
        self._m_steals = reg.counter(
            "batch_lease_steals_total",
            "expired shard leases stolen from dead/stalled workers",
        )
        self._m_crashes = reg.counter(
            "batch_worker_crashes_total",
            "batch worker threads killed by the batch.worker fault site",
        )
        self._m_submit_retries = reg.counter(
            "batch_submit_retries_total",
            "sample submits retried after a typed shed or an unhealthy pool",
        )
        # eager children (PR 15 pattern): every state scrapeable at zero
        # from construction, not from the first transition
        for state in ("pending", "leased", "done"):
            self._m_shards.labels(state)

    # ------------------------------------------------------------ control

    def request_stop(self) -> None:
        """Graceful preemption (SIGTERM): workers finish their in-flight
        window, release their leases, and exit; durable cursors mean a
        later run resumes sample-exactly."""
        self._stop.set()

    # ---------------------------------------------------------------- run

    def run(self) -> dict:
        """Execute (or resume) the job to completion; returns the summary.
        Safe to re-invoke after any crash — including after completion,
        when it just revalidates the manifest."""
        existing = read_manifest(self.manifest_path)
        if existing is not None:
            return self._summary(complete=True, already=True)

        table = LeaseTable(
            self.spec.shards, lease_s=self.spec.lease_s,
            clock=self._clock, journal=self.journal,
        )
        resumed = self._reconcile(table)
        self.journal.event(
            "job_start",
            shards=len(self.spec.shards),
            task=self.spec.task,
            tenant=self.spec.tenant,
            workers=self.spec.workers,
            output_dir=str(self.out),
            resumed_shards=resumed,
        )
        self._gauge(table)

        threads = [
            threading.Thread(
                target=self._worker, args=(f"w{i}", table),
                daemon=True, name=f"batch-worker-w{i}",
            )
            for i in range(self.spec.workers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        self._gauge(table)

        if self._stop.is_set() and not table.done():
            self.journal.event(
                "shutdown", reason="preempted", **table.counts()
            )
            return self._summary(complete=False, table=table)
        if not table.done():
            # every worker exited with shards outstanding (all killed, or
            # a shard kept failing) — the job is resumable, not complete
            self.journal.event(
                "shutdown", reason="exception",
                errors=self._errors[-5:], **table.counts(),
            )
            return self._summary(complete=False, table=table)

        entries = []
        total = 0
        quarantined = []
        for shard in self.spec.shards:
            info = self._done.get(shard, {})
            if info.get("quarantined"):
                quarantined.append(shard)
                continue
            entries.append(
                {
                    "shard": shard,
                    "part": info["part"],
                    "samples": info["samples"],
                    "sha256": info["sha256"],
                }
            )
            total += info["samples"]
        manifest_sha = write_manifest(self.manifest_path, entries, total)
        self.journal.event(
            "job_complete",
            shards=len(entries),
            quarantined=len(quarantined),
            total_samples=total,
            manifest_sha256=manifest_sha,
            lease_steals=table.steals,
        )
        return self._summary(
            complete=True, table=table, quarantined=quarantined,
            manifest_sha=manifest_sha,
        )

    # ---------------------------------------------------------- internals

    def _reconcile(self, table: LeaseTable) -> int:
        """Rebuild shard state from the durable parts on disk — the files
        are the authority, the journal is observability. Returns how many
        shards were already complete."""
        done = 0
        for shard in self.spec.shards:
            stem = part_stem(shard)
            part = self.parts_dir / f"{stem}.part"
            if part.exists():
                n, good = scan_part(part)
                if good == part.stat().st_size and n > 0:
                    table.mark_done(shard)
                    with self._done_lock:
                        self._done[shard] = {
                            "part": part.name,
                            "samples": n,
                            "sha256": file_sha256(part),
                        }
                    self._m_resumed.inc(n)
                    done += 1
                    continue
                # damaged final part: demote it to a partial and recompute
                # the tail (its good prefix is still exactly-once durable)
                part.rename(self.parts_dir / f"{stem}.partial")
        return done

    def _gauge(self, table: LeaseTable) -> None:
        for state, n in table.counts().items():
            self._m_shards.labels(state).set(n)

    def _record_error(self, where: str, exc: BaseException) -> None:
        with self._err_lock:
            self._errors.append(f"{where}: {type(exc).__name__}: {exc}")

    def _worker(self, name: str, table: LeaseTable) -> None:
        backoff = 0.01
        while not self._stop.is_set():
            claim = table.claim(name)
            if claim is None:
                if table.done():
                    return
                # nothing claimable now — a live worker holds every
                # remaining lease; wait for completion or expiry/steal
                time.sleep(min(backoff, 0.1))
                backoff = min(backoff * 2, 0.1)
                continue
            backoff = 0.01
            shard, lease = claim
            self._sync_steal_metric(table)
            self._gauge(table)
            try:
                self._process_shard(name, table, shard, lease)
            except _WorkerKilled:
                self._m_crashes.inc()
                return  # dead: the lease expires, someone else steals it
            except _Fenced:
                continue  # the thief owns the shard now; claim another
            except ShutdownError as e:
                self._record_error(shard, e)
                table.release(shard, name, lease)
                return
            except BaseException as e:  # noqa: BLE001 — shard error: release and move on
                self._record_error(shard, e)
                table.release(shard, name, lease)
                time.sleep(0.05)
            finally:
                self._gauge(table)

    def _submit_sample(self, image: np.ndarray):
        """Submit with shed/heal retries — batch traffic waits rather than
        fails when the pool is contended or mid-restart."""
        deadline = self._clock() + self.spec.submit_timeout_s
        delay = 0.02
        while True:
            try:
                return self._submit(
                    image,
                    task=self.spec.task,
                    deadline_ms=self.spec.deadline_ms,
                    meta=None,
                    tenant=self.spec.tenant,
                )
            except (QueueFullError, PoolUnhealthyError):
                if self._stop.is_set() or self._clock() >= deadline:
                    raise
                self._m_submit_retries.inc()
                time.sleep(delay)
                delay = min(delay * 2, 0.5)

    def _process_shard(
        self, name: str, table: LeaseTable, shard: str, lease: int
    ) -> None:
        stem = part_stem(shard)
        partial = self.parts_dir / f"{stem}.partial"
        part = self.parts_dir / f"{stem}.part"
        fence = table.shard_fence(shard)
        with fence:
            if not table.holds(shard, name, lease):
                raise _Fenced(shard)
            cursor, good = scan_part(partial)
            if partial.exists() and good != partial.stat().st_size:
                with open(partial, "r+b") as f:
                    f.truncate(good)
        if cursor:
            self._m_resumed.inc(cursor)

        written = cursor
        window: list = []
        was_quarantined = shard in QUARANTINE.snapshot()

        def flush() -> None:
            nonlocal written
            if not window:
                return
            rows = [
                (key, fut.result(timeout=self.spec.result_timeout_s))
                for key, fut in window
            ]
            with fence:
                if not table.holds(shard, name, lease):
                    raise _Fenced(shard)
                with open(partial, "ab") as f:
                    for key, out in rows:
                        append_record(f, encode_record(key, out))
                    f.flush()
                    os.fsync(f.fileno())
                written += len(rows)
                table.renew(shard, name, lease)
            window.clear()
            self._m_samples.inc(len(rows))
            if written % self.spec.cursor_every < len(rows):
                self.journal.event(
                    "job_cursor", shard=shard, worker=name, samples=written
                )

        for i, sample in enumerate(
            iter_tar_samples(shard, retry=self.spec.retry)
        ):
            if i < cursor:
                continue  # durable from a previous incarnation
            try:
                fault_point("batch.worker", key=name)
            except BaseException as e:  # noqa: BLE001 — injected worker death
                raise _WorkerKilled(str(e)) from e
            key = str(sample.get("__key__", f"sample-{i}"))
            window.append((key, self._submit_sample(self._decode(sample))))
            if len(window) >= self.spec.submit_window:
                flush()
            if self._stop.is_set():
                flush()
                table.release(shard, name, lease)
                return
        flush()

        if shard in QUARANTINE.snapshot() and not was_quarantined:
            # the stream gave up on this shard mid-pass: keep the durable
            # prefix as a .partial (a healed store resumes it next run)
            # but count the shard handled so the job can terminate
            self.journal.event(
                "job_shard_done", shard=shard, worker=name,
                samples=written, status="quarantined",
            )
            with self._done_lock:
                self._done[shard] = {"quarantined": True, "samples": written}
            table.complete(shard, name, lease)
            return

        with fence:
            if not table.holds(shard, name, lease):
                raise _Fenced(shard)
            sha = finalize_part(partial, part)
            if not table.complete(shard, name, lease):
                raise _Fenced(shard)
        with self._done_lock:
            self._done[shard] = {
                "part": part.name, "samples": written, "sha256": sha,
            }
        self.journal.event(
            "job_shard_done", shard=shard, worker=name,
            samples=written, part=part.name, sha256=sha, status="ok",
        )
        self._sync_steal_metric(table)

    def _sync_steal_metric(self, table: LeaseTable) -> None:
        delta = table.steals - self._steal_seen
        if delta > 0:
            self._m_steals.inc(delta)
            self._steal_seen = table.steals

    def _summary(
        self, *, complete: bool, table: LeaseTable | None = None,
        already: bool = False, quarantined=None, manifest_sha: str | None = None,
    ) -> dict:
        manifest = read_manifest(self.manifest_path)
        total = manifest.get("total_samples", 0) if manifest else 0
        return {
            "complete": complete,
            "already_complete": already,
            "shards": len(self.spec.shards),
            "counts": table.counts() if table is not None else None,
            "total_samples": total,
            "quarantined": list(quarantined or []),
            "lease_steals": table.steals if table is not None else 0,
            "manifest": str(self.manifest_path) if manifest else None,
            "manifest_sha256": manifest_sha,
            "errors": list(self._errors),
        }
