"""Journaled shard leases with expiry, steal, and write fencing.

A batch job decomposes into per-shard work units; a worker *claims* a
shard by taking a lease on it. Leases make worker death a non-event
instead of a stuck job:

- a live worker **renews** its lease every progress window, so the expiry
  horizon (``lease_s``) bounds how long a dead worker's shard stays
  orphaned;
- a claim that finds a leased-but-expired shard **steals** it — the
  ``job_lease`` journal event carries ``stolen_from`` so the offline
  doctor can name the worker whose work was rescued;
- every lease carries a monotonically increasing **lease id**, the fencing
  token: the shard writer re-checks :meth:`holds` under the per-shard
  write lock before every append window, so a slow-but-alive worker whose
  lease was stolen can never interleave frames with the thief (its next
  write attempt is fenced off instead).

All transitions are journaled (``job_lease``) for the lease timeline in
``tools/batch_doctor.py``; the in-memory table is the *authority* for the
current process — a restarted job rebuilds shard state from the durable
part files, not from the journal (observability, not recovery).
"""

from __future__ import annotations

import threading
import time
from typing import Callable


class LeaseTable:
    """Thread-safe shard → lease state table for in-process workers."""

    def __init__(
        self,
        shards,
        *,
        lease_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
        journal=None,
    ):
        self.lease_s = float(lease_s)
        self._clock = clock
        self._journal = journal
        self._lock = threading.Lock()
        # per-shard write fence: append windows and steal-time truncation
        # serialize here, so a fenced writer can never interleave frames
        self._fences = {s: threading.Lock() for s in shards}
        self._st: dict[str, dict] = {
            s: {"state": "pending", "worker": None, "lease": 0, "expires": 0.0}
            for s in shards
        }
        self._next_lease = 0
        self._steals = 0

    def shard_fence(self, shard: str) -> threading.Lock:
        return self._fences[shard]

    def claim(self, worker: str) -> tuple[str, int] | None:
        """Take the first pending — or leased-but-expired — shard; returns
        ``(shard, lease_id)`` or ``None`` when nothing is claimable now.
        Stealing an expired lease is journaled with ``stolen_from``."""
        now = self._clock()
        with self._lock:
            take = stolen = None
            for s, st in self._st.items():
                if st["state"] == "pending":
                    take = s
                    break
                if st["state"] == "leased" and st["expires"] <= now:
                    take, stolen = s, st["worker"]
                    break
            if take is None:
                return None
            self._next_lease += 1
            lease = self._next_lease
            self._st[take].update(
                state="leased", worker=worker, lease=lease,
                expires=now + self.lease_s,
            )
            if stolen is not None:
                self._steals += 1
        if self._journal is not None:
            fields = {"shard": take, "worker": worker, "lease": lease,
                      "lease_s": self.lease_s}
            if stolen is not None:
                fields["stolen_from"] = stolen
            self._journal.event("job_lease", **fields)
        return take, lease

    def holds(self, shard: str, worker: str, lease: int) -> bool:
        """The fencing check: does ``worker`` still own ``shard`` under
        this lease id? False the instant the lease is stolen/released."""
        with self._lock:
            st = self._st[shard]
            return (
                st["state"] == "leased"
                and st["worker"] == worker
                and st["lease"] == lease
            )

    def renew(self, shard: str, worker: str, lease: int) -> bool:
        with self._lock:
            st = self._st[shard]
            if (
                st["state"] == "leased"
                and st["worker"] == worker
                and st["lease"] == lease
            ):
                st["expires"] = self._clock() + self.lease_s
                return True
            return False

    def release(self, shard: str, worker: str, lease: int) -> bool:
        """Voluntarily hand a shard back (error path, graceful drain) —
        it becomes claimable immediately instead of at lease expiry."""
        with self._lock:
            st = self._st[shard]
            if (
                st["state"] == "leased"
                and st["worker"] == worker
                and st["lease"] == lease
            ):
                st.update(state="pending", worker=None, lease=0, expires=0.0)
                return True
            return False

    def complete(self, shard: str, worker: str, lease: int) -> bool:
        """Fenced completion: only the current lease holder can mark a
        shard done (a fenced zombie's complete is a no-op)."""
        with self._lock:
            st = self._st[shard]
            if (
                st["state"] == "leased"
                and st["worker"] == worker
                and st["lease"] == lease
            ):
                st.update(state="done", worker=None, expires=0.0)
                return True
            return False

    def mark_done(self, shard: str) -> None:
        """Pre-resolved at startup (a durable final part already exists)."""
        with self._lock:
            self._st[shard].update(
                state="done", worker=None, lease=0, expires=0.0
            )

    def done(self) -> bool:
        with self._lock:
            return all(st["state"] == "done" for st in self._st.values())

    def counts(self) -> dict[str, int]:
        out = {"pending": 0, "leased": 0, "done": 0}
        with self._lock:
            for st in self._st.values():
                out[st["state"]] += 1
        return out

    @property
    def steals(self) -> int:
        with self._lock:
            return self._steals
