"""Durable, torn-tail-tolerant per-shard output files ("parts").

The batch runner's exactly-once guarantee at the output rests on three
properties of this file format, mirroring the run journal's crash contract
(`obs/journal.py`):

- **append-only framed records** — each record is ``MAGIC + u32 length +
  sha256(payload)[:8] + payload``; a SIGKILL mid-append leaves a torn final
  frame that :func:`scan_part` detects (bad magic, short payload, or digest
  mismatch) and truncates, never a corrupted earlier record;
- **the partial file IS the resume cursor** — the number of good frames in
  ``<stem>.partial`` is exactly how many samples of the shard are durable;
  a restarted job re-streams the shard and skips that many samples (tar
  order is deterministic, so the skipped prefix is the written prefix);
- **deterministic bytes** — payloads are canonical JSON (sorted keys, fixed
  separators, numpy coerced to plain lists/scalars) of ``{key, out}``, so a
  killed-and-restarted job recomputes byte-identical frames and the final
  part file (and therefore the manifest's sha256) matches a fault-free run.

Completion is an atomic rename ``.partial`` → ``.part`` followed by an
``fsync_dir`` of the parent (rename alone is not durable across power
loss); the manifest lists every part with its sample count and sha256 and
carries **no timestamps or attempt counts** — byte-identical manifests are
the proof the chaos suite asserts.
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
from collections.abc import Iterator
from pathlib import Path

from jumbo_mae_tpu_tpu.obs.journal import fsync_dir

MAGIC = b"JMB1"
_HEAD = struct.Struct("<4sI8s")  # magic, payload length, sha256(payload)[:8]


def _json_default(obj):
    import numpy as np

    if isinstance(obj, (np.floating, np.integer, np.bool_)):
        return obj.item()
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, (bytes, bytearray)):
        return hashlib.sha256(bytes(obj)).hexdigest()
    raise TypeError(f"not JSON-encodable in a part record: {type(obj)!r}")


def encode_record(key: str, out) -> bytes:
    """Canonical payload bytes for one sample's result — deterministic
    across runs (sorted keys, fixed separators, no floats reformatting
    beyond json's repr, numpy coerced to plain types)."""
    return json.dumps(
        {"key": key, "out": out},
        sort_keys=True,
        separators=(",", ":"),
        default=_json_default,
        allow_nan=False,
    ).encode("utf-8")


def append_record(f, payload: bytes) -> None:
    """Append one framed record to an open binary file handle."""
    digest = hashlib.sha256(payload).digest()[:8]
    f.write(_HEAD.pack(MAGIC, len(payload), digest))
    f.write(payload)


def scan_part(path: str | Path) -> tuple[int, int]:
    """``(records, good_bytes)`` of a part/partial file — the resume
    cursor. Stops at the first torn/damaged frame; ``good_bytes`` is the
    offset a resuming writer must truncate to before appending."""
    p = Path(path)
    if not p.exists():
        return 0, 0
    data = p.read_bytes()
    off = 0
    n = 0
    while off + _HEAD.size <= len(data):
        magic, length, digest = _HEAD.unpack_from(data, off)
        if magic != MAGIC:
            break
        end = off + _HEAD.size + length
        if end > len(data):
            break
        payload = data[off + _HEAD.size : end]
        if hashlib.sha256(payload).digest()[:8] != digest:
            break
        n += 1
        off = end
    return n, off


def iter_records(path: str | Path) -> Iterator[dict]:
    """Yield the decoded ``{key, out}`` record dicts of a part file."""
    p = Path(path)
    data = p.read_bytes()
    off = 0
    while off + _HEAD.size <= len(data):
        magic, length, digest = _HEAD.unpack_from(data, off)
        if magic != MAGIC:
            break
        end = off + _HEAD.size + length
        if end > len(data):
            break
        payload = data[off + _HEAD.size : end]
        if hashlib.sha256(payload).digest()[:8] != digest:
            break
        yield json.loads(payload)
        off = end


def finalize_part(partial: Path, part: Path) -> str:
    """Durably promote ``.partial`` → ``.part``: fsync the data, atomic
    rename, fsync the directory; returns the part's content sha256."""
    fd = os.open(str(partial), os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(partial, part)
    fsync_dir(part.parent)
    return file_sha256(part)


def file_sha256(path: str | Path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def write_manifest(path: str | Path, entries: list[dict], total: int) -> str:
    """Atomically write the deterministic job manifest (no timestamps, no
    attempt counts — only what the data IS); returns its content sha256."""
    p = Path(path)
    payload = json.dumps(
        {"shards": entries, "total_samples": total},
        sort_keys=True,
        indent=2,
    ) + "\n"
    tmp = p.with_suffix(p.suffix + f".tmp.{os.getpid()}")
    tmp.write_text(payload, encoding="utf-8")
    fd = os.open(str(tmp), os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, p)
    fsync_dir(p.parent)
    return hashlib.sha256(payload.encode("utf-8")).hexdigest()


def read_manifest(path: str | Path) -> dict | None:
    try:
        return json.loads(Path(path).read_text(encoding="utf-8"))
    except (OSError, json.JSONDecodeError):
        return None
