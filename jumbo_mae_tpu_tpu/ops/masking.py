"""Random patch masking for MAE pretraining.

Behavioral parity target: ``random_masking`` / ``index_sequence`` in
``/root/reference/src/utils_mae.py:84-102``. The reference draws ONE uniform
noise vector of shape ``(length,)`` — a single permutation shared by the whole
per-device batch (upstream facebookresearch/mae permutes per sample). Shared
mode is the parity default here; ``per_sample`` mode is also provided because
it is strictly stronger as an augmentation and costs one batched argsort.

TPU notes: the shuffle/unshuffle gathers have two selectable lowerings:

- ``impl="take"`` (default) — ``jnp.take``(_along_axis); XLA lowers to a
  dynamic gather, cheap at these sizes.
- ``impl="onehot"`` — the gather becomes a 0/1 one-hot matmul on the MXU
  (the north-star's "HBM-friendly gather/scatter", done the TPU way: the
  systolic array IS the hardware gather engine, and the unshuffle variant
  drops the concat so the full-sequence intermediate is written to HBM
  once instead of twice). Numerically EXACT in any dtype — multiplying by
  1.0 and summing zeros is lossless — so the two impls are
  bit-interchangeable; pick by profile (``BENCH_GATHER_IMPL``).
"""

from __future__ import annotations

from typing import Literal

import jax
import jax.numpy as jnp

MaskMode = Literal["shared", "per_sample"]
GatherImpl = Literal["take", "onehot"]


# HIGHEST keeps f32 operands in full-precision MXU passes: the default
# precision would run bf16 passes and round f32 token values, breaking the
# bit-identical-to-take guarantee the A/B rests on. (For bf16 inputs it
# changes nothing — a 0/1 matmul has one nonzero product per output.)
_EXACT = jax.lax.Precision.HIGHEST


def _check_impl(impl: str) -> None:
    if impl not in ("take", "onehot"):
        raise ValueError(
            f"unknown gather impl {impl!r}; choose 'take' or 'onehot'"
        )


def index_sequence(
    x: jax.Array, ids: jax.Array, *, impl: GatherImpl = "take"
) -> jax.Array:
    """Gather along the sequence (second) axis.

    ``ids`` may be 1-D (shared permutation, applied to every batch row) or 2-D
    ``(batch, n)`` (per-sample permutation).
    """
    _check_impl(impl)
    if impl == "onehot":
        sel = jax.nn.one_hot(ids, x.shape[1], dtype=x.dtype)
        eq = "nk,bk...->bn..." if ids.ndim == 1 else "bnk,bk...->bn..."
        return jnp.einsum(eq, sel, x, precision=_EXACT)
    if ids.ndim == 1:
        return jnp.take(x, ids, axis=1)
    idx = ids.reshape(ids.shape + (1,) * (x.ndim - 2))
    return jnp.take_along_axis(x, idx, axis=1)


def random_masking(
    x: jax.Array,
    rng: jax.Array | None,
    keep_len: int,
    *,
    mode: MaskMode = "shared",
    noise: jax.Array | None = None,
    gather_impl: GatherImpl = "take",
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Randomly drop all but ``keep_len`` tokens of ``x`` (batch, len, dim).

    Returns ``(kept, mask, ids_restore)`` where ``kept`` is
    ``(batch, keep_len, dim)``, ``mask`` is ``(batch, len)`` float32 with 1 at
    MASKED positions, and ``ids_restore`` inverts the shuffle (1-D in shared
    mode, 2-D in per-sample mode).

    ``noise`` optionally overrides the drawn uniform noise (shape ``(len,)``
    shared / ``(batch, len)`` per-sample) so a caller can pin the permutation
    — used for fixed eval masks and cross-implementation parity tests; ``rng``
    may then be None.
    """
    batch, length, _ = x.shape
    expected = (length,) if mode == "shared" else (batch, length)
    if noise is not None and noise.shape != expected:
        raise ValueError(
            f"injected noise shape {noise.shape} != {expected} for "
            f"mode={mode!r}"
        )
    if mode == "shared":
        if noise is None:
            noise = jax.random.uniform(rng, (length,), dtype=jnp.float32)
        ids_shuffle = jnp.argsort(noise)
        ids_restore = jnp.argsort(ids_shuffle)
        kept = index_sequence(x, ids_shuffle[:keep_len], impl=gather_impl)
        shuffled_mask = (jnp.arange(length) >= keep_len).astype(jnp.float32)
        mask = jnp.broadcast_to(shuffled_mask[ids_restore], (batch, length))
        return kept, mask, ids_restore

    if mode == "per_sample":
        if noise is None:
            noise = jax.random.uniform(rng, (batch, length), dtype=jnp.float32)
        ids_shuffle = jnp.argsort(noise, axis=1)
        ids_restore = jnp.argsort(ids_shuffle, axis=1)
        kept = index_sequence(x, ids_shuffle[:, :keep_len], impl=gather_impl)
        shuffled_mask = jnp.broadcast_to(
            (jnp.arange(length) >= keep_len).astype(jnp.float32), (batch, length)
        )
        mask = jnp.take_along_axis(shuffled_mask, ids_restore, axis=1)
        return kept, mask, ids_restore

    raise ValueError(f"unknown masking mode: {mode!r}")


# --------------------------------------------------------------------------
# Mask algebra (parity: ``/root/reference/src/utils_mae.py:24-49``). Masks are
# float arrays with 1.0 at MASKED positions. The reference fork never calls
# these itself (they come from its m3ae ancestry), but they complete the
# utils_mae surface for users combining masks — e.g. masking the union of an
# MAE mask and a padding mask.
# --------------------------------------------------------------------------


def no_mask(x: jax.Array) -> jax.Array:
    """All-zeros (nothing masked) mask for a (batch, len, ...) sequence."""
    return jnp.zeros(x.shape[:2], dtype=jnp.float32)


def all_mask(x: jax.Array) -> jax.Array:
    """All-ones (everything masked) mask for a (batch, len, ...) sequence."""
    return jnp.ones(x.shape[:2], dtype=jnp.float32)


def mask_not(mask: jax.Array) -> jax.Array:
    """``1.0 - mask`` — exact reference semantics: unlike union/intersection
    (which binarize with the reference's ``>0`` contract), the reference's
    complement is pure arithmetic, so a soft 0.3 inverts to 0.7."""
    return 1.0 - mask.astype(jnp.float32)


def mask_union(*masks: jax.Array) -> jax.Array:
    """Positions masked (>0) in ANY input mask; output is binary 0/1 like the
    reference's helpers, so soft/weighted inputs collapse rather than
    propagate."""
    out = (masks[0] > 0)
    for m in masks[1:]:
        out = out | (m > 0)
    return out.astype(jnp.float32)


def mask_intersection(*masks: jax.Array) -> jax.Array:
    """Positions masked (>0) in EVERY input mask; binary 0/1 output."""
    out = (masks[0] > 0)
    for m in masks[1:]:
        out = out & (m > 0)
    return out.astype(jnp.float32)


def mask_select(
    mask: jax.Array, when_unmasked: jax.Array, when_masked: jax.Array
) -> jax.Array:
    """Elementwise choose ``when_unmasked`` where mask==0 else
    ``when_masked`` — the reference's argument order (second argument is the
    UNMASKED value). The mask broadcasts over trailing feature axes."""
    m = mask.reshape(mask.shape + (1,) * (when_unmasked.ndim - mask.ndim))
    return jnp.where(m > 0, when_masked, when_unmasked)


def unshuffle_with_mask_tokens(
    visible: jax.Array,
    mask_token: jax.Array,
    ids_restore: jax.Array,
    *,
    impl: GatherImpl = "take",
) -> jax.Array:
    """Restore the full sequence from visible tokens + a learned mask token.

    ``visible`` is ``(batch, keep_len, dim)``; ``mask_token`` broadcastable to
    ``(batch, length - keep_len, dim)``; ``ids_restore`` the inverse
    permutation from :func:`random_masking`. The number of mask tokens is
    derived as ``length - keep_len`` (the reference instead recomputes it as
    ``int(length * mask_ratio)``, which disagrees with ``keep_len`` for some
    ratios — ``/root/reference/src/pretraining.py:100-103``; fixed here).

    ``impl="onehot"`` skips the concat entirely: output rows whose restore
    index lands in the visible range come from a (length, keep_len) 0/1
    matmul against ``visible`` on the MXU; the rest add the broadcast mask
    token — the full-length intermediate is written once, not twice.
    """
    batch, keep_len, dim = visible.shape
    length = ids_restore.shape[-1]
    _check_impl(impl)
    if impl == "onehot":
        # rows selecting a masked slot have an all-zero one-hot row (index
        # >= keep_len matches nothing), so the matmul contributes 0 there
        # and the mask-token term fills it in
        sel = jax.nn.one_hot(ids_restore, keep_len, dtype=visible.dtype)
        eq = "nk,bkd->bnd" if ids_restore.ndim == 1 else "bnk,bkd->bnd"
        from_visible = jnp.einsum(eq, sel, visible, precision=_EXACT)
        masked = (ids_restore >= keep_len).astype(visible.dtype)[..., :, None]
        token = jnp.asarray(mask_token, visible.dtype).reshape(1, 1, dim)
        return from_visible + masked * token
    mask_tokens = jnp.broadcast_to(mask_token, (batch, length - keep_len, dim))
    full = jnp.concatenate([visible, mask_tokens.astype(visible.dtype)], axis=1)
    return index_sequence(full, ids_restore)
