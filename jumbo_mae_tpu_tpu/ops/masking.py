"""Random patch masking for MAE pretraining.

Behavioral parity target: ``random_masking`` / ``index_sequence`` in
``/root/reference/src/utils_mae.py:84-102``. The reference draws ONE uniform
noise vector of shape ``(length,)`` — a single permutation shared by the whole
per-device batch (upstream facebookresearch/mae permutes per sample). Shared
mode is the parity default here; ``per_sample`` mode is also provided because
it is strictly stronger as an augmentation and costs one batched argsort.

TPU notes: the shared-mode gather is a ``take`` along the sequence axis with a
traced 1-D index — XLA lowers it to a dynamic-gather that is cheap at these
sizes. ``ids_restore`` is carried to the decoder to unshuffle mask tokens;
``unshuffle_with_mask_tokens`` fuses the concat+gather so the scatter never
materializes an intermediate in HBM larger than the output.
"""

from __future__ import annotations

from typing import Literal

import jax
import jax.numpy as jnp

MaskMode = Literal["shared", "per_sample"]


def index_sequence(x: jax.Array, ids: jax.Array) -> jax.Array:
    """Gather along the sequence (second) axis.

    ``ids`` may be 1-D (shared permutation, applied to every batch row) or 2-D
    ``(batch, n)`` (per-sample permutation).
    """
    if ids.ndim == 1:
        return jnp.take(x, ids, axis=1)
    idx = ids.reshape(ids.shape + (1,) * (x.ndim - 2))
    return jnp.take_along_axis(x, idx, axis=1)


def random_masking(
    x: jax.Array,
    rng: jax.Array | None,
    keep_len: int,
    *,
    mode: MaskMode = "shared",
    noise: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Randomly drop all but ``keep_len`` tokens of ``x`` (batch, len, dim).

    Returns ``(kept, mask, ids_restore)`` where ``kept`` is
    ``(batch, keep_len, dim)``, ``mask`` is ``(batch, len)`` float32 with 1 at
    MASKED positions, and ``ids_restore`` inverts the shuffle (1-D in shared
    mode, 2-D in per-sample mode).

    ``noise`` optionally overrides the drawn uniform noise (shape ``(len,)``
    shared / ``(batch, len)`` per-sample) so a caller can pin the permutation
    — used for fixed eval masks and cross-implementation parity tests; ``rng``
    may then be None.
    """
    batch, length, _ = x.shape
    expected = (length,) if mode == "shared" else (batch, length)
    if noise is not None and noise.shape != expected:
        raise ValueError(
            f"injected noise shape {noise.shape} != {expected} for "
            f"mode={mode!r}"
        )
    if mode == "shared":
        if noise is None:
            noise = jax.random.uniform(rng, (length,), dtype=jnp.float32)
        ids_shuffle = jnp.argsort(noise)
        ids_restore = jnp.argsort(ids_shuffle)
        kept = index_sequence(x, ids_shuffle[:keep_len])
        shuffled_mask = (jnp.arange(length) >= keep_len).astype(jnp.float32)
        mask = jnp.broadcast_to(shuffled_mask[ids_restore], (batch, length))
        return kept, mask, ids_restore

    if mode == "per_sample":
        if noise is None:
            noise = jax.random.uniform(rng, (batch, length), dtype=jnp.float32)
        ids_shuffle = jnp.argsort(noise, axis=1)
        ids_restore = jnp.argsort(ids_shuffle, axis=1)
        kept = index_sequence(x, ids_shuffle[:, :keep_len])
        shuffled_mask = jnp.broadcast_to(
            (jnp.arange(length) >= keep_len).astype(jnp.float32), (batch, length)
        )
        mask = jnp.take_along_axis(shuffled_mask, ids_restore, axis=1)
        return kept, mask, ids_restore

    raise ValueError(f"unknown masking mode: {mode!r}")


# --------------------------------------------------------------------------
# Mask algebra (parity: ``/root/reference/src/utils_mae.py:24-49``). Masks are
# float arrays with 1.0 at MASKED positions. The reference fork never calls
# these itself (they come from its m3ae ancestry), but they complete the
# utils_mae surface for users combining masks — e.g. masking the union of an
# MAE mask and a padding mask.
# --------------------------------------------------------------------------


def no_mask(x: jax.Array) -> jax.Array:
    """All-zeros (nothing masked) mask for a (batch, len, ...) sequence."""
    return jnp.zeros(x.shape[:2], dtype=jnp.float32)


def all_mask(x: jax.Array) -> jax.Array:
    """All-ones (everything masked) mask for a (batch, len, ...) sequence."""
    return jnp.ones(x.shape[:2], dtype=jnp.float32)


def mask_not(mask: jax.Array) -> jax.Array:
    """``1.0 - mask`` — exact reference semantics: unlike union/intersection
    (which binarize with the reference's ``>0`` contract), the reference's
    complement is pure arithmetic, so a soft 0.3 inverts to 0.7."""
    return 1.0 - mask.astype(jnp.float32)


def mask_union(*masks: jax.Array) -> jax.Array:
    """Positions masked (>0) in ANY input mask; output is binary 0/1 like the
    reference's helpers, so soft/weighted inputs collapse rather than
    propagate."""
    out = (masks[0] > 0)
    for m in masks[1:]:
        out = out | (m > 0)
    return out.astype(jnp.float32)


def mask_intersection(*masks: jax.Array) -> jax.Array:
    """Positions masked (>0) in EVERY input mask; binary 0/1 output."""
    out = (masks[0] > 0)
    for m in masks[1:]:
        out = out & (m > 0)
    return out.astype(jnp.float32)


def mask_select(
    mask: jax.Array, when_unmasked: jax.Array, when_masked: jax.Array
) -> jax.Array:
    """Elementwise choose ``when_unmasked`` where mask==0 else
    ``when_masked`` — the reference's argument order (second argument is the
    UNMASKED value). The mask broadcasts over trailing feature axes."""
    m = mask.reshape(mask.shape + (1,) * (when_unmasked.ndim - mask.ndim))
    return jnp.where(m > 0, when_masked, when_unmasked)


def unshuffle_with_mask_tokens(
    visible: jax.Array,
    mask_token: jax.Array,
    ids_restore: jax.Array,
) -> jax.Array:
    """Restore the full sequence from visible tokens + a learned mask token.

    ``visible`` is ``(batch, keep_len, dim)``; ``mask_token`` broadcastable to
    ``(batch, length - keep_len, dim)``; ``ids_restore`` the inverse
    permutation from :func:`random_masking`. The number of mask tokens is
    derived as ``length - keep_len`` (the reference instead recomputes it as
    ``int(length * mask_ratio)``, which disagrees with ``keep_len`` for some
    ratios — ``/root/reference/src/pretraining.py:100-103``; fixed here).
    """
    batch, keep_len, dim = visible.shape
    length = ids_restore.shape[-1]
    mask_tokens = jnp.broadcast_to(mask_token, (batch, length - keep_len, dim))
    full = jnp.concatenate([visible, mask_tokens.astype(visible.dtype)], axis=1)
    return index_sequence(full, ids_restore)
