"""On-device image normalization.

The input pipeline ships uint8 tensors to the device (4× fewer host→device
bytes than float32) and normalization happens inside the jitted step — same
rationale as ``/root/reference/src/pretraining.py:88-91``. This framework's
native layout is NHWC (TPU-friendly); NCHW input is accepted for parity with
reference-style loaders and transposed on device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


def normalize_images(
    images: jax.Array,
    dtype=jnp.float32,
    mean: np.ndarray = IMAGENET_MEAN,
    std: np.ndarray = IMAGENET_STD,
) -> jax.Array:
    """uint8 (B,H,W,C) or (B,C,H,W) → normalized ``dtype`` NHWC."""
    if images.ndim != 4:
        raise ValueError(f"expected 4-D image batch, got {images.shape}")
    if images.shape[1] <= 4 < images.shape[-1]:  # NCHW heuristic: C in {1,3,4}
        images = jnp.moveaxis(images, 1, 3)
    x = images.astype(jnp.float32) / 255.0
    x = (x - mean) / std
    return x.astype(dtype)
