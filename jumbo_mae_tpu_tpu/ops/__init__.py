from jumbo_mae_tpu_tpu.ops.masking import (
    all_mask,
    index_sequence,
    mask_intersection,
    mask_not,
    mask_select,
    mask_union,
    no_mask,
    random_masking,
    unshuffle_with_mask_tokens,
)
from jumbo_mae_tpu_tpu.ops.patches import (
    extract_patches,
    merge_patches,
    patch_mse_loss,
    patch_mse_loss_per_sample,
)
from jumbo_mae_tpu_tpu.ops.posemb import sincos2d_positional_embedding

__all__ = [
    "all_mask",
    "index_sequence",
    "mask_intersection",
    "mask_not",
    "mask_select",
    "mask_union",
    "no_mask",
    "random_masking",
    "unshuffle_with_mask_tokens",
    "extract_patches",
    "merge_patches",
    "patch_mse_loss",
    "patch_mse_loss_per_sample",
    "sincos2d_positional_embedding",
]
