"""Pallas TPU flash-attention forward kernel.

Grid: (batch·heads, seq_q/block_q). Each program holds one query block in
VMEM and streams the full key/value sequence for its batch-head through a
``fori_loop`` of ``block_k`` chunks with the online-softmax recurrence —
the (seq, seq) score matrix never exists in HBM, scores are accumulated on
the MXU in float32.

The backward pass is delegated to the differentiable XLA blockwise
implementation (``ops/blockwise_attention.py``) via ``jax.custom_vjp``:
residuals are just (q, k, v), recomputed chunkwise — O(seq) memory both ways.

Heads are folded into the batch/grid dimension, so per-program tiles are 2-D
(block, head_dim) — aligned with the (8/16, 128) sublane×lane tiling as long
as head_dim is a multiple of 128 (true for every preset: 64-dim heads are
padded by Mosaic automatically, at some efficiency cost).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, valid_k: int):
    q = q_ref[0].astype(jnp.float32)  # (block_q, d)
    block_q, d = q.shape
    seq_k = k_ref.shape[1]

    def body(i, carry):
        m, l, acc = carry
        kb = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (block_q, block_k)
        if valid_k != seq_k:
            # keys beyond valid_k are zero-padding (ragged seq support):
            # force their scores to -inf so they get zero softmax weight.
            col = i * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(col < valid_k, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + p.sum(axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l, acc

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, seq_k // block_k, body, (m0, l0, acc0))
    o_ref[0] = (acc / l).astype(o_ref.dtype)


def _pad_seq(x, to: int):
    pad = to - x.shape[1]
    if not pad:
        return x
    return jnp.pad(x, ((0, 0), (0, pad), (0, 0), (0, 0)))


def _round_up(x: int, to: int) -> int:
    return -(-x // to) * to


def _largest_dividing_block(requested: int, seq_pad: int) -> int:
    """Largest block ≤ requested that divides ``seq_pad``. Production blocks
    stay on 128 multiples (seq_pad is one, so 128 always qualifies);
    sub-128 requests (interpreter tests) fall back to any exact divisor."""
    block = min(requested, seq_pad)
    if block >= 128:
        block = block // 128 * 128
        while seq_pad % block:
            block -= 128
    else:
        while seq_pad % block:
            block -= 1
    return block


def _flash_fwd(q, k, v, block_q, block_k, interpret):
    b, sq, h, d = q.shape
    sk = k.shape[1]
    # Pad ragged lengths only up to the 128-lane tile, then pick the largest
    # block ≤ requested that divides the padded length — never pad to a full
    # block multiple (at seq 787 that would waste ~30% of the rows). Pad
    # *keys* are masked inside the kernel (valid_k); pad *query* rows
    # compute garbage that is sliced off below (they still see ≥1 real key,
    # so no 0/0).
    sq_pad = _round_up(sq, 128)
    sk_pad = _round_up(sk, 128)
    block_q = _largest_dividing_block(block_q, sq_pad)
    block_k = _largest_dividing_block(block_k, sk_pad)
    q, k, v = _pad_seq(q, sq_pad), _pad_seq(k, sk_pad), _pad_seq(v, sk_pad)
    # fold heads into the grid's batch dim: (B*H, S, D)
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, sq_pad, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, sk_pad, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, sk_pad, d)

    out = pl.pallas_call(
        functools.partial(_fwd_kernel, block_k=block_k, valid_k=sk),
        grid=(b * h, sq_pad // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, sk_pad, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, sk_pad, d), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq_pad, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    out = out.reshape(b, h, sq_pad, d).transpose(0, 2, 1, 3)
    return out[:, :sq] if sq_pad != sq else out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def pallas_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    block_q: int = 256,
    block_k: int = 256,
    interpret: bool = False,
) -> jax.Array:
    """Flash attention over (batch, seq, heads, head_dim); q pre-scaled.

    Arbitrary sequence lengths: inputs are padded to block multiples and the
    pad keys are masked to -inf inside the kernel (MAE shapes like 199 are
    first-class). ``interpret=True`` runs the kernel in the Pallas
    interpreter (CPU tests).
    """
    return _flash_fwd(q, k, v, block_q, block_k, interpret)


def _vjp_fwd(q, k, v, block_q, block_k, interpret):
    return _flash_fwd(q, k, v, block_q, block_k, interpret), (q, k, v)


def _vjp_bwd(block_q, block_k, interpret, residuals, g):
    from jumbo_mae_tpu_tpu.ops.blockwise_attention import blockwise_attention

    q, k, v = residuals
    _, vjp = jax.vjp(
        functools.partial(blockwise_attention, block_k=block_k), q, k, v
    )
    return vjp(g)


pallas_flash_attention.defvjp(_vjp_fwd, _vjp_bwd)
