"""Pallas TPU flash-attention kernels — forward AND backward.

Forward: grid (batch·heads, seq_q/block_q). Each program holds one query
block in VMEM and streams the full key/value sequence for its batch-head
through a ``fori_loop`` of ``block_k`` chunks with the online-softmax
recurrence — the (seq, seq) score matrix never exists in HBM, scores are
accumulated on the MXU in float32. The per-row logsumexp is written as a
second output and saved for the backward.

Backward (FlashAttention-style, two kernels so no cross-program
accumulation is needed):

- ``_bwd_dq_kernel``   — grid over q blocks; recomputes P = exp(qkᵀ − lse)
  per k chunk and accumulates dQ = Σ (P ∘ (dO·Vᵀ − D))·K;
- ``_bwd_dkv_kernel``  — grid over k blocks; loops over q chunks and
  accumulates dV = Σ Pᵀ·dO and dK = Σ (P ∘ (dO·Vᵀ − D))ᵀ·Q,

where D = rowsum(dO ∘ O) is precomputed outside the kernels. Memory stays
O(seq) end to end — the residuals are just (q, k, v, o, lse).

Ragged sequence lengths are first-class: inputs pad to the 128-lane tile
and pad *keys* are masked to −inf wherever scores are (re)computed. Pad
*query* rows need no masking anywhere: their forward output is sliced off,
so their incoming dO is zero and every backward contribution vanishes.

Heads are folded into the batch/grid dimension, so per-program tiles are 2-D
(block, head_dim) — aligned with the (8/16, 128) sublane×lane tiling as long
as head_dim is a multiple of 128 (64/32-dim heads are padded by Mosaic
automatically, at some efficiency cost).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30
# Minor-dim width for the per-row scalar residuals (lse, D). 8 (one f32
# sublane tile) rather than 128: Mosaic accepts sub-lane-width minor dims
# with masked loads, and the 16× slimmer HBM buffers matter at scale — at
# the ViT-H bench shapes the 128-wide broadcast was ~840 MB of transient
# per buffer; gradient parity at width 8 is verified on-device (v5e).
# Mosaic's acceptance of sub-128 minor dims varies by TPU generation and
# compiler version: if compilation fails on another device kind with a
# Mosaic layout/lane error pointing at the lse/delta buffers, set
# JUMBO_PALLAS_LANE=128 — full-lane residual buffers, identical numerics,
# just fatter HBM transients.
LANE = int(os.environ.get("JUMBO_PALLAS_LANE", "8"))

# Matmul operand dtype inside the kernels: the INPUT dtype (bf16 in
# production) rather than an f32 upcast. bf16 operands feed the MXU at its
# native rate — the prior unconditional f32 upcast cost multiple MXU passes
# per dot, a plausible root cause of round 4's "flash loses to einsum
# everywhere both fit". The einsum path materializes bf16 scores AND bf16
# probs, so bf16 operands here are numerically comparable (scores still
# accumulate f32 via preferred_element_type, softmax math stays f32, and
# flash keeps its f32 online-softmax accumulation). f32 inputs (parity
# oracles) are untouched. JUMBO_PALLAS_MM_F32=1 restores the f32 upcast.
MM_F32 = os.environ.get("JUMBO_PALLAS_MM_F32") == "1"


def _mm_dtype(ref) -> jnp.dtype:
    return jnp.float32 if MM_F32 else ref.dtype

# Block planning: by default the padded sequence rounds to the 128-lane tile
# and the block shrinks to the largest divisor (at seq 787 → sk_pad 896 the
# requested 256 collapses to 128, doubling streaming passes). With
# JUMBO_PALLAS_PAD_TO_BLOCK=1 the sequence pads UP to a block multiple
# instead (more masked rows, fewer/fatter passes) — measured per shape.
PAD_TO_BLOCK = os.environ.get("JUMBO_PALLAS_PAD_TO_BLOCK") == "1"


def _mask_cols(s, col0: int, valid_k: int):
    """Set score columns at global key index ≥ valid_k to −inf."""
    rows, cols = s.shape
    col = col0 + jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 1)
    return jnp.where(col < valid_k, s, NEG_INF)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref=None, *, block_k: int, valid_k: int):
    mm = _mm_dtype(q_ref)
    q = q_ref[0].astype(mm)  # (block_q, d)
    block_q, d = q.shape
    seq_k = k_ref.shape[1]

    def body(i, carry):
        m, l, acc = carry
        kb = k_ref[0, pl.ds(i * block_k, block_k), :].astype(mm)
        vb = v_ref[0, pl.ds(i * block_k, block_k), :].astype(mm)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (block_q, block_k), f32 accumulation
        if valid_k != seq_k:
            s = _mask_cols(s, i * block_k, valid_k)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + p.sum(axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p.astype(mm), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l, acc

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, seq_k // block_k, body, (m0, l0, acc0))
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    if lse_ref is not None:
        # per-row scalar broadcast over an 8-wide (one f32 sublane tile)
        # minor dim — see the LANE constant for why not 128
        lse_ref[0] = jnp.broadcast_to(m + jnp.log(l), (block_q, LANE))


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref, dq_ref, *, block_k: int, valid_k: int
):
    mm = _mm_dtype(q_ref)
    q = q_ref[0].astype(mm)  # (block_q, d)
    do = do_ref[0].astype(mm)
    lse = lse_ref[0][:, :1]  # (block_q, 1) — scalar replicated over lanes
    dd = dd_ref[0][:, :1]
    block_q, d = q.shape
    seq_k = k_ref.shape[1]

    def body(i, dq):
        kb = k_ref[0, pl.ds(i * block_k, block_k), :].astype(mm)
        vb = v_ref[0, pl.ds(i * block_k, block_k), :].astype(mm)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if valid_k != seq_k:
            s = _mask_cols(s, i * block_k, valid_k)
        p = jnp.exp(s - lse)  # (block_q, block_k), f32
        dp = jax.lax.dot_general(
            do, vb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - dd)).astype(mm)
        return dq + jax.lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    dq = jax.lax.fori_loop(
        0, seq_k // block_k, body, jnp.zeros((block_q, d), jnp.float32)
    )
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref, dk_ref, dv_ref,
    *, block_q: int, valid_k: int, masked: bool,
):
    mm = _mm_dtype(k_ref)
    k = k_ref[0].astype(mm)  # (block_k, d)
    v = v_ref[0].astype(mm)
    block_k, d = k.shape
    seq_q = q_ref.shape[1]
    col0 = pl.program_id(1) * block_k

    def body(i, carry):
        dk, dv = carry
        qb = q_ref[0, pl.ds(i * block_q, block_q), :].astype(mm)
        dob = do_ref[0, pl.ds(i * block_q, block_q), :].astype(mm)
        lse = lse_ref[0, pl.ds(i * block_q, block_q), :1]
        dd = dd_ref[0, pl.ds(i * block_q, block_q), :1]
        s = jax.lax.dot_general(
            qb, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (block_q, block_k)
        if masked:
            s = _mask_cols(s, col0, valid_k)
        p = jnp.exp(s - lse)
        dv = dv + jax.lax.dot_general(
            p.astype(mm), dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            dob, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - dd)).astype(mm)
        dk = dk + jax.lax.dot_general(
            ds, qb, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk, dv

    zeros = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(0, seq_q // block_q, body, (zeros, zeros))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _pad_seq(x, to: int):
    pad = to - x.shape[1]
    if not pad:
        return x
    return jnp.pad(x, ((0, 0), (0, pad)) + ((0, 0),) * (x.ndim - 2))


def _round_up(x: int, to: int) -> int:
    return -(-x // to) * to


def _largest_dividing_block(requested: int, seq_pad: int) -> int:
    """Largest block ≤ requested that divides ``seq_pad``. Production blocks
    stay on 128 multiples (seq_pad is one, so 128 always qualifies);
    sub-128 requests (interpreter tests) fall back to any exact divisor."""
    block = min(requested, seq_pad)
    if block >= 128:
        block = block // 128 * 128
        while seq_pad % block:
            block -= 128
    else:
        while seq_pad % block:
            block -= 1
    return block


def _fold(x, b, h, s, d):
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _unfold(x, b, h, s, d):
    return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _plan(q, k, block_q, block_k):
    b, sq, h, d = q.shape
    sk = k.shape[1]
    # Default: pad ragged lengths only up to the 128-lane tile, then pick
    # the largest block ≤ requested that divides the padded length (at seq
    # 787 → 896 a requested 256 collapses to 128). PAD_TO_BLOCK instead
    # pads up to a block multiple — more masked rows (787 → 1024, +14%),
    # but fewer, fatter streaming passes; which wins is measured per shape
    # (tools/flash_microbench.py).
    if PAD_TO_BLOCK:
        sq_pad = _round_up(sq, min(block_q, _round_up(sq, 128)))
        sk_pad = _round_up(sk, min(block_k, _round_up(sk, 128)))
    else:
        sq_pad = _round_up(sq, 128)
        sk_pad = _round_up(sk, 128)
    return (
        b, sq, h, d, sk, sq_pad, sk_pad,
        _largest_dividing_block(block_q, sq_pad),
        _largest_dividing_block(block_k, sk_pad),
    )


def _flash_fwd(q, k, v, block_q, block_k, interpret, with_lse: bool):
    b, sq, h, d, sk, sq_pad, sk_pad, block_q, block_k = _plan(q, k, block_q, block_k)
    qf = _fold(_pad_seq(q, sq_pad), b, h, sq_pad, d)
    kf = _fold(_pad_seq(k, sk_pad), b, h, sk_pad, d)
    vf = _fold(_pad_seq(v, sk_pad), b, h, sk_pad, d)

    o_spec = pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0))
    o_shape = jax.ShapeDtypeStruct((b * h, sq_pad, d), q.dtype)
    if with_lse:
        # the lse output rides a LANE-wide (8, one sublane tile) minor dim
        # inside the kernel; only the first column is kept as residual
        out_specs = [o_spec, pl.BlockSpec((1, block_q, LANE), lambda bh, i: (bh, i, 0))]
        out_shape = [o_shape, jax.ShapeDtypeStruct((b * h, sq_pad, LANE), jnp.float32)]
    else:
        out_specs, out_shape = o_spec, o_shape

    res = pl.pallas_call(
        functools.partial(_fwd_kernel, block_k=block_k, valid_k=sk),
        grid=(b * h, sq_pad // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, sk_pad, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, sk_pad, d), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(qf, kf, vf)
    out, lse = res if with_lse else (res, None)
    out = _unfold(out, b, h, sq_pad, d)
    out = out[:, :sq] if sq_pad != sq else out
    return (out, lse[..., 0]) if with_lse else (out, None)


def _flash_bwd(q, k, v, o, lse, g, block_q, block_k, interpret, g_lse=None):
    b, sq, h, d, sk, sq_pad, sk_pad, block_q, block_k = _plan(q, k, block_q, block_k)
    qf = _fold(_pad_seq(q, sq_pad), b, h, sq_pad, d)
    kf = _fold(_pad_seq(k, sk_pad), b, h, sk_pad, d)
    vf = _fold(_pad_seq(v, sk_pad), b, h, sk_pad, d)
    dof = _fold(_pad_seq(g, sq_pad), b, h, sq_pad, d)
    of = _fold(_pad_seq(o, sq_pad), b, h, sq_pad, d)
    # D = rowsum(dO ∘ O): tiny and elementwise — jnp, not a kernel. Pad q
    # rows have dO = 0 ⇒ D = 0 ⇒ all their backward contributions vanish.
    # Both per-row scalars are replicated over the lane dim only here, at
    # kernel entry (the lse residual is stored compact, (b*h, sq_pad)).
    dd = (dof.astype(jnp.float32) * of.astype(jnp.float32)).sum(-1)
    if g_lse is not None:
        # lse cotangent (pallas_flash_attention_with_lse): ∂lse/∂s_j = p_j,
        # so it folds into the score cotangent as ds = p·(dp − (D − g_lse))
        # — shift D per row, kernels unchanged. Pad rows get 0 (no-op).
        dd = dd - jnp.pad(
            g_lse.astype(jnp.float32),
            ((0, 0), (0, sq_pad - g_lse.shape[1])),
        )
    dd = jnp.broadcast_to(dd[..., None], (b * h, sq_pad, LANE))
    lse = jnp.broadcast_to(lse[..., None], (b * h, sq_pad, LANE))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_k=block_k, valid_k=sk),
        grid=(b * h, sq_pad // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, sk_pad, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, sk_pad, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, block_q, LANE), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, block_q, LANE), lambda bh, i: (bh, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq_pad, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf, dof, lse, dd)

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, block_q=block_q, valid_k=sk, masked=sk != sk_pad
        ),
        grid=(b * h, sk_pad // block_k),
        in_specs=[
            pl.BlockSpec((1, sq_pad, d), lambda bh, j: (bh, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, j: (bh, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, j: (bh, j, 0)),
            pl.BlockSpec((1, sq_pad, d), lambda bh, j: (bh, 0, 0)),
            pl.BlockSpec((1, sq_pad, LANE), lambda bh, j: (bh, 0, 0)),
            pl.BlockSpec((1, sq_pad, LANE), lambda bh, j: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, j: (bh, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, j: (bh, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sk_pad, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, sk_pad, d), v.dtype),
        ],
        interpret=interpret,
    )(qf, kf, vf, dof, lse, dd)

    dq = _unfold(dq, b, h, sq_pad, d)[:, :sq]
    dk = _unfold(dk, b, h, sk_pad, d)[:, :sk]
    dv = _unfold(dv, b, h, sk_pad, d)[:, :sk]
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def pallas_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    block_q: int = 1024,
    block_k: int = 1024,
    interpret: bool = False,
) -> jax.Array:
    """Flash attention over (batch, seq, heads, head_dim); q pre-scaled.

    Arbitrary sequence lengths: inputs are padded to lane tiles and the pad
    keys are masked to -inf inside the kernels (MAE shapes like 199 are
    first-class). Forward and backward are both Pallas kernels with O(seq)
    memory. ``interpret=True`` runs them in the Pallas interpreter (CPU
    tests).

    Default blocks are 1024 (clamped per shape by ``_plan``): round-5
    microbenches (tools/flash_microbench.py, v5e) showed the requested-256
    default collapsing to 128 at seq 787 (896 tile-pad) and doubling the
    streaming passes — big requests resolve to full-row or near-full-row
    blocks (256@199, 896@787, 640@3139) and beat the einsum path at every
    long-context shape (9.0 vs 15.3 ms at 787, 24.7 vs 45.8 at 3139).
    """
    out, _ = _flash_fwd(q, k, v, block_q, block_k, interpret, with_lse=False)
    return out


def _vjp_fwd(q, k, v, block_q, block_k, interpret):
    out, lse = _flash_fwd(q, k, v, block_q, block_k, interpret, with_lse=True)
    return out, (q, k, v, out, lse)


def _vjp_bwd(block_q, block_k, interpret, residuals, g):
    q, k, v, o, lse = residuals
    return _flash_bwd(q, k, v, o, lse, g, block_q, block_k, interpret)


pallas_flash_attention.defvjp(_vjp_fwd, _vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def pallas_flash_attention_with_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    block_q: int = 1024,
    block_k: int = 1024,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Like :func:`pallas_flash_attention` but also returns the per-row
    logsumexp ``lse`` with shape (batch·heads, seq_q) — DIFFERENTIABLE in
    both outputs, which block-merging callers (ring attention's flash
    inner) need: the merge weights are functions of lse, so its cotangent
    must reach q and k.

    The lse cotangent costs nothing extra in the backward: with
    ``p = exp(s − lse)``, ``∂lse/∂s_j = p_j``, so the score cotangent
    becomes ``ds = p·(dp − (D − g_lse))`` — the existing kernels run
    unchanged with ``D`` shifted by ``−g_lse`` per row.
    """
    out, lse = _flash_fwd(q, k, v, block_q, block_k, interpret, with_lse=True)
    return out, lse[:, : q.shape[1]]


def _vjp_lse_fwd(q, k, v, block_q, block_k, interpret):
    out, lse = _flash_fwd(q, k, v, block_q, block_k, interpret, with_lse=True)
    return (out, lse[:, : q.shape[1]]), (q, k, v, out, lse)


def _vjp_lse_bwd(block_q, block_k, interpret, residuals, gs):
    q, k, v, o, lse = residuals
    g, g_lse = gs
    return _flash_bwd(
        q, k, v, o, lse, g, block_q, block_k, interpret, g_lse=g_lse
    )


pallas_flash_attention_with_lse.defvjp(_vjp_lse_fwd, _vjp_lse_bwd)
