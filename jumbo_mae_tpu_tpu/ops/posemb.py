"""Fixed 2-D sin/cos positional embeddings.

Behavioral parity target: ``fixed_sincos2d_embeddings`` in
``/root/reference/src/utils.py:114-121``. Two quirks of the reference are
preserved deliberately because pretrained checkpoints depend on them:

1. the frequency ladder is ``linspace(0, 1, dim//4)`` **including** the
   endpoint (upstream MAE excludes it);
2. the row/column coordinate grids are generated with ``nrows``/``ncols``
   swapped relative to their broadcast axes — harmless for square grids
   (the only configuration the reference ever runs).

We compute the table once in float32 numpy at module-construction time; it is
a compile-time constant folded into the XLA program, never a device transfer.
"""

from __future__ import annotations

import numpy as np


def sincos2d_positional_embedding(ncols: int, nrows: int, dim: int) -> np.ndarray:
    """Build a (ncols, nrows, dim) table of fixed 2-D sin/cos embeddings.

    ``dim`` must be divisible by 4: the feature axis is split into four
    equal bands — sin(col·f), cos(col·f), sin(row·f), cos(row·f).
    """
    if dim % 4 != 0:
        raise ValueError(f"posemb dim must be divisible by 4, got {dim}")
    nband = dim // 4
    inv_freq = 10000.0 ** -np.linspace(0.0, 1.0, nband, dtype=np.float64)

    # Angles for the two spatial coordinates. Matches the reference's
    # (swapped for non-square grids) broadcast layout.
    a = np.arange(nrows, dtype=np.float64)[:, None] * inv_freq[None, :]
    b = np.arange(ncols, dtype=np.float64)[:, None] * inv_freq[None, :]
    a_grid = np.broadcast_to(a[None, :, :], (ncols, nrows, nband))
    b_grid = np.broadcast_to(b[:, None, :], (ncols, nrows, nband))

    table = np.concatenate(
        [np.sin(a_grid), np.cos(a_grid), np.sin(b_grid), np.cos(b_grid)], axis=2
    )
    return table.astype(np.float32)
