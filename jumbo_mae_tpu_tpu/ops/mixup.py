"""Mixup and CutMix as pure functions.

Parity: the ``Mixup`` flax module in ``/root/reference/src/utils.py:66-111``
— Beta-sampled ratio, batch-permutation mixing, CutMix via a computed
bounding-box mask, and (when both are enabled) computing both and selecting
one with a coin flip so the program stays branch-free under jit. Implemented
here as stateless functions of an explicit PRNG key rather than a module with
an rng stream.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _mixup(key: jax.Array, images: jax.Array, labels: jax.Array, alpha: float):
    k1, k2 = jax.random.split(key)
    ratio = jax.random.beta(k1, alpha, alpha)
    perm = jax.random.permutation(k2, images.shape[0])
    images = ratio * images + (1 - ratio) * images[perm]
    labels = ratio * labels + (1 - ratio) * labels[perm]
    return images, labels


def _bounding_box_mask(
    key: jax.Array, ratio: jax.Array, height: int, width: int
) -> jax.Array:
    """1 outside the random box, 0 inside; box area ≈ (1 - ratio)."""
    size = jnp.sqrt(1 - ratio)
    cx, cy = jax.random.uniform(key, (2,))
    xs = jnp.linspace(0, 1, width)
    ys = jnp.linspace(0, 1, height)
    in_x = (cx - 0.5 * size <= xs) & (xs < cx + 0.5 * size)
    in_y = (cy - 0.5 * size <= ys) & (ys < cy + 0.5 * size)
    inside = in_y[:, None] & in_x[None, :]
    return (~inside)[None, :, :, None].astype(jnp.float32)


def _cutmix(key: jax.Array, images: jax.Array, labels: jax.Array, alpha: float):
    k1, k2, k3 = jax.random.split(key, 3)
    ratio = jax.random.beta(k1, alpha, alpha)
    mask = _bounding_box_mask(k2, ratio, images.shape[1], images.shape[2])
    label_ratio = mask.mean(axis=(1, 2))
    perm = jax.random.permutation(k3, images.shape[0])
    images = mask * images + (1 - mask) * images[perm]
    labels = label_ratio * labels + (1 - label_ratio) * labels[perm]
    return images, labels


def mixup_cutmix(
    key: jax.Array,
    images: jax.Array,
    labels: jax.Array,
    mixup_alpha: float = 0.8,
    cutmix_alpha: float = 1.0,
):
    """Apply mixup and/or cutmix to a float image batch and soft labels.

    With both alphas positive, both transforms are computed and one selected
    per batch by coin flip (branch-free under jit). With both zero this is
    the identity.
    """
    if mixup_alpha == 0 and cutmix_alpha == 0:
        return images, labels
    km, kc, kflip = jax.random.split(key, 3)
    if cutmix_alpha == 0:
        return _mixup(km, images, labels, mixup_alpha)
    if mixup_alpha == 0:
        return _cutmix(kc, images, labels, cutmix_alpha)

    im1, lb1 = _mixup(km, images, labels, mixup_alpha)
    im2, lb2 = _cutmix(kc, images, labels, cutmix_alpha)
    take_mixup = jax.random.uniform(kflip) > 0.5
    return (
        jnp.where(take_mixup, im1, im2),
        jnp.where(take_mixup, lb1, lb2),
    )
