"""Blockwise (memory-efficient) attention in pure XLA.

Online-softmax attention computed chunk-by-chunk over the key axis with
``lax.scan`` — O(seq) memory instead of the O(seq²) score tensor the
reference materializes (``/root/reference/src/modeling.py:136-137``). Fully
differentiable (each chunk rematerialized in the backward pass via
``jax.checkpoint``), so it also serves as the backward path for the Pallas
forward kernel and as the per-device compute of ring attention.

Inputs are (batch, seq, heads, head_dim), queries pre-scaled.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def blockwise_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    block_k: int = 512,
    bias: jax.Array | None = None,
) -> jax.Array:
    """Softmax(q·kᵀ + bias)·v without materializing the full score matrix.

    ``bias`` (optional) is broadcastable to (batch, heads, seq_q, seq_k) and
    is sliced along the key axis per chunk.
    """
    seq_k = k.shape[1]
    block_k = min(block_k, seq_k)
    num_blocks = -(-seq_k // block_k)
    pad = num_blocks * block_k - seq_k
    if pad:
        kp = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        vp = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
        pad_mask = jnp.arange(num_blocks * block_k) >= seq_k
        if bias is not None and bias.shape[-1] == seq_k:
            # keep the key axis broadcastable after padding; padded keys are
            # killed by pad_mask anyway, so the fill value is irrelevant
            bias = jnp.pad(
                bias, [(0, 0)] * (bias.ndim - 1) + [(0, pad)],
                constant_values=NEG_INF,
            )
    else:
        kp, vp, pad_mask = k, v, None

    # (blocks, B, block_k, H, D)
    ks = kp.reshape(kp.shape[0], num_blocks, block_k, *kp.shape[2:]).swapaxes(0, 1)
    vs = vp.reshape(vp.shape[0], num_blocks, block_k, *vp.shape[2:]).swapaxes(0, 1)

    bq, sq, h, d = q.shape
    m0 = jnp.full((bq, h, sq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, h, sq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, sq, h, d), jnp.float32)

    @partial(jax.checkpoint, prevent_cse=False)
    def chunk(carry, xs):
        m, l, acc = carry
        kb, vb, idx = xs
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q, kb, preferred_element_type=jnp.float32
        )
        if bias is not None:
            s = s + jax.lax.dynamic_slice_in_dim(
                jnp.broadcast_to(bias, (bq, h, sq, seq_k + pad)),
                idx * block_k,
                block_k,
                axis=3,
            )
        if pad_mask is not None:
            sel = jax.lax.dynamic_slice_in_dim(
                pad_mask, idx * block_k, block_k
            )
            s = jnp.where(sel[None, None, None, :], NEG_INF, s)
        m_new = jnp.maximum(m, s.max(-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + p.sum(-1, keepdims=True)
        acc = acc * alpha.transpose(0, 2, 1, 3) + jnp.einsum(
            "bhqk,bkhd->bqhd", p.astype(vb.dtype), vb,
            preferred_element_type=jnp.float32,
        )
        return (m_new, l, acc), None

    (m, l, acc), _ = jax.lax.scan(
        chunk, (m0, l0, acc0), (ks, vs, jnp.arange(num_blocks))
    )
    out = acc / l.transpose(0, 2, 1, 3)
    return out.astype(q.dtype)
