"""Memory-efficient attention for long sequences.

The reference materializes full (B,H,N,N) score tensors
(``/root/reference/src/modeling.py:136-137``) — fine at N=197, fatal for
long-context. This module provides ``flash_attention(q, k, v)`` over
(B, N, H, D) tensors:

- on TPU, a Pallas blockwise-softmax kernel (``ops/pallas/attention.py``)
  that never materializes the N×N score matrix in HBM — any sequence length
  (the kernel pads to lane tiles and masks pad keys internally);
- elsewhere, an XLA fallback that is numerically identical to the naive
  path (blockwise-chunked above 2048 tokens).

Inputs are expected pre-scaled (queries already multiplied by head_dim**-0.5,
matching the callers in ``models/layers.py``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def xla_attention(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    block_q: int = 1024,
    block_k: int = 1024,
) -> jax.Array:
    """Softmax(q·kᵀ)·v without materializing the score matrix.

    q, k, v: (batch, seq, heads, head_dim). Returns the same shape as q.
    Block defaults follow ``pallas_flash_attention`` (big requests, clamped
    per shape — see its docstring for the round-5 measurements).
    """
    seq_q, seq_k = q.shape[1], k.shape[1]
    if jax.default_backend() != "tpu":
        if max(seq_q, seq_k) >= 2048:
            from jumbo_mae_tpu_tpu.ops.blockwise_attention import (
                blockwise_attention,
            )

            return blockwise_attention(q, k, v, block_k=min(block_k, seq_k))
        return xla_attention(q, k, v)
    from jumbo_mae_tpu_tpu.ops.pallas.attention import pallas_flash_attention

    return pallas_flash_attention(q, k, v, block_q, block_k)
