"""Patchification and the masked-patch MSE loss.

Behavioral parity target: ``extract_patches`` / ``merge_patches`` /
``patch_mse_loss`` in ``/root/reference/src/utils_mae.py:51-82``. Pure
reshape/transpose — XLA fuses these into the surrounding program; no Pallas
needed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def extract_patches(images: jax.Array, patch_size: int) -> jax.Array:
    """(B, H, W, C) → (B, H/p · W/p, p²·C), row-major patch order."""
    b, h, w, c = images.shape
    gh, gw = h // patch_size, w // patch_size
    x = images.reshape(b, gh, patch_size, gw, patch_size, c)
    x = x.swapaxes(2, 3)
    return x.reshape(b, gh * gw, patch_size * patch_size * c)


def merge_patches(patches: jax.Array, patch_size: int) -> jax.Array:
    """(B, N, p²·C) → (B, H, W, C); inverse of :func:`extract_patches` for a
    square grid (N must be a perfect square)."""
    b, n, _ = patches.shape
    g = int(round(n**0.5))
    x = patches.reshape(b, g, g, patch_size, patch_size, -1)
    x = x.swapaxes(2, 3)
    return x.reshape(b, g * patch_size, g * patch_size, -1)


def patch_mse_loss_per_sample(
    output: jax.Array, target: jax.Array, mask: jax.Array | None = None
) -> jax.Array:
    """(B,) mean-squared error over MASKED patches only, per sample.

    ``mask`` is (B, N) with 1 at masked positions; the per-sample mean over
    patches is divided by the masked ratio so the result is the mean over
    masked patches. With ``mask=None`` this degrades to a plain per-sample MSE.
    """
    per_patch = jnp.mean(jnp.square(target - output), axis=-1)
    if mask is None:
        return jnp.mean(per_patch, axis=-1)
    masked_ratio = jnp.sum(mask, axis=-1) / mask.shape[-1]
    per_sample = jnp.mean(jnp.where(mask > 0.0, per_patch, 0.0), axis=-1)
    return per_sample / masked_ratio


def patch_mse_loss(
    output: jax.Array, target: jax.Array, mask: jax.Array | None = None
) -> jax.Array:
    """Scalar batch mean of :func:`patch_mse_loss_per_sample`."""
    return jnp.mean(patch_mse_loss_per_sample(output, target, mask))
