"""Unified training entry point: pretrain / finetune / linear probe.

The reference shipped two near-identical entry scripts
(``/root/reference/src/main_pretrain.py:48-96``,
``/root/reference/src/main_finetune.py:48-96``) driven by bash flag files;
here one loop covers all three modes, driven by YAML recipes
(``recipes/``). Structure parity with the reference loop: sanity eval before
step 1, step loop with metric meters, periodic eval + best/last
checkpointing — plus what it lacked: true resume, MFU/throughput reporting,
deterministic seeds, profiler capture.

Run:
    python -m jumbo_mae_tpu_tpu.cli.train --config recipes/pretrain_vit_b16_in1k_1600ep.yaml
    python -m jumbo_mae_tpu_tpu.cli.train --config ... --set run.training_steps=10 data.workers=0
"""

from __future__ import annotations

import argparse
import contextlib
import math
import os
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from jumbo_mae_tpu_tpu.config import (
    IMAGENET_TRAIN_SIZE,
    TrainConfig,
    config_to_dict,
    load_config,
)
from jumbo_mae_tpu_tpu.data import (
    TrainLoader,
    epoch_shard_order,
    merge_shard_states,
    prefetch_to_device,
    resize_assignment,
    split_for_accum,
    synthetic_batches,
    valid_loader,
)
from jumbo_mae_tpu_tpu.data.tario import QUARANTINE
from jumbo_mae_tpu_tpu.faults import (
    DivergenceError,
    DivergenceSentinel,
    SentinelConfig,
    fault_point,
    faults_active,
    host_leak_tick,
    install_plan,
    leak_ballast_bytes,
    set_host_index,
)
from jumbo_mae_tpu_tpu.models import (
    ClassificationModel,
    DecoderConfig,
    MAEPretrainModel,
    preset,
)
from jumbo_mae_tpu_tpu.parallel import batch_sharding, create_mesh
from jumbo_mae_tpu_tpu.train import (
    EXIT_FATAL,
    EXIT_HANG,
    EXIT_OK,
    Checkpointer,
    RunEngine,
    create_sharded_state,
    exit_code_for,
    load_pretrained_params,
    make_eval_step,
    make_optimizer,
    make_train_step,
)
from jumbo_mae_tpu_tpu.obs import (
    FleetAggregator,
    FlightRecorder,
    GoodputLedger,
    HangWatchdog,
    HealthState,
    HostBeacon,
    RunJournal,
    TelemetryServer,
    env_fingerprint,
    export_chrome_trace,
    first_nonfinite_group,
    get_registry,
    group_layout,
    publish_group_stats,
    span_timer,
    start_chrome_trace,
    stats_dict,
    trace,
)
from jumbo_mae_tpu_tpu.obs.costmodel import (
    cost_asdict,
    extract_cost,
    publish_cost,
    utilization_report,
)
from jumbo_mae_tpu_tpu.obs.memwatch import (
    LeakSentinel,
    MemAccountant,
    MemoryWatcher,
)
from jumbo_mae_tpu_tpu.obs.perfmodel import detect_chip, publish_drift, roofline
from jumbo_mae_tpu_tpu.utils import (
    AverageMeter,
    MetricLogger,
    StepTimer,
    classify_flops_per_image,
    mfu_report,
    param_summary,
    pretrain_flops_per_image,
)


def build_model(cfg: TrainConfig):
    """Construct the mode's flax module and its per-image train FLOPs."""
    m = cfg.model
    mode = cfg.run.mode
    if mode == "pretrain":
        enc = preset(m.preset, labels=None, **{"mask_ratio": 0.75, **m.overrides})
        dec = DecoderConfig(
            **{
                "layers": m.dec_layers,
                "dim": m.dec_dim,
                "heads": m.dec_heads,
                "dtype": m.dec_dtype,
                **m.dec_overrides,
            }
        )
        model = MAEPretrainModel(enc, dec, norm_pix_loss=m.norm_pix_loss)
        flops = pretrain_flops_per_image(enc, dec)
        return model, enc, flops
    linear = mode == "linear"
    enc = preset(
        m.preset,
        **{
            "mask_ratio": None,
            "linear_probing": linear,
            "batch_norm": linear,
            **m.overrides,
        },
    )
    model = ClassificationModel(
        enc,
        mixup_alpha=m.mixup,
        cutmix_alpha=m.cutmix,
        label_smoothing=m.label_smoothing,
        criterion=m.criterion,
    )
    return model, enc, classify_flops_per_image(enc)


def _example_batch(cfg: TrainConfig, per_process: int) -> dict:
    shape = (per_process, cfg.data.image_size, cfg.data.image_size, 3)
    batch = {"images": np.zeros(shape, np.uint8)}
    if cfg.run.mode != "pretrain":
        batch["labels"] = np.zeros((per_process,), np.int32)
    return split_for_accum(batch, cfg.run.grad_accum)


def _strip_for_model(cfg: TrainConfig, batch: dict) -> dict:
    if cfg.run.mode == "pretrain":
        return {"images": batch["images"]}
    return {k: batch[k] for k in ("images", "labels") if k in batch}


def make_train_iterator(
    cfg: TrainConfig,
    mesh,
    per_process: int,
    start_step: int = 0,
    data_cursor: dict | None = None,
    num_labels: int = 1000,
    shard_override: list | None = None,
    shard_preconsumed: dict | None = None,
):
    """Build the device-prefetched train iterator.

    Resume: with a checkpointed ``data_cursor`` the loader continues the
    deterministic stream sample-exactly (per-worker epoch/offset + the
    round-robin phase). Without one (old checkpoint, changed worker count)
    it falls back to the coarse epoch cursor: restart the stream at the
    epoch the resumed step falls in — per-epoch shard order and shuffles are
    keyed on (seed, epoch), so no sample skipping is needed. One stream
    epoch yields dataset_size × repeats samples (repeated augmentation
    clones count toward the batch).

    ``shard_override`` is the resize-consistent resume path: explicit
    ``(global_index, url)`` pairs for this process's share of the resume
    epoch (computed by :func:`_resize_shard_override` from the journaled
    shard cursors), replacing the topology-derived stripe for that epoch
    only. ``shard_preconsumed`` rides with it — the merged consumed set
    the override was derived from, seeded into the new generation's shard
    ledgers so their ``shard_cursor`` snapshots stay CUMULATIVE across
    generations (a second resize must subtract everything ever consumed,
    not just this generation's reads).

    Returns ``(iterator, source, cursor_log, shard_log)`` — ``cursor_log``
    maps each absolute step to the loader snapshot after that step's batch
    left the loader (prefetch-safe: recorded at loader exit, consumed by
    step index); ``shard_log`` likewise maps steps to the merged
    consumed-shard ledger snapshot, journaled as ``shard_cursor`` at each
    checkpoint so a future resized resume can reconstruct the assignment.
    """
    start_epoch = (start_step * cfg.run.train_batch_size) // max(
        1, cfg.data.dataset_size * max(1, cfg.data.repeats)
    )
    if start_step > 0 and data_cursor is None:
        if (
            cfg.data.dataset_size == IMAGENET_TRAIN_SIZE
            and cfg.data.train_shards
            and "imagenet" not in str(cfg.data.train_shards).lower()
        ):
            print(
                "[train] WARNING: resuming with the default (ImageNet) "
                "data.dataset_size but custom train_shards — if the real "
                "dataset is smaller, the resume epoch below is wrong; set "
                "data.dataset_size explicitly"
            )
        print(f"[train] data cursor: resuming stream at epoch {start_epoch}")
    cursor_log: dict[int, dict] = {}
    shard_log: dict[int, dict] = {}
    if cfg.run.synthetic_data:
        it = synthetic_batches(
            per_process,
            cfg.data.image_size,
            # the MODEL's class count — labels >= cfg.labels one-hot to
            # all-zero rows, silently zeroing CE loss and pinning acc at 1
            labels=num_labels if cfg.run.mode != "pretrain" else None,
            grad_accum=cfg.run.grad_accum,
            seed=cfg.run.seed,
        )
        source = None
    else:
        data_cursor = _pick_process_cursor(data_cursor)
        loader_kwargs = dict(
            process_index=jax.process_index(),
            process_count=jax.process_count(),
            start_epoch=start_epoch,
        )
        try:
            source = TrainLoader(
                cfg.data,
                per_process,
                cursor=data_cursor,
                epoch_shard_override=shard_override,
                shard_preconsumed=shard_preconsumed,
                **loader_kwargs,
            )
            if data_cursor is not None:
                print(
                    "[train] data cursor: sample-exact resume at epoch/offset "
                    f"{data_cursor['workers']}"
                )
        except ValueError as e:
            if data_cursor is None:
                raise
            print(f"[train] WARNING: {e}; falling back to epoch-{start_epoch} resume")
            source = TrainLoader(
                cfg.data,
                per_process,
                epoch_shard_override=shard_override,
                shard_preconsumed=shard_preconsumed,
                **loader_kwargs,
            )

        def tracked():
            step = start_step
            for b in source:
                step += 1
                cursor_log[step] = source.snapshot()
                shards = source.shard_snapshot()
                if shards is not None:
                    shard_log[step] = shards
                yield b

        it = (split_for_accum(b, cfg.run.grad_accum) for b in tracked())
    it = ({k: v for k, v in b.items() if k != "valid"} for b in it)
    it = (_strip_for_model(cfg, b) for b in it)
    sharding = batch_sharding(mesh, accum=cfg.run.grad_accum > 1)
    return prefetch_to_device(it, sharding), source, cursor_log, shard_log


def make_valid_iterator(
    cfg: TrainConfig, mesh, per_process: int, num_labels: int = 1000
):
    sharding = batch_sharding(mesh, accum=False)
    if cfg.run.synthetic_data:
        def gen():
            it = synthetic_batches(
                per_process,
                cfg.data.image_size,
                labels=num_labels if cfg.run.mode != "pretrain" else None,
                seed=cfg.run.seed + 1,
            )
            for _, batch in zip(range(4), it):
                batch["valid"] = np.ones((per_process,), bool)
                yield batch

        return lambda: prefetch_to_device(gen(), sharding)
    if not cfg.data.valid_shards:
        return None
    return lambda: prefetch_to_device(
        valid_loader(
            cfg.data,
            per_process,
            process_index=jax.process_index(),
            process_count=jax.process_count(),
        ),
        sharding,
    )


class PreemptionGuard:
    """SIGTERM-safe training: TPU pods get preempted with a grace window, so
    a termination signal flips a flag and the step loop checkpoints at the
    next step boundary instead of dying mid-state (the reference had no
    resume at all, let alone a graceful-preemption path). SIGINT gets the
    same treatment so ^C on an interactive run saves before exiting."""

    def __init__(self):
        self.flagged = False

    def install(self) -> bool:
        import signal

        def handler(signum, frame):
            if self.flagged:
                # second signal: restore default behavior so a stuck run
                # (hung collective, long compile) stays force-killable
                signal.signal(signum, signal.SIG_DFL)
                signal.raise_signal(signum)
                return
            self.flagged = True
            print(
                f"[train] caught signal {signum}: will checkpoint and exit "
                "at the next step boundary (signal again to force-exit)"
            )

        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                signal.signal(sig, handler)
            except ValueError:  # not the main thread (e.g. under a runner)
                print(
                    "[train] WARNING: not on the main thread — graceful "
                    "preemption disabled; SIGTERM will kill the run "
                    "without a checkpoint"
                )
                return False
        return True


def _agree_on_preemption(preempt: "PreemptionGuard", process_count: int) -> bool:
    """Whether to take the preemption exit — all processes must agree (a
    checkpoint save is collective), so multi-host gathers every host's flag."""
    if process_count == 1:
        return preempt.flagged
    from jax.experimental import multihost_utils

    return bool(
        multihost_utils.process_allgather(np.asarray(preempt.flagged)).any()
    )


def _pick_process_cursor(data_cursor: dict | None) -> dict | None:
    """Restore-side counterpart of :func:`_gather_data_cursor`: select this
    process's cursor from the checkpointed payload. The checkpoint records
    every process's cursor plus the saving topology (the saved JSON is
    host-0's); sample-exact resume is only valid with the SAME process count
    — shard stripes and per-process batch sizes are topology-dependent — so
    any mismatch drops every process to epoch resume together (a mixed
    schedule would be globally inconsistent)."""
    if data_cursor is None:
        return None
    saved_pc = int(data_cursor.get("process_count", 1))
    if saved_pc != jax.process_count():
        print(
            f"[train] WARNING: checkpoint data cursor was saved with "
            f"{saved_pc} processes but this run has "
            f"{jax.process_count()}; falling back to epoch resume"
        )
        return None
    if "per_process" in data_cursor:
        picked = {
            "workers": data_cursor["per_process"][jax.process_index()],
            "batches": data_cursor["batches"],
        }
        if data_cursor.get("native_threads") is not None:
            picked["native_threads"] = data_cursor["native_threads"]
        return picked
    return data_cursor


def _gather_data_cursor(snap: dict | None) -> dict | None:
    """Make a loader snapshot checkpoint-safe under multi-host: Orbax's JSON
    payload is host-0's, so every process's cursor is all-gathered into it
    (``per_process``); restore picks the entry for ``jax.process_index()``.
    Collective — every process must call this at the same step."""
    if snap is None:
        return None
    if jax.process_count() == 1:
        return {**snap, "process_count": 1}
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(
        np.asarray(snap["workers"], np.int64)
    )
    # override marker: any-host semantics — if even one host's streams are
    # still inside an epoch_shard_override epoch, its offsets are measured
    # against the override stripe and the whole fleet must take the
    # journal-derived resume path (a mixed schedule would be inconsistent)
    ov = multihost_utils.process_allgather(
        np.asarray(
            -1
            if snap.get("override_epoch") is None
            else int(snap["override_epoch"]),
            np.int64,
        )
    )
    out = {
        "per_process": gathered.tolist(),
        "batches": snap["batches"],
        "process_count": jax.process_count(),
    }
    # substrate marker must survive the gather or a native-IO cursor can
    # never restore on a pod (and would mis-resume on the worker path)
    if snap.get("native_threads") is not None:
        out["native_threads"] = snap["native_threads"]
    if int(ov.max()) >= 0:
        out["override_epoch"] = int(ov.max())
    return out


def _resize_shard_override(
    cfg: TrainConfig,
    run_dir: Path,
    start_step: int,
    old_world: int,
    *,
    world: int,
    host: int,
) -> tuple[list, dict, dict]:
    """Resize-consistent resume (data/resize.py): reconstruct this process's
    shard assignment for the resume epoch from the journaled cursors.

    Reads the run's merged journal, takes each old host's ``shard_cursor``
    at the restored step, unions the consumed sets, and stripes the
    epoch's remainder across the NEW world — a pure function of
    ``(world, host, journal)``, no collective, so every process computes a
    disjoint, exhaustive assignment independently. Raises when no cursor
    exists for the step (pre-elastic checkpoint, journal disabled) — the
    caller falls back to plain epoch resume.

    Returns ``(pairs, preconsumed, info)``: ``preconsumed`` is the merged
    consumed-set snapshot the assignment subtracted, in
    :meth:`~jumbo_mae_tpu_tpu.data.resize.ShardLedger.snapshot` shape —
    the caller seeds it into the new generation's ledgers so the next
    ``shard_cursor`` events stay cumulative across generations.
    """
    from jumbo_mae_tpu_tpu.obs.journal import read_merged_journal

    latest: dict[int, dict] = {}
    for e in read_merged_journal(run_dir):
        if (
            e.get("type") == "shard_cursor"
            and int(e.get("step", -1)) == start_step
        ):
            latest[int(e.get("host", 0))] = e
    if not latest:
        raise FileNotFoundError(
            f"no shard_cursor journal events at step {start_step} "
            f"under {run_dir}"
        )
    merged = merge_shard_states(
        [{"epochs": e.get("epochs") or {}} for e in latest.values()]
    )
    start_epoch = (start_step * cfg.run.train_batch_size) // max(
        1, cfg.data.dataset_size * max(1, cfg.data.repeats)
    )
    order = epoch_shard_order(
        cfg.data.train_shards, seed=cfg.run.seed, epoch=start_epoch
    )
    consumed = merged.get(start_epoch, set())
    pairs = resize_assignment(order, consumed, world_size=world, process_id=host)
    preconsumed = {
        "epochs": {str(e): sorted(v) for e, v in merged.items()}
    }
    info = {
        "step": start_step,
        "epoch": start_epoch,
        "old_world": old_world,
        "new_world": world,
        "shards_total": len(order),
        "shards_consumed": len(consumed),
        "shards_remaining": len(order) - len(consumed),
        "cursor_hosts": sorted(latest),
    }
    return pairs, preconsumed, info


def _apply_override_resume(
    cfg: TrainConfig,
    run_dir: Path,
    data_cursor: dict | None,
    start_step: int,
    *,
    process_count: int,
    host_index: int,
    emit,
) -> tuple[dict | None, list | None, dict | None]:
    """Decide the data-resume mode: sample-exact cursor vs journal-derived
    shard override. The override path is taken when the cursor was saved
    under a DIFFERENT world size (its per-worker offsets describe streams
    striped for the old topology), or when it carries ``override_epoch`` —
    the saving generation was itself running on an ``epoch_shard_override``,
    so the offsets were measured on the override stripe and replaying them
    against the topology stripe would silently yield different samples even
    at the SAME world size (crash/preemption restart mid-override).

    Returns ``(data_cursor, shard_override, shard_preconsumed)``. On the
    override path the sample cursor is voided (resume is shard-granular);
    when the journal cannot reconstruct the assignment, the cursor is also
    voided — its offsets are meaningless for this generation's stripes —
    and the run falls back to plain epoch resume.
    """
    if (
        data_cursor is None
        or cfg.run.synthetic_data
        or not cfg.data.train_shards
    ):
        return data_cursor, None, None
    old_world = int(data_cursor.get("process_count", 1))
    if old_world == process_count and data_cursor.get("override_epoch") is None:
        return data_cursor, None, None
    try:
        pairs, preconsumed, rinfo = _resize_shard_override(
            cfg,
            run_dir,
            start_step,
            old_world,
            world=process_count,
            host=host_index,
        )
    except Exception as e:  # noqa: BLE001 - epoch resume still works
        print(
            f"[train] WARNING: resize-consistent resume unavailable "
            f"({e}); falling back to epoch resume"
        )
        return None, None, None
    cause = "resize" if old_world != process_count else "override_restart"
    emit("elastic_resize", cause=cause, **rinfo)
    print(
        f"[train] elastic resize ({cause}): world {old_world} -> "
        f"{process_count}; epoch {rinfo['epoch']} resumes with "
        f"{rinfo['shards_remaining']}/{rinfo['shards_total']} "
        "shards unconsumed"
    )
    return None, pairs, preconsumed


def evaluate(eval_step, state, batches, pad_batch: dict | None = None) -> dict[str, float]:
    """Weighted-exact eval aggregation (sums / num_samples — fixes the
    reference's pretrain val-loss normalization, SURVEY defect #2).

    Multi-host: the jitted eval step contains collectives, so every process
    must issue the SAME number of calls even when shard striping gives them
    different batch counts. Processes that run out of data keep feeding
    ``pad_batch`` (all rows ``valid=False``) until every process is done —
    agreement reached with a tiny host-level all-gather per round.
    """
    multi = jax.process_count() > 1
    if multi:
        from jax.experimental import multihost_utils

    totals: dict[str, float] = {}
    it = iter(batches)
    i = 0
    pending: list = []
    while True:
        batch = next(it, None)
        if multi:
            anyone_has_data = bool(
                multihost_utils.process_allgather(
                    np.asarray(batch is not None)
                ).any()
            )
            if not anyone_has_data:
                break
            if batch is None:
                if pad_batch is None:
                    raise ValueError(
                        "multi-host eval needs pad_batch for exhausted processes"
                    )
                batch = pad_batch
        elif batch is None:
            break
        # accumulate device scalars; fetch ONCE after the loop — a per-batch
        # device_get would serialize host dispatch against device compute,
        # exactly what the train loop avoids at its log boundaries
        pending.append(eval_step(state, batch, i))
        i += 1
    for sums in jax.device_get(pending):
        for k, v in sums.items():
            totals[k] = totals.get(k, 0.0) + float(v)
    n = max(totals.pop("num_samples", 0.0), 1.0)
    return {f"val/{k}": v / n for k, v in totals.items()}


def train(cfg: TrainConfig) -> dict:
    """Run the configured job; returns the final summary metrics."""
    run = cfg.run
    if run.faults:
        # recipe-driven chaos: the plan outlives this call on purpose (the
        # GRAFT_FAULTS env path behaves the same) — tests clear it
        plan = install_plan(run.faults)
        print(f"[faults] injection plan active: sites={plan.sites()}")
    process_count = jax.process_count()
    host_index = jax.process_index()
    # pin the fault layer's host identity (the `@host=` selector) before any
    # site can fire; mirrored into GRAFT_HOST so data workers inherit it
    set_host_index(host_index)
    # elastic generation: stamped into the environment by the supervisor's
    # launch() so scrapes, beacons and merged journals can tell pre- from
    # post-restart processes (0 = first launch / no supervisor)
    generation = int(os.environ.get("GRAFT_GENERATION", "0") or 0)
    # goodput ledger (obs/goodput.py): the clock starts HERE, at the top of
    # train(), so state build, compile and restore are on the books — every
    # second of this process's wall-clock lands in exactly one bucket
    ledger = GoodputLedger(generation=generation)
    if run.train_batch_size % (process_count * run.grad_accum):
        raise ValueError(
            f"process_count * grad_accum ({process_count} * {run.grad_accum}) "
            f"must divide the global batch size ({run.train_batch_size})"
        )
    per_process = run.train_batch_size // process_count
    per_process_valid = max(1, run.valid_batch_size // process_count)

    if run.eval_only and not (cfg.data.valid_shards or run.synthetic_data):
        # fail before any device/state work
        raise ValueError(
            "run.eval_only requires validation data "
            "(data.valid_shards or run.synthetic_data)"
        )
    if run.eval_which not in ("last", "best"):
        raise ValueError(
            f"run.eval_which must be 'last' or 'best', got {run.eval_which!r}"
        )
    if run.eval_which != "last" and not (run.eval_only and run.resume):
        # never silently drop a knob: slot selection only has an effect on
        # the eval_only+resume restore (pretrained_ckpt goes through the
        # warm-start merge, training resume is defined as 'last')
        raise ValueError(
            "run.eval_which=best requires run.eval_only=true AND "
            "run.resume=true (other paths would silently ignore it)"
        )

    cfg.mesh.validate_pipe()
    pipe_microbatches = 0
    if cfg.mesh.pipe > 1:
        from jumbo_mae_tpu_tpu.parallel import create_pipeline_mesh

        n_dev = len(jax.devices())
        pipe_data = cfg.mesh.data
        if pipe_data in (1, -1):
            # untouched default (or explicit fill): cover every device —
            # and say so, because a gpipe microbatch-divisibility error
            # downstream would otherwise reference a data axis the user
            # never wrote (data=1 cannot opt out: a pipe mesh that strands
            # devices is rejected below, so 1 could only ever mean
            # n_dev == pipe, which the fill reproduces)
            pipe_data = max(1, n_dev // cfg.mesh.pipe)
            if pipe_data > 1:
                print(
                    f"[mesh] data axis auto-filled to {pipe_data} "
                    f"(pipe={cfg.mesh.pipe} over {n_dev} devices); set "
                    "mesh.data explicitly to override"
                )
        if pipe_data * cfg.mesh.pipe < n_dev:
            # silently training on a subset is an easy way to waste a pod
            raise ValueError(
                f"mesh data={pipe_data} x pipe={cfg.mesh.pipe} covers only "
                f"{pipe_data * cfg.mesh.pipe} of {n_dev} devices; choose "
                "mesh.pipe to divide the device count (mesh.data=-1 "
                "auto-fills the data axis), or expose fewer devices to "
                "the process"
            )
        mesh = create_pipeline_mesh(data=pipe_data, pipe=cfg.mesh.pipe)
        pipe_microbatches = cfg.mesh.pipe_microbatches or cfg.mesh.pipe
    else:
        mesh = create_mesh(cfg.mesh)
    if cfg.mesh.pipe_decoder and (run.mode != "pretrain" or not pipe_microbatches):
        # never silently drop a parallelism knob
        raise ValueError(
            "mesh.pipe_decoder requires run.mode=pretrain and mesh.pipe>1"
        )
    model, enc_cfg, flops_per_image = build_model(cfg)

    # after config/mesh validation (so invalid runs never create checkpoint
    # directories) but before the expensive sharded-state build, so an
    # unsatisfiable eval_only restore fails fast. A non-resume eval_only run
    # never saves — skip the Checkpointer (and its eager dir creation).
    ckpt = (
        None
        if run.eval_only and not run.resume
        else Checkpointer(cfg.checkpoint_config())
    )
    # the top-of-train guard pins eval_which to "last" outside eval_only
    eval_which = run.eval_which
    resuming = (
        run.resume
        and ckpt is not None
        and ckpt.latest_step(eval_which) is not None
    )
    if run.eval_only and run.resume and not resuming:
        # an explicit restore request that can't be satisfied must not fall
        # through to plausible-looking random-init metrics
        ckpt.close()
        raise FileNotFoundError(
            f"run.eval_only with run.resume=true but no '{eval_which}' "
            f"checkpoint under {cfg.checkpoint_config().directory}"
        )

    if run.eval_only:
        # evaluation never steps the optimizer — a no-op tx keeps AdamW's
        # ~2x-params moment buffers off the device entirely
        import optax

        tx = optax.identity()
    else:
        tx = make_optimizer(
            cfg.optim, run.train_batch_size, num_layers=enc_cfg.layers
        )

    example = _example_batch(cfg, per_process)
    state, state_sharding = create_sharded_state(
        model,
        tx,
        example,
        mesh,
        mode="pretrain" if run.mode == "pretrain" else "classify",
        init_seed=run.init_seed,
        rng_seed=run.seed,
        param_dtype=cfg.optim.param_dtype,
    )

    if run.pretrained_ckpt and not resuming:
        # (skipped on resume: the checkpoint restore below overwrites params
        # AND opt_state anyway — re-doing the merge + a full jitted tx.init
        # would only cost startup time and a transient opt-state allocation)
        # With low-precision param storage, merge into an f32 template so
        # the master copy keeps the checkpoint's full precision (merging
        # straight into bf16 params would quantize the master at init);
        # stored params are then the downcast, per the master-weights
        # contract.
        low_precision = cfg.optim.param_dtype and jnp.dtype(
            cfg.optim.param_dtype
        ) != jnp.float32
        template = (
            jax.tree_util.tree_map(
                lambda p: p.astype(jnp.float32), state.params
            )
            if low_precision
            else state.params
        )
        merged = load_pretrained_params(run.pretrained_ckpt, template)
        # Optimizer state derives from the params at tx.init time — re-init
        # so anything param-coupled follows the merge (critical with
        # optim.param_dtype: the f32 master copy in opt_state would
        # otherwise still hold the random init and the first step would
        # overwrite the warm start with master-derived values).
        opt_state = jax.jit(
            state.tx.init, out_shardings=state_sharding.opt_state
        )(merged)
        if low_precision:
            merged = jax.tree_util.tree_map(
                lambda m, p: m.astype(p.dtype), merged, state.params
            )
        state = state.replace(params=merged, opt_state=opt_state)

    start_step = 0
    data_cursor = None
    ckpt_fallbacks: list[dict] = []  # journaled once the journal exists
    if resuming:
        if run.eval_only:
            # params/batch_stats/rng only — the saved opt_state never
            # touches the device (tx is a no-op identity here)
            state, extra = ckpt.restore_eval(
                state, sharding=state_sharding, which=eval_which
            )
        else:
            # a corrupt/torn latest step (host died mid-commit, fs
            # hiccup) walks back to the previous committed step instead
            # of killing the resume — bounded, and journaled below so
            # the replayed window is auditable
            def _note_fallback(from_step, to_step, err):
                ckpt_fallbacks.append(
                    {
                        "from_step": int(from_step),
                        "to_step": int(to_step),
                        "error": f"{type(err).__name__}: {err}",
                    }
                )

            state, extra = ckpt.restore(
                state,
                sharding=state_sharding,
                fallback_steps=2,
                on_fallback=_note_fallback,
            )
        start_step = int(state.step)
        data_cursor = extra.get("data_cursor")
        if not run.eval_only:
            ledger.add("ckpt_restore", ckpt.last_restore_s or 0.0)
        print(f"[train] resumed from step {start_step}")

    mode_key = "pretrain" if run.mode == "pretrain" else "classify"
    # mesh.pipe_decoder additionally depth-shards the MAE decoder stack
    # (pretrain only; mesh.pipe must divide dec_layers)
    dec_cfg = model.decoder_cfg if cfg.mesh.pipe_decoder else None
    # per-layer-group diagnostics (obs/modelstats): a STATIC flag — with
    # diag_every=0 the compiled step program is byte-identical to pre-diag
    diag_on = run.diag_every > 0 and not run.eval_only
    diag_names = group_layout(state.params) if diag_on else ()
    train_step = (
        None
        if run.eval_only  # dead work in an eval-and-exit run
        else make_train_step(
            mesh,
            state_sharding,
            mode=mode_key,
            grad_accum=run.grad_accum,
            pipe_microbatches=pipe_microbatches,
            encoder_cfg=enc_cfg if pipe_microbatches else None,
            decoder_cfg=dec_cfg,
            guard_nonfinite=run.sentinel,
            diag=diag_on,
        )
    )
    eval_step = make_eval_step(mesh, state_sharding, mode=mode_key)

    is_main = host_index == 0
    if is_main:
        # startup parameter table (parity: the reference's module.tabulate
        # pre-flight print, /root/reference/src/pretraining.py:214)
        print(param_summary(state.params))
    preempt = PreemptionGuard()
    if not run.eval_only:
        # eval_only has no step loop to honor the flag and nothing to
        # checkpoint — default signal behavior (exit now) is the honest one
        preempt.install()
    # telemetry: metrics always record into the process registry; the HTTP
    # exporter (/metrics + /healthz) is opt-in per recipe. State is built and
    # (if requested) restored by this point, so readiness is honest.
    health = HealthState()
    health.set_ready(True, detail=f"mode={run.mode} start_step={start_step}")
    # data-layer resilience surfaced to the operator: shard URLs the retry
    # layer gave up on this process (worker subprocesses keep their own —
    # the inline and native-IO substrates report here)
    health.probe("quarantined_shards", lambda: sorted(QUARANTINE.snapshot()))
    telemetry = None
    if run.telemetry and is_main:
        telemetry = TelemetryServer(
            health=health, host=run.telemetry_host, port=run.telemetry_port
        ).start()
        print(
            f"[obs] exporter on {run.telemetry_host}:{telemetry.port} "
            "(/metrics, /healthz)"
        )
    logger = MetricLogger(
        Path(run.output_dir) / run.name,
        name=run.name,
        config=config_to_dict(cfg),
        enabled=is_main,
        use_wandb=run.use_wandb,
        wandb_project=run.wandb_project,
        wandb_entity=run.wandb_entity,
        wandb_tags=tuple(run.wandb_tags),
        wandb_id=run.wandb_id,
    )
    valid_factory = make_valid_iterator(
        cfg, mesh, per_process_valid, num_labels=enc_cfg.labels or 1000
    )
    # all-padding eval batch, pre-sharded by EVERY process at setup so
    # exhausted hosts can keep stepping the collective eval program
    pad_batch = None
    if valid_factory is not None and process_count > 1:
        size = cfg.data.image_size
        host_pad = {
            "images": np.zeros((per_process_valid, size, size, 3), np.uint8),
            "labels": np.full((per_process_valid,), -1, np.int32),
            "valid": np.zeros((per_process_valid,), bool),
        }
        pad_batch = next(
            prefetch_to_device(iter([host_pad]), batch_sharding(mesh, accum=False))
        )

    if run.eval_only:
        assert valid_factory is not None  # guaranteed by the top-of-train check
        if is_main and not (resuming or run.pretrained_ckpt):
            print(
                "[eval] WARNING: eval_only on a fresh random init — set "
                "run.pretrained_ckpt or run.resume=true to restore weights"
            )
        val = evaluate(eval_step, state, valid_factory(), pad_batch)
        logger.log(val, step=start_step)
        if is_main:
            print(f"[eval] step {start_step}: {val}")
        if ckpt is not None:
            ckpt.close()
        logger.close()
        if telemetry is not None:
            telemetry.close()
        return val

    if run.sanity_eval and valid_factory is not None:
        print(
            "[train] sanity eval:",
            evaluate(eval_step, state, valid_factory(), pad_batch),
        )

    # run-history diagnostics (EVERY host, unlike the logger): the crash-safe
    # journal — host 0 under <run_dir>/journal/, host i under
    # <run_dir>/journal-host<i>/, every row host-tagged, merged offline by
    # read_merged_journal — and the black-box flight recorder dumping into
    # <run_dir>/ (host-tagged filenames off host 0) on non-finite steps,
    # rollbacks, SIGTERM, or an escaping exception. Installed AFTER the
    # preemption guard so its SIGTERM handler dumps first, then chains into
    # graceful checkpointing.
    run_dir = Path(run.output_dir) / run.name
    journal = None
    if run.journal:
        jdir = run_dir / ("journal" if is_main else f"journal-host{host_index}")
        journal = RunJournal(jdir, host=host_index)
    flightrec = (
        FlightRecorder(run_dir, capacity=run.flightrec_steps, host=host_index)
        if run.flightrec_steps > 0
        else None
    )
    if flightrec is not None:
        flightrec.install()

    def _emit(etype: str, **fields) -> None:
        """One diagnostic event → journal (durable) + flight ring (memory)."""
        rec = {"ts": round(time.time(), 3), "type": etype, **fields}
        if journal is not None:
            try:
                rec = journal.event(etype, **fields)
            except OSError as e:  # a full disk must not kill the run
                print(f"[obs] WARNING: journal write failed: {e}")
        if flightrec is not None:
            flightrec.record_event(rec)

    def _black_box(reason: str, **extra) -> None:
        if flightrec is None:
            return
        path = flightrec.dump(reason, extra=extra or None)
        _emit("flight_record", reason=reason, path=str(path))
        print(f"[obs] flight record ({reason}) -> {path}")

    # retrace sentinel (obs/retrace.py): armed after the first step, every
    # further XLA compile journals a `retrace` event with shape/dtype-diff
    # attribution — unless it's expected (eval, fault-inject executables)
    retrace_sentinel = None
    if run.retrace:
        from jumbo_mae_tpu_tpu.obs.retrace import RetraceSentinel

        retrace_sentinel = RetraceSentinel("train", journal=journal)

    if journal is not None:
        health.probe("journal", lambda: str(journal.path))
    _emit(
        "run_start",
        config=config_to_dict(cfg),
        env=env_fingerprint(),
        start_step=start_step,
        resumed=bool(resuming),
        generation=generation,
        diag_every=run.diag_every,
        diag_groups=list(diag_names),
    )
    for fb in ckpt_fallbacks:
        _emit("ckpt_fallback", **fb)

    # fleet health (obs/fleet.py): every host rewrites its beacon each step;
    # host 0 additionally aggregates the beacon dir into fleet_* gauges (on
    # the exporter's scrape, so idle scans cost nothing), journals straggler/
    # lost/rejoined transitions via _emit, and feeds /healthz (soft degraded)
    beacon = None
    fleet_agg = None
    beacon_stats: dict = {"generation": generation}
    if run.fleet:
        beacon = HostBeacon(run_dir / "fleet", host=host_index)
        if is_main:
            fleet_agg = FleetAggregator(
                run_dir / "fleet",
                expected_hosts=process_count,
                lag_steps=run.fleet_lag_steps,
                ratio=run.fleet_ratio,
                dead_after_s=run.fleet_dead_after_s,
                on_event=_emit,
            )
            health.probe("fleet", fleet_agg.summary)
            health.degraded_when(fleet_agg.degraded)
            if telemetry is not None:
                telemetry.add_pre_scrape(fleet_agg.scan)

    def _beacon_write(step_now: int) -> None:
        if beacon is None:
            return
        try:
            beacon.write(step=step_now, **beacon_stats)
        except OSError:  # a shared-fs hiccup must not kill the run
            pass

    # hang watchdog (obs/hangwatch.py): beats ride the pre-step hook; a
    # wedged collective stops them, and at run.hangwatch_deadline_s the
    # watchdog journals the stall, drains the async checkpoint writer
    # (bounded), and exits EXIT_HANG — the elastic supervisor converts
    # that into a restart instead of an indefinite stall
    hangwatch = None
    if run.hangwatch_deadline_s > 0:
        hangwatch = HangWatchdog(
            run.hangwatch_deadline_s,
            exit_code=EXIT_HANG,
            drain=ckpt.wait,
        )

        @hangwatch.on_fire
        def _hang_fired(info):
            _emit("hang_detected", host=host_index, **info)
            # the stall the watchdog sat through is pure detection latency;
            # a final cumulative report makes it to the journal before the
            # os._exit — offline stitching reads it as this generation's
            # last word
            ledger.add("hang_latency", float(info.get("stalled_s") or 0.0))
            _emit(
                "goodput_report",
                **ledger.report(step=int(info.get("step") or 0), reason="hang"),
            )
            _beacon_write(int(info.get("step") or 0))
            if flightrec is not None:
                try:
                    flightrec.dump("hang_detected", extra=info)
                except Exception:  # noqa: BLE001 - already dying loudly
                    pass
            print(
                f"[train] HANG: no step progress for "
                f"{info['stalled_s']:.0f}s (deadline "
                f"{info['deadline_s']:.0f}s) — exiting {EXIT_HANG}"
            )

        hangwatch.start()
        print(
            f"[train] hang watchdog armed after step 1: deadline "
            f"{run.hangwatch_deadline_s:.0f}s -> exit {EXIT_HANG}"
        )

    def _hw_expected(reason: str):
        """Legitimately-slow phases (eval, rollback restore, checkpoint
        waits) suspend the step-deadline clock; the fleet.wedge fault and
        real collective stalls sit OUTSIDE every such window."""
        return (
            hangwatch.expected(reason)
            if hangwatch is not None
            else contextlib.nullcontext()
        )

    # resize-consistent resume: a checkpoint saved under a different world
    # size — or mid-override at the SAME world size — voids the sample-exact
    # cursor, but the journaled shard cursors reconstruct a shard-exact
    # assignment for this topology (no shard double-counted, none skipped —
    # tests/test_elastic.py)
    data_cursor, shard_override, shard_preconsumed = _apply_override_resume(
        cfg,
        run_dir,
        data_cursor,
        start_step,
        process_count=process_count,
        host_index=host_index,
        emit=_emit,
    )

    train_iter, source, cursor_log, shard_log = make_train_iterator(
        cfg, mesh, per_process, start_step, data_cursor,
        num_labels=enc_cfg.labels or 1000,
        shard_override=shard_override,
        shard_preconsumed=shard_preconsumed,
    )
    meter = AverageMeter()
    timer = StepTimer(warmup_steps=min(2, max(1, run.training_steps - 1)))
    n_chips = len(jax.devices())
    last_metrics: dict[str, float] = {}
    # divergence sentinel (faults/sentinel.py): the device guard inside the
    # step skips non-finite updates; this host half watches the fetched
    # metrics for bad streaks and drives rollback-to-last-checkpoint
    sentinel = (
        DivergenceSentinel(
            SentinelConfig(
                patience=run.sentinel_patience,
                spike_factor=run.sentinel_spike_factor,
                ema_beta=run.sentinel_ema_beta,
                max_rollbacks=run.sentinel_max_rollbacks,
            )
        )
        if run.sentinel
        else None
    )
    if sentinel is not None:
        # per-step sentinel verdicts into the journal with exact step
        # indices; the loop emits the richer rollback event itself
        sentinel.on_event = lambda kind, payload: (
            _emit(f"sentinel_{kind}", **payload)
            if kind != "rollback"
            else None
        )

    # step-loop telemetry: spans aggregate into span_seconds{name=...}; the
    # gauges publish the log-window derived numbers the logger prints.
    # train_step spans measure DISPATCH (the loop syncs only at log
    # boundaries); true step wall time is the steps_per_sec the MFU uses.
    reg = get_registry()
    g_mfu = reg.gauge("train_mfu", "model FLOP utilization (log-window)")
    g_ips = reg.gauge("train_images_per_sec", "global throughput (log-window)")
    g_wait_frac = reg.gauge(
        "train_data_wait_fraction", "share of wall time waiting on data"
    )
    g_step = reg.gauge("train_step", "current absolute step")
    g_grad_norm = reg.gauge(
        "train_grad_norm", "global gradient norm of the last fetched step"
    )
    c_steps = reg.counter("train_steps_total", "optimizer steps this process")
    g_hfu = reg.gauge(
        "train_hardware_flops_utilization",
        "XLA-counted flops (remat recompute included) / peak (log-window)",
    )
    g_gen = reg.gauge(
        "run_generation",
        "elastic supervisor generation of this process (0 = first launch)",
    )
    g_gen.set(generation)
    # compiled-cost observability: the AOT dispatch in train/steps exposes
    # the step's executable, so XLA's cost/memory analysis is a free readout
    # — no second compile. Extracted once at the first log boundary,
    # journaled, and folded into the MFU/HFU split + drift gauge below.
    step_cost = None  # None = not yet extracted, False = gave up
    chip = detect_chip()
    # memory observability (obs/memwatch.py): log-boundary device/host
    # samples + per-component byte accounting + the leak sentinel. The
    # fault ballast probe makes the injected host.leak chaos site show up
    # as a *named* component in the verdict, closing the loop the CI
    # mem-smoke asserts.
    memwatch = None
    leak_sentinel = None
    if run.memwatch:
        accountant = MemAccountant()
        accountant.register("fault_ballast", leak_ballast_bytes)
        if flightrec is not None:
            accountant.register("flightrec_ring", flightrec.ring_bytes)
        if journal is not None:
            accountant.register(
                "journal_file", lambda: journal.path.stat().st_size
            )
        memwatch = MemoryWatcher(accountant=accountant, chip=chip)
        leak_sentinel = LeakSentinel(
            window=run.memwatch_leak_window,
            min_growth_mb=run.memwatch_leak_mb,
        )
        health.probe("memory", memwatch.last_sample)
        health.degraded_when(leak_sentinel.degraded)
    sp_wait = span_timer("data_wait")
    sp_step = span_timer("train_step")
    sp_ckpt = span_timer("checkpoint_save")
    # liveness: a wedged collective / dead loader flips /healthz to 503 well
    # before an operator would spot a silent stall in the logs
    health.watch("train_step", max_age_s=3600.0)
    health.watch("data_batch", max_age_s=3600.0)
    if run.chrome_trace and is_main:
        start_chrome_trace()
    window_t0, window_wait = time.perf_counter(), 0.0
    window_steps = 0  # dispatches this log window (beacon step-time EMA)
    bad_total = 0  # cumulative sentinel-bad steps (beacon field)
    step_ema_s: float | None = None

    diag_pending: list = []  # [(step, device (G,3) stats)] fetched at log time
    prev_window_bad = False  # edge-trigger for the non-finite black box
    seen_quarantine: set = set()

    # -- the run engine (train/engine.py): the driver owns the step loop,
    # -- log-boundary metric fetch, rollback/preemption control flow, and
    # -- the crash/shutdown ladder; everything below registers into it ----
    def _next_batch(step_now: int):
        nonlocal window_wait
        with sp_wait:
            batch = next(train_iter)
        window_wait += sp_wait.last_s
        ledger.add("data_wait", sp_wait.last_s)
        health.beat("data_batch")
        return batch

    def _dispatch(state_now, batch, step_now: int):
        # fault sites train.loss / train.grad: traced multipliers into
        # the step (NaN at chosen invocations, no recompile); the
        # branch costs nothing when no plan is active
        inject = None
        if faults_active():
            # host.leak chaos site: corrupt(n) retains n MB/step in
            # the module ballast (the leak sentinel's test fixture);
            # a raise action models "the leak got fixed" and clears
            host_leak_tick(key=str(step_now))
            # fleet.wedge chaos site: delay(s) past the hangwatch
            # deadline holds THIS host's step outside any expected()
            # window — the watchdog, not the data path, must catch it
            fault_point("fleet.wedge", key=str(step_now), data=None)
            lm = fault_point("train.loss", key=str(step_now), data=1.0)
            gm = fault_point("train.grad", key=str(step_now), data=1.0)
            if (lm, gm) != (1.0, 1.0):
                inject = np.asarray([lm, gm], np.float32)
        if retrace_sentinel is not None:
            retrace_sentinel.note("train_step", batch)
        with sp_step:
            if inject is None:
                state_now, metrics = train_step(state_now, batch)
            elif retrace_sentinel is not None:
                # the inject arm is a distinct (legitimate)
                # executable — its first compile is not a retrace
                with retrace_sentinel.expected("fault-inject"):
                    state_now, metrics = train_step(state_now, batch, inject)
            else:
                state_now, metrics = train_step(state_now, batch, inject)
        # dispatch span → productive / compile (first dispatch) / rollback
        # recompute; the ledger routes by step number and process history
        ledger.note_step(step_now, sp_step.last_s)
        return state_now, metrics

    engine = RunEngine(
        training_steps=run.training_steps,
        start_step=start_step,
        log_interval=run.log_interval,
        eval_interval=run.eval_interval,
        ckpt_interval=run.ckpt_every,
        process_count=process_count,
        next_batch=_next_batch,
        dispatch=_dispatch,
        should_stop=lambda: _agree_on_preemption(preempt, process_count),
    )

    @engine.pre_step
    def _fleet_component(eng, step_now):
        # beacon BEFORE the data wait: under synchronous SPMD the
        # fetched step counts stay lockstep, but a host stuck waiting
        # on data sits at this step's entry while its peers dispatch
        # ahead — that dispatch gap is exactly what fleet_step_lag sees
        nonlocal window_steps
        _beacon_write(step_now)
        window_steps += 1
        if hangwatch is not None:
            hangwatch.beat(step_now)

    @engine.on_step
    def _telemetry_component(eng, ev):
        c_steps.inc()
        g_step.set(ev.step)
        health.beat("train_step")
        if ev.step == start_step + 1:
            # warmup over (first step compiled + dispatched): steady state
            if retrace_sentinel is not None:
                retrace_sentinel.arm()
            if hangwatch is not None:
                hangwatch.arm()

    @engine.on_step
    def _diag_component(eng, ev):
        if not diag_on:
            return
        # keep the (G,3) stats array OUT of the scalar pending list
        # (the meter/sentinel consume scalars); fetch it only at the
        # diag cadence — off-cadence arrays are dropped on device
        metrics = dict(ev.metrics)
        diag_dev = metrics.pop("diag")
        if ev.step % run.diag_every == 0 or ev.step == run.training_steps:
            diag_pending.append((ev.step, diag_dev))
        ev.metrics = metrics

    @engine.on_step
    def _pacing_component(eng, ev):
        timer.tick()
        # only cursor_log[step] (and prefetched future steps) are ever
        # read — prune dead entries every iteration, not just at save
        # time, or sparse checkpointing grows host memory without bound
        for k in [k for k in cursor_log if k < ev.step]:
            del cursor_log[k]
        for k in [k for k in shard_log if k < ev.step]:
            del shard_log[k]

    @engine.on_log_window
    def _log_window(eng, win):
        nonlocal step_cost, window_t0, window_wait, window_steps
        nonlocal bad_total, step_ema_s, prev_window_bad, last_metrics
        nonlocal seen_quarantine
        step = win.step
        window_bad: list[int] = []
        for (s, m) in win.fetched:
            skipped = float(m.get("skipped", 0.0)) >= 0.5
            loss_v = float(m.get("loss", math.nan))
            if skipped or not math.isfinite(loss_v):
                window_bad.append(s)
            gn = m.get("grad_norm")
            if gn is not None:
                g_grad_norm.set(float(gn))
            if flightrec is not None:
                entry = {"loss": loss_v}
                if gn is not None:
                    entry["grad_norm"] = float(gn)
                if "finite_frac" in m:
                    entry["finite_frac"] = float(m["finite_frac"])
                if skipped:
                    entry["skipped"] = True
                flightrec.record_step(s, entry)
            if sentinel is not None and sentinel.observe(s, m):
                eng.request_rollback()
            if not skipped:
                # a skipped step's loss is the garbage the guard
                # refused to apply — keep it out of the log means
                meter.update(m)
        win.bad_steps = window_bad
        # per-layer-group diagnostics: one small stacked array per
        # diag step, published as model_*{group=...} gauges
        latest_diag = None
        if diag_pending:
            for (ds, _), arr in zip(
                diag_pending,
                jax.device_get([a for _, a in diag_pending]),
            ):
                publish_group_stats(diag_names, arr)
                latest_diag = (ds, stats_dict(diag_names, arr), arr)
                if flightrec is not None:
                    flightrec.record_step(ds, {"diag": latest_diag[1]})
            diag_pending.clear()
        summary = meter.summary("train/")
        if step_cost is None:
            execs = getattr(train_step, "executables", None)
            if execs:
                cost = extract_cost(
                    next(iter(execs.values())), "train_step"
                )
                if cost is not None:
                    step_cost = cost
                    publish_cost(
                        cost,
                        bucket="",
                        dtype=cfg.model.overrides.get("dtype", ""),
                    )
                    _emit(
                        "compiled_program",
                        batch=run.train_batch_size,
                        **cost_asdict(cost),
                    )
                else:
                    step_cost = False  # backend reported nothing
        sps = timer.steps_per_sec
        if sps:
            imgs = sps * run.train_batch_size
            rep = mfu_report(flops_per_image, imgs / n_chips)
            summary |= {
                "perf/images_per_sec": imgs,
                "perf/images_per_sec_per_chip": imgs / n_chips,
                "perf/mfu": rep.mfu,
                "perf/tflops_per_chip": rep.achieved_tflops,
            }
            g_mfu.set(rep.mfu)
            g_ips.set(imgs)
            if step_cost:
                # MFU (analytic model flops) vs HFU (XLA-counted,
                # remat recompute included) + roofline drift
                util = utilization_report(
                    flops_per_image * run.train_batch_size,
                    step_cost.flops,
                    sps,
                    n_chips=n_chips,
                    peak_tflops=rep.peak_tflops,
                )
                pred = roofline(
                    step_cost.flops,
                    step_cost.bytes_accessed,
                    chip,
                    peak_hbm_bytes=step_cost.peak_bytes,
                )
                drift = publish_drift(
                    pred.step_time_s, 1.0 / sps, program="train_step"
                )
                summary |= {
                    "perf/model_flops_utilization": rep.mfu,
                    "perf/hardware_flops_utilization": (
                        util.hardware_flops_utilization
                    ),
                    "perf/predicted_step_ms": pred.step_time_s * 1e3,
                    "perf/predict_vs_measured": drift,
                }
                g_hfu.set(util.hardware_flops_utilization)
        now = time.perf_counter()
        wait_frac = window_wait / max(now - window_t0, 1e-9)
        g_wait_frac.set(wait_frac)
        ledger.publish()  # goodput_* gauges follow the log-window cadence
        # memory sample BEFORE the beacon write so this window's
        # rss/device-peak ride out in this window's beacon
        msnap = None
        if memwatch is not None:
            if step_cost:
                memwatch.record_predicted_peak(
                    "train_step", step_cost.peak_bytes
                )
            msnap = memwatch.sample()
            if "rss_bytes" in msnap:
                beacon_stats["rss_bytes"] = int(msnap["rss_bytes"])
            if "device_peak_bytes" in msnap:
                beacon_stats["device_peak_bytes"] = int(
                    msnap["device_peak_bytes"]
                )
            if "note" in msnap:
                print(f"[obs] {msnap['note']}")
        if beacon is not None:
            st = (now - window_t0) / max(window_steps, 1)
            step_ema_s = (
                st
                if step_ema_s is None
                else 0.5 * step_ema_s + 0.5 * st
            )
            bad_total += len(window_bad)
            beacon_stats.update(
                step_time_ema_s=round(step_ema_s, 4),
                data_wait_fraction=round(wait_frac, 4),
                shard_retries=int(
                    reg.counter(
                        "data_shard_retries_total",
                        "shard reads retried after a "
                        "transient failure",
                    ).value
                ),
                shard_quarantines=len(QUARANTINE.snapshot()),
                sentinel_bad_steps=bad_total,
                goodput_fraction=round(ledger.fraction(), 4),
            )
            _beacon_write(step)
            if fleet_agg is not None:
                fsum = None
                try:
                    fsum = fleet_agg.scan()
                except OSError:
                    pass
                if fsum and fsum.get("lost"):
                    # a peer's beacon went stale past dead_after_s: the
                    # next collective would block on it forever — exit
                    # EXIT_ELASTIC at the stop-safe boundary and let the
                    # supervisor relaunch at the surviving world size
                    eng.notify_host_lost(
                        {"hosts": fsum["lost"], "detected_by": "beacon"}
                    )
        window_t0, window_wait, window_steps = now, 0.0, 0
        logger.log(summary, step=step)
        last_metrics = summary
        win.summary = summary

        # durable step snapshot + newly quarantined shards
        if journal is not None or flightrec is not None:
            snap_ev = {
                "step": step,
                "metrics": summary,
                "data_wait_fraction": round(wait_frac, 4),
            }
            if window_bad:
                snap_ev["bad_steps"] = window_bad
            if latest_diag is not None:
                snap_ev["diag_step"] = latest_diag[0]
                snap_ev["diag"] = latest_diag[1]
            _emit("step", **snap_ev)
            new_q = set(QUARANTINE.snapshot()) - seen_quarantine
            if new_q:
                seen_quarantine |= new_q
                _emit("quarantine", shards=sorted(new_q))
        if msnap is not None:
            _emit(
                "mem_sample",
                step=step,
                **{k: v for k, v in msnap.items() if k != "ts"},
            )
            fired = (
                leak_sentinel.observe(msnap)
                if leak_sentinel is not None
                else None
            )
            if fired is not None:
                _emit("mem_leak_suspect", step=step, **fired)
                print(
                    "[obs] WARNING: leak sentinel fired — "
                    f"suspect {fired['component']} "
                    f"(+{fired['robust_growth_bytes'] // (1024 * 1024)}"
                    f" MiB robust growth over {fired['window']} "
                    "samples); /healthz degraded"
                )
                _black_box("mem_leak", **fired)
        # black box on the first bad window (edge-triggered: a long
        # NaN streak is one incident, not a dump per log boundary)
        if window_bad:
            if flightrec is not None:
                flightrec.mark_abnormal()
            if not prev_window_bad:
                grp = (
                    first_nonfinite_group(diag_names, latest_diag[2])
                    if latest_diag is not None
                    else None
                )
                _black_box(
                    "nonfinite_step",
                    bad_steps=window_bad,
                    first_nonfinite_group=grp,
                )
        prev_window_bad = bool(window_bad)

    @engine.on_rollback
    def _rollback(eng, step, win):
        # persistent divergence: restore the last checkpoint
        # (params + optimizer + RNG + data cursor) and continue
        # from there. Skipping alone can't fix a state that is
        # already bad — rewinding to a known-good one can.
        nonlocal train_iter, source, cursor_log, shard_log, prev_window_bad
        if ckpt.latest_step("last") is None:
            raise DivergenceError(
                f"training diverged at step {step} with no "
                "checkpoint to roll back to — lower the LR or "
                "set run.eval_interval below the failure point"
            )
        sentinel.record_rollback()  # raises once budget is spent
        t0_restore = time.perf_counter()
        with _hw_expected("rollback"):
            ckpt.wait()  # a save may still be in flight
            eng.state, extra = ckpt.restore(
                eng.state, sharding=state_sharding
            )
        ledger.add("ckpt_restore", time.perf_counter() - t0_restore)
        rolled_from, new_step = step, int(eng.state.step)
        # every step re-dispatched up to rolled_from is recompute, not
        # progress — lost work the goodput report makes visible
        ledger.note_rollback(rolled_from, new_step)
        print(
            f"[train] sentinel rollback #{sentinel.rollbacks} → "
            f"resuming from step {new_step}"
        )
        _emit(
            "rollback",
            from_step=rolled_from,
            to_step=new_step,
            rollbacks=sentinel.rollbacks,
            bad_steps=win.bad_steps,
        )
        # every rollback leaves a black box: the per-step ring
        # around the divergence, not just the fact of it
        _black_box(
            "sentinel_rollback",
            from_step=rolled_from,
            to_step=new_step,
            rollbacks=sentinel.rollbacks,
        )
        prev_window_bad = False  # restored stream starts clean
        if source is not None:
            source.close()
        # a rollback checkpoint saved mid-override carries the same
        # override_epoch marker a crash restart would see — re-derive
        # the stripe from the journal instead of replaying its offsets
        rb_cursor, rb_override, rb_preconsumed = _apply_override_resume(
            cfg,
            run_dir,
            extra.get("data_cursor"),
            new_step,
            process_count=process_count,
            host_index=host_index,
            emit=_emit,
        )
        with _hw_expected("rollback-restart"):
            train_iter, source, cursor_log, shard_log = make_train_iterator(
                cfg, mesh, per_process, new_step,
                rb_cursor,
                num_labels=enc_cfg.labels or 1000,
                shard_override=rb_override,
                shard_preconsumed=rb_preconsumed,
            )
        return new_step

    @engine.on_eval
    def _eval_component(eng, step, state_now):
        nonlocal last_metrics
        if valid_factory is None:
            return None
        t0_eval = time.perf_counter()
        with _hw_expected("eval"):
            if retrace_sentinel is not None:
                with retrace_sentinel.expected("eval"):
                    val = evaluate(
                        eval_step, state_now, valid_factory(), pad_batch
                    )
            else:
                val = evaluate(eval_step, state_now, valid_factory(), pad_batch)
        ledger.add("eval", time.perf_counter() - t0_eval)
        logger.log(val, step=step)
        last_metrics |= val
        return val

    def _emit_shard_cursor(step: int) -> None:
        # every host journals its consumed-shard ledger AT the
        # checkpointed step — the crash-safe, per-host cursor a future
        # resized resume merges (data/resize.py); no collective, so a
        # SIGKILL'd peer can't strand it
        shards = shard_log.get(step)
        if shards is not None:
            _emit("shard_cursor", step=step, world=process_count, **shards)

    @engine.on_checkpoint
    def _checkpoint_component(eng, cev):
        step = cev.step
        if cev.reason == "preemption":
            snap = _gather_data_cursor(cursor_log.get(step))
            with _hw_expected("checkpoint"), sp_ckpt:
                ckpt.save(
                    step,
                    eng.state,
                    extra={"data_cursor": snap} if snap is not None else None,
                )
            ledger.add("ckpt_save", sp_ckpt.last_s)
            _emit("checkpoint_save", step=step, preemption=True)
            _emit_shard_cursor(step)
            return
        snap = _gather_data_cursor(cursor_log.get(step))
        extra = {"data_cursor": snap} if snap is not None else None
        for k in [k for k in cursor_log if k <= step]:
            del cursor_log[k]
        with _hw_expected("checkpoint"), sp_ckpt:
            ckpt.save(step, eng.state, metrics=cev.metrics, extra=extra)
        cev.save_seconds = round(sp_ckpt.last_s, 3)
        ledger.add("ckpt_save", sp_ckpt.last_s)
        _emit(
            "checkpoint_save",
            step=step,
            eval_metrics=cev.metrics,
            save_seconds=cev.save_seconds,
        )
        # periodic cumulative attribution snapshot, one per committed
        # checkpoint — the offline stitcher keys lost work off these
        ledger.publish()
        _emit("goodput_report", **ledger.report(step=step))
        _emit_shard_cursor(step)
        for k in [k for k in shard_log if k <= step]:
            del shard_log[k]

    @engine.on_host_lost
    def _host_lost_component(eng, info):
        _emit("host_lost", step=eng.step, **info)
        _black_box("host_lost", step=eng.step, **info)

    @engine.on_crash
    def _crash_component(eng, exc):
        # the black box is most valuable exactly here: the run is dying and
        # the in-memory ring is about to vanish
        eng.exit_reason = (
            "diverged"
            if isinstance(exc, DivergenceError)
            else f"exception:{type(exc).__name__}"
        )
        if flightrec is not None:
            try:
                flightrec.dump(
                    "exception", extra={"error": f"{type(exc).__name__}: {exc}"}
                )
            except Exception:  # noqa: BLE001 - never mask the real failure
                pass

    @engine.on_shutdown
    def _drain_shutdown(eng, reason, step):
        # the watchdog stands down FIRST: a long final wait_until_finished
        # is a clean drain, not a hang. The drain itself runs on every
        # supervisor-visible exit path (SIGTERM preemption, host_lost,
        # crash) — an async Orbax save left in flight at process exit is
        # a torn step the next resume would have to walk back from.
        if hangwatch is not None:
            hangwatch.disarm()
            hangwatch.stop()
        try:
            ckpt.wait()
        except Exception as e:  # noqa: BLE001 - never mask the real failure
            print(f"[train] WARNING: checkpoint drain on shutdown failed: {e}")

    @engine.on_shutdown
    def _retrace_shutdown(eng, reason, step):
        if retrace_sentinel is not None:
            rsum = retrace_sentinel.summary()
            print(
                f"[train] retrace sentinel: {rsum['violations']} unexpected "
                f"recompile(s) after warmup "
                f"({rsum['compiles']} compiles seen, "
                f"{rsum['expected']} expected)"
            )
            retrace_sentinel.close()

    @engine.on_shutdown
    def _journal_shutdown(eng, reason, step):
        # final authoritative ledger word: covers the tail past the last
        # checkpoint and carries the exit reason
        ledger.publish()
        _emit("goodput_report", **ledger.report(step=step, reason=reason))
        _emit("shutdown", reason=reason, step=step)
        _beacon_write(step)  # final heartbeat: a clean exit is not a lost host
        if flightrec is not None:
            flightrec.uninstall()
        if journal is not None:
            journal.close()

    # continuous deployment (serve/publisher.py): gate-passing checkpoints
    # export int8/delta artifacts into the swap-watch dir the serving
    # tier polls; host 0 only (the export fetches the full tree to host)
    publisher = None
    if run.publish_dir and is_main:
        from jumbo_mae_tpu_tpu.serve.publisher import CheckpointPublisher

        publisher = CheckpointPublisher(
            run.publish_dir,
            quant=run.publish_quant,
            min_interval_steps=run.publish_min_interval_steps,
            full_every=run.publish_full_every,
            metric_key=run.publish_metric_key,
            metric_floor=run.publish_metric_floor,
            metric_sense=run.publish_metric_sense,
            emit=_emit,
        )
        publisher.register(engine)
        print(f"[publish] gated weights publisher -> {run.publish_dir}")

    try:
        with trace(run.profile_dir or None):
            engine.run(state)
    finally:
        state = engine.state

    ckpt.wait()
    ckpt.close()
    logger.close()
    if run.chrome_trace and is_main:
        print(f"[obs] chrome trace -> {export_chrome_trace(run.chrome_trace)}")
    if telemetry is not None:
        telemetry.close()
    if source is not None:
        source.close()
    # the exit reason rides the metrics dict so main() can map it onto the
    # supervisor exit-code protocol (host_lost -> EXIT_ELASTIC, ...)
    return {**last_metrics, "_exit_reason": engine.exit_reason}


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--config", type=str, default=None, help="YAML recipe path")
    parser.add_argument(
        "--set",
        dest="overrides",
        nargs="*",
        action="extend",
        default=[],
        help="dotted config overrides: optim.learning_rate=1e-3 "
        "(repeatable — `--set a=1 --set b=2` and `--set a=1 b=2` are "
        "equivalent; without extend, a repeated flag would silently "
        "drop the earlier overrides)",
    )
    parser.add_argument(
        "--distributed",
        action="store_true",
        help="call jax.distributed.initialize() (multi-host pods)",
    )
    parser.add_argument(
        "--coordinator",
        type=str,
        default=None,
        help="explicit coordinator address (host:port) for --distributed; "
        "needed off-TPU (e.g. the multi-process CPU fleet smoke) where "
        "auto-detection has no metadata server to ask",
    )
    parser.add_argument(
        "--num-processes",
        type=int,
        default=None,
        help="process count for --distributed with --coordinator",
    )
    parser.add_argument(
        "--process-id",
        type=int,
        default=None,
        help="this process's index for --distributed with --coordinator",
    )
    parser.add_argument(
        "--elastic",
        type=int,
        default=0,
        metavar="N",
        help="supervise N local training processes instead of training in "
        "this one: dead/wedged hosts trigger a budgeted relaunch from the "
        "last committed checkpoint at the surviving world size, with a "
        "rejoin back to N once the budget and timer allow "
        "(train/elastic.py; budgets under run.elastic_*)",
    )
    return parser


def _run_elastic(args) -> int:
    """``--elastic N``: run the :class:`ElasticSupervisor` over N child
    training processes on localhost. Each generation gets a fresh gloo
    coordinator port; every child is forced to ``run.resume=true`` so a
    relaunch continues from the last committed checkpoint (a fresh run
    simply finds no checkpoint). Returns the supervisor's exit code."""
    import socket
    import subprocess
    import sys

    from jumbo_mae_tpu_tpu.train.elastic import ElasticSupervisor

    cfg = load_config(args.config, args.overrides)
    run = cfg.run
    world = int(args.elastic)
    accum = max(1, run.grad_accum)

    def _world_ok(w: int) -> bool:
        # the child's own top-of-train validation: world * grad_accum must
        # divide the global batch size. The supervisor clamps any downsized
        # world through this, so a 4->3 resize can never relaunch children
        # that all die on the same config error until the budget is gone.
        return run.train_batch_size % (w * accum) == 0

    if not _world_ok(world):
        raise ValueError(
            f"--elastic {world} (x grad_accum {accum}) must divide "
            f"run.train_batch_size ({run.train_batch_size})"
        )
    run_dir = Path(run.output_dir) / run.name
    run_dir.mkdir(parents=True, exist_ok=True)
    # the supervisor shares host-0's journal DIRECTORY but owns a fresh
    # segment (RunJournal always opens max+1), so its role="supervisor"
    # rows interleave cleanly under read_merged_journal
    journal = RunJournal(run_dir / "journal") if run.journal else None

    def _free_port() -> int:
        s = socket.socket()
        try:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]
        finally:
            s.close()

    base = [sys.executable, "-m", "jumbo_mae_tpu_tpu.cli.train"]
    if args.config:
        base += ["--config", args.config]
    for ov in args.overrides or []:
        base += ["--set", ov]

    def launch(world_size: int, gen: int) -> list:
        port = _free_port()
        # children learn their generation from the environment (it is not
        # a config field): beacons, run_start events and the run_generation
        # gauge all stamp it, so merged journals distinguish pre- and
        # post-restart processes
        env = dict(os.environ, GRAFT_GENERATION=str(gen))
        procs = []
        for i in range(world_size):
            procs.append(
                subprocess.Popen(
                    base
                    + [
                        "--set",
                        "run.resume=true",
                        "--distributed",
                        "--coordinator",
                        f"127.0.0.1:{port}",
                        "--num-processes",
                        str(world_size),
                        "--process-id",
                        str(i),
                    ],
                    env=env,
                )
            )
        print(
            f"[elastic] generation {gen}: world={world_size} "
            f"on 127.0.0.1:{port} (pids {[p.pid for p in procs]})"
        )
        return procs

    sup = ElasticSupervisor(
        run_dir=run_dir,
        world_size=world,
        launch=launch,
        max_restarts=run.elastic_max_restarts,
        backoff_s=run.elastic_backoff_s,
        backoff_cap_s=run.elastic_backoff_cap_s,
        rejoin_after_s=run.elastic_rejoin_after_s,
        wedge_after_s=run.elastic_wedge_after_s,
        world_ok=_world_ok,
        journal=journal,
    )
    import signal

    def _stop(signum, frame):
        print(f"[elastic] caught signal {signum}: draining the fleet")
        sup.request_stop()

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, _stop)
    try:
        rc = sup.run()
    finally:
        if journal is not None:
            journal.close()
    print(f"[elastic] supervisor exiting {rc}")
    return rc


def main(argv: list[str] | None = None):
    args = build_parser().parse_args(argv)
    if args.elastic:
        raise SystemExit(_run_elastic(args))
    if args.distributed:
        if os.environ.get("JAX_PLATFORMS", "") == "cpu":
            # multi-process CPU (the CI fleet smoke): cross-process
            # collectives need the gloo backend, and the flag must land
            # before the first backend touch or XLA raises "Multiprocess
            # computations aren't implemented on the CPU backend"
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        if args.coordinator:
            jax.distributed.initialize(
                coordinator_address=args.coordinator,
                num_processes=args.num_processes,
                process_id=args.process_id,
            )
        else:
            jax.distributed.initialize()
    cfg = load_config(args.config, args.overrides)
    try:
        metrics = train(cfg)
    except DivergenceError as e:
        # deterministic failure: exit EXIT_FATAL so a supervisor does not
        # burn its restart budget re-proving the divergence
        print(f"[train] FATAL: {e}")
        raise SystemExit(EXIT_FATAL)
    reason = "completed"
    if isinstance(metrics, dict):
        reason = str(metrics.pop("_exit_reason", "completed"))
    print("[train] done:", metrics)
    code = exit_code_for(reason)
    if code != EXIT_OK:
        print(f"[train] exit reason {reason!r} -> exit code {code}")
        raise SystemExit(code)


if __name__ == "__main__":
    main()
