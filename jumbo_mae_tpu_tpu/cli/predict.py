"""Serving entry point: run any model head over images from the CLI.

The thin front end over ``jumbo_mae_tpu_tpu.infer`` — restore once, compile
per bucket once, then stream requests:

    # classification (finetune / linear-probe checkpoints)
    python -m jumbo_mae_tpu_tpu.cli.predict --config recipes/finetune_vit_b16.yaml \
        --ckpt runs/ft/ckpt --task logits --images cat.jpg dog.jpg --topk 5

    # frozen-encoder features
    python -m jumbo_mae_tpu_tpu.cli.predict --config recipes/linear_sgd_vit_b16.yaml \
        --ckpt runs/pretrain/ckpt --task features --pool cls \
        --images *.jpg --out feats.npz

    # MAE reconstruction (pretrain checkpoints)
    python -m jumbo_mae_tpu_tpu.cli.predict --config recipes/pretrain_vit_b16_in1k_1600ep.yaml \
        --ckpt runs/pretrain/ckpt --task reconstruct --images cat.jpg --out recon.npz

Files are resized + center-cropped by the eval transform (same geometry as
validation). ``--serve`` additionally routes the requests through the
micro-batching queue one image at a time — a single-process demo of the
serving path (``--max-delay-ms``/``--max-batch`` are the coalescing knobs);
the default path batches the whole file list directly. Results land in
``--out`` (``.npz``) and, for ``logits``, as one JSON line per image on
stdout.
"""

from __future__ import annotations

import argparse
import json
from pathlib import Path


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", default=None, help="YAML recipe path")
    p.add_argument(
        "--ckpt",
        default="",
        help="Orbax run/checkpoint dir, .msgpack params, or a published "
        "train→serve artifact dir (publish-NNNNNN); random init if omitted",
    )
    p.add_argument(
        "--task", choices=("features", "logits", "reconstruct"), default="logits"
    )
    p.add_argument(
        "--images", nargs="+", default=[], metavar="FILE", help="image files"
    )
    p.add_argument(
        "--synthetic",
        type=int,
        default=0,
        metavar="N",
        help="use N synthetic images instead of --images (smoke/bench)",
    )
    p.add_argument("--out", default="", help="output .npz path")
    p.add_argument("--pool", choices=("cls", "gap", "tokens"), default="cls")
    p.add_argument("--topk", type=int, default=5, help="logits: classes per line")
    p.add_argument("--seed", type=int, default=0, help="reconstruct: mask seed")
    p.add_argument("--max-batch", type=int, default=64)
    p.add_argument(
        "--max-delay-ms", type=float, default=5.0, help="--serve coalescing deadline"
    )
    p.add_argument(
        "--max-queue",
        type=int,
        default=None,
        metavar="N",
        help="--serve backpressure bound: submits beyond N pending "
        "requests shed with QueueFullError (default: unbounded)",
    )
    p.add_argument(
        "--deadline-ms",
        type=float,
        default=None,
        metavar="MS",
        help="--serve per-request deadline: a request still queued after "
        "MS fails with DeadlineExceededError instead of riding a batch",
    )
    p.add_argument(
        "--serve",
        action="store_true",
        help="submit images one-by-one through the micro-batching queue",
    )
    p.add_argument(
        "--replicas",
        type=int,
        default=0,
        metavar="N",
        help="--serve: run N supervised engine replicas behind the queue "
        "(crash-isolated request retry, restart with capped backoff, "
        "quorum circuit breaker in /healthz); 0 = the single-engine "
        "micro-batcher",
    )
    p.add_argument(
        "--interarrival-ms",
        type=float,
        default=0.0,
        metavar="MS",
        help="--serve: pace submits MS apart instead of firing them all at "
        "once (steady offered load for chaos and canary runs)",
    )
    p.add_argument(
        "--pack",
        action="store_true",
        help="--serve --replicas: token-packed dispatch — the continuous "
        "scheduler coalesces requests by TOKEN budget (mixed resolutions "
        "ride together) and each group runs through engine.predict_packed "
        "as one packed executable. features/logits only; --pool cls|gap",
    )
    p.add_argument(
        "--pack-budget",
        type=int,
        default=0,
        metavar="TOKENS",
        help="--pack: the scheduler's token fill target per dispatch group "
        "(0 = the engine's max_tokens default); the packer itself keeps "
        "rung headroom above this for flushes that merge groups",
    )
    p.add_argument(
        "--pack-resolutions",
        default="",
        metavar="SPEC",
        help="--pack --synthetic: seeded mixed-resolution traffic, e.g. "
        "'224:0.5,448:0.3,896:0.2' (size:weight; sizes must be "
        "patch-aligned and need posemb=sincos2d when non-native); "
        "default: every request at the native size",
    )
    p.add_argument(
        "--pack-parity-n",
        type=int,
        default=8,
        metavar="N",
        help="--pack: packed-vs-unpacked per-request parity gate over the "
        "first N requests before serving traffic (0 = skip); a failed "
        "gate aborts the run",
    )
    p.add_argument(
        "--tenants",
        default="",
        metavar="SPEC",
        help="--replicas: traffic shaping — weighted multi-tenant admission "
        "plus continuous batching and per-tenant cost metering. "
        "Comma-separated name=class[:rate=N][:burst=N][:budget=D][:window=W] "
        "entries (budget = device-seconds per window; classes: "
        "interactive|batch|scavenger); requests round-robin across tenants, "
        "low classes shed first under pressure, and the continuous "
        "scheduler coalesces late arrivals into pending batches",
    )
    p.add_argument(
        "--autoscale",
        default="",
        metavar="MIN:MAX",
        help="--replicas: reconcile the replica count between MIN and MAX "
        "from SLO burn rate, queue depth, and roofline capacity; "
        "scale-down drains the replica first (in-flight work is never "
        "killed) and every resize journals an autoscale event",
    )
    p.add_argument(
        "--autoscale-interval-s",
        type=float,
        default=1.0,
        help="--autoscale reconcile tick seconds",
    )
    p.add_argument(
        "--swap-watch",
        default="",
        metavar="DIR",
        help="--replicas: poll DIR for newly appearing checkpoint files or "
        "dirs and run each through the parity- and canary-gated weight "
        "hot-swap (promote on pass, automatic rollback on breach)",
    )
    p.add_argument(
        "--swap-poll-s",
        type=float,
        default=0.5,
        help="--swap-watch poll interval in seconds",
    )
    p.add_argument(
        "--swap-parity-min",
        type=float,
        default=0.98,
        help="hot-swap parity gate: min feature cosine of the candidate "
        "weights vs the live weights on the probe batch",
    )
    p.add_argument(
        "--swap-canary-requests",
        type=int,
        default=8,
        help="hot-swap canary window: live requests the flipped replica "
        "must serve before promotion",
    )
    p.add_argument(
        "--swap-canary-timeout-s",
        type=float,
        default=10.0,
        help="hot-swap canary window wall-clock bound",
    )
    p.add_argument(
        "--warmup",
        action="store_true",
        help="pre-compile every (task, bucket) executable before the first "
        "request, so request latencies measure serving, not compilation",
    )
    p.add_argument(
        "--access-log",
        default="",
        metavar="DIR",
        help="--serve: write a crash-safe JSONL access log (one row per "
        "finished request) into DIR; read it back with tools/serve_doctor.py",
    )
    p.add_argument(
        "--slo",
        default=None,
        metavar="SPEC",
        help="--serve SLO objectives, e.g. 'p99_latency_ms<=250;"
        "success_rate>=0.99' (default: run.slo from the recipe); breaches "
        "latch the degraded flag in /healthz and the slo_* gauges",
    )
    p.add_argument(
        "--slo-window-s",
        type=float,
        default=None,
        help="SLO rolling window seconds (default: run.slo_window_s)",
    )
    p.add_argument(
        "--slo-fast-window-s",
        type=float,
        default=None,
        help="SLO fast confirmation window seconds "
        "(default: run.slo_fast_window_s; 0 = window/12)",
    )
    p.add_argument(
        "--dtype",
        default=None,
        help="serving compute dtype override (e.g. float32 for the exact path)",
    )
    p.add_argument(
        "--quant",
        choices=("int8",),
        default=None,
        help="weight-only post-training quantization: int8 kernels with "
        "per-output-channel f32 scales, dequantized on use (embeddings, "
        "norms, biases stay f32)",
    )
    p.add_argument(
        "--warmcache",
        default=None,
        metavar="DIR",
        help="persistent executable cache directory (default: the per-host "
        "dir under ~/.cache/jumbo_mae_tpu/warmcache; restarted replicas "
        "load instead of compiling)",
    )
    p.add_argument(
        "--no-warmcache",
        action="store_true",
        help="disable the persistent executable cache for this run",
    )
    p.add_argument(
        "--encoder-cache",
        type=int,
        default=0,
        metavar="N",
        help="reconstruct: LRU-cache up to N encoder outputs keyed by "
        "(image bytes, seed) — repeated decode of the same image skips "
        "the encoder (shared mask mode only)",
    )
    p.add_argument(
        "--encoder-cache-mb",
        type=float,
        default=0.0,
        metavar="MB",
        help="byte bound on the encoder-output LRU on top of "
        "--encoder-cache: whichever cap trips first evicts "
        "(0 = entries-only)",
    )
    p.add_argument(
        "--metrics-port",
        type=int,
        default=None,
        metavar="PORT",
        help="serve Prometheus /metrics + /healthz on this port "
        "(0 = any free port, printed at startup; omit to disable)",
    )
    p.add_argument(
        "--metrics-hold-s",
        type=float,
        default=0.0,
        help="keep the exporter up N seconds after the requests finish "
        "(lets an external scraper read the final counters; CI smoke uses it)",
    )
    p.add_argument(
        "--set",
        dest="overrides",
        metavar="KEY.PATH=VALUE",
        nargs="*",
        action="extend",
        default=[],
        help="dotted config overrides, same grammar as cli.train",
    )
    return p


def main(argv: list[str] | None = None) -> Path | None:
    args = build_parser().parse_args(argv)

    import jax
    import numpy as np

    from jumbo_mae_tpu_tpu.config import load_config
    from jumbo_mae_tpu_tpu.infer import InferenceEngine, MicroBatcher

    if jax.process_count() > 1:
        raise SystemExit("predict is a single-process tool; run it on one host")
    if bool(args.images) == bool(args.synthetic):
        raise SystemExit("pass exactly one of --images or --synthetic N")

    cfg = load_config(args.config, args.overrides)

    telemetry = None
    health = None
    if args.metrics_port is not None:
        from jumbo_mae_tpu_tpu.obs import HealthState, TelemetryServer

        health = HealthState()  # not ready until the engine is constructed
        telemetry = TelemetryServer(health=health, port=args.metrics_port).start()
        print(f"[predict] exporter on :{telemetry.port} (/metrics, /healthz)")

    # memory observability (obs/memwatch.py): sampled per /metrics scrape —
    # device/host gauges, per-component byte accounting of the serving
    # caches, and the HBM predict-vs-measured drift per compiled executable
    memwatch = None
    mem_accountant = None
    if telemetry is not None and cfg.run.memwatch:
        from jumbo_mae_tpu_tpu.obs.memwatch import MemAccountant, MemoryWatcher
        from jumbo_mae_tpu_tpu.obs.perfmodel import detect_chip

        mem_accountant = MemAccountant()
        memwatch = MemoryWatcher(accountant=mem_accountant, chip=detect_chip())
        health.probe("memory", memwatch.last_sample)

    replicated = bool(args.serve and args.replicas > 0)
    if (args.tenants or args.autoscale) and not replicated:
        raise SystemExit("--tenants/--autoscale require --serve --replicas N")
    pack_mix: list[tuple[int, float]] | None = None
    if args.pack:
        if not replicated:
            raise SystemExit("--pack requires --serve --replicas N")
        if args.task not in ("features", "logits"):
            raise SystemExit(
                "--pack serves the encoder-sharing tasks: features|logits"
            )
        if args.pool == "tokens":
            raise SystemExit("--pack pools per segment: --pool cls or gap")
        if args.pack_resolutions:
            pack_mix = []
            for part in args.pack_resolutions.split(","):
                s, _, w = part.partition(":")
                pack_mix.append((int(s), float(w or 1.0)))
    elif args.pack_resolutions:
        raise SystemExit("--pack-resolutions requires --pack")
    # restarts and promoted swaps read the checkpoint through this cell,
    # so a replica rebuilt after a promote comes up on the new weights
    ckpt_ref = {"ckpt": args.ckpt}

    # retrace sentinel (obs/retrace.py): once warmup has pre-compiled the
    # serving executables, the serve loop must be compile-free — armed
    # after the first served batch, every further XLA compile warns with
    # shape/dtype-diff attribution and counts into retrace_events_total
    retrace_sentinel = None
    if args.serve and args.warmup and cfg.run.retrace:
        from jumbo_mae_tpu_tpu.obs.retrace import RetraceSentinel

        retrace_sentinel = RetraceSentinel("predict")

    def make_engine():
        return InferenceEngine(
            cfg,
            ckpt=ckpt_ref["ckpt"],
            dtype=args.dtype,
            max_batch=args.max_batch,
            # the packer's rung ceiling, kept ABOVE the scheduler's fill
            # target (--pack-budget): a busy replica merges consecutive
            # dispatch groups into one flush, and rungs capped at the fill
            # target would force pow2-row padding on those merged flushes
            **(
                {"max_tokens": max(args.pack_budget, 4096)}
                if args.pack_budget
                else {}
            ),
            quant=args.quant,
            warm_cache=(
                False if args.no_warmcache
                else args.warmcache if args.warmcache is not None
                else True
            ),
            encoder_cache=args.encoder_cache,
            encoder_cache_bytes=int(args.encoder_cache_mb * 1024 * 1024),
        )

    if args.ckpt == "":
        print("[predict] WARNING: no --ckpt — serving a random init")
    engine = None
    if not replicated:
        engine = make_engine()
        if engine.warmcache is not None:
            print(f"[predict] warmcache: {engine.warmcache.root}")
        if args.warmup:
            n_compiles = engine.warmup((args.task,), pool=args.pool)
            hits = sum(engine.warm_hits.values())
            print(
                f"[predict] warmup: {n_compiles} executable(s) compiled, "
                f"{hits} loaded from warmcache"
            )
    if memwatch is not None and engine is not None:
        mem_accountant.register("engine_enc_cache", engine.encoder_cache_bytes)
        mem_accountant.register(
            "engine_exec_cache", engine.executable_cache_bytes
        )
        if engine.warmcache is not None:
            mem_accountant.register(
                "warmcache_disk", engine.warmcache.disk_bytes
            )

        def _sync_predicted_peaks(eng=engine):
            # executables compile lazily on the request path too — refresh
            # the prediction side of the drift gauge before every scrape
            for prog, peak in eng.predicted_peak_hbm().items():
                memwatch.record_predicted_peak(prog, peak)

        telemetry.add_pre_scrape(_sync_predicted_peaks)
        telemetry.add_pre_scrape(memwatch.sample)
    if health is not None and not replicated:
        health.set_ready(
            True, detail=f"engine up (ckpt={'yes' if args.ckpt else 'random'})"
        )

    # request observability (obs/reqtrace.py, obs/slo.py) rides the serving
    # path only — the direct batch path stays telemetry-free
    tracer = None
    slo_tracker = None
    if args.serve:
        from jumbo_mae_tpu_tpu.obs import (
            AccessLog,
            RequestTracer,
            SLOTracker,
            parse_slo,
        )

        slo_spec = args.slo if args.slo is not None else cfg.run.slo
        if slo_spec:
            slo_tracker = SLOTracker(
                parse_slo(slo_spec),
                window_s=(
                    args.slo_window_s
                    if args.slo_window_s is not None
                    else cfg.run.slo_window_s
                ),
                fast_window_s=(
                    args.slo_fast_window_s
                    if args.slo_fast_window_s is not None
                    else cfg.run.slo_fast_window_s
                ),
                burn_threshold=cfg.run.slo_burn_threshold,
            )
            print(
                f"[predict] SLO: {slo_spec} over "
                f"{slo_tracker.window_s:g}s/{slo_tracker.fast_window_s:g}s windows"
            )
        access = AccessLog(args.access_log) if args.access_log else None
        if access is not None:
            print(f"[predict] access log -> {access.path}")
        if access is not None or slo_tracker is not None or telemetry is not None:
            tracer = RequestTracer(
                access_log=access,
                # replicated: each flush passes its own engine's breakdown
                breakdown=engine.last_breakdown if engine is not None else None,
                on_finish=(
                    slo_tracker.observe_trace if slo_tracker is not None else None
                ),
            )
        if slo_tracker is not None:
            if health is not None:
                health.degraded_when(slo_tracker.degraded)
                health.probe("slo", slo_tracker.healthz_info)
            if telemetry is not None:
                telemetry.add_pre_scrape(slo_tracker.evaluate)

    rs = None
    swap_ctl = None
    if replicated:
        from jumbo_mae_tpu_tpu.infer import ReplicaSet, WeightSwapController

        def _warm(eng):
            if not args.warmup:
                return
            if args.pack:
                # warm the per-resolution embed stages + the packed
                # executable the representative mix's plan lands on
                res_list = (
                    [s for s, _ in pack_mix] if pack_mix else [eng.image_size]
                )
                eng.warmup_packed(res_list, (args.task,), pool=args.pool)
            else:
                eng.warmup((args.task,), pool=args.pool)

        def engine_provider(idx):
            # a (re)built replica compiles its own executables — during
            # chaos restarts that happens while the sentinel is armed, and
            # it is legitimate, not a retrace
            if retrace_sentinel is not None:
                with retrace_sentinel.expected("replica build"):
                    eng = make_engine()
                    _warm(eng)
                    return eng
            eng = make_engine()
            _warm(eng)
            return eng

        def run_replica(eng, batch, metas):
            def _go():
                if args.pack:
                    # batch is the raw image list for mixed shapes (see
                    # ReplicaSet._flush); one packed dispatch serves it
                    return eng.predict_packed(
                        list(batch), args.task, pool=args.pool
                    )
                return eng.predict(batch, task=args.task, **kw)

            if retrace_sentinel is None:
                return _go()
            retrace_sentinel.note("replica_batch", batch)
            out = _go()
            retrace_sentinel.arm()  # first batch served: steady state
            return out

        rs = ReplicaSet(
            engine_provider,
            run_replica,
            replicas=args.replicas,
            max_batch=args.max_batch,
            max_delay_ms=args.max_delay_ms,
            max_queue=args.max_queue,
            tracer=tracer,
            task=args.task,
            health=health,
            breakdown=lambda eng: eng.last_breakdown(),
        )
        eng0 = rs.replica(0).engine
        if eng0.warmcache is not None:
            print(f"[predict] warmcache: {eng0.warmcache.root}")
        if memwatch is not None:
            # per-replica accounting: probes resolve the CURRENT engine at
            # sample time, so restarted/rebuilt replicas stay accounted
            for i in range(args.replicas):
                mem_accountant.register(
                    f"replica{i}_enc_cache",
                    lambda i=i: rs.replica(i).engine.encoder_cache_bytes(),
                )
                mem_accountant.register(
                    f"replica{i}_exec_cache",
                    lambda i=i: rs.replica(i).engine.executable_cache_bytes(),
                )
            if eng0.warmcache is not None:
                mem_accountant.register(
                    "warmcache_disk",
                    lambda: rs.replica(0).engine.warmcache.disk_bytes(),
                )

            def _sync_replica_peaks():
                for i in range(args.replicas):
                    try:
                        peaks = rs.replica(i).engine.predicted_peak_hbm()
                    except Exception:  # noqa: BLE001 — replica mid-restart
                        continue
                    for prog, peak in peaks.items():
                        memwatch.record_predicted_peak(prog, peak)

            telemetry.add_pre_scrape(_sync_replica_peaks)
            telemetry.add_pre_scrape(memwatch.sample)
        print(
            f"[predict] replica pool: {args.replicas} replicas, "
            f"quorum {rs.quorum}"
        )
        if health is not None:
            health.set_ready(True, detail=f"pool up ({args.replicas} replicas)")
            if slo_tracker is not None:
                health.degraded_when(
                    lambda: slo_tracker.degraded() or rs.degraded()
                )
            else:
                health.degraded_when(rs.degraded)
        if args.swap_watch:

            def _swap_restore(path):
                # publish artifacts (serve/publisher.py) resolve their
                # delta chain with fingerprint verification; anything else
                # takes the plain checkpoint restore path
                from jumbo_mae_tpu_tpu.serve.publisher import (
                    is_publish_artifact,
                    resolve_chain,
                )

                if is_publish_artifact(path):
                    params, stats, _ = resolve_chain(path)
                    return params, stats
                from jumbo_mae_tpu_tpu.train.checkpoint import (
                    restore_inference_state,
                )

                return restore_inference_state(path, to_device=False)

            swap_ctl = WeightSwapController(
                rs,
                restore_fn=_swap_restore,
                parity_min_cosine=args.swap_parity_min,
                canary_requests=args.swap_canary_requests,
                canary_timeout_s=args.swap_canary_timeout_s,
                on_promote=lambda c: ckpt_ref.__setitem__("ckpt", c),
                # refuse a push the double-buffered restore cannot fit:
                # rejected at the "headroom" stage before any replica flips
                headroom_fn=(
                    memwatch.headroom_check if memwatch is not None else None
                ),
            )
        engine = eng0  # image geometry below; requests go through the pool

    size = engine.image_size
    if args.synthetic:
        if pack_mix:
            # seeded mixed-resolution traffic: same seed, same trace —
            # the packed-vs-bucketed A/B compares like against like
            rs_img = np.random.RandomState(0)
            sizes = [s for s, _ in pack_mix]
            w = np.array([max(wt, 0.0) for _, wt in pack_mix], np.float64)
            w /= w.sum()
            picks = rs_img.choice(len(sizes), size=args.synthetic, p=w)
            images = [
                rs_img.randint(
                    0, 256, (sizes[c], sizes[c], 3)
                ).astype(np.uint8)
                for c in picks
            ]
            names = [
                f"synthetic[{i}]@{im.shape[0]}" for i, im in enumerate(images)
            ]
        else:
            images = (
                np.random.RandomState(0)
                .randint(0, 256, (args.synthetic, size, size, 3))
                .astype(np.uint8)
            )
            names = [f"synthetic[{i}]" for i in range(args.synthetic)]
    else:
        from PIL import Image

        from jumbo_mae_tpu_tpu.data.transforms import eval_transform

        images = np.stack(
            [
                eval_transform(
                    np.asarray(Image.open(f).convert("RGB"), np.uint8),
                    size,
                    crop_ratio=cfg.data.test_crop_ratio,
                )
                for f in args.images
            ]
        )
        names = list(args.images)

    kw = {"pool": args.pool} if args.task == "features" else (
        {"seed": args.seed} if args.task == "reconstruct" else {}
    )
    if args.serve and rs is not None:
        import threading
        import time as _time

        if slo_tracker is not None:
            slo_tracker.add_probe(
                "queue_depth", lambda: rs.stats()["queue_depth"]
            )
            slo_tracker.add_probe(
                "healthy_replicas", lambda: rs.stats()["healthy"]
            )
            slo_tracker.add_probe(
                "batch_occupancy", lambda: rs.stats()["batch_occupancy"]
            )
        # traffic shaping (jumbo_mae_tpu_tpu/serve): tenant-weighted
        # admission + continuous batching in front of the pool
        sched = None
        admission = None
        tenant_names: list[str] = []
        meter = None
        if args.tenants:
            from jumbo_mae_tpu_tpu.serve import (
                AdmissionController,
                CostMeter,
                parse_tenants,
            )

            tenant_specs = parse_tenants(args.tenants)
            tenant_names = [t.name for t in tenant_specs]
            # meter every dispatched batch: per-tenant device-seconds/FLOPs
            # ledgers feed serve_tenant_* metrics, tenant_usage journal
            # rows, the access log's device_ms/cost_flops columns, and the
            # budget= checks below
            meter = CostMeter(tenant_specs, tracer=tracer)
            rs.set_costmeter(meter)
            admission = AdmissionController(tenant_specs, meter=meter)
            print(
                "[predict] traffic shaping: "
                + ", ".join(f"{t.name}={t.tclass}" for t in tenant_specs)
            )
        if args.tenants or args.pack:
            from jumbo_mae_tpu_tpu.serve import ContinuousScheduler

            # the scheduler's accumulator becomes the admission-visible
            # queue; give the pool headroom above it so a dispatched group
            # doesn't race the pool's own hard cap and shed an
            # already-admitted interactive request
            if rs.max_queue is not None:
                rs.max_queue = rs.max_queue + 2 * args.max_batch
            pack_budget = args.pack_budget or engine.max_tokens
            sched = ContinuousScheduler(
                rs.submit_group,
                max_batch=args.max_batch,
                max_delay_ms=args.max_delay_ms,
                max_queue=args.max_queue,
                admission=admission,
                tracer=tracer,
                task=args.task,
                packed=args.pack,
                token_budget=pack_budget if args.pack else None,
                seq_len_fn=(
                    (lambda arr: engine.seq_len(arr.shape[0]))
                    if args.pack
                    else None
                ),
            )
            if args.pack:
                print(
                    f"[predict] token packing: budget={pack_budget} "
                    f"tokens/dispatch, pool={args.pool}"
                )
            # combined pressure: scheduler accumulator OR pool backlog —
            # either filling sheds low classes before interactive traffic
            # hits a hard queue-full
            if admission is not None:
                admission.set_pressure_fn(
                    lambda: max(sched.pressure(), rs.pressure())
                )
        autoscaler = None
        if args.autoscale:
            from jumbo_mae_tpu_tpu.serve import Autoscaler, roofline_capacity

            try:
                lo, hi = (int(x) for x in args.autoscale.split(":"))
            except ValueError:
                raise SystemExit("--autoscale expects MIN:MAX, e.g. 2:6")
            # roofline capacity estimate for the serving bucket: forward
            # FLOPs per image + the coarse activation-traffic bytes model
            capacity_fn = None
            enc_cfg = getattr(engine, "_enc", None)
            if enc_cfg is not None:
                from jumbo_mae_tpu_tpu.obs.mfu import encoder_flops_per_image

                flops = encoder_flops_per_image(enc_cfg, masked=False)
                act_bytes = 2.0 * flops / max(enc_cfg.dim, 1)
                capacity_fn = lambda: roofline_capacity(flops, act_bytes)  # noqa: E731
            autoscaler = Autoscaler(
                rs,
                min_replicas=lo,
                max_replicas=hi,
                interval_s=args.autoscale_interval_s,
                slo=slo_tracker,
                capacity_fn=capacity_fn,
                tracer=tracer,
            )
            print(
                f"[predict] autoscaler: [{lo}, {hi}] replicas, "
                f"tick {args.autoscale_interval_s:g}s"
            )
        swap_stop = threading.Event()
        swap_thread = None
        if swap_ctl is not None:
            import os

            from jumbo_mae_tpu_tpu.obs.metrics import get_registry

            watch_root = Path(args.swap_watch)
            watch_root.mkdir(parents=True, exist_ok=True)
            c_quarantined = get_registry().counter(
                "serve_publish_quarantined_total",
                "publish artifacts the swap watcher quarantined before restore",
            )

            def _quarantine_artifact(p):
                # a torn/poisoned publish artifact is evidence, not trash:
                # move it aside (atomic, same filesystem) so the doctor can
                # autopsy it and the watcher never retries it
                qdir = watch_root / ".quarantine"
                try:
                    qdir.mkdir(exist_ok=True)
                    os.replace(p, qdir / p.name)
                except OSError:
                    pass  # leave it in place; `seen` already skips it
                c_quarantined.inc()

            def _watch_swaps():
                from jumbo_mae_tpu_tpu.serve.publisher import (
                    PublishIntegrityError,
                    is_publish_artifact,
                    verify_artifact,
                )

                # entries present at startup are the baseline, not pushes;
                # push checkpoints by atomic rename so a partial write
                # never gets picked up
                seen = {p.name for p in watch_root.iterdir()}
                while True:
                    stopping = swap_stop.is_set()
                    for p in sorted(watch_root.iterdir()):
                        if p.name in seen or p.name.startswith("."):
                            continue
                        seen.add(p.name)
                        print(f"[predict] swap-watch: new checkpoint {p}")
                        if is_publish_artifact(p):
                            # manifest fingerprint check BEFORE any bytes
                            # reach a restore: torn or corrupted artifacts
                            # are quarantined, never crash the watcher
                            try:
                                verify_artifact(p)
                            except PublishIntegrityError as e:
                                print(
                                    f"[predict] swap {p.name}: "
                                    f"verdict=quarantined stage=verify ({e})"
                                )
                                _quarantine_artifact(p)
                                continue
                        rep = swap_ctl.swap(str(p))
                        msg = (
                            f"[predict] swap {p.name}: "
                            f"verdict={rep['verdict']} stage={rep['stage']}"
                        )
                        if rep.get("parity"):
                            msg += (
                                f" cosine_min="
                                f"{rep['parity']['cosine_min']:.4f}"
                            )
                        print(msg)
                    if stopping:
                        return  # one final sweep ran after stop was set
                    swap_stop.wait(args.swap_poll_s)

            swap_thread = threading.Thread(target=_watch_swaps, daemon=True)
            swap_thread.start()
            print(
                f"[predict] swap-watch: polling {watch_root} "
                f"every {args.swap_poll_s:g}s"
            )
        if args.pack and args.pack_parity_n > 0:
            # correctness gate before traffic: every packed output must
            # match its own unpacked forward (cosine / top-1 agreement)
            par = engine.packed_parity(
                list(images[: args.pack_parity_n]),
                args.task,
                pool=args.pool,
            )
            cos = par["feature_cosine_min"]
            t1 = par["logits_top1_agree"]
            print(
                f"[predict] pack parity: pass={par['pass']} n={par['n']} "
                f"cosine_min={'-' if cos is None else format(cos, '.6f')} "
                f"top1_agree={'-' if t1 is None else format(t1, '.4f')}"
            )
            if not par["pass"]:
                raise SystemExit("[predict] pack parity gate FAILED")
        futs = []
        shed = 0
        for i, img in enumerate(images):
            try:
                if sched is not None:
                    futs.append(
                        sched.submit(
                            img,
                            deadline_ms=args.deadline_ms,
                            tenant=(
                                tenant_names[i % len(tenant_names)]
                                if tenant_names
                                else None
                            ),
                        )
                    )
                else:
                    futs.append(rs.submit(img, deadline_ms=args.deadline_ms))
            except Exception as e:  # noqa: BLE001 — admission sheds are tallied, not fatal
                shed += 1
                futs.append(None)
                print(f"[predict] request shed: {type(e).__name__}: {e}")
            if args.interarrival_ms > 0:
                _time.sleep(args.interarrival_ms / 1000.0)
        rows, failed = [], shed
        for f in futs:
            if f is None:
                rows.append(None)
                continue
            try:
                rows.append(f.result())
            except Exception as e:  # noqa: BLE001 — typed failures are tallied, not fatal
                failed += 1
                rows.append(None)
                print(f"[predict] request failed: {type(e).__name__}: {e}")
        print(
            f"[predict] pool served {len(rows) - failed}/{len(rows)} ok "
            f"({failed} failed)"
        )
        if swap_thread is not None:
            swap_stop.set()
            swap_thread.join(timeout=args.swap_canary_timeout_s + 60.0)
        if autoscaler is not None:
            autoscaler.close()
            print(f"[predict] autoscale events: {len(autoscaler.events)}")
        if sched is not None:
            sched.close()
            if args.pack:
                st = sched.stats()
                print(
                    f"[predict] pack stats: dispatched={st['dispatched']} "
                    f"batches={st['batches']} expired={st['expired']}"
                )
            if admission is not None:
                print(f"[predict] admission: {json.dumps(admission.stats())}")
        if meter is not None:
            meter.flush()  # final tenant_usage rows before the log closes
            bill = meter.snapshot()
            costs = ", ".join(
                f"{t}={b['device_s']:.3f}s"
                for t, b in bill["tenants"].items()
            )
            print(
                f"[predict] tenant cost: {costs} "
                f"(total {bill['total_device_s']:.3f} device-s, "
                f"{bill['total_batches']} batches)"
            )
        st = rs.stats()
        print(f"[predict] replicas: {json.dumps(st['replicas'])}")
        rs.close()
        kept = [(n, r) for n, r in zip(names, rows) if r is not None]
        if not kept:
            raise SystemExit("[predict] every request failed")
        names = [n for n, _ in kept]
        rows = [r for _, r in kept]
        out = (
            {k: np.stack([r[k] for r in rows]) for k in rows[0]}
            if isinstance(rows[0], dict)
            else np.stack(rows)
        )
        if slo_tracker is not None:
            rep = slo_tracker.evaluate()
            objs = "; ".join(
                f"{o['name']}: value={o['value']:g} "
                f"burn={o['burn_slow']:g} breached={o['breached']}"
                for o in rep["objectives"]
            )
            print(
                f"[predict] SLO verdict: degraded={rep['degraded']} "
                f"shed_rate={rep['shed_rate']:g} — {objs}"
            )
            if tracer is not None:
                tracer.event("slo_summary", report=rep)
        if tracer is not None:
            tracer.close()
    elif args.serve:
        def run_fn(batch):
            if health is not None:
                health.beat("infer_batch")
            if retrace_sentinel is None:
                return engine.predict(batch, task=args.task, **kw)
            retrace_sentinel.note("serve_batch", batch)
            out = engine.predict(batch, task=args.task, **kw)
            retrace_sentinel.arm()  # first batch served: steady state
            return out

        with MicroBatcher(
            run_fn,
            max_batch=args.max_batch,
            max_delay_ms=args.max_delay_ms,
            max_queue=args.max_queue,
            tracer=tracer,
            task=args.task,
        ) as mb:
            if health is not None:
                # live autoscaler snapshot (queue depth / occupancy / shed
                # rate) in the /healthz info payload while serving
                health.probe("serving", mb.stats)
            if mem_accountant is not None:
                mem_accountant.register(
                    "batcher_queue", lambda: mb.stats()["queue_bytes"]
                )
            if slo_tracker is not None:
                # ...and the same signals as slo_* gauges per scrape
                slo_tracker.add_probe(
                    "queue_depth", lambda: mb.stats()["queue_depth"]
                )
                slo_tracker.add_probe(
                    "batch_occupancy", lambda: mb.stats()["batch_occupancy"]
                )
            rows = [
                f.result()
                for f in [
                    mb.submit(img, deadline_ms=args.deadline_ms)
                    for img in images
                ]
            ]
        out = (
            {k: np.stack([r[k] for r in rows]) for k in rows[0]}
            if isinstance(rows[0], dict)
            else np.stack(rows)
        )
        print(f"[predict] micro-batch sizes: {mb.batch_sizes}")
        if slo_tracker is not None:
            rep = slo_tracker.evaluate()
            objs = "; ".join(
                f"{o['name']}: value={o['value']:g} "
                f"burn={o['burn_slow']:g} breached={o['breached']}"
                for o in rep["objectives"]
            )
            print(
                f"[predict] SLO verdict: degraded={rep['degraded']} "
                f"shed_rate={rep['shed_rate']:g} — {objs}"
            )
            if tracer is not None:
                tracer.event("slo_summary", report=rep)
        if tracer is not None:
            tracer.close()
    else:
        out = engine.predict(images, task=args.task, **kw)

    if args.task == "logits":
        probs = np.exp(out - out.max(-1, keepdims=True))
        probs /= probs.sum(-1, keepdims=True)
        k = min(args.topk, out.shape[-1])
        for name, p_row in zip(names, probs):
            top = np.argsort(-p_row)[:k]
            print(
                json.dumps(
                    {
                        "image": name,
                        "classes": top.tolist(),
                        "probs": [round(float(p_row[i]), 6) for i in top],
                    }
                )
            )
    payload = out if isinstance(out, dict) else {args.task: out}
    result: Path | None = None
    if args.out:
        result = Path(args.out)
        result.parent.mkdir(parents=True, exist_ok=True)
        np.savez(result, **payload)
        print(f"[predict] wrote {args.task} for {len(names)} image(s) -> {result}")
    if retrace_sentinel is not None:
        rsum = retrace_sentinel.summary()
        print(
            f"[predict] retrace sentinel: {rsum['violations']} unexpected "
            f"recompile(s) after warmup ({rsum['compiles']} compiles seen, "
            f"{rsum['expected']} expected)"
        )
        retrace_sentinel.close()
    if telemetry is not None:
        if args.metrics_hold_s > 0:
            import time

            print(
                f"[predict] holding exporter for {args.metrics_hold_s:g}s "
                f"(scrape :{telemetry.port}/metrics)"
            )
            time.sleep(args.metrics_hold_s)
        telemetry.close()
    return result


if __name__ == "__main__":
    main()
