"""Serving entry point: run any model head over images from the CLI.

The thin front end over ``jumbo_mae_tpu_tpu.infer`` — restore once, compile
per bucket once, then stream requests:

    # classification (finetune / linear-probe checkpoints)
    python -m jumbo_mae_tpu_tpu.cli.predict --config recipes/finetune_vit_b16.yaml \
        --ckpt runs/ft/ckpt --task logits --images cat.jpg dog.jpg --topk 5

    # frozen-encoder features
    python -m jumbo_mae_tpu_tpu.cli.predict --config recipes/linear_sgd_vit_b16.yaml \
        --ckpt runs/pretrain/ckpt --task features --pool cls \
        --images *.jpg --out feats.npz

    # MAE reconstruction (pretrain checkpoints)
    python -m jumbo_mae_tpu_tpu.cli.predict --config recipes/pretrain_vit_b16_in1k_1600ep.yaml \
        --ckpt runs/pretrain/ckpt --task reconstruct --images cat.jpg --out recon.npz

Files are resized + center-cropped by the eval transform (same geometry as
validation). ``--serve`` additionally routes the requests through the
micro-batching queue one image at a time — a single-process demo of the
serving path (``--max-delay-ms``/``--max-batch`` are the coalescing knobs);
the default path batches the whole file list directly. Results land in
``--out`` (``.npz``) and, for ``logits``, as one JSON line per image on
stdout.
"""

from __future__ import annotations

import argparse
import json
from pathlib import Path


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", default=None, help="YAML recipe path")
    p.add_argument(
        "--ckpt",
        default="",
        help="Orbax run/checkpoint dir or .msgpack params; random init if omitted",
    )
    p.add_argument(
        "--task", choices=("features", "logits", "reconstruct"), default="logits"
    )
    p.add_argument(
        "--images", nargs="+", default=[], metavar="FILE", help="image files"
    )
    p.add_argument(
        "--synthetic",
        type=int,
        default=0,
        metavar="N",
        help="use N synthetic images instead of --images (smoke/bench)",
    )
    p.add_argument("--out", default="", help="output .npz path")
    p.add_argument("--pool", choices=("cls", "gap", "tokens"), default="cls")
    p.add_argument("--topk", type=int, default=5, help="logits: classes per line")
    p.add_argument("--seed", type=int, default=0, help="reconstruct: mask seed")
    p.add_argument("--max-batch", type=int, default=64)
    p.add_argument(
        "--max-delay-ms", type=float, default=5.0, help="--serve coalescing deadline"
    )
    p.add_argument(
        "--max-queue",
        type=int,
        default=None,
        metavar="N",
        help="--serve backpressure bound: submits beyond N pending "
        "requests shed with QueueFullError (default: unbounded)",
    )
    p.add_argument(
        "--deadline-ms",
        type=float,
        default=None,
        metavar="MS",
        help="--serve per-request deadline: a request still queued after "
        "MS fails with DeadlineExceededError instead of riding a batch",
    )
    p.add_argument(
        "--serve",
        action="store_true",
        help="submit images one-by-one through the micro-batching queue",
    )
    p.add_argument(
        "--warmup",
        action="store_true",
        help="pre-compile every (task, bucket) executable before the first "
        "request, so request latencies measure serving, not compilation",
    )
    p.add_argument(
        "--access-log",
        default="",
        metavar="DIR",
        help="--serve: write a crash-safe JSONL access log (one row per "
        "finished request) into DIR; read it back with tools/serve_doctor.py",
    )
    p.add_argument(
        "--slo",
        default=None,
        metavar="SPEC",
        help="--serve SLO objectives, e.g. 'p99_latency_ms<=250;"
        "success_rate>=0.99' (default: run.slo from the recipe); breaches "
        "latch the degraded flag in /healthz and the slo_* gauges",
    )
    p.add_argument(
        "--slo-window-s",
        type=float,
        default=None,
        help="SLO rolling window seconds (default: run.slo_window_s)",
    )
    p.add_argument(
        "--slo-fast-window-s",
        type=float,
        default=None,
        help="SLO fast confirmation window seconds "
        "(default: run.slo_fast_window_s; 0 = window/12)",
    )
    p.add_argument(
        "--dtype",
        default=None,
        help="serving compute dtype override (e.g. float32 for the exact path)",
    )
    p.add_argument(
        "--quant",
        choices=("int8",),
        default=None,
        help="weight-only post-training quantization: int8 kernels with "
        "per-output-channel f32 scales, dequantized on use (embeddings, "
        "norms, biases stay f32)",
    )
    p.add_argument(
        "--warmcache",
        default=None,
        metavar="DIR",
        help="persistent executable cache directory (default: the per-host "
        "dir under ~/.cache/jumbo_mae_tpu/warmcache; restarted replicas "
        "load instead of compiling)",
    )
    p.add_argument(
        "--no-warmcache",
        action="store_true",
        help="disable the persistent executable cache for this run",
    )
    p.add_argument(
        "--encoder-cache",
        type=int,
        default=0,
        metavar="N",
        help="reconstruct: LRU-cache up to N encoder outputs keyed by "
        "(image bytes, seed) — repeated decode of the same image skips "
        "the encoder (shared mask mode only)",
    )
    p.add_argument(
        "--metrics-port",
        type=int,
        default=None,
        metavar="PORT",
        help="serve Prometheus /metrics + /healthz on this port "
        "(0 = any free port, printed at startup; omit to disable)",
    )
    p.add_argument(
        "--metrics-hold-s",
        type=float,
        default=0.0,
        help="keep the exporter up N seconds after the requests finish "
        "(lets an external scraper read the final counters; CI smoke uses it)",
    )
    p.add_argument(
        "--set",
        dest="overrides",
        metavar="KEY.PATH=VALUE",
        nargs="*",
        action="extend",
        default=[],
        help="dotted config overrides, same grammar as cli.train",
    )
    return p


def main(argv: list[str] | None = None) -> Path | None:
    args = build_parser().parse_args(argv)

    import jax
    import numpy as np

    from jumbo_mae_tpu_tpu.config import load_config
    from jumbo_mae_tpu_tpu.infer import InferenceEngine, MicroBatcher

    if jax.process_count() > 1:
        raise SystemExit("predict is a single-process tool; run it on one host")
    if bool(args.images) == bool(args.synthetic):
        raise SystemExit("pass exactly one of --images or --synthetic N")

    cfg = load_config(args.config, args.overrides)

    telemetry = None
    health = None
    if args.metrics_port is not None:
        from jumbo_mae_tpu_tpu.obs import HealthState, TelemetryServer

        health = HealthState()  # not ready until the engine is constructed
        telemetry = TelemetryServer(health=health, port=args.metrics_port).start()
        print(f"[predict] exporter on :{telemetry.port} (/metrics, /healthz)")

    engine = InferenceEngine(
        cfg,
        ckpt=args.ckpt,
        dtype=args.dtype,
        max_batch=args.max_batch,
        quant=args.quant,
        warm_cache=(
            False if args.no_warmcache
            else args.warmcache if args.warmcache is not None
            else True
        ),
        encoder_cache=args.encoder_cache,
    )
    if args.ckpt == "":
        print("[predict] WARNING: no --ckpt — serving a random init")
    if engine.warmcache is not None:
        print(f"[predict] warmcache: {engine.warmcache.root}")
    if args.warmup:
        n_compiles = engine.warmup((args.task,), pool=args.pool)
        hits = sum(engine.warm_hits.values())
        print(
            f"[predict] warmup: {n_compiles} executable(s) compiled, "
            f"{hits} loaded from warmcache"
        )
    if health is not None:
        health.set_ready(
            True, detail=f"engine up (ckpt={'yes' if args.ckpt else 'random'})"
        )

    # request observability (obs/reqtrace.py, obs/slo.py) rides the serving
    # path only — the direct batch path stays telemetry-free
    tracer = None
    slo_tracker = None
    if args.serve:
        from jumbo_mae_tpu_tpu.obs import (
            AccessLog,
            RequestTracer,
            SLOTracker,
            parse_slo,
        )

        slo_spec = args.slo if args.slo is not None else cfg.run.slo
        if slo_spec:
            slo_tracker = SLOTracker(
                parse_slo(slo_spec),
                window_s=(
                    args.slo_window_s
                    if args.slo_window_s is not None
                    else cfg.run.slo_window_s
                ),
                fast_window_s=(
                    args.slo_fast_window_s
                    if args.slo_fast_window_s is not None
                    else cfg.run.slo_fast_window_s
                ),
                burn_threshold=cfg.run.slo_burn_threshold,
            )
            print(
                f"[predict] SLO: {slo_spec} over "
                f"{slo_tracker.window_s:g}s/{slo_tracker.fast_window_s:g}s windows"
            )
        access = AccessLog(args.access_log) if args.access_log else None
        if access is not None:
            print(f"[predict] access log -> {access.path}")
        if access is not None or slo_tracker is not None or telemetry is not None:
            tracer = RequestTracer(
                access_log=access,
                breakdown=engine.last_breakdown,
                on_finish=(
                    slo_tracker.observe_trace if slo_tracker is not None else None
                ),
            )
        if slo_tracker is not None:
            if health is not None:
                health.degraded_when(slo_tracker.degraded)
                health.probe("slo", slo_tracker.healthz_info)
            if telemetry is not None:
                telemetry.add_pre_scrape(slo_tracker.evaluate)

    size = engine.image_size
    if args.synthetic:
        images = (
            np.random.RandomState(0)
            .randint(0, 256, (args.synthetic, size, size, 3))
            .astype(np.uint8)
        )
        names = [f"synthetic[{i}]" for i in range(args.synthetic)]
    else:
        from PIL import Image

        from jumbo_mae_tpu_tpu.data.transforms import eval_transform

        images = np.stack(
            [
                eval_transform(
                    np.asarray(Image.open(f).convert("RGB"), np.uint8),
                    size,
                    crop_ratio=cfg.data.test_crop_ratio,
                )
                for f in args.images
            ]
        )
        names = list(args.images)

    kw = {"pool": args.pool} if args.task == "features" else (
        {"seed": args.seed} if args.task == "reconstruct" else {}
    )
    if args.serve:
        def run_fn(batch):
            if health is not None:
                health.beat("infer_batch")
            return engine.predict(batch, task=args.task, **kw)

        with MicroBatcher(
            run_fn,
            max_batch=args.max_batch,
            max_delay_ms=args.max_delay_ms,
            max_queue=args.max_queue,
            tracer=tracer,
            task=args.task,
        ) as mb:
            if health is not None:
                # live autoscaler snapshot (queue depth / occupancy / shed
                # rate) in the /healthz info payload while serving
                health.probe("serving", mb.stats)
            if slo_tracker is not None:
                # ...and the same signals as slo_* gauges per scrape
                slo_tracker.add_probe(
                    "queue_depth", lambda: mb.stats()["queue_depth"]
                )
                slo_tracker.add_probe(
                    "batch_occupancy", lambda: mb.stats()["batch_occupancy"]
                )
            rows = [
                f.result()
                for f in [
                    mb.submit(img, deadline_ms=args.deadline_ms)
                    for img in images
                ]
            ]
        out = (
            {k: np.stack([r[k] for r in rows]) for k in rows[0]}
            if isinstance(rows[0], dict)
            else np.stack(rows)
        )
        print(f"[predict] micro-batch sizes: {mb.batch_sizes}")
        if slo_tracker is not None:
            rep = slo_tracker.evaluate()
            objs = "; ".join(
                f"{o['name']}: value={o['value']:g} "
                f"burn={o['burn_slow']:g} breached={o['breached']}"
                for o in rep["objectives"]
            )
            print(
                f"[predict] SLO verdict: degraded={rep['degraded']} "
                f"shed_rate={rep['shed_rate']:g} — {objs}"
            )
            if tracer is not None:
                tracer.event("slo_summary", report=rep)
        if tracer is not None:
            tracer.close()
    else:
        out = engine.predict(images, task=args.task, **kw)

    if args.task == "logits":
        probs = np.exp(out - out.max(-1, keepdims=True))
        probs /= probs.sum(-1, keepdims=True)
        k = min(args.topk, out.shape[-1])
        for name, p_row in zip(names, probs):
            top = np.argsort(-p_row)[:k]
            print(
                json.dumps(
                    {
                        "image": name,
                        "classes": top.tolist(),
                        "probs": [round(float(p_row[i]), 6) for i in top],
                    }
                )
            )
    payload = out if isinstance(out, dict) else {args.task: out}
    result: Path | None = None
    if args.out:
        result = Path(args.out)
        result.parent.mkdir(parents=True, exist_ok=True)
        np.savez(result, **payload)
        print(f"[predict] wrote {args.task} for {len(names)} image(s) -> {result}")
    if telemetry is not None:
        if args.metrics_hold_s > 0:
            import time

            print(
                f"[predict] holding exporter for {args.metrics_hold_s:g}s "
                f"(scrape :{telemetry.port}/metrics)"
            )
            time.sleep(args.metrics_hold_s)
        telemetry.close()
    return result


if __name__ == "__main__":
    main()
