"""Offline batch inference entry point: tar shards → durable part files.

Runs a resumable :class:`~jumbo_mae_tpu_tpu.batch.BatchJobRunner` over the
full serving stack — continuous scheduler, tenant admission (the job is a
budget-capped ``batch``-class tenant by default), cost meter, supervised
replica pool — so an offline dataset pass shares capacity, admission, and
chargeback with interactive traffic instead of bypassing them.

    python -m jumbo_mae_tpu_tpu.cli.batch shard-{0..9}.tar --out runs/job1
    # killed? preempted? just run the same command again: it resumes
    # sample-exactly and the final manifest is byte-identical

SIGTERM/SIGINT request a graceful drain: workers finish their in-flight
window, release their shard leases, and the job exits resumable (the
driver's preemption contract). A second signal aborts hard — which is
also safe, only slower to resume.

Without ``--config`` a deterministic service-time model stands in for the
engine (CI and smoke tests); with it, real ``InferenceEngine`` replicas
serve the job. The last stdout line is one JSON summary object (manifest
path, samples, lease steals, replica preemptions, per-tenant usage) for
scripted callers.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import time

import numpy as np

from jumbo_mae_tpu_tpu.batch import BatchJobRunner, JobSpec


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("shards", nargs="+", help="tar shard URLs/paths, in order")
    p.add_argument("--out", required=True, help="job output directory")
    p.add_argument("--task", default="features")
    p.add_argument("--tenant", default="batch")
    p.add_argument(
        "--tenants",
        default="batch=batch",
        help="tenant spec list (serve.parse_tenants syntax); the job "
        "submits as --tenant and shares the gate with any others listed",
    )
    p.add_argument("--workers", type=int, default=2, help="shard-parallel job workers")
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--max-delay-ms", type=float, default=2.0)
    p.add_argument("--max-queue", type=int, default=64)
    p.add_argument("--lease-s", type=float, default=30.0, help="shard lease horizon")
    p.add_argument("--submit-window", type=int, default=8)
    p.add_argument("--deadline-ms", type=float, default=None)
    p.add_argument("--config", default=None, help="model config -> real engine replicas")
    p.add_argument("--service-overhead-ms", type=float, default=1.0)
    p.add_argument("--service-per-item-ms", type=float, default=0.2)
    p.add_argument("--model-gflops-per-item", type=float, default=1.0)
    return p


class _StubEngine:
    """Deterministic service-time model (same role as loadgen's): output
    depends only on the input bytes, so restarted jobs recompute
    byte-identical part files."""

    def __init__(self, overhead_s: float, per_item_s: float):
        self.overhead_s = overhead_s
        self.per_item_s = per_item_s

    def run(self, batch: np.ndarray) -> list[dict]:
        time.sleep(self.overhead_s + len(batch) * self.per_item_s)
        return [
            {"sum": int(row.astype(np.int64).sum()), "dim": int(row.size)}
            for row in batch
        ]


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)

    from jumbo_mae_tpu_tpu.infer.replicaset import ReplicaSet
    from jumbo_mae_tpu_tpu.obs import AccessLog, RequestTracer
    from jumbo_mae_tpu_tpu.obs.journal import read_journal
    from jumbo_mae_tpu_tpu.serve import (
        AdmissionController,
        ContinuousScheduler,
        CostMeter,
        default_cost_fn,
        parse_tenants,
    )

    tenants = parse_tenants(args.tenants)
    access_dir = f"{args.out}/access"
    access = AccessLog(access_dir)
    tracer = RequestTracer(access_log=access)

    if args.config:
        from jumbo_mae_tpu_tpu.config import load_config
        from jumbo_mae_tpu_tpu.infer import InferenceEngine

        cfg = load_config(args.config, [])

        def provider(idx):
            return InferenceEngine(cfg, max_batch=args.max_batch)

        def run(engine, batch, metas):
            return engine.predict(batch, task=args.task)

        cost_fn = default_cost_fn
    else:
        overhead = args.service_overhead_ms / 1000.0
        per_item = args.service_per_item_ms / 1000.0

        def provider(idx):
            return _StubEngine(overhead, per_item)

        def run(engine, batch, metas):
            return engine.run(batch)

        flops_per_row = args.model_gflops_per_item * 1e9

        def cost_fn(engine, task, bucket):
            return {"flops": bucket * flops_per_row}

    # continuous mode headroom: the scheduler's accumulator is the
    # admission-visible queue; the pool takes dispatched groups above it
    meter = CostMeter(tenants, cost_fn=cost_fn, tracer=tracer)
    rs = ReplicaSet(
        provider,
        run,
        replicas=args.replicas,
        max_batch=args.max_batch,
        max_delay_ms=args.max_delay_ms,
        max_queue=args.max_queue + 2 * args.max_batch,
        tracer=tracer,
        task=args.task,
        costmeter=meter,
    )
    admission = AdmissionController(tenants, meter=meter)
    sched = ContinuousScheduler(
        rs.submit_group,
        max_batch=args.max_batch,
        max_delay_ms=args.max_delay_ms,
        max_queue=args.max_queue,
        admission=admission,
        tracer=tracer,
        task=args.task,
    )
    admission.set_pressure_fn(lambda: max(sched.pressure(), rs.pressure()))

    spec = JobSpec(
        shards=tuple(args.shards),
        output_dir=args.out,
        task=args.task,
        tenant=args.tenant,
        workers=args.workers,
        submit_window=args.submit_window,
        lease_s=args.lease_s,
        deadline_ms=args.deadline_ms,
    )
    runner = BatchJobRunner(spec, sched.submit)

    def _drain(signum, frame):
        # first signal: graceful, resumable drain; a repeat falls through
        # to the default handler (hard kill — still resumable, just rude)
        print(f"[batch] signal {signum}: draining (resumable)", file=sys.stderr)
        runner.request_stop()
        signal.signal(signum, signal.SIG_DFL)

    signal.signal(signal.SIGTERM, _drain)
    signal.signal(signal.SIGINT, _drain)

    try:
        summary = runner.run()
    finally:
        sched.close()
        rs.close()
        meter.flush()  # final tenant_usage rows before the log closes
        tracer.close()

    # per-tenant usage + preemptions from the access journal: what the
    # costmeter billed and what the pool survived while this job ran
    usage: dict[str, dict] = {}
    preemptions = 0
    try:
        for e in read_journal(access_dir):
            if e.get("type") == "tenant_usage" and e.get("tenant"):
                usage[str(e["tenant"])] = {
                    "device_s": e.get("device_s"),
                    "requests": e.get("requests"),
                }
            elif e.get("type") == "replica_preempted":
                preemptions += 1
    except FileNotFoundError:
        pass
    summary["tenant_usage"] = usage
    summary["replica_preemptions"] = preemptions
    print(json.dumps(summary, sort_keys=True))
    return 0 if summary["complete"] else 3


if __name__ == "__main__":
    sys.exit(main())
