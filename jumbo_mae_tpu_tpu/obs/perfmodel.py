"""Analytic capacity model: predicted step time / throughput / peak HBM.

MoFa-style (arXiv:2511.09837) roofline over the costs ``obs/costmodel.py``
extracts: each compiled program is bounded by the slowest of its compute
term (flops / peak flops), its memory term (bytes accessed / HBM bandwidth),
and — for sharded training — its collective term (comm bytes / ICI
bandwidth). The FSDP comms accounting follows "Memory and Bandwidth are All
You Need for FSDP" (arXiv:2504.03655): per step, each device all-gathers
the parameters twice (forward + backward) and reduce-scatters the grads
once, 3·P·(n−1)/n bytes over the slowest link; plain DP pays one grad
all-reduce, ≈ 2·P·(n−1)/n.

Two uses:

- **capacity planning** (ROADMAP item 5): given (model config, mesh, per-
  device batch, chip), predict step time / images-per-sec / peak HBM before
  burning chip time — ``predict_train_step`` works from the analytic FLOP
  counts alone, no backend needed;
- **live drift**: the train loop and the serving engine publish
  ``perf_predict_vs_measured{program}`` = measured / predicted each log
  window, so a run that detaches from its own roofline (input stall, host
  sync, background noise) is visible as a ratio, not a vibe.

Chip tables are public spec-sheet numbers; CPU (and any unknown kind) gets
an order-of-magnitude generic entry so the drift gauge still publishes on
the smoke backend — predictions there are for *plumbing*, not accuracy.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass

from jumbo_mae_tpu_tpu.obs.mfu import PEAK_TFLOPS, normalize_device_kind

# HBM bandwidth GB/s per chip, by the same canonical generation keys as
# PEAK_TFLOPS (public spec sheets).
HBM_GBPS = {
    "v2": 700.0,
    "v3": 900.0,
    "v4": 1228.0,
    "v5e": 819.0,
    "v5p": 2765.0,
    "v6e": 1640.0,
}

# One-directional ICI link bandwidth GB/s per chip (approximate; the
# roofline wants the per-device collective drain rate).
ICI_GBPS = {
    "v2": 62.5,
    "v3": 70.0,
    "v4": 100.0,
    "v5e": 100.0,
    "v5p": 200.0,
    "v6e": 200.0,
}

# HBM capacity GiB per chip (public spec sheets) — the denominator of
# mem_doctor's OOM-risk estimate (measured peak / capacity).
HBM_GIB = {
    "v2": 8.0,
    "v3": 16.0,
    "v4": 32.0,
    "v5e": 16.0,
    "v5p": 95.0,
    "v6e": 32.0,
}

# Order-of-magnitude generic host CPU: keeps the predict-vs-measured gauge
# publishing on the smoke backend. Never used for capacity claims —
# capacity 0 means "no HBM to run out of", and consumers must skip the
# OOM-risk math rather than divide by a made-up number.
GENERIC_CPU = ("cpu", 0.5, 20.0, 10.0, 0.0)


@dataclass(frozen=True)
class ChipSpec:
    name: str
    peak_tflops: float
    hbm_gbps: float
    ici_gbps: float
    hbm_bytes: float = 0.0  # capacity; 0 = unknown/not-an-accelerator


def chip_spec(kind: str | None) -> ChipSpec:
    """Resolve a PJRT ``device_kind`` string to a spec table entry; unknown
    kinds (CPU included) get the documented generic-cpu entry."""
    canon = normalize_device_kind(kind or "")
    if canon is not None and canon in HBM_GBPS:
        return ChipSpec(
            canon,
            PEAK_TFLOPS[canon],
            HBM_GBPS[canon],
            ICI_GBPS[canon],
            HBM_GIB[canon] * 1024**3,
        )
    return ChipSpec(*GENERIC_CPU)


def detect_chip() -> ChipSpec:
    """ChipSpec of the current backend's first device (generic on failure)."""
    try:
        import jax

        return chip_spec(jax.devices()[0].device_kind)
    except Exception:  # noqa: BLE001 - no backend → generic
        return chip_spec(None)


@dataclass
class PerfPrediction:
    """One program's roofline: the three terms and which one binds."""

    step_time_s: float
    throughput_per_sec: float  # items/s if batch given, else steps/s
    peak_hbm_bytes: float
    bound: str  # "compute" | "bandwidth" | "comm"
    t_compute_s: float
    t_memory_s: float
    t_comm_s: float


def roofline(
    flops: float,
    bytes_accessed: float,
    chip: ChipSpec,
    *,
    comm_bytes: float = 0.0,
    batch: int | None = None,
    peak_hbm_bytes: float = 0.0,
) -> PerfPrediction:
    """max(compute, memory, comm) lower bound on one program execution."""
    t_c = flops / (chip.peak_tflops * 1e12)
    t_m = bytes_accessed / (chip.hbm_gbps * 1e9)
    t_x = comm_bytes / (chip.ici_gbps * 1e9)
    step = max(t_c, t_m, t_x, 1e-12)
    bound = {t_c: "compute", t_m: "bandwidth", t_x: "comm"}[max(t_c, t_m, t_x)]
    return PerfPrediction(
        step_time_s=step,
        throughput_per_sec=(batch if batch else 1.0) / step,
        peak_hbm_bytes=peak_hbm_bytes,
        bound=bound,
        t_compute_s=t_c,
        t_memory_s=t_m,
        t_comm_s=t_x,
    )


def prediction_asdict(pred: PerfPrediction | None) -> dict | None:
    return None if pred is None else asdict(pred)


# ----------------------------------------------------------------- comms


def fsdp_comm_bytes(param_bytes: float, *, fsdp: int) -> float:
    """Per-device FSDP collective bytes per step: all-gather params for
    forward, again for backward, reduce-scatter grads — 3·P·(n−1)/n."""
    if fsdp <= 1:
        return 0.0
    return 3.0 * param_bytes * (fsdp - 1) / fsdp


def dp_comm_bytes(param_bytes: float, *, dp: int) -> float:
    """Per-device DP grad all-reduce bytes per step (ring): 2·P·(n−1)/n."""
    if dp <= 1:
        return 0.0
    return 2.0 * param_bytes * (dp - 1) / dp


# ------------------------------------------------- analytic train predictor


def approx_param_count(enc_cfg, dec_cfg=None) -> float:
    """Matmul-weight parameter count from the config (embeddings and norms
    are noise at this precision)."""
    d, h = enc_cfg.dim, enc_cfg.hidden_dim
    per_layer = 4 * d * d + 2 * d * h  # qkv+out proj, MLP in/out
    jumbo = 2 * (enc_cfg.num_cls_tokens * d) * (4 * enc_cfg.num_cls_tokens * d)
    n = enc_cfg.layers * (per_layer + jumbo / max(enc_cfg.layers, 1))
    n += enc_cfg.patch_size**2 * 3 * d  # patchify
    if dec_cfg is not None:
        dd, dh = dec_cfg.dim, dec_cfg.hidden_dim
        n += dec_cfg.layers * (4 * dd * dd + 2 * dd * dh)
        n += d * dd + dd * enc_cfg.patch_size**2 * 3  # in/out projections
    return float(n)


def predict_train_step(
    enc_cfg,
    dec_cfg=None,
    *,
    per_device_batch: int,
    mode: str = "pretrain",
    chip: ChipSpec | None = None,
    dp: int = 1,
    fsdp: int = 1,
    param_bytes_per_elt: float = 4.0,
) -> PerfPrediction:
    """Analytic (no-backend) prediction for one train step on one device.

    Flops come from the ``obs/mfu`` counters; the bytes model is coarse by
    design — optimizer state + grads + params traffic ≈ 8× param bytes per
    step, plus one activation read/write per flop-byte of batch work — and
    is documented as such wherever the number surfaces.
    """
    from jumbo_mae_tpu_tpu.obs.mfu import (
        classify_flops_per_image,
        pretrain_flops_per_image,
    )

    if chip is None:
        chip = detect_chip()
    if mode == "pretrain":
        flops_img = pretrain_flops_per_image(enc_cfg, dec_cfg, training=True)
    else:
        flops_img = classify_flops_per_image(enc_cfg, training=True)
    flops = flops_img * per_device_batch
    p_bytes = approx_param_count(enc_cfg, dec_cfg) * param_bytes_per_elt
    # params + grads + adam m/v read and written once each ≈ 8×P, plus an
    # activation-traffic term proportional to batch compute intensity
    act_bytes = 2.0 * flops / max(enc_cfg.dim, 1)
    bytes_accessed = 8.0 * p_bytes + act_bytes
    comm = fsdp_comm_bytes(p_bytes, fsdp=fsdp) + dp_comm_bytes(p_bytes, dp=dp)
    # optimizer state (m, v) + params + grads live across the step
    peak_hbm = 4.0 * p_bytes + act_bytes / 8.0
    return roofline(
        flops,
        bytes_accessed,
        chip,
        comm_bytes=comm,
        batch=per_device_batch,
        peak_hbm_bytes=peak_hbm,
    )


# ------------------------------------------------------------- drift gauge


def publish_drift(
    predicted_s: float, measured_s: float, *, program: str, registry=None
) -> float:
    """Publish ``perf_predicted_step_seconds{program}`` and the drift ratio
    ``perf_predict_vs_measured{program}`` = measured / predicted (1.0 = on
    the roofline; ≫1 = detached from it). Returns the ratio."""
    if registry is None:
        from jumbo_mae_tpu_tpu.obs.metrics import get_registry

        registry = get_registry()
    ratio = measured_s / max(predicted_s, 1e-12)
    registry.gauge(
        "perf_predicted_step_seconds",
        "roofline-predicted execution seconds",
        labels=("program",),
    ).labels(program).set(predicted_s)
    registry.gauge(
        "perf_predict_vs_measured",
        "measured / roofline-predicted execution time",
        labels=("program",),
    ).labels(program).set(ratio)
    return ratio
