"""Goodput accounting — where did the run's wall-clock actually go?

Resilience (sentinel rollbacks, elastic restarts, hang watchdogs) has a
price, and nothing in the stack measured it: a run could spend half its
wall-clock in supervisor backoff and recompute and still report healthy
step times. This module attributes **every second of run wall-clock to
exactly one bucket** and enforces a conservation invariant — the buckets
must sum to wall-clock within tolerance, so time can neither vanish nor
be counted twice.

Two halves:

``GoodputLedger`` (live, per-process)
    Fed by the train CLI's RunEngine hooks: data-wait and dispatch spans,
    eval and checkpoint spans, rollback recompute windows, hang-detection
    latency. Publishes ``goodput_*`` gauges, rides a ``goodput_fraction``
    field on fleet beacons, and journals cumulative ``goodput_report``
    events at checkpoint boundaries and shutdown. ``idle`` is the residual
    (wall − attributed), clamped at zero — so the conservation failure
    mode this catches is *over*-attribution (double counting), which is
    exactly the bug class a bucket taxonomy invites.

``stitch_generations`` (offline, cross-process)
    An elastic run is several process generations separated by supervisor
    downtime that no in-process clock can see. Stitching walks the merged
    journal: each generation's last cumulative ``goodput_report`` gives
    its in-process buckets, the inter-generation gap (previous generation's
    last step activity → next generation's ledger epoch) becomes
    ``hang_latency`` + ``restart_downtime``, and lost work is
    steps executed − steps committed at the moment of death. This is the
    first observability layer that spans generations rather than a single
    process lifetime.

``advise_ckpt_interval``
    Young/Daly optimal checkpoint interval √(2·save_cost·MTBF) from the
    measured save cost and observed failure rate, converted to a concrete
    ``run.ckpt_every`` step count via the measured step time.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

from jumbo_mae_tpu_tpu.obs.metrics import get_registry

# Every second of wall-clock lands in exactly one of these. Order is
# display order in reports; ``idle`` is always the residual.
GOODPUT_BUCKETS = (
    "productive",          # step compute for steps that advance the run
    "compile",             # first-step trace+compile (and retraces)
    "data_wait",           # host blocked on the input pipeline
    "eval",                # evaluation passes
    "ckpt_save",           # checkpoint save (synchronous portion)
    "ckpt_restore",        # checkpoint restore (startup + rollback)
    "rollback_recompute",  # re-training steps past the last committed step
    "restart_downtime",    # supervisor teardown + backoff + relaunch
    "hang_latency",        # stall time before the watchdog fired
    "idle",                # residual: wall − everything above
)

_DISPLAY = {
    "productive": "productive step compute",
    "compile": "compile/retrace",
    "data_wait": "data wait",
    "eval": "eval",
    "ckpt_save": "checkpoint save",
    "ckpt_restore": "checkpoint restore",
    "rollback_recompute": "rollback recompute",
    "restart_downtime": "restart downtime",
    "hang_latency": "hang-detection latency",
    "idle": "idle",
}


def bucket_display(bucket: str) -> str:
    """Human name for a bucket key (``restart_downtime`` → ``restart
    downtime``)."""
    return _DISPLAY.get(bucket, bucket.replace("_", " "))


class GoodputLedger:
    """Live wall-clock attribution for one training process.

    The clock starts at construction (top of ``train()``), so setup,
    compile and restore are all on the books. ``add`` charges a measured
    span to a bucket; ``note_step`` routes per-step dispatch time to
    ``compile`` (first dispatch after a (re)start traces+compiles),
    ``rollback_recompute`` (steps at or below the step we rolled back
    from) or ``productive``. Unattributed time is ``idle`` — computed at
    snapshot time as the residual, never stored — which makes the
    conservation invariant ``attributed ≤ wall`` the thing unit tests can
    actually falsify.
    """

    def __init__(
        self,
        *,
        generation: int = 0,
        clock: Callable[[], float] = time.perf_counter,
        registry=None,
    ):
        self.generation = int(generation)
        self._clock = clock
        self._t0 = float(clock())
        self._lock = threading.Lock()
        self._s: dict[str, float] = {
            b: 0.0 for b in GOODPUT_BUCKETS if b != "idle"
        }
        self._steps = 0            # productive steps dispatched
        self._recompute_steps = 0  # steps re-trained after rollbacks
        self._first_dispatch_done = False
        self._recompute_until: int | None = None
        reg = registry if registry is not None else get_registry()
        self._g_fraction = reg.gauge(
            "goodput_fraction",
            "share of wall-clock spent in productive step compute",
        )
        self._g_wall = reg.gauge(
            "goodput_wall_seconds",
            "wall-clock seconds covered by the goodput ledger",
        )
        self._g_bucket = reg.gauge(
            "goodput_bucket_seconds",
            "wall-clock seconds attributed to each goodput bucket",
            labels=("bucket",),
        )
        self._g_recompute = reg.gauge(
            "goodput_recompute_steps",
            "steps re-trained past the last committed step after rollbacks",
        )

    # -- feeding ---------------------------------------------------------
    def add(self, bucket: str, seconds: float) -> None:
        """Charge ``seconds`` of measured wall-clock to ``bucket``."""
        if bucket not in self._s:
            raise KeyError(f"unknown goodput bucket {bucket!r}")
        with self._lock:
            self._s[bucket] += max(0.0, float(seconds))

    def note_step(self, step: int, dispatch_s: float) -> None:
        """Attribute one step's dispatch span.

        The first dispatch of a process is trace+compile, not training;
        steps at or below a pending rollback watermark are recompute.
        """
        dispatch_s = max(0.0, float(dispatch_s))
        with self._lock:
            if not self._first_dispatch_done:
                self._first_dispatch_done = True
                self._s["compile"] += dispatch_s
                return
            if (
                self._recompute_until is not None
                and int(step) <= self._recompute_until
            ):
                self._s["rollback_recompute"] += dispatch_s
                self._recompute_steps += 1
                if int(step) >= self._recompute_until:
                    self._recompute_until = None
                return
            self._s["productive"] += dispatch_s
            self._steps += 1

    def note_rollback(self, from_step: int, to_step: int) -> None:
        """Steps re-dispatched up to ``from_step`` are recompute, not
        progress — they were already trained once before the rollback."""
        with self._lock:
            hw = int(from_step)
            if self._recompute_until is None or hw > self._recompute_until:
                self._recompute_until = hw

    # -- reading ---------------------------------------------------------
    def wall_s(self) -> float:
        return max(0.0, float(self._clock()) - self._t0)

    def snapshot(self) -> dict[str, float]:
        """Bucket seconds including the ``idle`` residual."""
        with self._lock:
            buckets = dict(self._s)
        wall = self.wall_s()
        attributed = sum(buckets.values())
        buckets["idle"] = max(0.0, wall - attributed)
        return buckets

    def fraction(self) -> float:
        wall = self.wall_s()
        if wall <= 0.0:
            return 0.0
        with self._lock:
            return min(1.0, self._s["productive"] / wall)

    def conservation_error(self) -> float:
        """Relative attribution error. ``idle`` absorbs under-attribution,
        so a nonzero error means over-attribution (double counting)."""
        wall = self.wall_s()
        if wall <= 0.0:
            return 0.0
        with self._lock:
            attributed = sum(self._s.values())
        return max(0.0, attributed - wall) / wall

    def report(
        self, *, step: int | None = None, reason: str | None = None
    ) -> dict[str, Any]:
        """Cumulative attribution snapshot, shaped for a ``goodput_report``
        journal event (and for offline stitching)."""
        buckets = self.snapshot()
        wall = self.wall_s()
        attributed = sum(v for k, v in buckets.items() if k != "idle")
        out: dict[str, Any] = {
            "generation": self.generation,
            "wall_s": round(wall, 3),
            "attributed_s": round(attributed, 3),
            "idle_s": round(buckets["idle"], 3),
            "goodput_fraction": round(self.fraction(), 4),
            "conservation_error": round(self.conservation_error(), 4),
            "steps": self._steps,
            "recompute_steps": self._recompute_steps,
            "buckets": {k: round(v, 3) for k, v in buckets.items()},
        }
        if step is not None:
            out["step"] = int(step)
        if reason is not None:
            out["reason"] = str(reason)
        return out

    def publish(self) -> None:
        """Push the current attribution to the metrics registry."""
        buckets = self.snapshot()
        self._g_fraction.set(self.fraction())
        self._g_wall.set(self.wall_s())
        self._g_recompute.set(float(self._recompute_steps))
        for k, v in buckets.items():
            self._g_bucket.labels(bucket=k).set(v)


# ---------------------------------------------------------------------------
# Offline: stitch per-generation journals from an elastic run
# ---------------------------------------------------------------------------


def _new_gen(event: dict, index: int) -> dict[str, Any]:
    start = int(event.get("start_step") or 0)
    return {
        "generation": int(event.get("generation", index)),
        "start_ts": float(event.get("ts") or 0.0),
        "first_step_ts": None,
        "last_step_ts": None,
        "last_ts": float(event.get("ts") or 0.0),
        "start_step": start,
        "max_step": start,
        "committed_step": start,
        "save_costs": [],
        "hang_stalled_s": 0.0,
        "report": None,
    }


def stitch_generations(events: list[dict]) -> dict[str, Any]:
    """Cross-generation goodput from a merged journal.

    Uses host-0 events as the canonical per-run record (supervisor events
    are journaled on host 0 too). Each ``run_start`` opens a generation;
    its last cumulative ``goodput_report`` supplies in-process buckets.
    The gap between a generation's last step activity and the next
    generation's ledger epoch (``report.ts − report.wall_s``) is downtime:
    first charged to ``hang_latency`` (up to the stalled time the watchdog
    observed), the remainder to ``restart_downtime``. Lost steps per
    restart = steps executed − steps committed when the generation died.
    """
    gens: list[dict[str, Any]] = []
    restarts: list[dict[str, Any]] = []
    cur: dict[str, Any] | None = None
    for e in events:
        if int(e.get("host") or 0) != 0:
            continue
        ts = float(e.get("ts") or 0.0)
        etype = e.get("type")
        if etype == "run_start" and e.get("role") != "supervisor":
            if cur is not None:
                gens.append(cur)
            cur = _new_gen(e, len(gens))
            continue
        if etype == "elastic_restart":
            restarts.append(dict(e))
            continue
        if cur is None:
            continue
        cur["last_ts"] = max(cur["last_ts"], ts)
        if etype == "step":
            step = int(e.get("step") or 0)
            cur["max_step"] = max(cur["max_step"], step)
            cur["last_step_ts"] = max(cur["last_step_ts"] or ts, ts)
            if cur["first_step_ts"] is None:
                cur["first_step_ts"] = ts
        elif etype == "checkpoint_save":
            cur["committed_step"] = max(
                cur["committed_step"], int(e.get("step") or 0)
            )
            cur["last_step_ts"] = max(cur["last_step_ts"] or ts, ts)
            sv = e.get("save_seconds")
            if sv is not None:
                try:
                    cur["save_costs"].append(float(sv))
                except (TypeError, ValueError):
                    pass
        elif etype == "hang_detected":
            try:
                cur["hang_stalled_s"] = max(
                    cur["hang_stalled_s"], float(e.get("stalled_s") or 0.0)
                )
            except (TypeError, ValueError):
                pass
        elif etype == "goodput_report":
            cur["report"] = dict(e)
    if cur is not None:
        gens.append(cur)

    buckets = {b: 0.0 for b in GOODPUT_BUCKETS}
    total_steps = 0
    save_costs: list[float] = []
    for g in gens:
        save_costs.extend(g["save_costs"])
        rep = g["report"]
        if rep:
            # in-process idle is NOT accumulated: the stall before a hang
            # death is idle to the in-process ledger but becomes
            # hang_latency/restart_downtime here — stitched idle is always
            # recomputed as the cross-generation residual below.
            for k, v in (rep.get("buckets") or {}).items():
                if k in buckets and k != "idle":
                    try:
                        buckets[k] += float(v)
                    except (TypeError, ValueError):
                        pass
            total_steps += int(rep.get("steps") or 0)
        # ledger epoch: when this generation's clock started. The report is
        # cumulative, so its journal ts minus its wall_s recovers t0 even
        # though the ledger predates the journal.
        rep_ts = float(rep.get("ts") or 0.0) if rep else 0.0
        rep_wall = float(rep.get("wall_s") or 0.0) if rep else 0.0
        g["ledger_t0"] = rep_ts - rep_wall if rep else g["start_ts"]

    for i, g in enumerate(gens[1:], start=1):
        prev = gens[i - 1]
        prev_end = prev["last_step_ts"] or prev["last_ts"]
        down = max(0.0, g["ledger_t0"] - prev_end)
        hang = min(down, prev["hang_stalled_s"])
        buckets["hang_latency"] += hang
        buckets["restart_downtime"] += down - hang
        lost = max(0, prev["max_step"] - prev["committed_step"])
        restart_meta = next(
            (
                r
                for r in restarts
                if int(r.get("generation", -1)) == g["generation"]
            ),
            {},
        )
        g["restart"] = {
            "generation": g["generation"],
            "reason": restart_meta.get("reason", "unknown"),
            "backoff_s": float(restart_meta.get("backoff_s") or 0.0),
            "detection_s": round(hang, 3),
            "downtime_s": round(down, 3),
            "lost_steps": lost,
        }

    wall = 0.0
    if gens:
        t0 = min(g["ledger_t0"] for g in gens)
        t1 = max(g["last_ts"] for g in gens)
        wall = max(0.0, t1 - t0)
    attributed = sum(v for k, v in buckets.items() if k != "idle")
    buckets["idle"] += max(0.0, wall - attributed)
    err = max(0.0, attributed - wall) / wall if wall > 0 else 0.0

    committed = max((g["committed_step"] for g in gens), default=0)
    lost_steps = sum(
        g.get("restart", {}).get("lost_steps", 0) for g in gens
    )
    step_time = (
        buckets["productive"] / total_steps if total_steps > 0 else None
    )
    failures = len([g for g in gens if "restart" in g])
    mtbf = wall / failures if failures > 0 and wall > 0 else None
    for g in gens:
        restart = g.get("restart")
        if restart is not None and step_time is not None:
            restart["lost_seconds"] = round(
                restart["lost_steps"] * step_time, 3
            )
    return {
        "wall_s": round(wall, 3),
        "buckets": {k: round(v, 3) for k, v in buckets.items()},
        "goodput_fraction": (
            round(buckets["productive"] / wall, 4) if wall > 0 else 0.0
        ),
        "conservation_error": round(err, 4),
        "generations": gens,
        "restarts": [g["restart"] for g in gens if "restart" in g],
        "steps_committed": committed,
        "steps_lost": lost_steps,
        "failures": failures,
        "mtbf_s": round(mtbf, 3) if mtbf is not None else None,
        "save_cost_s": (
            round(sum(save_costs) / len(save_costs), 3) if save_costs else None
        ),
        "step_time_s": round(step_time, 4) if step_time is not None else None,
    }


# ---------------------------------------------------------------------------
# Checkpoint-interval advisor
# ---------------------------------------------------------------------------


def advise_ckpt_interval(
    save_cost_s: float,
    mtbf_s: float,
    step_time_s: float,
    *,
    observed_span_s: float | None = None,
) -> dict[str, Any]:
    """Young's optimal checkpoint interval: ``√(2·save_cost·MTBF)``.

    With no observed failures, callers pass the run span as a *lower
    bound* on MTBF via ``observed_span_s`` — the recommendation is then a
    floor (checkpoint at least this rarely), flagged ``mtbf_is_bound``.
    Returns a concrete ``ckpt_every`` step count via the step time.
    """
    bound = False
    if not mtbf_s or mtbf_s <= 0:
        mtbf_s = max(float(observed_span_s or 0.0), 1.0)
        bound = True
    save_cost_s = max(float(save_cost_s), 1e-3)
    interval_s = (2.0 * save_cost_s * float(mtbf_s)) ** 0.5
    step_time_s = max(float(step_time_s), 1e-6)
    ckpt_every = max(1, int(round(interval_s / step_time_s)))
    return {
        "interval_s": round(interval_s, 3),
        "ckpt_every": ckpt_every,
        "save_cost_s": round(save_cost_s, 3),
        "mtbf_s": round(float(mtbf_s), 1),
        "step_time_s": round(step_time_s, 4),
        "mtbf_is_bound": bound,
    }
