"""Shared helpers for the offline doctors (`tools/run_doctor.py`,
`tools/serve_doctor.py`).

Both tools turn a crash-safe JSONL artifact (the run journal, the serving
access log) into a markdown diagnosis, and both need the same primitives:
number formatting that tolerates the journal's ``"nan"``/``"inf"`` string
encoding, merging sorted indices into contiguous windows, and naming those
windows the way an operator reads them ("steps 5–7", "requests 24–39").
Extracted here so the two reports stay consistent instead of drifting as
copy-pastes.
"""

from __future__ import annotations

from pathlib import Path


def fmt_num(v, nd: int = 4) -> str:
    """Compact human formatting: ints stay ints, floats get ``nd``
    significant digits, the journal's stringified non-finites pass through."""
    if isinstance(v, (int, float)):
        try:
            f = float(v)
        except (TypeError, ValueError):
            return str(v)
        if f != f or f in (float("inf"), float("-inf")):
            return str(f)
        if isinstance(v, int) or f.is_integer():
            return str(int(f))
        return f"{f:.{nd}g}"
    return str(v)


def contiguous_windows(indices) -> list[tuple[int, int]]:
    """Merge an iterable of ints into sorted inclusive ``(lo, hi)`` runs:
    ``{5, 6, 7, 12}`` → ``[(5, 7), (12, 12)]``."""
    windows: list[tuple[int, int]] = []
    for s in sorted(set(int(i) for i in indices)):
        if windows and s == windows[-1][1] + 1:
            windows[-1] = (windows[-1][0], s)
        else:
            windows.append((s, s))
    return windows


def spans_text(windows: list[tuple[int, int]], noun: str = "step") -> str:
    """Operator-readable window naming: ``[(5, 7), (12, 12)]`` with noun
    ``"step"`` → ``"steps 5–7, step 12"``."""
    return ", ".join(
        f"{noun}s {a}–{b}" if a != b else f"{noun} {a}" for a, b in windows
    )


def write_report(markdown: str, out: str | None, *, tool: str) -> int:
    """Land the diagnosis: write to ``out`` (creating parents) or print to
    stdout. Returns the success exit code (0) so ``main`` can tail-call."""
    if out:
        p = Path(out)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(markdown)
        print(f"[{tool}] diagnosis -> {out}")
    else:
        print(markdown)
    return 0
