"""Append-only, crash-safe JSONL run journal.

The in-memory metrics registry dies with the process; the journal is the
part of a run's history that *survives* — one fsync'd JSON line per event,
written under ``<run_dir>/journal/``, readable offline by
``tools/run_doctor.py`` long after the run (or the host) is gone.

Event shape: every line is ``{"ts": epoch_s, "seq": n, "type": t, ...}``.
The wired event types (free-form types are allowed):

- ``run_start``        — full config dict + environment fingerprint
- ``step``             — log-cadence metric snapshot (loss, grad_norm,
  throughput, data-wait fraction, per-layer-group diag stats when enabled)
- ``checkpoint_save``  — a checkpoint left the step loop
- ``sentinel_bad_step`` / ``sentinel_loss_spike`` — per-step sentinel
  verdicts (exact step indices, unlike the windowed ``step`` snapshots)
- ``rollback``         — sentinel rollback: from/to steps, budget used
- ``quarantine``       — shard URLs the retry layer gave up on
- ``flight_record``    — a flight-recorder dump was written (with its path)
- ``shutdown``         — how the run ended (completed / preempted /
  exception / diverged)

Crash-safety contract:

- every ``event()`` is flushed AND fsync'd before returning — a SIGKILL
  loses at most the line being written, never a prior one;
- a torn final line (the process died mid-write) is *skipped* by
  :func:`read_journal`, never an error;
- rotation starts a new numbered segment (``journal-00001.jsonl`` …) and
  never rewrites an old one; a restarted run opens a fresh segment, so a
  torn tail can never be appended after.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path

_SEGMENT_PREFIX = "journal-"
_SEGMENT_SUFFIX = ".jsonl"


def _json_default(obj):
    """Journal payloads carry numpy scalars/arrays and Paths; make them JSON."""
    import numpy as np

    if isinstance(obj, (np.floating, np.integer)):
        return obj.item()
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, Path):
        return str(obj)
    return repr(obj)


def _sanitize(value):
    """JSON refuses NaN/Inf under allow_nan=False; the journal must encode a
    non-finite loss (it's the whole point) — stringify them."""
    if isinstance(value, float):
        if value != value:
            return "nan"
        if value in (float("inf"), float("-inf")):
            return "inf" if value > 0 else "-inf"
        return value
    if isinstance(value, dict):
        return {k: _sanitize(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_sanitize(v) for v in value]
    return value


class RunJournal:
    """Writer half: fsync-per-line JSONL segments with size-based rotation.

    Not thread-safe by design — events come from the single train loop
    thread at log cadence (the fsync is the cost ceiling, not a lock).
    """

    def __init__(
        self,
        directory: str | Path,
        *,
        max_bytes: int = 4 * 1024 * 1024,
        keep: int = 64,
        fsync: bool = True,
    ):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.max_bytes = int(max_bytes)
        self.keep = int(keep)
        self.fsync = bool(fsync)
        self._seq = 0
        # a restarted run continues in a NEW segment after the highest
        # existing index — an old torn tail stays torn, ordering by
        # filename stays total
        self._index = self._next_index()
        self._file = open(self._segment_path(self._index), "a", encoding="utf-8")

    def _next_index(self) -> int:
        existing = sorted(self.directory.glob(f"{_SEGMENT_PREFIX}*{_SEGMENT_SUFFIX}"))
        if not existing:
            return 0
        last = existing[-1].name[len(_SEGMENT_PREFIX):-len(_SEGMENT_SUFFIX)]
        try:
            return int(last) + 1
        except ValueError:  # foreign file matching the glob
            return len(existing)

    def _segment_path(self, index: int) -> Path:
        return self.directory / f"{_SEGMENT_PREFIX}{index:05d}{_SEGMENT_SUFFIX}"

    @property
    def path(self) -> Path:
        """The segment currently being appended to."""
        return self._segment_path(self._index)

    def event(self, etype: str, **fields) -> dict:
        """Append one event; returns the record as written (post-sanitize)."""
        rec = {
            "ts": round(time.time(), 3),
            "seq": self._seq,
            "type": etype,
            **_sanitize(fields),
        }
        line = json.dumps(
            rec, default=_json_default, separators=(",", ":"), allow_nan=False
        )
        self._file.write(line + "\n")
        self._file.flush()
        if self.fsync:
            os.fsync(self._file.fileno())
        self._seq += 1
        if self._file.tell() >= self.max_bytes:
            self._rotate()
        return rec

    def _rotate(self) -> None:
        self._file.close()
        self._index += 1
        self._file = open(self._segment_path(self._index), "a", encoding="utf-8")
        # prune the oldest segments beyond the retention budget
        segments = sorted(self.directory.glob(f"{_SEGMENT_PREFIX}*{_SEGMENT_SUFFIX}"))
        for old in segments[: max(0, len(segments) - self.keep)]:
            try:
                old.unlink()
            except OSError:  # pragma: no cover - concurrent cleanup
                pass

    def close(self) -> None:
        if not self._file.closed:
            self._file.flush()
            if self.fsync:
                os.fsync(self._file.fileno())
            self._file.close()

    def __enter__(self) -> "RunJournal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def journal_dir(path: str | Path) -> Path | None:
    """Resolve a user-supplied path (run dir, journal dir, or one segment
    file) to the journal location, or None when there is no journal there."""
    p = Path(path)
    if p.is_file():
        return p
    if p.is_dir():
        if list(p.glob(f"{_SEGMENT_PREFIX}*{_SEGMENT_SUFFIX}")):
            return p
        sub = p / "journal"
        if sub.is_dir() and list(sub.glob(f"{_SEGMENT_PREFIX}*{_SEGMENT_SUFFIX}")):
            return sub
    return None


def read_journal(path: str | Path) -> list[dict]:
    """Reader half: every parseable event across all segments, in order.

    Tolerates exactly the damage a crash can cause: a torn final line
    (partial write + SIGKILL) is skipped; any other unparseable line is
    skipped too rather than aborting the whole read — a diagnosis from 999
    events beats an exception over 1. Raises ``FileNotFoundError`` only when
    there is no journal at ``path`` at all.
    """
    loc = journal_dir(path)
    if loc is None:
        raise FileNotFoundError(f"no journal segments under {path}")
    files = [loc] if loc.is_file() else sorted(
        loc.glob(f"{_SEGMENT_PREFIX}*{_SEGMENT_SUFFIX}")
    )
    events: list[dict] = []
    for f in files:
        text = f.read_bytes().decode("utf-8", errors="replace")
        for line in text.split("\n"):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail or damaged line — skip, keep reading
            if isinstance(rec, dict):
                events.append(rec)
    return events


def env_fingerprint() -> dict:
    """What was this process, exactly? Enough to tell two restarts apart and
    to blame a config/environment change across a divergence boundary."""
    import platform
    import socket
    import sys

    from jumbo_mae_tpu_tpu import __version__

    info = {
        "version": __version__,
        "python": platform.python_version(),
        "platform": platform.platform(),
        "hostname": socket.gethostname(),
        "pid": os.getpid(),
        "argv": list(sys.argv),
    }
    try:
        import jax

        info["jax"] = jax.__version__
        info["backend"] = jax.default_backend()
        info["device_count"] = jax.device_count()
        info["process_count"] = jax.process_count()
    except Exception:  # noqa: BLE001 - fingerprint must never fail a run
        info["jax"] = "unavailable"
    for var in ("JAX_PLATFORMS", "GRAFT_FAULTS", "JUMBO_COMPILE_CACHE"):
        if os.environ.get(var):
            info.setdefault("env", {})[var] = os.environ[var]
    return info
