"""Append-only, crash-safe JSONL run journal.

The in-memory metrics registry dies with the process; the journal is the
part of a run's history that *survives* — one fsync'd JSON line per event,
written under ``<run_dir>/journal/``, readable offline by
``tools/run_doctor.py`` long after the run (or the host) is gone.

Event shape: every line is ``{"ts": epoch_s, "seq": n, "type": t, ...}``.
The wired event types (free-form types are allowed):

- ``run_start``        — full config dict + environment fingerprint
- ``step``             — log-cadence metric snapshot (loss, grad_norm,
  throughput, data-wait fraction, per-layer-group diag stats when enabled)
- ``checkpoint_save``  — a checkpoint left the step loop
- ``sentinel_bad_step`` / ``sentinel_loss_spike`` — per-step sentinel
  verdicts (exact step indices, unlike the windowed ``step`` snapshots)
- ``rollback``         — sentinel rollback: from/to steps, budget used
- ``quarantine``       — shard URLs the retry layer gave up on
- ``flight_record``    — a flight-recorder dump was written (with its path)
- ``shutdown``         — how the run ended (completed / preempted /
  exception / diverged)

Crash-safety contract:

- every ``event()`` is flushed AND fsync'd before returning — a SIGKILL
  loses at most the line being written, never a prior one;
- a torn final line (the process died mid-write) is *skipped* by
  :func:`read_journal`, never an error;
- rotation starts a new numbered segment (``journal-00001.jsonl`` …) and
  never rewrites an old one; a restarted run opens a fresh segment, so a
  torn tail can never be appended after.

Multi-host: every process writes its OWN journal — host 0 under
``<run_dir>/journal/``, host *i* under ``<run_dir>/journal-host<i>/`` —
and every row carries a ``host`` field (the writer). There is no shared
write path to coordinate; :func:`read_merged_journal` merges the per-host
streams offline, ordered by ``(ts, host, seq)`` and tolerant of a torn
tail in any one host's segment (a host SIGKILLed mid-line costs that line,
nothing else).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from pathlib import Path

_SEGMENT_PREFIX = "journal-"
_SEGMENT_SUFFIX = ".jsonl"
_HOST_DIR_RE = re.compile(r"^journal-host(\d+)$")

# The frozen event schema: every event type the project emits with a
# literal name. Free-form types still *work* (the writer doesn't validate
# at runtime — a crash-safe log must never refuse a row), but readers,
# doctors, and ``tools.graftlint`` CON002 treat this set as the contract:
# emitting a literal type outside it is drift, caught statically.
JOURNAL_EVENTS = frozenset(
    {
        "run_start",
        "step",
        "checkpoint_save",
        "sentinel_bad_step",
        "sentinel_loss_spike",
        "rollback",
        "quarantine",
        "flight_record",
        "compiled_program",
        "profile",
        "shutdown",
        "fleet_straggler",
        "fleet_host_lost",
        "fleet_host_rejoined",
        "retrace",
        "lock_order_violation",
        "mem_sample",
        "mem_leak_suspect",
        "autoscale",
        "replica_added",
        "replica_removed",
        "replica_preempted",
        "tenant_usage",
        "job_start",
        "job_lease",
        "job_cursor",
        "job_shard_done",
        "job_complete",
        "publish",
        "publish_skipped",
        "publish_failed",
        # elastic fleet training (train/elastic.py + cli/train.py)
        "hang_detected",
        "host_lost",
        "elastic_restart",
        "elastic_resize",
        "elastic_rejoin",
        "elastic_exhausted",
        "ckpt_fallback",
        "shard_cursor",
        # goodput accounting (obs/goodput.py): cumulative wall-clock
        # attribution snapshots, journaled at checkpoint boundaries, on
        # hang detection, and at shutdown
        "goodput_report",
    }
)


def fsync_dir(path: "str | Path") -> None:
    """fsync a directory so a just-renamed (or just-created) entry survives
    power loss — ``os.replace`` alone only orders the rename against other
    operations on the *file*; the new directory entry itself is volatile
    until the parent directory's metadata reaches disk. Best-effort: on
    filesystems/platforms that refuse directory fds the rename still
    happened, we just lose the power-loss guarantee we never had before.
    """
    try:
        fd = os.open(str(path), os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover - e.g. fsync on FAT/network mounts
        pass
    finally:
        os.close(fd)


def _json_default(obj):
    """Journal payloads carry numpy scalars/arrays and Paths; make them JSON."""
    import numpy as np

    if isinstance(obj, (np.floating, np.integer)):
        return obj.item()
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, Path):
        return str(obj)
    return repr(obj)


def _sanitize(value):
    """JSON refuses NaN/Inf under allow_nan=False; the journal must encode a
    non-finite loss (it's the whole point) — stringify them."""
    if isinstance(value, float):
        if value != value:
            return "nan"
        if value in (float("inf"), float("-inf")):
            return "inf" if value > 0 else "-inf"
        return value
    if isinstance(value, dict):
        return {k: _sanitize(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_sanitize(v) for v in value]
    return value


class RunJournal:
    """Writer half: fsync-per-line JSONL segments with size-based rotation.

    Writes are serialized by one lock — the train loop owns the cadence,
    but the fleet aggregator emits transition events from the exporter's
    scrape thread (the fsync is the cost ceiling, not the lock). With
    ``host`` set, every record carries it so merged multi-host reads can
    attribute rows.
    """

    def __init__(
        self,
        directory: str | Path,
        *,
        max_bytes: int = 4 * 1024 * 1024,
        keep: int = 64,
        fsync: bool = True,
        host: int | None = None,
    ):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.max_bytes = int(max_bytes)
        self.keep = int(keep)
        self.fsync = bool(fsync)
        self.host = None if host is None else int(host)
        self._lock = threading.Lock()
        self._seq = 0
        # a restarted run continues in a NEW segment after the highest
        # existing index — an old torn tail stays torn, ordering by
        # filename stays total
        self._index = self._next_index()
        self._file = open(self._segment_path(self._index), "a", encoding="utf-8")
        if self.fsync:
            # the segment's directory entry must be durable too: fsync'd
            # lines inside a file whose name was lost to power loss are gone
            fsync_dir(self.directory)

    def _next_index(self) -> int:
        existing = sorted(self.directory.glob(f"{_SEGMENT_PREFIX}*{_SEGMENT_SUFFIX}"))
        if not existing:
            return 0
        last = existing[-1].name[len(_SEGMENT_PREFIX):-len(_SEGMENT_SUFFIX)]
        try:
            return int(last) + 1
        except ValueError:  # foreign file matching the glob
            return len(existing)

    def _segment_path(self, index: int) -> Path:
        return self.directory / f"{_SEGMENT_PREFIX}{index:05d}{_SEGMENT_SUFFIX}"

    @property
    def path(self) -> Path:
        """The segment currently being appended to."""
        return self._segment_path(self._index)

    def event(self, etype: str, **fields) -> dict:
        """Append one event; returns the record as written (post-sanitize)."""
        with self._lock:
            rec = {
                "ts": round(time.time(), 3),
                "seq": self._seq,
                "type": etype,
            }
            if self.host is not None:
                rec["host"] = self.host
            rec.update(_sanitize(fields))
            line = json.dumps(
                rec, default=_json_default, separators=(",", ":"), allow_nan=False
            )
            self._file.write(line + "\n")
            self._file.flush()
            if self.fsync:
                os.fsync(self._file.fileno())
            self._seq += 1
            if self._file.tell() >= self.max_bytes:
                self._rotate()
            return rec

    def _rotate(self) -> None:
        self._file.close()
        self._index += 1
        self._file = open(self._segment_path(self._index), "a", encoding="utf-8")
        if self.fsync:
            fsync_dir(self.directory)
        # prune the oldest segments beyond the retention budget
        segments = sorted(self.directory.glob(f"{_SEGMENT_PREFIX}*{_SEGMENT_SUFFIX}"))
        for old in segments[: max(0, len(segments) - self.keep)]:
            try:
                old.unlink()
            except OSError:  # pragma: no cover - concurrent cleanup
                pass

    def close(self) -> None:
        with self._lock:
            if not self._file.closed:
                self._file.flush()
                if self.fsync:
                    os.fsync(self._file.fileno())
                self._file.close()

    def __enter__(self) -> "RunJournal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def journal_dir(path: str | Path) -> Path | None:
    """Resolve a user-supplied path (run dir, journal dir, or one segment
    file) to the journal location, or None when there is no journal there."""
    p = Path(path)
    if p.is_file():
        return p
    if p.is_dir():
        if list(p.glob(f"{_SEGMENT_PREFIX}*{_SEGMENT_SUFFIX}")):
            return p
        sub = p / "journal"
        if sub.is_dir() and list(sub.glob(f"{_SEGMENT_PREFIX}*{_SEGMENT_SUFFIX}")):
            return sub
    return None


def read_journal(path: str | Path) -> list[dict]:
    """Reader half: every parseable event across all segments, in order.

    Tolerates exactly the damage a crash can cause: a torn final line
    (partial write + SIGKILL) is skipped; any other unparseable line is
    skipped too rather than aborting the whole read — a diagnosis from 999
    events beats an exception over 1. Raises ``FileNotFoundError`` only when
    there is no journal at ``path`` at all.
    """
    loc = journal_dir(path)
    if loc is None:
        raise FileNotFoundError(f"no journal segments under {path}")
    files = [loc] if loc.is_file() else sorted(
        loc.glob(f"{_SEGMENT_PREFIX}*{_SEGMENT_SUFFIX}")
    )
    events: list[dict] = []
    for f in files:
        text = f.read_bytes().decode("utf-8", errors="replace")
        for line in text.split("\n"):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail or damaged line — skip, keep reading
            if isinstance(rec, dict):
                events.append(rec)
    return events


def _host_of_journal_dir(d: Path) -> int:
    m = _HOST_DIR_RE.match(d.name)
    return int(m.group(1)) if m else 0


def read_merged_journal(path: str | Path) -> list[dict]:
    """Merged multi-host read: every parseable event from host 0's
    ``journal/`` AND every ``journal-host<i>/`` under a run dir, ordered by
    ``(ts, host, seq)``. Rows missing a ``host`` field (pre-multi-host
    journals, hand-built fixtures) inherit the host index encoded in their
    directory name (``journal/`` → 0), so legacy journals read identically.

    Accepts the same inputs as :func:`read_journal` — a run dir, one journal
    dir, or one segment file — and degrades to exactly its behavior (plus
    the ordering pass) when there is only one host's journal to read. Torn
    lines are per-segment, so one host dying mid-write never hides another
    host's rows. Raises ``FileNotFoundError`` when no journal exists at all.
    """
    p = Path(path)
    dirs: list[Path] = []
    if p.is_dir() and not list(p.glob(f"{_SEGMENT_PREFIX}*{_SEGMENT_SUFFIX}")):
        # a run dir: collect host-0's journal/ plus every journal-host<i>/
        cand = [p / "journal"] + sorted(
            (d for d in p.glob("journal-host*") if d.is_dir()),
            key=_host_of_journal_dir,
        )
        dirs = [
            d
            for d in cand
            if d.is_dir() and list(d.glob(f"{_SEGMENT_PREFIX}*{_SEGMENT_SUFFIX}"))
        ]
    if not dirs:
        # single file / single journal dir → read_journal's resolution rules
        events = read_journal(p)
        inferred = _host_of_journal_dir(p) if p.is_dir() else 0
        for e in events:
            e.setdefault("host", inferred)
    else:
        events = []
        for d in dirs:
            h = _host_of_journal_dir(d)
            for e in read_journal(d):
                e.setdefault("host", h)
                events.append(e)
    events.sort(
        key=lambda e: (e.get("ts", 0.0), e.get("host", 0), e.get("seq", 0))
    )
    return events


def env_fingerprint() -> dict:
    """What was this process, exactly? Enough to tell two restarts apart and
    to blame a config/environment change across a divergence boundary."""
    import platform
    import socket
    import sys

    from jumbo_mae_tpu_tpu import __version__

    info = {
        "version": __version__,
        "python": platform.python_version(),
        "platform": platform.platform(),
        "hostname": socket.gethostname(),
        "pid": os.getpid(),
        "argv": list(sys.argv),
    }
    try:
        import jax

        info["jax"] = jax.__version__
        info["backend"] = jax.default_backend()
        info["device_count"] = jax.device_count()
        info["process_count"] = jax.process_count()
    except Exception:  # noqa: BLE001 - fingerprint must never fail a run
        info["jax"] = "unavailable"
    for var in ("JAX_PLATFORMS", "GRAFT_FAULTS", "JUMBO_COMPILE_CACHE"):
        if os.environ.get(var):
            info.setdefault("env", {})[var] = os.environ[var]
    return info
