"""Unified metrics registry: counters, gauges, fixed-bucket histograms.

One registry threads through serve (`infer/engine.py`, `infer/batching.py`),
train (`cli/train.py` step loop) and data (`data/loader.py`) so every layer
reports through the same export surface (`obs/exporter.py` renders it as
Prometheus text; `obs/trace.py` aggregates spans into it). The reference had
no telemetry at all; the previous ad-hoc helpers (`utils/meters.py`,
`utils/mfu.py`) live here now behind compat shims.

Design constraints, in order:

- **Hot-path cheap.** A counter ``inc`` is one lock + one float add; metric
  *handles* are resolved once at instrument-time (``registry.counter(...)``
  / ``family.labels(...)``), never per observation. Disabling telemetry is
  swapping the default registry for :data:`NULL_REGISTRY`, whose handles are
  no-ops — instrumented code never branches.
- **Thread-safe.** Serving traffic hits the same histogram from many client
  threads; every metric guards its state with its own lock (pinned by
  ``tests/test_obs.py`` under a thread storm).
- **Fixed buckets.** Histograms are cumulative fixed-bound buckets (the
  Prometheus model): O(len(buckets)) memory forever, mergeable across
  scrapes, p50/p99 recoverable by the scraper — no unbounded sample lists
  on the request path.
"""

from __future__ import annotations

import threading
from bisect import bisect_left

import numpy as np

# Request/step latency default bounds (seconds). Wide on purpose: the same
# buckets serve sub-ms CPU smoke forwards and multi-second chip steps.
LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)
# Occupancy/fraction bounds for 0..1 ratios (batch fill, data-wait share).
RATIO_BUCKETS = (0.1, 0.25, 0.5, 0.75, 0.9, 1.0)


class Counter:
    """Monotonic float counter."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counters only go up, got {amount}")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Instantaneous value; settable and incrementable."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket cumulative histogram (Prometheus semantics: a bucket with
    upper bound ``le`` counts every observation ``<= le``; ``+Inf`` is
    implicit)."""

    __slots__ = ("_lock", "bounds", "_counts", "_sum", "_count")

    def __init__(self, buckets=LATENCY_BUCKETS):
        bounds = tuple(float(b) for b in buckets)
        if not bounds or list(bounds) != sorted(bounds):
            raise ValueError(f"buckets must be sorted and non-empty: {buckets}")
        self._lock = threading.Lock()
        self.bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # last slot = +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        idx = bisect_left(self.bounds, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1

    def observe_many(self, values) -> None:
        """Record a batch of observations under ONE lock hand-off — the
        per-request shape for hot serving paths (the micro-batcher records a
        whole flushed batch's latencies at once)."""
        bounds, counts = self.bounds, self._counts
        with self._lock:
            s = 0.0
            for v in values:
                counts[bisect_left(bounds, v)] += 1
                s += v
            self._sum += s
            self._count += len(values)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def cumulative(self) -> list[tuple[float, int]]:
        """``[(le, cumulative_count), ..., (inf, total)]`` — the scrape shape."""
        with self._lock:
            counts = list(self._counts)
        out, running = [], 0
        for bound, c in zip((*self.bounds, float("inf")), counts):
            running += c
            out.append((bound, running))
        return out

    def quantile(self, q: float) -> float:
        """Bucket-resolution quantile (upper bound of the bucket the q-th
        observation falls in) — a readout for reports/tests, not a substitute
        for scraper-side histogram_quantile."""
        cum = self.cumulative()
        total = cum[-1][1]
        if total == 0:
            return 0.0
        rank = q * total
        for bound, running in cum:
            if running >= rank:
                return bound
        return cum[-1][0]  # pragma: no cover - rank <= total always matches


class _NullCounter(Counter):
    def inc(self, amount: float = 1.0) -> None:
        pass


class _NullGauge(Gauge):
    def set(self, value: float) -> None:
        pass

    def inc(self, amount: float = 1.0) -> None:
        pass


class _NullHistogram(Histogram):
    def observe(self, value: float) -> None:
        pass

    def observe_many(self, values) -> None:
        pass


_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class Family:
    """One named metric with a fixed label schema; ``labels(...)`` resolves
    (and caches) the child for one label-value tuple. A label-less metric is
    a family with a single ``()`` child, and the family proxies the child's
    methods so instrument sites never special-case."""

    def __init__(self, name: str, kind: str, help: str, labelnames, **kw):
        self.name = name
        self.kind = kind
        self.help = help
        self.labelnames = tuple(labelnames)
        self._kw = kw
        self._children: dict[tuple, Counter | Gauge | Histogram] = {}
        self._lock = threading.Lock()
        if not self.labelnames:
            self.labels()  # eager default child → always rendered

    def labels(self, *values, **kwvalues):
        if kwvalues:
            values = tuple(str(kwvalues[n]) for n in self.labelnames)
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name} takes labels {self.labelnames}, got {values}"
            )
        child = self._children.get(values)
        if child is None:
            with self._lock:
                child = self._children.get(values)
                if child is None:
                    child = _TYPES[self.kind](**self._kw)
                    self._children[values] = child
        return child

    def children(self) -> dict[tuple, Counter | Gauge | Histogram]:
        with self._lock:
            return dict(self._children)

    # label-less convenience: family.inc()/set()/observe() hit the () child
    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def set(self, value: float) -> None:
        self.labels().set(value)

    def dec(self, amount: float = 1.0) -> None:
        self.labels().dec(amount)

    def observe(self, value: float) -> None:
        self.labels().observe(value)

    def observe_many(self, values) -> None:
        self.labels().observe_many(values)

    @property
    def value(self):
        return self.labels().value

    @property
    def count(self):
        return self.labels().count

    @property
    def sum(self):
        return self.labels().sum

    def quantile(self, q: float) -> float:
        return self.labels().quantile(q)

    def cumulative(self):
        return self.labels().cumulative()


def _escape_label(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class MetricsRegistry:
    """Thread-safe collection of metric families.

    ``counter/gauge/histogram`` are get-or-create and type-checked, so every
    layer can ask for its handle independently (the engine, the batcher and
    the train loop may all run in one process) and re-registration with a
    conflicting type fails loudly instead of silently splitting a name.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._families: dict[str, Family] = {}

    def _get(self, name: str, kind: str, help: str, labelnames, **kw) -> Family:
        fam = self._families.get(name)
        if fam is None:
            with self._lock:
                fam = self._families.get(name)
                if fam is None:
                    fam = Family(name, kind, help, labelnames, **kw)
                    self._families[name] = fam
        if fam.kind != kind:
            raise ValueError(
                f"metric {name!r} already registered as {fam.kind}, not {kind}"
            )
        if fam.labelnames != tuple(labelnames):
            raise ValueError(
                f"metric {name!r} already registered with labels "
                f"{fam.labelnames}, not {tuple(labelnames)}"
            )
        return fam

    def counter(self, name: str, help: str = "", labels=()) -> Family:
        return self._get(name, "counter", help, labels)

    def gauge(self, name: str, help: str = "", labels=()) -> Family:
        return self._get(name, "gauge", help, labels)

    def histogram(
        self, name: str, help: str = "", labels=(), buckets=LATENCY_BUCKETS
    ) -> Family:
        return self._get(name, "histogram", help, labels, buckets=buckets)

    def families(self) -> list[Family]:
        with self._lock:
            return [self._families[k] for k in sorted(self._families)]

    def render(self) -> str:
        """Prometheus text exposition format (0.0.4)."""
        lines: list[str] = []
        for fam in self.families():
            if fam.help:
                lines.append(f"# HELP {fam.name} {fam.help}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for values, child in sorted(fam.children().items()):
                pairs = [
                    f'{n}="{_escape_label(v)}"'
                    for n, v in zip(fam.labelnames, values)
                ]
                base = ",".join(pairs)
                if fam.kind == "histogram":
                    for le, cum in child.cumulative():
                        sel = ",".join([*pairs, f'le="{_fmt(le)}"'])
                        lines.append(f"{fam.name}_bucket{{{sel}}} {cum}")
                    sfx = f"{{{base}}}" if base else ""
                    lines.append(f"{fam.name}_sum{sfx} {_fmt(child.sum)}")
                    lines.append(f"{fam.name}_count{sfx} {child.count}")
                else:
                    sfx = f"{{{base}}}" if base else ""
                    lines.append(f"{fam.name}{sfx} {_fmt(child.value)}")
        return "\n".join(lines) + "\n" if lines else ""

    def snapshot(self) -> dict:
        """Nested plain-python readout (tests / JSON reports): name →
        {labels-tuple-as-str: value-or-histogram-dict}."""
        out: dict = {}
        for fam in self.families():
            entry: dict = {}
            for values, child in fam.children().items():
                key = ",".join(values)
                if fam.kind == "histogram":
                    entry[key] = {"count": child.count, "sum": child.sum}
                else:
                    entry[key] = child.value
            out[fam.name] = entry
        return out


class NullRegistry(MetricsRegistry):
    """Telemetry-off registry: hands out no-op metric children, so swapping
    the default registry disables every instrument site with zero branches
    in instrumented code (the bench's telemetry-off leg runs through this)."""

    def counter(self, name, help="", labels=()):
        fam = Family(name, "counter", help, labels)
        fam._children.clear()
        _null_children(fam, _NullCounter)
        return fam

    def gauge(self, name, help="", labels=()):
        fam = Family(name, "gauge", help, labels)
        fam._children.clear()
        _null_children(fam, _NullGauge)
        return fam

    def histogram(self, name, help="", labels=(), buckets=LATENCY_BUCKETS):
        fam = Family(name, "histogram", help, labels, buckets=buckets)
        fam._children.clear()
        _null_children(fam, _NullHistogram, buckets=buckets)
        return fam

    def render(self) -> str:
        return ""


def _null_children(fam: Family, cls, **kw):
    null = cls(**kw)
    fam.labels = lambda *a, **k: null  # type: ignore[method-assign]
    if not fam.labelnames:
        fam._children[()] = null


NULL_REGISTRY = NullRegistry()
_default = MetricsRegistry()
_default_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry every instrument site reports to
    unless handed an explicit one."""
    return _default


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    """Swap the process default (telemetry off = ``NULL_REGISTRY``); returns
    the previous registry so callers can restore it."""
    global _default
    with _default_lock:
        prev, _default = _default, registry
    return prev


class AverageMeter:
    """Host-side metric aggregation (the train loop's log-window buffer).

    Equivalent of the reference's ``AverageMeter``
    (``/root/reference/src/utils.py:36-52``): buffer per-step metric dicts,
    then emit prefixed means — except keys marked ``use_latest`` (the live
    learning rate) which report their last value.
    """

    def __init__(self, *, use_latest: tuple[str, ...] = ("learning_rate",)):
        self.use_latest = set(use_latest)
        self.buffer: dict[str, list[float]] = {}

    def update(self, metrics: dict):
        for k, v in metrics.items():
            self.buffer.setdefault(k, []).append(float(np.asarray(v)))

    def summary(self, prefix: str = "") -> dict[str, float]:
        out = {}
        for k, vals in self.buffer.items():
            if not vals:
                continue
            value = vals[-1] if k in self.use_latest else float(np.mean(vals))
            out[prefix + k] = value
        self.buffer = {}
        return out
