"""Opt-in instrumented locks: runtime lock-order and long-hold sentinel.

``tools.graftlint`` proves lock discipline *statically* for the shapes it
can see; lockwatch is the runtime half — it watches the orders threads
actually take locks in while a chaos suite is hammering the serving tier,
and turns an inversion into a metric + journal event instead of a
once-a-month production deadlock.

Usage: construct locks through the factory instead of ``threading.Lock``::

    self._lock = lockwatch.lock("engine.master")

With lockwatch disabled (the default) the factory returns a plain
``threading.Lock`` — zero overhead, byte-identical behavior. Enabled
(``GRAFT_LOCKWATCH=1`` in the env, or :func:`enable`), it returns a
wrapper that keeps a per-thread stack of held lockwatch locks and
maintains a process-global first-seen acquisition-order graph:

* acquiring B while holding A records the directed edge A→B; if B→A was
  ever observed before, that is an **order inversion** — two threads on
  the two paths can deadlock. It increments
  ``lock_order_violations_total``, journals a ``lock_order_violation``
  event (when a journal is attached), and warns once per pair.
* a hold longer than ``GRAFT_LOCKWATCH_HOLD_S`` seconds (default 0.5) is
  a **blocking-while-held** proxy — something slow (compile, fsync,
  device sync) ran under the lock. It increments
  ``lock_blocking_while_held_total{lock=...}``.

Per-lock gauges/counters: ``lock_acquire_total{lock}``,
``lock_wait_seconds{lock}``, ``lock_hold_seconds{lock}``,
``lock_order_violations_total``, ``lock_blocking_while_held_total{lock}``.

The sentinel's own bookkeeping runs under one internal lock that is never
held across user code, metrics, or the journal — lockwatch cannot deadlock
the thing it watches.
"""

from __future__ import annotations

import os
import threading
import time
import warnings

__all__ = [
    "lock",
    "enable",
    "disable",
    "enabled",
    "attach_journal",
    "order_edges",
    "violations",
    "reset",
    "WatchedLock",
]

_ENV_VAR = "GRAFT_LOCKWATCH"
_HOLD_ENV_VAR = "GRAFT_LOCKWATCH_HOLD_S"

_enabled = os.environ.get(_ENV_VAR, "") not in ("", "0", "false")
_hold_threshold_s = float(os.environ.get(_HOLD_ENV_VAR, "0.5") or "0.5")

# --- process-global sentinel state -------------------------------------
_state_lock = threading.Lock()   # guards the maps below; never held
                                 # across user code / metrics / journal
_edges: dict[tuple[str, str], dict] = {}      # (held, acquired) -> info
_violations: list[dict] = []
_warned_pairs: set[frozenset] = set()
_journal = None                  # attach_journal() target (duck-typed)

_held = threading.local()        # per-thread stack of held lock names

_metrics = None                  # lazy _Metrics singleton


def _held_stack() -> list:
    stack = getattr(_held, "stack", None)
    if stack is None:
        stack = _held.stack = []
    return stack


class _Metrics:
    def __init__(self):
        from jumbo_mae_tpu_tpu.obs.metrics import get_registry

        reg = get_registry()
        self.acquires = reg.counter(
            "lock_acquire_total",
            "lockwatch: acquisitions per instrumented lock",
            labels=("lock",),
        )
        self.wait = reg.histogram(
            "lock_wait_seconds",
            "lockwatch: time spent waiting to acquire",
            labels=("lock",),
        )
        self.hold = reg.histogram(
            "lock_hold_seconds",
            "lockwatch: time the lock was held",
            labels=("lock",),
        )
        self.order_violations = reg.counter(
            "lock_order_violations_total",
            "lockwatch: acquisition-order inversions observed (A before B "
            "on one thread, B before A on another)",
        )
        self.long_holds = reg.counter(
            "lock_blocking_while_held_total",
            "lockwatch: holds longer than GRAFT_LOCKWATCH_HOLD_S — "
            "something blocking ran under the lock",
            labels=("lock",),
        )


def _get_metrics() -> _Metrics:
    global _metrics
    if _metrics is None:
        _metrics = _Metrics()
    return _metrics


def enabled() -> bool:
    return _enabled


def enable() -> None:
    """Turn the factory on for locks created *after* this call."""
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def attach_journal(journal) -> None:
    """Journal ``lock_order_violation`` events to ``journal`` (anything
    with an ``event(etype, **fields)`` method); pass None to detach."""
    global _journal
    _journal = journal


def order_edges() -> dict:
    """Snapshot of the observed acquisition-order graph (test/debug)."""
    with _state_lock:
        return {k: dict(v) for k, v in _edges.items()}


def violations() -> list[dict]:
    with _state_lock:
        return [dict(v) for v in _violations]


def reset() -> None:
    """Drop all observed edges/violations (tests)."""
    global _metrics
    with _state_lock:
        _edges.clear()
        _violations.clear()
        _warned_pairs.clear()
    _metrics = None


def _record_acquisition(name: str, holder_stack: list[str]) -> list[dict]:
    """Record edges holder→name; return inversion records to publish
    (computed under the state lock, published by the caller outside it)."""
    inversions: list[dict] = []
    thread = threading.current_thread().name
    with _state_lock:
        for holder in holder_stack:
            if holder == name:
                continue
            edge = (holder, name)
            if edge not in _edges:
                _edges[edge] = {"thread": thread, "count": 0}
            _edges[edge]["count"] += 1
            reverse = _edges.get((name, holder))
            if reverse is not None:
                pair = frozenset((holder, name))
                record = {
                    "held": holder,
                    "acquired": name,
                    "thread": thread,
                    "reverse_thread": reverse["thread"],
                    "reverse_count": reverse["count"],
                }
                _violations.append(record)
                if pair not in _warned_pairs:
                    _warned_pairs.add(pair)
                    inversions.append(record)
                else:
                    inversions.append(None)  # counted, not re-warned
    return inversions


def _publish_inversions(inversions: list) -> None:
    metrics = _get_metrics()
    for record in inversions:
        metrics.order_violations.inc()
        if record is None:
            continue
        warnings.warn(
            f"lockwatch: lock-order inversion — thread "
            f"{record['thread']!r} acquired {record['acquired']!r} while "
            f"holding {record['held']!r}, but thread "
            f"{record['reverse_thread']!r} has taken them in the opposite "
            "order; these two paths can deadlock",
            RuntimeWarning,
            stacklevel=4,
        )
        journal = _journal
        if journal is not None:
            try:
                journal.event("lock_order_violation", **record)
            except Exception:  # noqa: BLE001 — the sentinel must not kill serving
                pass


class WatchedLock:
    """Drop-in for ``threading.Lock`` with order/hold instrumentation."""

    __slots__ = ("name", "_lock", "_acquired_at")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._acquired_at = {}  # thread ident -> monotonic acquire time

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        t0 = time.monotonic()
        ok = self._lock.acquire(blocking, timeout)
        if not ok:
            return False
        waited = time.monotonic() - t0
        stack = _held_stack()
        inversions = _record_acquisition(self.name, list(stack))
        stack.append(self.name)
        self._acquired_at[threading.get_ident()] = time.monotonic()
        metrics = _get_metrics()
        metrics.acquires.labels(lock=self.name).inc()
        metrics.wait.labels(lock=self.name).observe(waited)
        if inversions:
            _publish_inversions(inversions)
        return True

    def release(self) -> None:
        held_s = None
        t0 = self._acquired_at.pop(threading.get_ident(), None)
        if t0 is not None:
            held_s = time.monotonic() - t0
        stack = _held_stack()
        if stack and stack[-1] == self.name:
            stack.pop()
        elif self.name in stack:  # released out of LIFO order: still remove
            stack.remove(self.name)
        self._lock.release()
        if held_s is not None:
            metrics = _get_metrics()
            metrics.hold.labels(lock=self.name).observe(held_s)
            if held_s > _hold_threshold_s:
                metrics.long_holds.labels(lock=self.name).inc()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"WatchedLock({self.name!r}, locked={self.locked()})"


def lock(name: str):
    """Lock factory: a :class:`WatchedLock` when lockwatch is enabled,
    else a plain ``threading.Lock`` (zero overhead, identical semantics)."""
    if _enabled:
        return WatchedLock(name)
    return threading.Lock()
