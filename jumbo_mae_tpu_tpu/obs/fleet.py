"""File-based fleet-health protocol: per-host beacons + a host-0 aggregator.

At pod scale the failure modes that dominate are exactly the ones a
single-host telemetry stack cannot see: one straggler host dragging every
synchronous step (collectives make the *fleet* as slow as its slowest
member), one host silently quarantining its data shards, or one host dying
outright while the others hang in a collective. This module makes those
visible with **no networking at all** — the only shared medium is the run
directory (NFS / GCS-fuse on a real pod, tmpfs in tests), so the protocol
is CPU-testable with plain files and never adds an RPC dependency to the
train loop.

Protocol:

- every process owns one **beacon** file, ``<run_dir>/fleet/host-<i>.json``,
  rewritten atomically (tmp + ``os.replace``) at each step entry and log
  boundary. Schema: ``host``, ``pid``, ``hostname``, ``step``, ``heartbeat``
  (epoch seconds), ``step_time_ema_s``, ``data_wait_fraction``,
  ``shard_retries``, ``shard_quarantines``, ``sentinel_bad_steps``, plus
  optional memory fields ``rss_bytes`` / ``device_peak_bytes`` (omitted
  when unknown — readers must tolerate their absence, so old-schema
  beacons keep parsing). A reader can never observe a torn beacon — only
  the previous or the next version.
- host 0 runs a :class:`FleetAggregator` that scans the beacon dir (at its
  own log boundaries and as an exporter pre-scrape hook), publishes
  ``fleet_*{host=}`` gauges, and drives a per-host status machine:

  * **straggler** — the host trails the fleet-max step by ``lag_steps``,
    its step-time EMA exceeds ``ratio`` × the fleet median, or its data-wait
    fraction is both high (≥ 0.3) and far above the fleet median (≥ 2×) —
    the last one matters because a fully synchronous fleet is *lockstep*
    (steps and EMAs equalize; only the time breakdown differs). Needs ≥ 2
    live hosts. Entering emits a ``fleet_straggler`` journal event carrying
    the dominant *symptom* (``data_wait`` / ``step_time`` / ``step_lag``).
  * **lost** — the heartbeat is older than ``dead_after_s``; emits
    ``fleet_host_lost``. A fresh beacon after that emits
    ``fleet_host_rejoined`` (a restarted process rejoining the run).

  ``degraded()`` (any host straggling/lost) is shaped for
  :meth:`HealthState.degraded_when` — soft, never a 503 — and ``summary()``
  for ``HealthState.probe`` so ``/healthz`` carries per-host health.

Event payloads name the affected host ``host_id`` — ``host`` on a journal
row is the row's *writer* (always 0 for aggregator events), stamped by
:class:`~jumbo_mae_tpu_tpu.obs.journal.RunJournal`.

Caveats by design: beacon timestamps are wall clocks compared across hosts,
so thresholds are seconds-scale and assume NTP-sane skew; a host that never
beacons at all (crashed before its first step) shows up as *missing* in the
summary but emits no lost event — there is no heartbeat history to age.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from pathlib import Path

from jumbo_mae_tpu_tpu.obs.metrics import MetricsRegistry, get_registry

__all__ = ["FleetAggregator", "HostBeacon", "read_beacons"]

_BEACON_PREFIX = "host-"
_BEACON_SUFFIX = ".json"

# per-host gauge fields copied straight from beacon → fleet_<name>{host=}
_BEACON_GAUGES = (
    ("step", "fleet_step", "last step this host reported"),
    (
        "step_time_ema_s",
        "fleet_step_time_ema_seconds",
        "per-host step-time EMA from its beacon",
    ),
    (
        "data_wait_fraction",
        "fleet_data_wait_fraction",
        "per-host share of wall time waiting on data (last log window)",
    ),
    ("shard_retries", "fleet_shard_retries", "per-host shard read retries"),
    (
        "shard_quarantines",
        "fleet_shard_quarantines",
        "per-host shards abandoned by the retry layer",
    ),
    (
        "sentinel_bad_steps",
        "fleet_sentinel_bad_steps",
        "per-host non-finite/skipped steps seen by the sentinel",
    ),
    (
        "rss_bytes",
        "fleet_rss_bytes",
        "per-host resident set size from its beacon (memwatch sample)",
    ),
    (
        "device_peak_bytes",
        "fleet_device_peak_bytes",
        "per-host high-water device (HBM) bytes from its beacon",
    ),
    (
        "goodput_fraction",
        "fleet_goodput_fraction",
        "per-host share of wall-clock in productive step compute",
    ),
    (
        "generation",
        "fleet_generation",
        "elastic generation this host's process was launched in",
    ),
)


class HostBeacon:
    """Writer half: one process's atomically-replaced health file.

    ``write`` is called from the step loop (heartbeat cadence), so it must
    be cheap: one small JSON dump + rename, no fsync — a beacon lost to a
    power cut is immediately superseded by the next one, durability buys
    nothing here (the *journal* owns durable history).
    """

    def __init__(
        self,
        directory: str | Path,
        *,
        host: int,
        pid: int | None = None,
        hostname: str | None = None,
    ):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.host = int(host)
        self.pid = os.getpid() if pid is None else int(pid)
        self.hostname = hostname or socket.gethostname()
        self.path = self.directory / f"{_BEACON_PREFIX}{self.host}{_BEACON_SUFFIX}"
        # pid-suffixed tmp name: two processes mistakenly sharing a host
        # index corrupt nothing — last rename wins, both files stay whole
        self._tmp = self.directory / f".{_BEACON_PREFIX}{self.host}.tmp.{self.pid}"
        self.writes = 0

    def write(
        self,
        *,
        step: int,
        step_time_ema_s: float | None = None,
        data_wait_fraction: float | None = None,
        shard_retries: int = 0,
        shard_quarantines: int = 0,
        sentinel_bad_steps: int = 0,
        rss_bytes: int | None = None,
        device_peak_bytes: int | None = None,
        now: float | None = None,
        **extra,
    ) -> dict:
        """Publish this host's current health; returns the payload written."""
        payload = {
            "host": self.host,
            "pid": self.pid,
            "hostname": self.hostname,
            "step": int(step),
            "heartbeat": round(time.time() if now is None else float(now), 3),
            "step_time_ema_s": (
                None if step_time_ema_s is None else round(float(step_time_ema_s), 6)
            ),
            "data_wait_fraction": (
                None if data_wait_fraction is None else round(float(data_wait_fraction), 4)
            ),
            "shard_retries": int(shard_retries),
            "shard_quarantines": int(shard_quarantines),
            "sentinel_bad_steps": int(sentinel_bad_steps),
        }
        # memory fields are OPTIONAL schema: written only when known, so a
        # beacon from a build/backend without memwatch stays byte-identical
        # to the old schema and every reader keeps working
        if rss_bytes is not None:
            payload["rss_bytes"] = int(rss_bytes)
        if device_peak_bytes is not None:
            payload["device_peak_bytes"] = int(device_peak_bytes)
        payload.update(extra)
        self._tmp.write_text(json.dumps(payload, separators=(",", ":")))
        # deliberately NOT fsync_dir'd (unlike journal/ckpt/warmcache
        # commits): beacons are per-step advisory liveness data rewritten
        # every few seconds — losing one to power loss costs a single
        # staleness window, while an fsync here would tax every step
        os.replace(self._tmp, self.path)
        self.writes += 1
        return payload


def read_beacons(directory: str | Path) -> dict[int, dict]:
    """Reader half: ``{host index → beacon payload}`` for every parseable
    beacon under ``directory``. Atomic replacement means a *well-behaved*
    writer can never be caught torn, but a corrupt or foreign file (manual
    edit, partial copy of the run dir) is skipped, never an error."""
    out: dict[int, dict] = {}
    d = Path(directory)
    if not d.is_dir():
        return out
    for p in sorted(d.glob(f"{_BEACON_PREFIX}*{_BEACON_SUFFIX}")):
        name = p.name[len(_BEACON_PREFIX) : -len(_BEACON_SUFFIX)]
        try:
            host = int(name)
        except ValueError:
            continue
        try:
            rec = json.loads(p.read_text())
        except (OSError, json.JSONDecodeError):
            continue
        if isinstance(rec, dict):
            out[host] = rec
    return out


class FleetAggregator:
    """Host-0 half: scan beacons → gauges + status machine + journal events.

    ``scan()`` is safe to call from both the train loop and the exporter's
    scrape thread (one lock); it is cheap — N small file reads — so calling
    it at every scrape keeps /metrics live even while host 0's main thread
    is blocked inside a collective waiting on the very host being diagnosed.
    """

    OK, STRAGGLER, LOST = "ok", "straggler", "lost"

    def __init__(
        self,
        directory: str | Path,
        *,
        expected_hosts: int | None = None,
        lag_steps: int = 2,
        ratio: float = 1.5,
        dead_after_s: float = 60.0,
        mem_ratio: float = 1.5,
        mem_floor_bytes: int = 256 * 1024 * 1024,
        on_event=None,
        registry: MetricsRegistry | None = None,
    ):
        self.directory = Path(directory)
        self.expected_hosts = None if expected_hosts is None else int(expected_hosts)
        self.lag_steps = max(1, int(lag_steps))
        self.ratio = float(ratio)
        self.dead_after_s = float(dead_after_s)
        # memory outlier: rss >= mem_ratio × fleet median AND the excess
        # over the median clears an absolute floor — the ratio alone would
        # flag noise on small-RSS smoke processes
        self.mem_ratio = float(mem_ratio)
        self.mem_floor_bytes = int(mem_floor_bytes)
        self.on_event = on_event  # on_event(etype, **payload) → journal
        reg = registry if registry is not None else get_registry()
        self._g_beacon = [
            (field, reg.gauge(name, help, labels=("host",)))
            for field, name, help in _BEACON_GAUGES
        ]
        self._g_lag = reg.gauge(
            "fleet_step_lag",
            "steps this host trails the fleet-max reported step",
            labels=("host",),
        )
        self._g_age = reg.gauge(
            "fleet_heartbeat_age_seconds",
            "seconds since this host's beacon was last refreshed",
            labels=("host",),
        )
        self._g_straggler = reg.gauge(
            "fleet_straggler",
            "1 while this host is flagged a straggler (lag, step-time ratio, "
            "or data-wait dominance)",
            labels=("host",),
        )
        self._g_up = reg.gauge(
            "fleet_host_up",
            "1 while this host's heartbeat is fresher than run.fleet_dead_after_s",
            labels=("host",),
        )
        self._g_mem_outlier = reg.gauge(
            "fleet_mem_outlier",
            "1 while this host's beacon RSS is a fleet memory outlier "
            "(>= mem_ratio x the fleet median, past the absolute floor)",
            labels=("host",),
        )
        self._g_alive = reg.gauge("fleet_hosts_alive", "hosts with a fresh heartbeat")
        self._g_goodput = reg.gauge(
            "fleet_goodput",
            "fleet goodput: mean productive wall-clock share across live hosts",
        )
        self._g_expected = reg.gauge(
            "fleet_hosts_expected", "process count this run was launched with"
        )
        self._lock = threading.Lock()
        self._status: dict[int, str] = {}
        self._summary: dict = {"hosts": {}, "alive": 0, "stragglers": [], "lost": []}
        self._last_scan = 0.0  # monotonic; rate-limits the /healthz probes

    # ------------------------------------------------------------- scanning

    def scan(self, now: float | None = None) -> dict:
        """Read every beacon, refresh gauges, run the status machine, emit
        transition events. Returns (and caches) the fleet summary."""
        with self._lock:
            return self._scan_locked(time.time() if now is None else float(now))

    def _scan_locked(self, now: float) -> dict:
        beacons = read_beacons(self.directory)
        alive = {
            h: b
            for h, b in beacons.items()
            if now - float(b.get("heartbeat", 0.0)) <= self.dead_after_s
        }
        max_step = max(
            (int(b.get("step", 0)) for b in (alive or beacons).values()), default=0
        )
        # LOWER-middle medians: with an even fleet (the common 2-host case)
        # the upper middle would be the slow host's own number, so no host
        # could ever exceed ratio × median — the straggler check would be
        # structurally blind exactly where the CI smoke exercises it
        emas = sorted(
            float(b["step_time_ema_s"])
            for b in alive.values()
            if b.get("step_time_ema_s")
        )
        median_ema = emas[(len(emas) - 1) // 2] if emas else 0.0
        waits = sorted(
            float(b["data_wait_fraction"])
            for b in alive.values()
            if b.get("data_wait_fraction") is not None
        )
        median_wait = waits[(len(waits) - 1) // 2] if waits else 0.0
        rsses = sorted(
            float(b["rss_bytes"])
            for b in alive.values()
            if b.get("rss_bytes") is not None
        )
        median_rss = rsses[(len(rsses) - 1) // 2] if rsses else 0.0

        hosts: dict[int, dict] = {}
        events: list[tuple[str, dict]] = []
        for h, b in sorted(beacons.items()):
            age = max(0.0, now - float(b.get("heartbeat", 0.0)))
            step = int(b.get("step", 0))
            lag = max(0, max_step - step)
            ema = b.get("step_time_ema_s")
            wait = b.get("data_wait_fraction")
            lost = age > self.dead_after_s
            slow_ema = (
                not lost
                and len(alive) >= 2
                and ema is not None
                and median_ema > 0
                and float(ema) >= self.ratio * median_ema
            )
            # under fully synchronous collectives the fleet is LOCKSTEP: the
            # slow host drags everyone, so step counters and wall-clock EMAs
            # equalize fleet-wide and neither lag nor the ratio check can
            # single it out — the distinguishing signal is where the time
            # goes, i.e. a data-wait share far above the fleet's
            slow_wait = (
                not lost
                and len(alive) >= 2
                and wait is not None
                and float(wait) >= 0.3
                and float(wait) >= 2.0 * max(median_wait, 0.05)
            )
            straggler = not lost and len(alive) >= 2 and (
                lag >= self.lag_steps or slow_ema or slow_wait
            )
            status = self.LOST if lost else self.STRAGGLER if straggler else self.OK
            symptom = self._symptom(wait, median_wait, slow_ema)
            prev = self._status.get(h, self.OK)
            if status != prev:
                if status == self.LOST:
                    events.append(
                        (
                            "fleet_host_lost",
                            {"host_id": h, "last_step": step, "heartbeat_age_s": round(age, 3)},
                        )
                    )
                elif prev == self.LOST:
                    events.append(
                        (
                            "fleet_host_rejoined",
                            {"host_id": h, "step": step, "lost_for_s": round(age, 3)},
                        )
                    )
                if status == self.STRAGGLER:
                    events.append(
                        (
                            "fleet_straggler",
                            {
                                "host_id": h,
                                "step": step,
                                "lag": lag,
                                "symptom": symptom,
                                "step_time_ema_s": ema,
                                "fleet_median_step_s": round(median_ema, 6),
                                "data_wait_fraction": wait,
                            },
                        )
                    )
            # memory outlier: a flag, not a status — a leaking host still
            # makes lockstep progress, so it must not shadow straggler/lost
            rss = b.get("rss_bytes")
            mem_outlier = (
                not lost
                and len(alive) >= 2
                and rss is not None
                and median_rss > 0
                and float(rss) >= self.mem_ratio * median_rss
                and float(rss) - median_rss >= self.mem_floor_bytes
            )
            self._status[h] = status
            hosts[h] = {
                "status": status,
                "step": step,
                "lag": lag,
                "heartbeat_age_s": round(age, 3),
                "step_time_ema_s": ema,
                "data_wait_fraction": wait,
                "shard_retries": int(b.get("shard_retries", 0) or 0),
                "shard_quarantines": int(b.get("shard_quarantines", 0) or 0),
                "sentinel_bad_steps": int(b.get("sentinel_bad_steps", 0) or 0),
                "rss_bytes": None if rss is None else int(rss),
                "device_peak_bytes": (
                    None
                    if b.get("device_peak_bytes") is None
                    else int(b["device_peak_bytes"])
                ),
                "mem_outlier": bool(mem_outlier),
                "symptom": symptom if status != self.OK else None,
            }
            # gauges (string label values per Prometheus convention)
            hs = str(h)
            for field, fam in self._g_beacon:
                v = b.get(field)
                if v is not None:
                    fam.labels(host=hs).set(float(v))
            self._g_lag.labels(host=hs).set(lag)
            self._g_age.labels(host=hs).set(age)
            self._g_straggler.labels(host=hs).set(1 if status == self.STRAGGLER else 0)
            self._g_up.labels(host=hs).set(0 if lost else 1)
            self._g_mem_outlier.labels(host=hs).set(1 if mem_outlier else 0)

        self._g_alive.set(len(alive))
        # fleet goodput: lockstep collectives equalize productive time, so
        # the mean over live hosts IS the fleet figure (a wedged host drags
        # every ledger down with it)
        goodputs = [
            float(b["goodput_fraction"])
            for b in alive.values()
            if b.get("goodput_fraction") is not None
        ]
        fleet_goodput = (
            round(sum(goodputs) / len(goodputs), 4) if goodputs else None
        )
        if fleet_goodput is not None:
            self._g_goodput.set(fleet_goodput)
        if self.expected_hosts is not None:
            self._g_expected.set(self.expected_hosts)
        missing = (
            sorted(set(range(self.expected_hosts)) - set(beacons))
            if self.expected_hosts is not None
            else []
        )
        summary = {
            "hosts": hosts,
            "alive": len(alive),
            "expected": self.expected_hosts,
            "max_step": max_step,
            "missing": missing,
            "stragglers": [h for h, s in hosts.items() if s["status"] == self.STRAGGLER],
            "lost": [h for h, s in hosts.items() if s["status"] == self.LOST],
            "mem_outliers": [h for h, s in hosts.items() if s["mem_outlier"]],
            "goodput_fraction": fleet_goodput,
        }
        summary["degraded"] = bool(summary["stragglers"] or summary["lost"])
        self._summary = summary
        self._last_scan = time.monotonic()
        # events OUTSIDE per-host loop state but inside the lock: transition
        # order within one scan is deterministic; the journal has its own lock
        if self.on_event is not None:
            for etype, payload in events:
                try:
                    self.on_event(etype, **payload)
                except Exception:  # noqa: BLE001 — health must not kill the run
                    pass
        return summary

    @staticmethod
    def _symptom(wait, median_wait: float, slow_ema: bool) -> str:
        """Dominant-symptom attribution for an unhealthy host: a data-starved
        host shows a wait fraction far above the fleet's; otherwise blame the
        step-time ratio if that's what tripped, else plain step lag."""
        if wait is not None and float(wait) >= 0.3 and float(wait) >= 2.0 * max(
            median_wait, 0.05
        ):
            return "data_wait"
        if slow_ema:
            return "step_time"
        return "step_lag"

    def _fresh_summary(self, max_age_s: float = 1.0) -> dict:
        with self._lock:
            if time.monotonic() - self._last_scan > max_age_s:
                return self._scan_locked(time.time())
            return self._summary

    # -------------------------------------------------- /healthz integration

    def degraded(self) -> bool:
        """Shaped for :meth:`HealthState.degraded_when`: any straggling or
        lost host. Rescans when the cached verdict is stale, so a /healthz
        poll flips within one heartbeat window of a host dying even while
        the train thread is wedged in a collective."""
        return bool(self._fresh_summary().get("degraded"))

    def summary(self) -> dict:
        """Shaped for ``HealthState.probe("fleet", ...)``: the per-host
        health table under ``info.fleet`` in the /healthz body."""
        return self._fresh_summary()
