"""Step-deadline hang watchdog: convert a wedged collective into a fast,
distinct-exit-code death the :class:`~jumbo_mae_tpu_tpu.train.elastic.ElasticSupervisor`
can act on.

Why exit instead of recover in-process: a blocked all-reduce cannot be
cancelled from Python — the runtime thread is parked inside the collective
waiting for a peer that will never answer. The only useful move is to die
*quickly* and *legibly*: journal a ``hang_detected`` event, give the async
checkpoint writer a bounded chance to drain, and ``os._exit`` with a code
the supervisor maps to "restart me" (``EXIT_HANG``), not "I crashed".

Shape:

- :meth:`HangWatchdog.beat` is called from the step loop (pre-step hook)
  and resets the deadline. No beat for ``deadline_s`` seconds → fire.
- :meth:`HangWatchdog.expected` mirrors the retrace sentinel's
  ``expected()`` pattern: a re-entrant pause window for phases that are
  legitimately slow and collective-free (first-step compile, eval build,
  checkpoint restore). While any window is open the deadline is suspended,
  and the clock restarts from the moment the last window closes.
- :meth:`HangWatchdog.check` contains *all* firing logic and takes the
  current time as an argument, so unit tests drive it with a fake clock
  and never need the poll thread. The thread (:meth:`start`) just calls
  ``check(clock())`` every ``poll_s``.
- Fires at most once (latched), even with a racing poll thread.

The watchdog is per-host and deliberately knows nothing about the fleet:
host 0 may *also* detect the wedge via stale beacons, but a wedged host 0
can't run its own aggregator scan — its step loop is parked. Self-death by
deadline is the only detector that works on the wedged host itself.
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from typing import Callable

#: Default exit code — kept equal to ``train.engine.EXIT_HANG`` (pinned by
#: a unit test; obs must not import train).
DEFAULT_EXIT_CODE = 44


class HangWatchdog:
    """Deadline watchdog over step progress (see module docstring).

    ``on_fire(info)`` callbacks run in firing order before the drain; they
    must be fast and exception-safe (exceptions are swallowed — the exit
    must happen). ``drain()`` is the bounded checkpoint drain hook (e.g.
    ``Checkpointer.wait``); it runs in a side thread joined with
    ``drain_timeout_s`` so a wedged Orbax commit cannot turn the watchdog
    itself into a hang. ``exit_fn`` defaults to ``os._exit`` — ``sys.exit``
    would only unwind the watchdog thread, and atexit machinery may block
    on the same wedged collective.
    """

    def __init__(
        self,
        deadline_s: float,
        *,
        clock: Callable[[], float] = time.monotonic,
        exit_code: int = DEFAULT_EXIT_CODE,
        exit_fn: Callable[[int], None] = os._exit,
        drain: Callable[[], None] | None = None,
        drain_timeout_s: float = 30.0,
        poll_s: float = 1.0,
    ):
        self.deadline_s = float(deadline_s)
        self.exit_code = int(exit_code)
        self._clock = clock
        self._exit_fn = exit_fn
        self._drain = drain
        self.drain_timeout_s = float(drain_timeout_s)
        self.poll_s = float(poll_s)
        self._lock = threading.Lock()
        self._armed = False
        self._fired = False
        self._expected_depth = 0
        self._last_beat = float(clock())
        self._last_step = 0
        self._on_fire: list[Callable[[dict], None]] = []
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    # -- registration / lifecycle ----------------------------------------
    def on_fire(self, fn: Callable[[dict], None]):
        """Register ``fn(info)`` to run when the deadline trips (before the
        drain and the exit). Usable as a decorator."""
        self._on_fire.append(fn)
        return fn

    def arm(self) -> None:
        """Start enforcing the deadline, measured from now."""
        with self._lock:
            self._armed = True
            self._last_beat = float(self._clock())

    def disarm(self) -> None:
        with self._lock:
            self._armed = False

    def beat(self, step: int | None = None) -> None:
        """Record step progress; resets the deadline."""
        with self._lock:
            self._last_beat = float(self._clock())
            if step is not None:
                self._last_step = int(step)

    @contextmanager
    def expected(self, reason: str = ""):
        """Re-entrant pause window for legitimately slow, collective-free
        phases (compile, eval, restore) — mirrors ``RetraceSentinel``."""
        del reason  # documentation at the call site; not recorded
        with self._lock:
            self._expected_depth += 1
        try:
            yield
        finally:
            with self._lock:
                self._expected_depth -= 1
                # restart the clock: time spent inside the window is not
                # evidence of a wedge
                self._last_beat = float(self._clock())

    # -- firing logic ----------------------------------------------------
    def check(self, now: float | None = None) -> bool:
        """Evaluate the deadline at time ``now`` (defaults to the clock).
        Returns True iff this call fired the watchdog. All state reads and
        the fire latch happen under the lock; the side-effecting fire path
        runs outside it."""
        if now is None:
            now = float(self._clock())
        with self._lock:
            if (
                self._fired
                or not self._armed
                or self._expected_depth > 0
                or self.deadline_s <= 0
            ):
                return False
            stalled_s = now - self._last_beat
            if stalled_s < self.deadline_s:
                return False
            self._fired = True  # latch before releasing the lock
            info = {
                "stalled_s": round(stalled_s, 3),
                "deadline_s": self.deadline_s,
                "step": self._last_step,
            }
        self._fire(info)
        return True

    @property
    def fired(self) -> bool:
        with self._lock:
            return self._fired

    def _fire(self, info: dict) -> None:
        for fn in self._on_fire:
            try:
                fn(info)
            except Exception:  # noqa: BLE001 - the exit must happen
                pass
        if self._drain is not None:
            # Bounded drain: the async checkpoint commit usually finishes,
            # but if Orbax is itself wedged behind the dead collective we
            # must not hang here — the supervisor's fallback restore walks
            # back past a torn step.
            t = threading.Thread(target=self._safe_drain, daemon=True)
            t.start()
            t.join(self.drain_timeout_s)
        self._exit_fn(self.exit_code)

    def _safe_drain(self) -> None:
        try:
            self._drain()  # type: ignore[misc]
        except Exception:  # noqa: BLE001
            pass

    # -- poll thread -----------------------------------------------------
    def start(self) -> None:
        """Spawn the daemon poll thread (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._poll, name="hangwatch", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        """Stop the poll thread (does not reset the fired latch)."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(self.poll_s * 2 + 1.0)
        self._thread = None

    def _poll(self) -> None:
        while not self._stop.wait(self.poll_s):
            if self.check():
                return
