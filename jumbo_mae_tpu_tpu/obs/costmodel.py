"""Compiled-cost extraction: what XLA says each program costs.

The analytic FLOP counts in ``obs/mfu.py`` are what the *model* should cost;
this module records what the *compiled program* actually costs, straight from
XLA's own accounting (``Compiled.cost_analysis()`` / ``memory_analysis()``):
flops, bytes accessed, and the argument/output/temp HBM footprint. Three
consumers hang off one extraction:

- gauges: every compiled program publishes ``xla_flops`` / ``xla_bytes_*`` /
  ``xla_peak_bytes`` with ``(program, bucket, dtype)`` labels — the train
  step via ``cli/train.py``, every engine bucket executable via
  ``infer/engine.py``;
- the journal: one ``compiled_program`` event per program at compile time,
  so the cost basis of a run survives the process;
- the MFU split: analytic flops / measured time = *model* flops utilization
  (MFU), XLA-counted flops / measured time = *hardware* flops utilization
  (HFU; includes remat recompute and fusion overhead). HFU ≥ MFU, and the
  gap is the recompute bill.

Extraction must never cost a compile: both analyses are free readouts of an
already-compiled executable, and every path here degrades to ``None`` when a
backend reports nothing (PJRT plugins may legally return empty analyses).
"""

from __future__ import annotations

from dataclasses import asdict, dataclass

# Bump when the ProgramCost field set changes: journal events and ledger rows
# carry it so offline readers can tell schemas apart.
COST_SCHEMA_VERSION = 1


@dataclass
class ProgramCost:
    """XLA's accounting for one compiled executable.

    ``source`` records how much the backend gave us: ``"compiled"`` (cost +
    memory analysis), ``"lowered"`` (cost analysis only — no memory stats),
    or the instance is absent entirely (extraction returned ``None``).
    """

    program: str
    flops: float = 0.0
    bytes_accessed: float = 0.0
    argument_bytes: float = 0.0
    output_bytes: float = 0.0
    temp_bytes: float = 0.0
    peak_bytes: float = 0.0
    generated_code_bytes: float = 0.0
    source: str = "compiled"


def _cost_dict(executable) -> dict | None:
    """Normalize ``cost_analysis()`` across jax versions: 0.4.x returns a
    list with one dict per partition, newer versions a plain dict."""
    ca = executable.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    return ca if isinstance(ca, dict) and ca else None


def extract_cost(executable, program: str) -> ProgramCost | None:
    """Read XLA's cost/memory analysis off a ``Compiled`` (or ``Lowered``)
    executable. Never compiles, never raises: a backend that reports nothing
    yields ``None`` and the caller publishes nothing."""
    try:
        ca = _cost_dict(executable)
    except Exception:  # noqa: BLE001 - optional per PJRT contract
        ca = None
    if ca is None:
        return None
    cost = ProgramCost(
        program=program,
        flops=max(0.0, float(ca.get("flops", 0.0) or 0.0)),
        bytes_accessed=max(0.0, float(ca.get("bytes accessed", 0.0) or 0.0)),
    )
    try:
        mem = executable.memory_analysis()
    except Exception:  # noqa: BLE001
        mem = None
    if mem is None:
        cost.source = "lowered"
        return cost
    get = lambda attr: float(getattr(mem, attr, 0) or 0)  # noqa: E731
    cost.argument_bytes = get("argument_size_in_bytes")
    cost.output_bytes = get("output_size_in_bytes")
    cost.temp_bytes = get("temp_size_in_bytes")
    cost.generated_code_bytes = get("generated_code_size_in_bytes")
    # live-at-once upper bound: args + outputs + scratch, minus donated
    # aliases (counted in both argument and output sizes)
    cost.peak_bytes = max(
        0.0,
        cost.argument_bytes
        + cost.output_bytes
        + cost.temp_bytes
        - get("alias_size_in_bytes"),
    )
    return cost


def cost_asdict(cost: ProgramCost) -> dict:
    """Journal/ledger payload shape for one program's cost."""
    return {"cost_schema": COST_SCHEMA_VERSION, **asdict(cost)}


def lookup_cost(cost_reports, task: str, bucket: int) -> ProgramCost | None:
    """Resolve the ``ProgramCost`` for one dispatched ``(task, bucket)``.

    Engine cost tables are keyed ``(task_key, bucket)`` where ``task_key``
    may be pool-suffixed (``"features/mean"``); the dispatcher only knows
    the plain task name. Resolution order: exact key, then any key at the
    same bucket whose task component equals or extends ``task``, then any
    key at that bucket (single-task engines). ``None`` when the table is
    empty or the bucket was never compiled — the meter then bills
    device-time only."""
    if not cost_reports:
        return None
    exact = cost_reports.get((task, int(bucket)))
    if exact is not None:
        return exact
    fallback = None
    for (key_task, key_bucket), cost in cost_reports.items():
        if int(key_bucket) != int(bucket):
            continue
        if key_task == task or str(key_task).startswith(f"{task}/"):
            return cost
        if fallback is None:
            fallback = cost
    return fallback


_GAUGES = (
    ("xla_flops", "flops", "XLA-counted flops per execution"),
    ("xla_bytes_accessed", "bytes_accessed", "XLA-counted bytes accessed per execution"),
    ("xla_peak_bytes", "peak_bytes", "estimated live-at-once memory (args+out+temp-aliased)"),
    ("xla_argument_bytes", "argument_bytes", "argument buffer bytes"),
    ("xla_output_bytes", "output_bytes", "output buffer bytes"),
    ("xla_temp_bytes", "temp_bytes", "scratch/temp buffer bytes"),
)


def publish_cost(
    cost: ProgramCost, *, bucket: str = "", dtype: str = "", registry=None
) -> None:
    """Set the ``xla_*{program,bucket,dtype}`` gauge family for one program.

    Called once per compile — gauge handles are resolved here, not on the
    hot path."""
    if cost is None:
        return
    if registry is None:
        from jumbo_mae_tpu_tpu.obs.metrics import get_registry

        registry = get_registry()
    labels = (cost.program, str(bucket), str(dtype))
    for name, field, help_ in _GAUGES:
        fam = registry.gauge(name, help_, labels=("program", "bucket", "dtype"))
        fam.labels(*labels).set(getattr(cost, field))


@dataclass
class UtilizationReport:
    """The MFU/HFU split over one measured steady-state window."""

    model_flops_utilization: float
    hardware_flops_utilization: float
    achieved_model_tflops: float
    achieved_hardware_tflops: float
    peak_tflops: float


def utilization_report(
    analytic_flops_per_step: float,
    xla_flops_per_step: float | None,
    steps_per_sec: float,
    *,
    n_chips: int = 1,
    peak_tflops: float | None = None,
) -> UtilizationReport:
    """MFU (analytic model flops) vs HFU (XLA-counted flops, remat included)
    over one throughput measurement. ``xla_flops_per_step`` is the whole
    program's count; both are divided across ``n_chips``."""
    if peak_tflops is None:
        from jumbo_mae_tpu_tpu.obs.mfu import detect_peak_tflops

        peak_tflops = detect_peak_tflops()
    peak = max(float(peak_tflops), 1e-12)
    model_t = analytic_flops_per_step / max(n_chips, 1) * steps_per_sec / 1e12
    hw_t = (
        (xla_flops_per_step or 0.0) / max(n_chips, 1) * steps_per_sec / 1e12
    )
    return UtilizationReport(
        model_flops_utilization=model_t / peak,
        hardware_flops_utilization=hw_t / peak,
        achieved_model_tflops=model_t,
        achieved_hardware_tflops=hw_t,
        peak_tflops=peak,
    )
