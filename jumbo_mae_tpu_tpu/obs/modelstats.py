"""On-device per-layer-group model statistics for training diagnostics.

When a run diverges, the interesting question is not "did the loss go NaN"
(the sentinel already answers that) but *where*: which part of the model
blew up first, and was the update/param ratio drifting before it did. This
module buckets every parameter leaf into a small set of named **layer
groups** — ``patch_embed`` / ``cls`` / ``blocks.N`` / ``jumbo_mlp`` /
``norm`` / ``decoder`` / ``head`` — and computes, *inside the jitted train
step*, three numbers per group:

- ``grad_norm``      — L2 norm of the group's gradients
- ``param_norm``     — L2 norm of the group's parameters (pre-update)
- ``update_ratio``   — ``||new - old|| / (||old|| + eps)``, the effective
  per-group step size (the number that drifts upward before a blow-up)

stacked into ONE ``(groups, 3)`` float32 array, so the host fetches a
single small transfer per diagnostic step instead of a tree of scalars.
The grouping itself is static Python over the pytree structure — it traces
once and adds no dynamic work to the compiled program. With the step
factory's ``diag`` flag off, none of this is traced and the base program's
HLO is unchanged.

Host side, :func:`publish_group_stats` turns the fetched array into labeled
gauges in the PR-3 registry (``model_grad_norm{group=...}`` etc.) and
:func:`stats_dict` into the nested dict the run journal / flight recorder
store.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from jumbo_mae_tpu_tpu.obs.metrics import get_registry

# Column order of the stacked stats array.
STAT_NAMES = ("grad_norm", "param_norm", "update_ratio")

_METRIC_HELP = {
    "grad_norm": "L2 gradient norm per layer group (diag steps only)",
    "param_norm": "L2 parameter norm per layer group (diag steps only)",
    "update_ratio": "||update|| / ||param|| per layer group (diag steps only)",
}

# Canonical group ordering for display/stacking: input side first, then the
# transformer trunk, then the task-specific tails.
_GROUP_RANK = {
    "patch_embed": 0,
    "cls": 1,
    # blocks.N rank between cls and jumbo_mlp, ordered by N (see _order_key)
    "jumbo_mlp": 3,
    "norm": 4,
    "head": 5,
    "decoder": 6,
    "other": 7,
}


def _path_names(key_path) -> list[str]:
    """Flatten a jax key path into plain name strings."""
    names = []
    for k in key_path:
        if hasattr(k, "key"):        # DictKey
            names.append(str(k.key))
        elif hasattr(k, "name"):     # GetAttrKey
            names.append(str(k.name))
        elif hasattr(k, "idx"):      # SequenceKey
            names.append(str(k.idx))
        else:  # pragma: no cover - future key kinds degrade to repr
            names.append(str(k))
    return names


def group_of(path: list[str] | tuple[str, ...]) -> str:
    """Map one parameter leaf path to its layer-group name.

    Handles both model trees: MAE pretrain (``encoder/...`` + the
    decoder-side leaves at top level) and classification (everything under
    ``model/...`` including ``head``).
    """
    parts = list(path)
    if parts and parts[0] in ("encoder", "model"):
        parts = parts[1:]
    if not parts:
        return "other"
    head = parts[0]
    if head in ("decoder", "decoder_proj", "mask_token", "pixel_proj"):
        return "decoder"
    if head == "embed":
        return "patch_embed"
    if head.startswith("block_"):
        return f"blocks.{head[len('block_'):]}"
    if head == "cls_tokens":
        return "cls"
    if head == "jumbo_mlp":
        return "jumbo_mlp"
    if head == "head":
        return "head"
    if head == "ln":
        return "norm"
    return "other"


def _order_key(name: str) -> tuple:
    if name.startswith("blocks."):
        try:
            return (2, int(name.split(".", 1)[1]))
        except ValueError:  # pragma: no cover - non-integer block suffix
            return (2, 1 << 30)
    return (_GROUP_RANK.get(name, 7), 0)


def group_layout(params) -> tuple[str, ...]:
    """The ordered tuple of group names present in ``params`` — the static
    row layout of the stacked stats array."""
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    names = {group_of(_path_names(kp)) for kp, _ in leaves}
    return tuple(sorted(names, key=_order_key))


def group_stats(old_params, grads, new_params) -> jax.Array:
    """Per-group (grad_norm, param_norm, update_ratio), stacked ``(G, 3)``.

    Traced inside the train step: the grouping loop is Python-time, so the
    compiled program only contains the per-leaf square-sums (which XLA fuses
    with the update it already computes) and one tiny stack. Row order is
    :func:`group_layout`'s; accumulate in float32 regardless of the stored
    param dtype (bf16 square-sums lose mantissa fast).
    """
    path_leaves = jax.tree_util.tree_flatten_with_path(old_params)[0]
    grad_leaves = jax.tree_util.tree_leaves(grads)
    new_leaves = jax.tree_util.tree_leaves(new_params)
    sums: dict[str, list] = {}
    for (kp, p), g, n in zip(path_leaves, grad_leaves, new_leaves):
        grp = group_of(_path_names(kp))
        acc = sums.setdefault(grp, [jnp.float32(0), jnp.float32(0), jnp.float32(0)])
        pf = p.astype(jnp.float32)
        gf = g.astype(jnp.float32)
        df = n.astype(jnp.float32) - pf
        acc[0] = acc[0] + jnp.sum(gf * gf)
        acc[1] = acc[1] + jnp.sum(pf * pf)
        acc[2] = acc[2] + jnp.sum(df * df)
    rows = []
    for grp in sorted(sums, key=_order_key):
        g_sq, p_sq, u_sq = sums[grp]
        p_norm = jnp.sqrt(p_sq)
        rows.append(
            jnp.stack([jnp.sqrt(g_sq), p_norm, jnp.sqrt(u_sq) / (p_norm + 1e-12)])
        )
    return jnp.stack(rows)


def stats_dict(names: tuple[str, ...], array) -> dict[str, dict[str, float]]:
    """Fetched ``(G, 3)`` array → ``{group: {stat: float}}`` (journal shape).

    Non-finite values survive as the JSON-safe strings ``"nan"``/``"inf"``
    so a blown-up group is still readable from a journal parsed by strict
    JSON tooling.
    """
    arr = np.asarray(array, np.float64)
    out: dict[str, dict[str, float]] = {}
    for gi, grp in enumerate(names):
        row = {}
        for si, stat in enumerate(STAT_NAMES):
            v = float(arr[gi, si])
            row[stat] = v if np.isfinite(v) else ("nan" if np.isnan(v) else "inf")
        out[grp] = row
    return out


def publish_group_stats(names: tuple[str, ...], array, registry=None) -> None:
    """Push one fetched stats array into ``model_<stat>{group=...}`` gauges."""
    reg = registry if registry is not None else get_registry()
    arr = np.asarray(array, np.float64)
    for si, stat in enumerate(STAT_NAMES):
        fam = reg.gauge(f"model_{stat}", _METRIC_HELP[stat], labels=("group",))
        for gi, grp in enumerate(names):
            fam.labels(grp).set(float(arr[gi, si]))


def first_nonfinite_group(
    names: tuple[str, ...], array
) -> str | None:
    """The first group (in layout order) whose grad norm is non-finite in
    one stats array — the "where did it blow up" readout ``run_doctor`` and
    the flight recorder lead with. None when every group is finite."""
    arr = np.asarray(array, np.float64)
    for gi, grp in enumerate(names):
        if not np.isfinite(arr[gi, 0]):
            return grp
    return None
