"""Declarative serving SLOs: rolling windows, burn rates, a degraded flag.

An objective is one line of operator intent — ``p99_latency_ms<=250`` ("no
more than 1% of requests slower than 250 ms"), ``success_rate>=0.99`` —
parsed by :func:`parse_slo` from the ``run.slo`` recipe key or the predict
``--slo`` flag. The :class:`SLOTracker` evaluates every objective over two
rolling windows (the SRE multi-window burn-rate pattern):

- **burn rate** = observed violation fraction / error budget. A latency
  objective ``pNN_latency_ms<=T`` has budget ``(100-NN)/100``; a
  ``success_rate>=S`` objective has budget ``1-S``. Burn 1.0 means the
  budget is being spent exactly as fast as it accrues; 10 means ten times
  too fast.
- an objective **breaches** when the slow window burns above
  ``burn_threshold`` AND the fast window agrees (or has no samples — a
  stalled request stream must not mask a breach).
- a breach latches the **degraded** flag for one slow window — the signal
  ``/healthz`` surfaces (via :meth:`HealthState.degraded_when`) and an
  autoscaler keys on without having to re-derive windows from counters.

Every evaluation publishes the ``slo_*`` gauge family — burn rates, values,
thresholds, breach flags, shed rate, plus any attached probes (queue depth,
batch occupancy) — exactly the autoscaling inputs ROADMAP §2 names.
"""

from __future__ import annotations

import re
import threading
import time
from collections import deque
from dataclasses import dataclass

from jumbo_mae_tpu_tpu.obs.metrics import get_registry

_SPEC_RE = re.compile(
    r"^\s*(?P<metric>[a-z0-9_]+)\s*(?P<op><=|>=)\s*(?P<threshold>[0-9.]+)\s*$"
)
_LATENCY_RE = re.compile(r"^p(?P<pct>\d{1,2}(?:\.\d+)?)_latency_ms$")


@dataclass(frozen=True)
class SLOObjective:
    """One parsed objective. ``metric`` is ``pNN_latency_ms`` (op ``<=``,
    threshold in ms) or ``success_rate`` (op ``>=``, threshold in [0,1])."""

    metric: str
    op: str
    threshold: float

    @property
    def name(self) -> str:
        return f"{self.metric}{self.op}{self.threshold:g}"

    @property
    def percentile(self) -> float | None:
        m = _LATENCY_RE.match(self.metric)
        return float(m.group("pct")) if m else None

    @property
    def budget(self) -> float:
        """Error budget as a fraction of requests."""
        pct = self.percentile
        if pct is not None:
            return max((100.0 - pct) / 100.0, 1e-6)
        return max(1.0 - self.threshold, 1e-6)


def parse_slo(spec: str) -> list[SLOObjective]:
    """Parse ``"p99_latency_ms<=250;success_rate>=0.99"`` into objectives.
    Unknown metrics / mismatched operators fail loudly — an SLO typo must
    not silently evaluate to 'never breached'."""
    objectives: list[SLOObjective] = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        m = _SPEC_RE.match(part)
        if not m:
            raise ValueError(
                f"bad SLO objective {part!r}; expected metric<=N or metric>=N"
            )
        metric, op, thr = m.group("metric"), m.group("op"), float(m.group("threshold"))
        if _LATENCY_RE.match(metric):
            if op != "<=":
                raise ValueError(f"latency objective {metric} needs <=, got {op}")
        elif metric == "success_rate":
            if op != ">=":
                raise ValueError(f"success_rate needs >=, got {op}")
            if not 0.0 < thr < 1.0:
                raise ValueError(f"success_rate threshold must be in (0,1), got {thr}")
        else:
            raise ValueError(
                f"unknown SLO metric {metric!r} (pNN_latency_ms or success_rate)"
            )
        objectives.append(SLOObjective(metric, op, thr))
    if not objectives:
        raise ValueError(f"empty SLO spec {spec!r}")
    return objectives


class SLOTracker:
    """Rolling-window SLO evaluation over the request stream.

    Feed it every finished request — :meth:`observe_trace` is shaped as a
    :class:`RequestTracer` ``on_finish`` hook — then :meth:`evaluate` (the
    exporter's pre-scrape hook and the ``/healthz`` probe both call it) to
    refresh gauges and the degraded verdict. ``probes`` maps gauge-name
    suffixes to zero-arg callables sampled at evaluation time (e.g.
    ``{"queue_depth": lambda: mb.stats()["queue_depth"]}`` →
    ``slo_queue_depth``). ``clock`` is injectable for deterministic tests.
    """

    def __init__(
        self,
        objectives: list[SLOObjective],
        *,
        window_s: float = 60.0,
        fast_window_s: float = 0.0,
        burn_threshold: float = 1.0,
        registry=None,
        probes: dict | None = None,
        max_samples: int = 200_000,
        clock=time.monotonic,
    ):
        if not objectives:
            raise ValueError("SLOTracker needs at least one objective")
        self.objectives = tuple(objectives)
        self.window_s = float(window_s)
        self.fast_window_s = float(fast_window_s) or max(self.window_s / 12.0, 1.0)
        self.burn_threshold = float(burn_threshold)
        self._clock = clock
        self._probes = dict(probes or {})
        self._lock = threading.Lock()
        # (t, latency_s, outcome) — bounded so a windowless flood of
        # requests cannot grow host memory without limit
        self._samples: deque = deque(maxlen=int(max_samples))
        self._last_breach_t: float | None = None
        self.last_report: dict | None = None
        reg = registry if registry is not None else get_registry()
        self._g_value = reg.gauge(
            "slo_value", "current value of each SLO metric", labels=("objective",)
        )
        self._g_threshold = reg.gauge(
            "slo_threshold", "configured threshold per objective", labels=("objective",)
        )
        self._g_burn = reg.gauge(
            "slo_burn_rate",
            "error-budget burn rate per objective and window",
            labels=("objective", "window"),
        )
        self._g_breached = reg.gauge(
            "slo_breached", "1 while the objective is in breach", labels=("objective",)
        )
        self._g_degraded = reg.gauge(
            "slo_degraded",
            "1 while any objective breached within the last window_s",
        )
        self._g_shed = reg.gauge(
            "slo_shed_rate", "shed requests / finished requests over window_s"
        )
        self._registry = reg
        self._g_probes = {
            name: reg.gauge(f"slo_{name}", f"SLO probe: {name}")
            for name in self._probes
        }
        for obj in self.objectives:
            self._g_threshold.labels(obj.name).set(obj.threshold)

    def add_probe(self, name: str, fn) -> None:
        """Attach a live probe after construction (the tracker usually
        exists before the micro-batcher it wants to watch): ``fn`` is a
        zero-arg callable sampled at each evaluation, published as
        ``slo_<name>``."""
        with self._lock:
            if name not in self._g_probes:
                self._g_probes[name] = self._registry.gauge(
                    f"slo_{name}", f"SLO probe: {name}"
                )
            self._probes[name] = fn

    # -------------------------------------------------------------- feeding

    def observe(self, latency_s: float | None, outcome: str) -> None:
        with self._lock:
            self._samples.append((self._clock(), latency_s, outcome))

    def observe_trace(self, tr) -> None:
        """`RequestTracer.on_finish`-shaped feed."""
        self.observe(tr.latency_s, tr.outcome)

    # ----------------------------------------------------------- evaluation

    def _window(self, samples, now: float, span: float):
        cutoff = now - span
        return [s for s in samples if s[0] >= cutoff]

    @staticmethod
    def _violation_frac(window, obj: SLOObjective) -> float:
        if not window:
            return 0.0
        if obj.percentile is not None:
            # latency objective: violations among requests that completed
            ok = [lat for _, lat, out in window if out == "ok" and lat is not None]
            if not ok:
                return 0.0
            return sum(1 for lat in ok if lat * 1000.0 > obj.threshold) / len(ok)
        return sum(1 for _, _, out in window if out != "ok") / len(window)

    @staticmethod
    def _value(window, obj: SLOObjective) -> float:
        if obj.percentile is not None:
            ok = sorted(
                lat for _, lat, out in window if out == "ok" and lat is not None
            )
            if not ok:
                return 0.0
            # exact sample percentile (nearest-rank) — no bucket rounding
            rank = min(len(ok) - 1, max(0, int(obj.percentile / 100.0 * len(ok))))
            return ok[rank] * 1000.0
        if not window:
            return 1.0
        return sum(1 for _, _, out in window if out == "ok") / len(window)

    def evaluate(self, now: float | None = None) -> dict:
        """Evaluate every objective, refresh all ``slo_*`` gauges, and
        return the verdict dict (`/healthz` probe body)."""
        now = self._clock() if now is None else now
        with self._lock:
            samples = list(self._samples)
        slow = self._window(samples, now, self.window_s)
        fast = self._window(samples, now, self.fast_window_s)
        report: dict = {
            "window_s": self.window_s,
            "fast_window_s": self.fast_window_s,
            "samples": len(slow),
            "objectives": [],
        }
        breached_any = False
        for obj in self.objectives:
            burn_slow = self._violation_frac(slow, obj) / obj.budget
            burn_fast = self._violation_frac(fast, obj) / obj.budget
            breached = bool(slow) and burn_slow > self.burn_threshold and (
                not fast or burn_fast > self.burn_threshold
            )
            breached_any = breached_any or breached
            value = self._value(slow, obj)
            self._g_value.labels(obj.name).set(value)
            self._g_burn.labels(obj.name, "slow").set(burn_slow)
            self._g_burn.labels(obj.name, "fast").set(burn_fast)
            self._g_breached.labels(obj.name).set(1.0 if breached else 0.0)
            report["objectives"].append(
                {
                    "name": obj.name,
                    "value": round(value, 4),
                    "threshold": obj.threshold,
                    "burn_slow": round(burn_slow, 4),
                    "burn_fast": round(burn_fast, 4),
                    "breached": breached,
                }
            )
        if breached_any:
            with self._lock:
                self._last_breach_t = now
        degraded = self._degraded_at(now)
        report["degraded"] = degraded
        self._g_degraded.set(1.0 if degraded else 0.0)
        shed = sum(1 for _, _, out in slow if out == "shed")
        self._g_shed.set(shed / len(slow) if slow else 0.0)
        report["shed_rate"] = round(shed / len(slow), 4) if slow else 0.0
        with self._lock:
            probes = list(self._probes.items())
        for name, fn in probes:
            try:
                self._g_probes[name].set(float(fn()))
            except Exception:  # noqa: BLE001 — a probe must not break evals
                pass
        self.last_report = report
        return report

    def worst_burn(self, now: float | None = None) -> float:
        """Fresh evaluation collapsed to the autoscaler's scalar input:
        the worst slow-window burn rate across objectives (1.0 = budget
        spent exactly as it accrues; >1 = too fast)."""
        rep = self.evaluate(now)
        return max((o["burn_slow"] for o in rep["objectives"]), default=0.0)

    def _degraded_at(self, now: float) -> bool:
        with self._lock:
            last = self._last_breach_t
        return last is not None and (now - last) <= self.window_s

    def degraded(self) -> bool:
        """Latched breach flag: true within one slow window of the last
        breach (an instantaneous flag would flap off the moment the fast
        window drains — useless to an autoscaler). Shaped for
        :meth:`HealthState.degraded_when`."""
        self.evaluate()
        return self._degraded_at(self._clock())

    def healthz_info(self) -> dict:
        """`/healthz` probe body: the full evaluation, refreshed at probe
        time."""
        return self.evaluate()
