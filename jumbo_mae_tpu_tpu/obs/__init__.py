"""Observability subsystem: one registry across serve / train / data.

- ``obs.metrics``  — thread-safe counters/gauges/histograms with labels,
  Prometheus text rendering, process default registry (+ null registry for
  telemetry-off A/B runs); hosts ``AverageMeter``.
- ``obs.exporter`` — stdlib HTTP server for ``/metrics`` and ``/healthz``.
- ``obs.trace``    — host-side spans aggregating into the registry, optional
  chrome-trace export, and the XLA device-trace capture helpers.
- ``obs.mfu``      — analytic FLOPs + MFU reporting (fed into the registry
  by the train loop), with one device_kind normalizer for the peak-TFLOPS
  tables.
- ``obs.costmodel`` — XLA ``cost_analysis``/``memory_analysis`` extraction
  for every compiled program (``xla_*`` gauges, journal events, MFU vs HFU
  split).
- ``obs.perfmodel`` — analytic roofline capacity model (predicted step time
  / throughput / peak HBM; FSDP/DP comm terms) + the live
  predict-vs-measured drift gauge.
- ``obs.perfledger`` — schema-versioned BENCH_HISTORY.jsonl writer/reader
  the benches append to and ``tools/perf_doctor.py`` diagnoses.
- ``obs.modelstats`` — per-layer-group grad/param/update statistics computed
  inside the jitted train step (``run.diag_every``).
- ``obs.journal``  — append-only crash-safe JSONL run journal (per-host
  segments under multi-process runs) + single and merged multi-host readers.
- ``obs.flightrec`` — crash flight recorder (ring buffer + black-box dumps,
  host-tagged filenames on non-zero hosts).
- ``obs.fleet``    — file-based fleet-health protocol: per-host beacons +
  the host-0 aggregator (straggler/lost detection, ``fleet_*`` gauges).
- ``obs.reqtrace`` — per-request trace context for the serving path + the
  crash-safe JSONL access log (``tools/serve_doctor.py`` reads it offline).
- ``obs.memwatch`` — live memory observability: device/host sampling with
  the HBM predict-vs-measured drift gauge, per-component byte accounting,
  and the robust-slope leak sentinel (``tools/mem_doctor.py`` reads the
  journaled samples offline).
- ``obs.lockwatch`` — opt-in instrumented locks (``GRAFT_LOCKWATCH=1``):
  runtime lock-order inversion + long-hold detection, ``lock_*`` metrics,
  ``lock_order_violation`` journal events.
- ``obs.goodput``  — goodput accounting: wall-clock attribution ledger
  (``goodput_*`` gauges, ``goodput_report`` journal events), cross-
  generation journal stitching, and the checkpoint-interval advisor.
- ``obs.hangwatch`` — step-deadline hang watchdog: converts a wedged
  collective into a fast ``EXIT_HANG`` death the elastic supervisor can
  restart (``hang_detected`` journal event, bounded checkpoint drain).
- ``obs.retrace``  — retrace sentinel: hooks JAX compile telemetry and
  turns any post-warmup recompile into a ``retrace`` journal event with
  shape/dtype-diff attribution.
- ``obs.slo``      — declarative SLO objectives, rolling-window burn rates,
  and the latched degraded flag surfaced in ``/healthz``.
- ``obs.doctor_common`` — markdown/window helpers shared by the offline
  doctors (``tools/run_doctor.py``, ``tools/serve_doctor.py``).

The former ``utils/meters.py`` / ``utils/mfu.py`` / ``utils/profiling.py``
modules remain as import-compatible shims over this package.
"""

from jumbo_mae_tpu_tpu.obs.exporter import HealthState, TelemetryServer
from jumbo_mae_tpu_tpu.obs.fleet import FleetAggregator, HostBeacon, read_beacons
from jumbo_mae_tpu_tpu.obs.flightrec import FlightRecorder
from jumbo_mae_tpu_tpu.obs.goodput import (
    GOODPUT_BUCKETS,
    GoodputLedger,
    advise_ckpt_interval,
    bucket_display,
    stitch_generations,
)
from jumbo_mae_tpu_tpu.obs.hangwatch import HangWatchdog
from jumbo_mae_tpu_tpu.obs.journal import (
    JOURNAL_EVENTS,
    RunJournal,
    env_fingerprint,
    journal_dir,
    read_journal,
    read_merged_journal,
)
from jumbo_mae_tpu_tpu.obs.lockwatch import WatchedLock
from jumbo_mae_tpu_tpu.obs.memwatch import (
    LeakSentinel,
    MemAccountant,
    MemoryWatcher,
    host_available_bytes,
    host_rss_bytes,
    tree_nbytes,
)
from jumbo_mae_tpu_tpu.obs.retrace import RetraceSentinel
from jumbo_mae_tpu_tpu.obs.modelstats import (
    STAT_NAMES,
    first_nonfinite_group,
    group_layout,
    group_of,
    group_stats,
    publish_group_stats,
    stats_dict,
)
from jumbo_mae_tpu_tpu.obs.metrics import (
    LATENCY_BUCKETS,
    NULL_REGISTRY,
    RATIO_BUCKETS,
    AverageMeter,
    Counter,
    Family,
    Gauge,
    Histogram,
    MetricsRegistry,
    NullRegistry,
    get_registry,
    set_registry,
)
from jumbo_mae_tpu_tpu.obs.costmodel import (
    COST_SCHEMA_VERSION,
    ProgramCost,
    UtilizationReport,
    cost_asdict,
    extract_cost,
    publish_cost,
    utilization_report,
)
from jumbo_mae_tpu_tpu.obs.mfu import (
    PEAK_TFLOPS,
    MfuReport,
    classify_flops_per_image,
    detect_peak_tflops,
    encoder_flops_per_image,
    lookup_peak_tflops,
    mfu_report,
    normalize_device_kind,
    pretrain_flops_per_image,
)
from jumbo_mae_tpu_tpu.obs.perfledger import (
    LEDGER_SCHEMA,
    append_row,
    comparable_env,
    make_row,
    read_ledger,
    resolve_history_path,
)
from jumbo_mae_tpu_tpu.obs.perfmodel import (
    ChipSpec,
    PerfPrediction,
    chip_spec,
    detect_chip,
    dp_comm_bytes,
    fsdp_comm_bytes,
    predict_train_step,
    publish_drift,
    roofline,
)
from jumbo_mae_tpu_tpu.obs.reqtrace import (
    OUTCOMES,
    AccessLog,
    RequestTrace,
    RequestTracer,
)
from jumbo_mae_tpu_tpu.obs.slo import SLOObjective, SLOTracker, parse_slo
from jumbo_mae_tpu_tpu.obs.trace import (
    annotate,
    export_chrome_trace,
    span,
    span_timer,
    start_chrome_trace,
    stop_chrome_trace,
    trace,
)

__all__ = [
    "AccessLog",
    "AverageMeter",
    "COST_SCHEMA_VERSION",
    "ChipSpec",
    "Counter",
    "Family",
    "FleetAggregator",
    "FlightRecorder",
    "GOODPUT_BUCKETS",
    "Gauge",
    "GoodputLedger",
    "HangWatchdog",
    "HostBeacon",
    "HealthState",
    "Histogram",
    "LATENCY_BUCKETS",
    "LEDGER_SCHEMA",
    "LeakSentinel",
    "MemAccountant",
    "MemoryWatcher",
    "MetricsRegistry",
    "MfuReport",
    "NULL_REGISTRY",
    "NullRegistry",
    "OUTCOMES",
    "PEAK_TFLOPS",
    "PerfPrediction",
    "ProgramCost",
    "RATIO_BUCKETS",
    "RequestTrace",
    "RequestTracer",
    "JOURNAL_EVENTS",
    "RetraceSentinel",
    "RunJournal",
    "WatchedLock",
    "SLOObjective",
    "SLOTracker",
    "STAT_NAMES",
    "TelemetryServer",
    "UtilizationReport",
    "advise_ckpt_interval",
    "annotate",
    "append_row",
    "bucket_display",
    "chip_spec",
    "classify_flops_per_image",
    "comparable_env",
    "cost_asdict",
    "detect_chip",
    "detect_peak_tflops",
    "dp_comm_bytes",
    "encoder_flops_per_image",
    "env_fingerprint",
    "export_chrome_trace",
    "extract_cost",
    "first_nonfinite_group",
    "fsdp_comm_bytes",
    "get_registry",
    "group_layout",
    "group_of",
    "group_stats",
    "host_available_bytes",
    "host_rss_bytes",
    "journal_dir",
    "lookup_peak_tflops",
    "make_row",
    "mfu_report",
    "normalize_device_kind",
    "parse_slo",
    "predict_train_step",
    "pretrain_flops_per_image",
    "publish_cost",
    "publish_drift",
    "publish_group_stats",
    "read_beacons",
    "read_journal",
    "read_ledger",
    "read_merged_journal",
    "resolve_history_path",
    "roofline",
    "set_registry",
    "span",
    "span_timer",
    "start_chrome_trace",
    "stats_dict",
    "stitch_generations",
    "stop_chrome_trace",
    "trace",
    "tree_nbytes",
    "utilization_report",
]
