"""Observability subsystem: one registry across serve / train / data.

- ``obs.metrics``  — thread-safe counters/gauges/histograms with labels,
  Prometheus text rendering, process default registry (+ null registry for
  telemetry-off A/B runs); hosts ``AverageMeter``.
- ``obs.exporter`` — stdlib HTTP server for ``/metrics`` and ``/healthz``.
- ``obs.trace``    — host-side spans aggregating into the registry, optional
  chrome-trace export, and the XLA device-trace capture helpers.
- ``obs.mfu``      — analytic FLOPs + MFU reporting (fed into the registry
  by the train loop).
- ``obs.modelstats`` — per-layer-group grad/param/update statistics computed
  inside the jitted train step (``run.diag_every``).
- ``obs.journal``  — append-only crash-safe JSONL run journal + reader.
- ``obs.flightrec`` — crash flight recorder (ring buffer + black-box dumps).
- ``obs.reqtrace`` — per-request trace context for the serving path + the
  crash-safe JSONL access log (``tools/serve_doctor.py`` reads it offline).
- ``obs.slo``      — declarative SLO objectives, rolling-window burn rates,
  and the latched degraded flag surfaced in ``/healthz``.
- ``obs.doctor_common`` — markdown/window helpers shared by the offline
  doctors (``tools/run_doctor.py``, ``tools/serve_doctor.py``).

The former ``utils/meters.py`` / ``utils/mfu.py`` / ``utils/profiling.py``
modules remain as import-compatible shims over this package.
"""

from jumbo_mae_tpu_tpu.obs.exporter import HealthState, TelemetryServer
from jumbo_mae_tpu_tpu.obs.flightrec import FlightRecorder
from jumbo_mae_tpu_tpu.obs.journal import (
    RunJournal,
    env_fingerprint,
    journal_dir,
    read_journal,
)
from jumbo_mae_tpu_tpu.obs.modelstats import (
    STAT_NAMES,
    first_nonfinite_group,
    group_layout,
    group_of,
    group_stats,
    publish_group_stats,
    stats_dict,
)
from jumbo_mae_tpu_tpu.obs.metrics import (
    LATENCY_BUCKETS,
    NULL_REGISTRY,
    RATIO_BUCKETS,
    AverageMeter,
    Counter,
    Family,
    Gauge,
    Histogram,
    MetricsRegistry,
    NullRegistry,
    get_registry,
    set_registry,
)
from jumbo_mae_tpu_tpu.obs.mfu import (
    PEAK_TFLOPS,
    MfuReport,
    classify_flops_per_image,
    detect_peak_tflops,
    encoder_flops_per_image,
    mfu_report,
    pretrain_flops_per_image,
)
from jumbo_mae_tpu_tpu.obs.reqtrace import (
    OUTCOMES,
    AccessLog,
    RequestTrace,
    RequestTracer,
)
from jumbo_mae_tpu_tpu.obs.slo import SLOObjective, SLOTracker, parse_slo
from jumbo_mae_tpu_tpu.obs.trace import (
    annotate,
    export_chrome_trace,
    span,
    span_timer,
    start_chrome_trace,
    stop_chrome_trace,
    trace,
)

__all__ = [
    "AccessLog",
    "AverageMeter",
    "Counter",
    "Family",
    "FlightRecorder",
    "Gauge",
    "HealthState",
    "Histogram",
    "LATENCY_BUCKETS",
    "MetricsRegistry",
    "MfuReport",
    "NULL_REGISTRY",
    "NullRegistry",
    "OUTCOMES",
    "PEAK_TFLOPS",
    "RATIO_BUCKETS",
    "RequestTrace",
    "RequestTracer",
    "RunJournal",
    "SLOObjective",
    "SLOTracker",
    "STAT_NAMES",
    "TelemetryServer",
    "annotate",
    "classify_flops_per_image",
    "detect_peak_tflops",
    "encoder_flops_per_image",
    "env_fingerprint",
    "export_chrome_trace",
    "first_nonfinite_group",
    "get_registry",
    "group_layout",
    "group_of",
    "group_stats",
    "journal_dir",
    "mfu_report",
    "parse_slo",
    "pretrain_flops_per_image",
    "publish_group_stats",
    "read_journal",
    "set_registry",
    "span",
    "span_timer",
    "start_chrome_trace",
    "stats_dict",
    "stop_chrome_trace",
    "trace",
]
