"""Schema-versioned perf-regression ledger: BENCH_HISTORY.jsonl.

The bench trajectory was empty because results never landed anywhere
comparable: ``bench.py`` and ``tools/bench_infer.py`` each print one JSON
line and exit, and nothing relates run N to run N−1. This module is the
landing strip — every bench appends one row here, and
``tools/perf_doctor.py`` reads the trail back to call regressions.

Row shape (``LEDGER_SCHEMA`` = 1)::

    {"schema": 1, "ts": ..., "bench": "train"|"infer", "metric": ...,
     "git_sha": ..., "env": {...}, "env_key": "...",
     "legs": {name: value}, "quantiles": {name: value},
     "prediction": {...roofline...} | null}

Comparability is explicit: ``env_key`` hashes the subset of the environment
fingerprint that makes two rows comparable (host, backend, device count,
versions) and deliberately EXCLUDES per-process noise (pid, argv) — two runs
of the same bench on the same host MUST get the same key (CI asserts it).
The doctor only baselines rows against same-``env_key`` history.

Writes reuse the journal's crash-safety idioms (sanitize + fsync per line;
torn final lines are skipped on read) and are best-effort: a read-only CWD
or a full disk must never fail a bench.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
import time
from pathlib import Path

from jumbo_mae_tpu_tpu.obs.journal import (
    _json_default,
    _sanitize,
    env_fingerprint,
    read_journal,
)

LEDGER_SCHEMA = 1
DEFAULT_LEDGER = "BENCH_HISTORY.jsonl"

# env_fingerprint keys that make two rows comparable; pid/argv/process-local
# env vars are deliberately absent.
_COMPARABLE_KEYS = (
    "version",
    "python",
    "platform",
    "hostname",
    "jax",
    "backend",
    "device_count",
)


def git_sha() -> str:
    """Short sha of the repo HEAD, or "" outside a checkout."""
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True,
            text=True,
            timeout=5,
            cwd=Path(__file__).resolve().parent,
        ).stdout.strip()
    except Exception:  # noqa: BLE001 - best-effort provenance
        return ""


def comparable_env() -> dict:
    """The env-fingerprint subset two comparable bench rows must share,
    plus the accelerator kind (a v4 row never baselines a v5e row)."""
    fp = env_fingerprint()
    env = {k: fp[k] for k in _COMPARABLE_KEYS if k in fp}
    try:
        import jax

        env["device_kind"] = jax.devices()[0].device_kind
    except Exception:  # noqa: BLE001
        env["device_kind"] = "unavailable"
    return env


def env_key(env: dict) -> str:
    blob = json.dumps(_sanitize(env), sort_keys=True, default=_json_default)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def make_row(
    *,
    bench: str,
    metric: str,
    legs: dict,
    quantiles: dict | None = None,
    prediction: dict | None = None,
    extra: dict | None = None,
) -> dict:
    """One schema-versioned ledger row. ``legs`` maps leg name → headline
    number; ``quantiles`` carries latency percentiles; ``prediction`` is the
    cost-model roofline (``perfmodel.prediction_asdict``)."""
    env = comparable_env()
    row = {
        "schema": LEDGER_SCHEMA,
        "ts": round(time.time(), 3),
        "bench": bench,
        "metric": metric,
        "git_sha": git_sha(),
        "env": env,
        "env_key": env_key(env),
        "legs": dict(legs),
        "quantiles": dict(quantiles or {}),
        "prediction": prediction,
    }
    if extra:
        row.update(extra)
    return row


def append_row(path: str | os.PathLike, row: dict) -> bool:
    """Append one row, fsync'd; best-effort (False + stderr on failure)."""
    try:
        line = json.dumps(
            _sanitize(row),
            default=_json_default,
            separators=(",", ":"),
            allow_nan=False,
        )
        p = Path(path)
        if p.parent and not p.parent.exists():
            p.parent.mkdir(parents=True, exist_ok=True)
        with open(p, "a", encoding="utf-8") as f:
            # a prior crash can leave a torn line with no trailing newline;
            # start on a fresh line so the torn fragment corrupts only
            # itself, not this row
            if f.tell() > 0:
                with open(p, "rb") as rf:
                    rf.seek(-1, os.SEEK_END)
                    if rf.read(1) != b"\n":
                        f.write("\n")
            f.write(line + "\n")
            f.flush()
            os.fsync(f.fileno())
    except Exception as e:  # noqa: BLE001 - a bench must not fail on this
        print(f"[perfledger] append to {path} failed: {e}", file=sys.stderr)
        return False
    return True


def read_ledger(path: str | os.PathLike) -> list[dict]:
    """Every parseable row in file order; torn final lines are skipped
    (same reader contract as the run journal)."""
    rows = read_journal(path)
    return [r for r in rows if r.get("schema") and r.get("bench")]


def resolve_history_path(cli_value: str | None = None) -> Path | None:
    """Where a bench should append: the CLI flag wins, then the
    ``BENCH_HISTORY`` env var, then ``BENCH_HISTORY.jsonl`` in the CWD.
    ``off``/``0``/empty-string disables the ledger (returns None)."""
    value = cli_value if cli_value is not None else os.environ.get(
        "BENCH_HISTORY", DEFAULT_LEDGER
    )
    if not value or str(value).lower() in ("off", "0", "none"):
        return None
    return Path(value)
