"""Crash flight recorder: a ring buffer of recent step diagnostics + events,
dumped to ``<workdir>/flightrec-<ts>-<reason>.json`` when something goes
wrong (``flightrec-h<i>-...`` on non-zero hosts of a multi-process run —
every host records and dumps its own black box into the shared run dir).

The journal (``obs/journal.py``) records *log-cadence* snapshots durably;
the flight recorder keeps the last N *per-step* diagnostics in memory —
too chatty to fsync every step, exactly what you want written out the
moment a step goes non-finite, the sentinel rolls back, a SIGTERM lands,
or an exception escapes the step loop. Like an aircraft black box: cheap
to feed, only materialized on impact.

Triggers (the train loop calls :meth:`dump` for the first two; ``install``
hooks the rest):

- non-finite / skipped step observed at a log boundary
- sentinel rollback (every PR-4 rollback leaves a record)
- SIGTERM — chained in FRONT of any existing handler (the preemption
  guard's graceful-checkpoint flow still runs after the dump)
- unhandled exception — ``sys.excepthook`` chain, plus an ``atexit``
  fallback that fires only when an abnormal condition was recorded but no
  dump was ever written (an exception swallowed upstream).

All hooks are reversible (:meth:`uninstall`) so in-process test runs and
repeated ``train()`` calls never leak handlers.
"""

from __future__ import annotations

import atexit
import json
import signal
import sys
import threading
import time
from collections import deque
from pathlib import Path

from jumbo_mae_tpu_tpu.obs.journal import _json_default, _sanitize


class FlightRecorder:
    """Bounded in-memory recorder with on-demand JSON dumps.

    ``record_step``/``record_event`` are O(1) deque appends under one lock
    (the step loop and a signal handler may race); ``dump`` snapshots and
    writes atomically-enough (tmp + rename) so a dump interrupted by the
    dying process never leaves a half-JSON at the final name.
    """

    def __init__(
        self,
        workdir: str | Path,
        *,
        capacity: int = 256,
        event_capacity: int = 128,
        host: int = 0,
    ):
        self.workdir = Path(workdir)
        # non-zero hosts tag their dump filenames (flightrec-h<i>-...) so a
        # pod-wide incident leaves one attributable black box per host in the
        # shared run dir; host 0 keeps the historical name unchanged
        self.host = int(host)
        self._lock = threading.Lock()
        self._steps: deque = deque(maxlen=max(1, int(capacity)))
        self._events: deque = deque(maxlen=max(1, int(event_capacity)))
        self._dumps: list[str] = []
        self._dump_seq = 0
        self._abnormal = False
        self._prev_handlers: dict = {}
        self._prev_excepthook = None
        self._installed = False

    # ------------------------------------------------------------- feeding

    def record_step(self, step: int, payload: dict) -> None:
        with self._lock:
            self._steps.append({"step": int(step), **payload})

    def record_event(self, event: dict) -> None:
        with self._lock:
            self._events.append(dict(event))

    def mark_abnormal(self) -> None:
        """Arm the atexit fallback: something bad was seen; if nothing ever
        dumps before exit, the atexit hook writes one last record."""
        self._abnormal = True

    def ring_bytes(self) -> int:
        """Shallow byte estimate of the in-memory rings — the accounting
        probe ``obs/memwatch.py`` registers as the ``flightrec_ring``
        component. Shallow ``getsizeof`` per entry (container overhead, not
        deep payload bytes): cheap enough to run per log window, and it
        tracks ring *growth*, which is all the leak sentinel needs."""
        import sys as _sys

        with self._lock:
            entries = list(self._steps) + list(self._events)
        return sum(_sys.getsizeof(e) for e in entries)

    # ------------------------------------------------------------- dumping

    @property
    def dumps(self) -> list[str]:
        with self._lock:
            return list(self._dumps)

    def dump(self, reason: str, *, extra: dict | None = None) -> Path:
        """Write the black box now; returns the file path. Always writes a
        new file (timestamped + sequence-numbered), never overwrites."""
        with self._lock:
            steps = list(self._steps)
            events = list(self._events)
            self._dump_seq += 1
            seq = self._dump_seq
        self.workdir.mkdir(parents=True, exist_ok=True)
        ts = time.strftime("%Y%m%d-%H%M%S")
        tag = "" if self.host == 0 else f"h{self.host}-"
        path = self.workdir / f"flightrec-{tag}{ts}-{seq:02d}-{reason}.json"
        payload = {
            "reason": reason,
            "host": self.host,
            "written_at": round(time.time(), 3),
            "steps": _sanitize(steps),
            "events": _sanitize(events),
        }
        if extra:
            payload["extra"] = _sanitize(extra)
        tmp = path.with_suffix(".json.tmp")
        tmp.write_text(
            json.dumps(payload, default=_json_default, allow_nan=False)
        )
        tmp.rename(path)
        with self._lock:
            self._dumps.append(str(path))
        return path

    # ----------------------------------------------------------- installers

    def install(self, *, signals=(signal.SIGTERM,)) -> bool:
        """Hook SIGTERM + ``sys.excepthook`` + atexit. Handlers chain to
        whatever was installed before (the preemption guard keeps working).
        Returns False when not on the main thread (signals unavailable)."""
        if self._installed:
            return True
        ok = True
        for sig in signals:
            try:
                prev = signal.getsignal(sig)
                signal.signal(sig, self._make_signal_handler(sig, prev))
                self._prev_handlers[sig] = prev
            except ValueError:  # not the main thread
                ok = False
        self._prev_excepthook = sys.excepthook
        sys.excepthook = self._excepthook
        atexit.register(self._atexit)
        self._installed = True
        return ok

    def uninstall(self) -> None:
        if not self._installed:
            return
        for sig, prev in self._prev_handlers.items():
            try:
                # only restore if OUR handler is still installed — someone
                # (e.g. the guard's force-exit path) may have replaced it
                current = signal.getsignal(sig)
                if getattr(current, "__flightrec__", False):
                    signal.signal(sig, prev)
            except ValueError:  # pragma: no cover - teardown off-main-thread
                pass
        self._prev_handlers.clear()
        if sys.excepthook is self._excepthook:
            sys.excepthook = self._prev_excepthook
        self._prev_excepthook = None
        try:
            atexit.unregister(self._atexit)
        except Exception:  # pragma: no cover - registry already torn down
            pass
        self._installed = False

    def _make_signal_handler(self, sig, prev):
        def handler(signum, frame):
            try:
                self.dump(f"signal_{signum}")
            except Exception:  # noqa: BLE001 - never mask the signal flow
                pass
            if callable(prev):
                prev(signum, frame)
            elif prev == signal.SIG_DFL:
                # re-deliver with default semantics (terminate)
                signal.signal(signum, signal.SIG_DFL)
                signal.raise_signal(signum)

        handler.__flightrec__ = True
        return handler

    def _excepthook(self, etype, value, tb):
        try:
            self.dump(
                "exception",
                extra={"error": f"{etype.__name__}: {value}"},
            )
        except Exception:  # noqa: BLE001 - never mask the real traceback
            pass
        hook = self._prev_excepthook or sys.__excepthook__
        hook(etype, value, tb)

    def _atexit(self) -> None:
        # last-chance dump: abnormal condition seen, nothing ever written
        with self._lock:
            pending = self._abnormal and not self._dumps
        if pending:
            try:
                self.dump("atexit")
            except Exception:  # noqa: BLE001 - interpreter is shutting down
                pass
