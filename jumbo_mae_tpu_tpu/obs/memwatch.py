"""Memory observability: live device/host telemetry, byte accounting, leaks.

The repo already knows memory *statically*: `obs/perfmodel.py` predicts
``peak_hbm_bytes`` and `obs/costmodel.py` extracts XLA's compile-time
``memory_analysis()``. Nothing measured it live, so a leaking cache or an
under-predicted activation footprint stayed invisible until the OOM. This
module closes the loop from prediction to measurement, the same
predict-vs-measured discipline `perf_predict_vs_measured` applies to step
time:

- :class:`MemoryWatcher` samples per-device memory via
  ``device.memory_stats()`` (``bytes_in_use`` / ``peak_bytes_in_use``)
  plus host RSS and the Python allocator's live block count, publishing
  ``mem_device_bytes{device=}``, ``mem_device_peak_bytes{device=}``,
  ``mem_host_rss_bytes`` and ``mem_py_alloc_blocks``. Backends without
  memory stats (XLA:CPU) degrade gracefully: the device/drift gauges are
  *never registered* (absent from the scrape, not zero) and the first
  degraded sample carries a one-shot ``note`` the caller can journal.
- The watcher cross-checks the capacity model: feed it the predicted peak
  for each active executable (``record_predicted_peak``, from
  ``ProgramCost.peak_bytes`` / ``PerfPrediction.peak_hbm_bytes``) and
  every sample publishes ``mem_hbm_predict_vs_measured{program=}`` =
  measured device peak / predicted peak. A ratio drifting above 1 means
  the model under-predicts (OOM risk); far below 1 means capacity planning
  is leaving batch size on the table.
- :class:`MemAccountant` is one registry for byte-level accounting of
  every in-process cache and buffer (engine executable cache, encoder
  LRU, warmcache disk dir, MicroBatcher queue, journal/flightrec rings)
  publishing ``mem_component_bytes{component=}`` — so "RSS grew 2 GiB"
  decomposes into *which* cache grew.
- :class:`LeakSentinel` fits a robust (Theil–Sen) slope over a rolling
  window of RSS + per-component samples; sustained growth names the
  fastest-growing component, and the caller journals ``mem_leak_suspect``,
  dumps the flight recorder, and latches ``/healthz`` degraded. Chaos
  coverage comes from the ``host.leak`` fault site (`faults/inject.py`).
- `tools/mem_doctor.py` turns the journaled ``mem_sample`` rows into the
  offline diagnosis (peak timeline, component attribution, leak verdict,
  OOM-risk vs the ChipSpec HBM capacity).

Sampling is log-boundary / scrape-rate work, never per-step: one
``/proc/self/status`` read, one ``memory_stats()`` call per device, and
one cheap probe per registered component (PERF.md §Memwatch overhead).
"""

from __future__ import annotations

import sys
import threading
import time
from collections import deque
from typing import Callable

from jumbo_mae_tpu_tpu.obs.metrics import get_registry

MB = 1024 * 1024


# --------------------------------------------------------------- host probes


def host_rss_bytes() -> int | None:
    """Current resident set size from ``/proc/self/status`` (Linux).

    Falls back to ``ru_maxrss`` (the *peak* RSS — still monotone under a
    leak, so the sentinel keeps working) where /proc is missing; ``None``
    when neither source exists.
    """
    try:
        with open("/proc/self/status", "rb") as f:
            for line in f:
                if line.startswith(b"VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource

        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        return None


def host_available_bytes() -> int | None:
    """``MemAvailable`` from ``/proc/meminfo`` — the kernel's estimate of
    how much can be allocated without swapping; ``None`` off-Linux."""
    try:
        with open("/proc/meminfo", "rb") as f:
            for line in f:
                if line.startswith(b"MemAvailable:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    return None


def tree_nbytes(tree) -> int:
    """Total array bytes of a pytree (params/opt-state size on host).

    Counts anything with ``.nbytes`` (numpy and jax arrays alike); other
    leaves (scalars, None) count zero.
    """
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        total += int(getattr(leaf, "nbytes", 0) or 0)
    return total


def _device_memory_stats() -> list[tuple[str, int | None, int | None]] | None:
    """``[(label, bytes_in_use, peak_bytes_in_use)]`` per local device.

    ``None`` when the backend has no usable memory stats (XLA:CPU raises
    or returns an empty/useless dict) — the caller must degrade to
    host-only telemetry, not publish zeros.
    """
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return None
    out: list[tuple[str, int | None, int | None]] = []
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            return None
        if not stats or "bytes_in_use" not in stats:
            return None
        out.append(
            (
                f"{d.platform}:{d.id}",
                stats.get("bytes_in_use"),
                stats.get("peak_bytes_in_use"),
            )
        )
    return out or None


def _theil_sen_slope(values) -> float:
    """Median pairwise slope per *sample index* — robust to one-off jumps
    (an eval allocating a temp buffer) that would swing a least-squares
    fit; O(n²) pairs on a ≤ window-sized input."""
    n = len(values)
    if n < 2:
        return 0.0
    slopes = [
        (values[j] - values[i]) / (j - i)
        for i in range(n)
        for j in range(i + 1, n)
    ]
    slopes.sort()
    m = len(slopes)
    mid = m // 2
    if m % 2:
        return float(slopes[mid])
    return float(slopes[mid - 1] + slopes[mid]) / 2.0


# ----------------------------------------------------------- MemAccountant


class MemAccountant:
    """One registry for byte accounting of every in-process cache/buffer.

    Components register a zero-arg probe returning their current byte
    footprint (or ``None`` while unknowable); :meth:`sample` polls every
    probe and publishes ``mem_component_bytes{component=}``. Probes must
    be cheap (a counter read, a ``stat()``) — they run per log window and
    per scrape. A probe that raises is skipped for that sample, never
    fatal: accounting must not take down the thing it accounts.
    """

    def __init__(self, registry=None):
        reg = registry if registry is not None else get_registry()
        self._g = reg.gauge(
            "mem_component_bytes",
            "live byte accounting per in-process cache/buffer",
            labels=("component",),
        )
        self._probes: dict[str, Callable[[], float | None]] = {}
        self._lock = threading.Lock()

    def register(self, component: str, probe: Callable[[], float | None]):
        with self._lock:
            self._probes[component] = probe

    def unregister(self, component: str):
        with self._lock:
            self._probes.pop(component, None)

    def components(self) -> list[str]:
        with self._lock:
            return sorted(self._probes)

    def sample(self) -> dict[str, int]:
        with self._lock:
            probes = list(self._probes.items())
        out: dict[str, int] = {}
        for name, probe in probes:
            try:
                v = probe()
            except Exception:
                continue
            if v is None:
                continue
            out[name] = int(v)
            self._g.labels(component=name).set(float(v))
        return out


# ---------------------------------------------------------- MemoryWatcher


class MemoryWatcher:
    """Samples device + host memory and validates the HBM prediction.

    Host gauges (``mem_host_rss_bytes``, ``mem_py_alloc_blocks``) register
    eagerly — they exist on every backend. Device gauges
    (``mem_device_bytes``, ``mem_device_peak_bytes``) and the drift gauge
    (``mem_hbm_predict_vs_measured``) register lazily on the first
    *successful* ``memory_stats()`` read, so a CPU scrape simply doesn't
    carry them. The first degraded sample sets a one-shot ``note`` field
    in the snapshot — the caller journals it once, then the watcher stays
    quiet about it.
    """

    def __init__(self, *, accountant: MemAccountant | None = None,
                 registry=None, chip=None):
        reg = registry if registry is not None else get_registry()
        self._reg = reg
        self.accountant = accountant
        # chip: obs.perfmodel.ChipSpec | None — carries the HBM capacity
        # the doctor's OOM-risk estimate divides by (0 on generic CPU)
        self.chip = chip
        self._g_rss = reg.gauge(
            "mem_host_rss_bytes", "host resident set size of this process"
        )
        self._g_blocks = reg.gauge(
            "mem_py_alloc_blocks",
            "live Python allocator blocks (sys.getallocatedblocks) — a "
            "unit-free heap-growth signal",
        )
        self._g_dev = None
        self._g_dev_peak = None
        self._g_drift = None
        self._predicted: dict[str, float] = {}
        self._lock = threading.Lock()
        self._device_degraded = False
        self._degrade_noted = False
        self._last: dict = {}

    # -- prediction side of the drift gauge ------------------------------

    def record_predicted_peak(self, program: str, peak_bytes) -> None:
        """Attach the capacity-model peak for ``program`` (train step, an
        engine ``task/bucket`` executable); every subsequent sample
        publishes measured/predicted for it. Zero/None predictions are
        ignored — no division theater."""
        try:
            v = float(peak_bytes or 0)
        except (TypeError, ValueError):
            return
        if v > 0:
            with self._lock:
                self._predicted[program] = v

    def predicted_peaks(self) -> dict[str, float]:
        with self._lock:
            return dict(self._predicted)

    # -- sampling ---------------------------------------------------------

    def sample(self) -> dict:
        """One telemetry sample; publishes gauges, returns the snapshot
        dict the caller can journal as a ``mem_sample`` event. Usable
        directly as a ``TelemetryServer.add_pre_scrape`` hook."""
        snap: dict = {"ts": time.time()}
        rss = host_rss_bytes()
        if rss is not None:
            self._g_rss.set(float(rss))
            snap["rss_bytes"] = int(rss)
        blocks = sys.getallocatedblocks()
        self._g_blocks.set(float(blocks))
        snap["py_alloc_blocks"] = int(blocks)

        dev = _device_memory_stats()
        if dev is None:
            self._device_degraded = True
            if not self._degrade_noted:
                self._degrade_noted = True
                snap["note"] = (
                    "device memory_stats() unavailable on this backend — "
                    "HBM gauges degraded to host-only telemetry"
                )
        else:
            self._device_degraded = False
            if self._g_dev is None:
                self._g_dev = self._reg.gauge(
                    "mem_device_bytes",
                    "live device (HBM) bytes in use",
                    labels=("device",),
                )
                self._g_dev_peak = self._reg.gauge(
                    "mem_device_peak_bytes",
                    "high-water device (HBM) bytes since process start",
                    labels=("device",),
                )
            peak_max = 0
            in_use_total = 0
            for label, in_use, peak in dev:
                if in_use is not None:
                    self._g_dev.labels(device=label).set(float(in_use))
                    in_use_total += int(in_use)
                if peak is not None:
                    self._g_dev_peak.labels(device=label).set(float(peak))
                    peak_max = max(peak_max, int(peak))
            snap["device_bytes"] = int(in_use_total)
            snap["device_peak_bytes"] = int(peak_max)
            drift = self._publish_drift(peak_max)
            if drift:
                snap["hbm_drift"] = drift
        if self.chip is not None and getattr(self.chip, "hbm_bytes", 0):
            snap["hbm_capacity_bytes"] = int(self.chip.hbm_bytes)
        if self.accountant is not None:
            comps = self.accountant.sample()
            if comps:
                snap["components"] = comps
        self._last = snap
        return snap

    def _publish_drift(self, measured_peak: int) -> dict[str, float]:
        if measured_peak <= 0:
            return {}
        with self._lock:
            predicted = dict(self._predicted)
        if not predicted:
            return {}
        if self._g_drift is None:
            self._g_drift = self._reg.gauge(
                "mem_hbm_predict_vs_measured",
                "measured device peak bytes / capacity-model predicted "
                "peak, per active executable (>1 = model under-predicts)",
                labels=("program",),
            )
        out: dict[str, float] = {}
        for program, pred in predicted.items():
            ratio = round(measured_peak / pred, 4)
            self._g_drift.labels(program=program).set(ratio)
            out[program] = ratio
        return out

    # -- readouts ---------------------------------------------------------

    @property
    def device_stats_degraded(self) -> bool:
        return self._device_degraded

    def last_sample(self) -> dict:
        """Most recent snapshot — shaped for ``HealthState.probe()``."""
        return self._last

    def headroom_check(
        self, need_bytes: int, *, margin_frac: float = 0.10
    ) -> str | None:
        """``None`` when ``need_bytes`` fits inside the host's available
        memory with ``margin_frac`` slack; otherwise the refusal reason.
        Unknowable headroom (no /proc/meminfo) is *not* a refusal — the
        check exists to stop a predictable OOM, not to block platforms
        it can't read."""
        avail = host_available_bytes()
        if avail is None:
            return None
        budget = int(avail * (1.0 - margin_frac))
        if int(need_bytes) > budget:
            return (
                f"needs {int(need_bytes) // MB} MiB but only "
                f"{budget // MB} MiB of host memory is safely available "
                f"(MemAvailable {avail // MB} MiB, {margin_frac:.0%} margin)"
            )
        return None


# ----------------------------------------------------------- LeakSentinel


class LeakSentinel:
    """Names the fastest-growing component under sustained RSS growth.

    Feed it every :meth:`MemoryWatcher.sample` snapshot. Over a rolling
    window it fits a Theil–Sen slope to RSS *per sample*; when the robust
    growth across the window exceeds ``min_growth_mb`` it fires **once**
    (latched — `/healthz` stays degraded for the rest of the run, exactly
    like an SLO breach) and returns the suspect dict for the caller to
    journal as ``mem_leak_suspect`` and hand to the flight recorder. The
    suspect is the registered component with the largest robust slope; if
    no component explains the growth the verdict is ``unaccounted`` —
    pointing at native/JAX allocations outside the accountant's reach.

    The robust fit is the stable-workload guard: a one-sample spike (an
    eval window, a compile) moves the median pairwise slope very little,
    while a real leak grows every sample and moves it fully.
    """

    def __init__(self, *, window: int = 12, min_samples: int = 4,
                 min_growth_mb: float = 32.0, registry=None):
        if window < 2:
            raise ValueError(f"window must be >= 2, got {window}")
        self.window = int(window)
        self.min_samples = max(2, int(min_samples))
        self.min_growth_bytes = float(min_growth_mb) * MB
        self._reg = registry if registry is not None else get_registry()
        self._g_suspect = None
        self._samples: deque = deque(maxlen=self.window)
        self._fired: dict | None = None

    def degraded(self) -> bool:
        """Latched verdict — compose into ``HealthState.degraded_when``."""
        return self._fired is not None

    @property
    def suspect(self) -> dict | None:
        return self._fired

    def observe(self, snap: dict) -> dict | None:
        """Account one snapshot; returns the suspect dict on the single
        firing transition, ``None`` otherwise (including while latched)."""
        rss = snap.get("rss_bytes")
        if rss is None:
            return None
        self._samples.append(
            (float(snap.get("ts", 0.0)), int(rss),
             dict(snap.get("components") or {}))
        )
        if self._fired is not None or len(self._samples) < self.min_samples:
            return None
        rss_series = [s[1] for s in self._samples]
        slope = _theil_sen_slope(rss_series)
        n = len(rss_series)
        robust_growth = slope * (n - 1)
        if robust_growth < self.min_growth_bytes:
            return None
        suspect, comp_slope = "unaccounted", 0.0
        names = set()
        for _, _, comps in self._samples:
            names.update(comps)
        for name in sorted(names):
            series = [s[2].get(name, 0) for s in self._samples]
            s = _theil_sen_slope(series)
            if s > comp_slope:
                suspect, comp_slope = name, s
        # a component only takes the blame when its growth is a real share
        # of the RSS growth — a mildly warming cache must not eat the
        # verdict for a native leak it didn't cause
        if suspect != "unaccounted" and comp_slope < 0.2 * slope:
            suspect, comp_slope = "unaccounted", 0.0
        span_s = self._samples[-1][0] - self._samples[0][0]
        self._fired = {
            "component": suspect,
            "rss_growth_bytes": int(rss_series[-1] - rss_series[0]),
            "robust_growth_bytes": int(robust_growth),
            "slope_bytes_per_sample": int(slope),
            "component_slope_bytes_per_sample": int(comp_slope),
            "window": n,
            "window_span_s": round(max(span_s, 0.0), 3),
        }
        if self._g_suspect is None:
            self._g_suspect = self._reg.gauge(
                "mem_leak_suspect",
                "1 once the leak sentinel latched, naming the "
                "fastest-growing component",
                labels=("component",),
            )
        self._g_suspect.labels(component=suspect).set(1.0)
        return dict(self._fired)
