"""Lightweight host-side spans + chrome-trace export + XLA profiler capture.

Three layers of timing, cheapest first:

- :func:`span` — a ``with span("data_wait"):`` context that aggregates the
  duration into the registry's ``span_seconds{name=...}`` histogram. Always
  on (two ``perf_counter`` calls + one histogram observe); this is the
  per-stage timing the MoFa-style performance models start from.
- chrome-trace — ``start_chrome_trace()`` additionally buffers every span as
  a complete event; ``export_chrome_trace(path)`` writes the standard
  ``{"traceEvents": [...]}`` JSON that chrome://tracing and Perfetto open.
  Host-side complement to the XLA device traces below — one timeline shows
  the data waits and checkpoint stalls *between* the device programs.
- :func:`trace` / :func:`annotate` — the ``jax.profiler`` device-trace
  helpers (moved from ``utils/profiling.py``, which remains as a shim):
  XProf/TensorBoard captures showing MXU utilization and HBM traffic.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from pathlib import Path

from jumbo_mae_tpu_tpu.obs.metrics import get_registry

_SPAN_HELP = "host-side span durations by stage"


class _ChromeTracer:
    """Process-wide span event buffer (chrome trace 'X' complete events)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._events: list[dict] | None = None  # None = disabled

    @property
    def enabled(self) -> bool:
        return self._events is not None

    def start(self) -> None:
        with self._lock:
            self._events = []

    def add(self, name: str, start_s: float, dur_s: float) -> None:
        evt = {
            "name": name,
            "ph": "X",
            "ts": start_s * 1e6,  # chrome trace timestamps are microseconds
            "dur": dur_s * 1e6,
            "pid": os.getpid(),
            "tid": threading.get_ident(),
        }
        with self._lock:
            if self._events is not None:
                self._events.append(evt)

    def export(self, path: str | Path) -> Path:
        with self._lock:
            events = list(self._events or [])
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(
            json.dumps({"traceEvents": events, "displayTimeUnit": "ms"})
        )
        return path

    def stop(self) -> None:
        with self._lock:
            self._events = None


_TRACER = _ChromeTracer()


def start_chrome_trace() -> None:
    """Begin buffering spans as chrome-trace events (clears prior events)."""
    _TRACER.start()


def stop_chrome_trace() -> None:
    _TRACER.stop()


def export_chrome_trace(path: str | Path) -> Path:
    """Write buffered span events as chrome://tracing / Perfetto JSON."""
    return _TRACER.export(path)


@contextmanager
def span(name: str, registry=None):
    """Time a host-side stage into ``span_seconds{name=...}`` (and the
    chrome-trace buffer when capturing). The histogram handle is resolved
    per entry — for per-step hot loops, hoist with :func:`span_timer`."""
    reg = registry if registry is not None else get_registry()
    hist = reg.histogram("span_seconds", _SPAN_HELP, labels=("name",)).labels(name)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dur = time.perf_counter() - t0
        hist.observe(dur)
        if _TRACER.enabled:
            _TRACER.add(name, t0, dur)


class span_timer:  # noqa: N801 - context-manager factory, used like span()
    """Pre-resolved reusable span: same contract as :func:`span` but the
    histogram lookup happens once at construction — the shape for per-step
    loops (train step, data wait)."""

    __slots__ = ("name", "_hist", "_t0", "last_s")

    def __init__(self, name: str, registry=None):
        reg = registry if registry is not None else get_registry()
        self.name = name
        self._hist = reg.histogram(
            "span_seconds", _SPAN_HELP, labels=("name",)
        ).labels(name)
        self._t0 = 0.0
        self.last_s = 0.0  # duration of the most recent exit (loop bookkeeping)

    def __enter__(self) -> "span_timer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        dur = time.perf_counter() - self._t0
        self.last_s = dur
        self._hist.observe(dur)
        if _TRACER.enabled:
            _TRACER.add(self.name, self._t0, dur)

    def observe(self, dur_s: float) -> None:
        """Record an externally measured duration under this span's name."""
        self._hist.observe(dur_s)
        if _TRACER.enabled:
            _TRACER.add(self.name, time.perf_counter() - dur_s, dur_s)


@contextmanager
def trace(log_dir: str | None):
    """Capture an XLA device trace into ``log_dir`` (no-op when None)."""
    if not log_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextmanager
def annotate(name: str):
    """Named region in the device-trace timeline
    (``jax.profiler.TraceAnnotation``)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
