"""Stdlib-only HTTP exporter: Prometheus ``/metrics`` + ``/healthz``.

The externally scrapeable surface of the telemetry subsystem. Opt-in from
both entry points (``run.telemetry`` in ``cli/train.py``, ``--metrics-port``
in ``cli/predict.py``); a scrape never touches the hot path — it reads the
registry under the same per-metric locks the instrument sites use, so the
worst contention is one lock hand-off per metric per scrape.

``/healthz`` answers the operator questions the ROADMAP's serving story
needs: is the process *ready* (engine warm / state restored), and are its
loops *live* (last-step age, loader liveness) — each liveness check is a
named heartbeat with a max age, registered by whoever owns the loop.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from jumbo_mae_tpu_tpu.obs.metrics import MetricsRegistry, get_registry

# Process start (well, module import — the closest observable moment) for
# process_uptime_seconds: a scrape-visible restart detector. A counter that
# resets to ~0 tells the scraper "same target, new process" even when every
# app-level counter happens to be small.
_PROCESS_START = time.monotonic()


class HealthState:
    """Thread-safe readiness + liveness state behind ``/healthz``.

    Readiness is a single flag (set when the serving/training state is
    usable). Liveness is a set of named heartbeats: ``watch(name, max_age_s)``
    registers the requirement, ``beat(name)`` is the one-liner the owning
    loop calls. The report is unhealthy if not ready, or any watched
    heartbeat is older than its budget (a watched name never beaten is
    age-infinite, i.e. unhealthy — a loop that never started is not live).

    Named **info probes** (``probe(name, fn)``) attach extra read-only
    context to the report body — e.g. the data layer's quarantined-shard
    list — without affecting the health verdict; a probe that raises
    reports its error string instead of breaking the endpoint.
    """

    def __init__(self, *, ready: bool = False):
        self._lock = threading.Lock()
        self._ready = bool(ready)
        self._detail = ""
        self._max_age: dict[str, float] = {}
        self._beats: dict[str, float] = {}
        self._probes: dict[str, object] = {}
        self._degraded_fns: list = []

    def set_ready(self, ready: bool = True, detail: str = "") -> None:
        with self._lock:
            self._ready = bool(ready)
            self._detail = detail

    def watch(self, name: str, max_age_s: float) -> None:
        with self._lock:
            self._max_age[name] = float(max_age_s)

    def unwatch(self, name: str) -> None:
        """Drop a liveness requirement (e.g. the loader finished cleanly)."""
        with self._lock:
            self._max_age.pop(name, None)

    def beat(self, name: str) -> None:
        # monotonic: wall-clock jumps must not flip health
        self._beats[name] = time.monotonic()

    def probe(self, name: str, fn) -> None:
        """Attach a zero-arg callable whose JSON-able return value is
        included in the report body under ``info[name]``."""
        with self._lock:
            self._probes[name] = fn

    def degraded_when(self, fn) -> None:
        """Attach a zero-arg predicate (e.g. ``SLOTracker.degraded``,
        ``FleetAggregator.degraded``) whose truthiness feeds
        ``body["degraded"]``. Repeated calls *compose* — the body reports
        the OR of every registered predicate, so the SLO tracker, the
        replica breaker, and the fleet aggregator can all contribute
        without overwriting each other. Degraded is *soft*: the process is
        serving but missing its SLO — it must NOT flip the 503
        readiness/liveness verdict, or an autoscaler reacting to load
        would see its overloaded replicas drop out of rotation and make the
        overload worse."""
        with self._lock:
            self._degraded_fns.append(fn)

    def report(self) -> tuple[bool, dict]:
        now = time.monotonic()
        with self._lock:
            ready, detail = self._ready, self._detail
            watches = dict(self._max_age)
            probes = dict(self._probes)
            degraded_fns = list(self._degraded_fns)
        checks = {}
        ok = ready
        for name, budget in sorted(watches.items()):
            last = self._beats.get(name)
            age = None if last is None else now - last
            alive = age is not None and age <= budget
            ok = ok and alive
            checks[name] = {
                "age_s": None if age is None else round(age, 3),
                "max_age_s": budget,
                "ok": alive,
            }
        body = {"ok": ok, "ready": ready, "checks": checks}
        if degraded_fns:
            degraded: bool | str = False
            for fn in degraded_fns:
                try:
                    if fn():
                        degraded = True
                        break
                except Exception as e:  # noqa: BLE001 — never break /healthz
                    # an erroring probe only reports when no other says True
                    if degraded is False:
                        degraded = f"probe error: {type(e).__name__}: {e}"
            body["degraded"] = degraded
        if probes:
            info = {}
            for name, fn in sorted(probes.items()):
                try:
                    info[name] = fn()
                except Exception as e:  # noqa: BLE001 — never break /healthz
                    info[name] = f"probe error: {type(e).__name__}: {e}"
            body["info"] = info
        if detail:
            body["detail"] = detail
        return ok, body


class _Handler(BaseHTTPRequestHandler):
    server: "_Server"

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            pre = getattr(self.server, "pre_scrape", None)
            if pre is not None:
                pre()  # refresh scrape-time gauges (uptime)
            body = self.server.registry.render().encode()
            self._reply(200, body, "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/healthz":
            ok, report = self.server.health.report()
            body = (json.dumps(report) + "\n").encode()
            self._reply(200 if ok else 503, body, "application/json")
        else:
            self._reply(404, b"not found\n", "text/plain")

    def _reply(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, format, *args):  # noqa: A002 - stdlib signature
        pass  # scrapes must not spam the training log


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    registry: MetricsRegistry
    health: HealthState
    pre_scrape = None  # optional zero-arg callable run before each /metrics


class TelemetryServer:
    """The exporter: serve ``registry`` and ``health`` over HTTP in a daemon
    thread. ``port=0`` binds any free port (tests/CI); the bound port is
    ``self.port`` after ``start()``. Use as a context manager or ``close()``.
    """

    def __init__(
        self,
        registry: MetricsRegistry | None = None,
        health: HealthState | None = None,
        *,
        host: str = "0.0.0.0",
        port: int = 9100,
    ):
        self.registry = registry if registry is not None else get_registry()
        self.health = health if health is not None else HealthState(ready=True)
        self.host = host
        self.port = int(port)
        self._httpd: _Server | None = None
        self._thread: threading.Thread | None = None
        self._pre_scrape: list = []

    def add_pre_scrape(self, fn) -> None:
        """Register a zero-arg callable run before every ``/metrics`` render
        (scrape-time gauge refresh — uptime, SLO evaluation). Safe to call
        before or after ``start()``; a hook that raises is swallowed so one
        broken refresher cannot take down the scrape."""
        self._pre_scrape.append(fn)

    def _run_pre_scrape(self) -> None:
        for fn in list(self._pre_scrape):
            try:
                fn()
            except Exception:  # noqa: BLE001 — scrape must survive hooks
                pass

    def start(self) -> "TelemetryServer":
        if self._httpd is not None:
            return self
        httpd = _Server((self.host, self.port), _Handler)
        httpd.registry = self.registry
        httpd.health = self.health
        # restart-distinguishing metadata: build_info{version,jax_version}=1
        # (the Prometheus info-metric idiom) + a scrape-time-refreshed
        # process uptime gauge. Registered at start() so a NullRegistry A/B
        # stays no-op and import stays jax-free.
        from jumbo_mae_tpu_tpu import __version__

        try:
            import jax

            jax_version = jax.__version__
        except Exception:  # noqa: BLE001 - exporter must work jax-less
            jax_version = "unavailable"
        self.registry.gauge(
            "build_info",
            "constant 1; the labels identify the running build",
            labels=("version", "jax_version"),
        ).labels(version=__version__, jax_version=jax_version).set(1)
        g_uptime = self.registry.gauge(
            "process_uptime_seconds",
            "seconds since process start — a near-zero value means restart",
        )
        self.add_pre_scrape(
            lambda: g_uptime.set(time.monotonic() - _PROCESS_START)
        )
        httpd.pre_scrape = self._run_pre_scrape
        self.port = httpd.server_address[1]
        self._httpd = httpd
        self._thread = threading.Thread(
            target=httpd.serve_forever, daemon=True, name="telemetry-exporter"
        )
        self._thread.start()
        return self

    def close(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            if self._thread is not None:
                self._thread.join(timeout=5)
                self._thread = None

    def __enter__(self) -> "TelemetryServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()
