"""Analytic FLOP counting and MFU reporting for the Jumbo-MAE workloads.

The reference published no throughput or MFU numbers at all (SURVEY §5/§6);
this module closes that observability gap. FLOPs are counted from the model
configs analytically (matmuls only — elementwise work is bandwidth, not MXU),
so MFU = achieved / peak is comparable across chips and runs. Lives in the
telemetry subsystem since the train loop exports the resulting MFU/throughput
through the metrics registry (``utils/mfu.py`` remains as a compat shim).
"""

from __future__ import annotations

import sys
from dataclasses import dataclass

# Peak dense bf16 TFLOP/s per chip by TPU generation (public spec sheet
# numbers; override via ``peak_tflops=`` for other hardware).
PEAK_TFLOPS = {
    "v2": 46.0,
    "v3": 123.0,
    "v4": 275.0,
    "v5e": 197.0,
    # PJRT device_kind spells the e-variants "lite": 'TPU v5 lite',
    # 'TPU v6 lite' (observed live; the v5e key alone never matched, which
    # silently disabled bench.py's timing-plausibility guard on real v5e)
    "v5 lite": 197.0,
    "v5litepod": 197.0,
    "v5p": 459.0,
    "v6e": 918.0,
    "v6 lite": 918.0,
}

# The spelling aliases above all collapse onto one canonical generation —
# every consumer (peak flops here, the HBM/ICI tables in ``obs/perfmodel``)
# resolves device_kind through ONE normalizer so the "v5 lite never matched
# v5e" bug class can't come back per-table.
CANONICAL_KINDS = {"v5 lite": "v5e", "v5litepod": "v5e", "v6 lite": "v6e"}

_warned_kinds: set[str] = set()


def normalize_device_kind(kind: str) -> str | None:
    """Map a raw PJRT ``device_kind`` string ('TPU v5 lite', 'TPU v4', ...)
    to its canonical generation key ('v5e', 'v4'), or None if unmatched."""
    k = str(kind).lower()
    for gen in sorted(PEAK_TFLOPS, key=len, reverse=True):
        if gen in k:
            return CANONICAL_KINDS.get(gen, gen)
    return None


def lookup_peak_tflops(kind: str, default: float | None = None) -> float | None:
    """Peak bf16 TFLOP/s for a device_kind string.

    An unmatched kind is an observability event, not a silent default: warn
    once per kind on stderr and set ``mfu_peak_unknown{kind}`` so a scrape
    shows the timing-plausibility guard is running blind."""
    gen = normalize_device_kind(kind)
    if gen is not None:
        return PEAK_TFLOPS[gen]
    if kind not in _warned_kinds:
        _warned_kinds.add(kind)
        print(
            f"[mfu] unknown device_kind {kind!r}: no peak-TFLOPS entry — "
            f"MFU and timing-plausibility checks fall back to "
            f"default={default}",
            file=sys.stderr,
        )
        try:
            from jumbo_mae_tpu_tpu.obs.metrics import get_registry

            get_registry().gauge(
                "mfu_peak_unknown",
                "1 when the backend device_kind has no PEAK_TFLOPS entry",
                labels=("kind",),
            ).labels(str(kind)).set(1)
        except Exception:  # noqa: BLE001 - telemetry must not fail lookup
            pass
    return default


def _attention_flops(seq: int, dim: int, *, causal: bool = False) -> float:
    """Matmul FLOPs for one MHSA block on one sample: qkv+out projections and
    the two (N,N) einsums. 2·m·n·k per matmul."""
    proj = 4 * 2 * seq * dim * dim
    scores = 2 * 2 * seq * seq * dim
    if causal:
        scores /= 2
    return proj + scores


def _mlp_flops(seq: int, dim: int, hidden: int) -> float:
    return 2 * 2 * seq * dim * hidden


def encoder_flops_per_image(cfg, *, masked: bool) -> float:
    """Forward FLOPs for the Jumbo-ViT encoder on one image.

    ``masked=True`` uses the MAE visible-token count (``cfg.keep_len``), the
    whole point of encoder-on-visible-only MAE.
    """
    patches = cfg.keep_len if masked else cfg.num_patches
    seq = patches + cfg.num_cls_tokens
    d = cfg.dim
    per_layer = (
        _attention_flops(seq, d)
        + _mlp_flops(patches, d, cfg.hidden_dim)  # patch-token FF
        + _mlp_flops(1, cfg.num_cls_tokens * d, 4 * cfg.num_cls_tokens * d)  # jumbo MLP
    )
    # patchify conv runs on ALL patches (masking happens after embedding)
    embed = 2 * cfg.num_patches * d * (cfg.patch_size**2 * 3)
    return cfg.layers * per_layer + embed


def decoder_flops_per_image(enc_cfg, dec_cfg) -> float:
    seq = enc_cfg.num_patches + enc_cfg.num_cls_tokens
    d = dec_cfg.dim
    per_layer = _attention_flops(seq, d) + _mlp_flops(seq, d, dec_cfg.hidden_dim)
    proj_in = 2 * seq * enc_cfg.dim * d
    proj_out = 2 * enc_cfg.num_patches * d * (enc_cfg.patch_size**2 * 3)
    return dec_cfg.layers * per_layer + proj_in + proj_out


def pretrain_flops_per_image(enc_cfg, dec_cfg, *, training: bool = True) -> float:
    fwd = encoder_flops_per_image(enc_cfg, masked=True) + decoder_flops_per_image(
        enc_cfg, dec_cfg
    )
    return fwd * (3.0 if training else 1.0)  # bwd ≈ 2× fwd


def classify_flops_per_image(enc_cfg, *, training: bool = True) -> float:
    fwd = encoder_flops_per_image(enc_cfg, masked=False)
    if enc_cfg.labels:
        fwd += 2 * enc_cfg.num_cls_tokens * enc_cfg.dim * enc_cfg.labels
    return fwd * (3.0 if training else 1.0)


def detect_peak_tflops(default: float = 275.0) -> float:
    """Best-effort peak bf16 TFLOP/s of the current accelerator."""
    try:
        import jax

        kind = jax.devices()[0].device_kind
    except Exception:  # noqa: BLE001 - no backend → default
        return default
    peak = lookup_peak_tflops(kind, default=default)
    return default if peak is None else peak


@dataclass
class MfuReport:
    images_per_sec: float
    flops_per_image: float
    achieved_tflops: float
    peak_tflops: float

    @property
    def mfu(self) -> float:
        return self.achieved_tflops / self.peak_tflops


def mfu_report(
    flops_per_image: float,
    images_per_sec_per_chip: float,
    *,
    peak_tflops: float | None = None,
) -> MfuReport:
    peak = peak_tflops if peak_tflops is not None else detect_peak_tflops()
    achieved = flops_per_image * images_per_sec_per_chip / 1e12
    return MfuReport(
        images_per_sec=images_per_sec_per_chip,
        flops_per_image=flops_per_image,
        achieved_tflops=achieved,
        peak_tflops=peak,
    )
