"""Per-request trace context for the serving path.

The aggregate counters (`infer_*`) say *how much* the serving stack did;
this module says *what happened to request 8 131*. Every request that
enters the micro-batcher gets a :class:`RequestTrace` — a monotonic request
id plus wall/perf timestamps — threaded through
``MicroBatcher.submit → _admit → _flush`` and the engine's predict, so the
full latency breakdown survives per request:

- **queue_wait** — ``submit()`` call start → admission into a batch
  (includes any submit-side stall, so an injected ``serve.submit`` delay is
  visible where the caller felt it);
- **admission** — admitted → the batch's flush began (coalescing wait for
  co-travelers, bounded by ``max_delay_ms``);
- **compute** — the batched forward (device dispatch + execution);
- **fetch** — device→host transfer of the result rows;
- plus the **bucket** the chunk ran in, the batch size, the **pad
  fraction**, and the terminal **outcome**:
  ``ok | shed | deadline | late | aborted | shutdown``
  (``late`` = the deadline passed *after* admission, during coalescing or
  compute — the resolution-time check `infer_requests_late_total` counts).

With a replicated serving tier (``infer/replicaset.py``) each trace also
carries **replica attribution**: ``replica_id`` (which replica served it),
``retries`` (how many times it was requeued off a dying replica), and
``requeued_from`` (the excluded-replica trail) — the exactly-once invariant
extended with *who* served the request and *who failed to*.

Each finished trace is emitted twice: into labeled ``request_*`` histograms
on the metrics registry (scrapeable live) and, when an :class:`AccessLog`
is attached, as one JSONL row in a crash-safe rotated-segment access log
(the ``obs/journal.py`` writer) that ``tools/serve_doctor.py`` reads
offline. A ``MicroBatcher`` constructed without a tracer pays nothing —
every hook site is a ``None`` check — which is the telemetry-off A/B leg
PERF.md's overhead budget is measured against.
"""

from __future__ import annotations

import itertools
import threading
import time
from pathlib import Path
from typing import Callable

from jumbo_mae_tpu_tpu.obs.journal import RunJournal
from jumbo_mae_tpu_tpu.obs.metrics import RATIO_BUCKETS, get_registry

OUTCOMES = ("ok", "shed", "deadline", "late", "aborted", "shutdown")


class RequestTrace:
    """One request's context: identity, timestamps, and the breakdown
    filled in as it moves through the pipeline. Plain slots — created per
    request on the submit path."""

    __slots__ = (
        "rid", "task", "deadline_ms", "wall_ts", "t0", "t_admit", "t_flush",
        "queue_wait_s", "admission_s", "compute_s", "fetch_s",
        "batch", "bucket", "pad_fraction", "latency_s", "outcome", "error",
        "replica_id", "retries", "requeued_from", "tenant", "tclass",
        "device_s", "cost_flops", "tokens",
    )

    def __init__(
        self,
        rid: int,
        task: str,
        deadline_ms: float | None,
        tenant: str | None = None,
        tclass: str | None = None,
    ):
        self.rid = rid
        self.task = task
        self.deadline_ms = deadline_ms
        self.tenant = tenant
        self.tclass = tclass
        self.wall_ts = time.time()
        self.t0 = time.perf_counter()
        self.t_admit = None
        self.t_flush = None
        self.queue_wait_s = None
        self.admission_s = None
        self.compute_s = None
        self.fetch_s = None
        self.batch = None
        self.bucket = None
        self.pad_fraction = None
        self.latency_s = None
        self.outcome = None
        self.error = None
        self.replica_id = None
        self.retries = 0
        self.requeued_from = None
        self.device_s = None
        self.cost_flops = None
        # patch+CLS token count, stamped by the packed scheduler path —
        # the costmeter bills device time token-pro-rata when present
        self.tokens = None


class AccessLog:
    """Thread-safe crash-safe JSONL access log: the journal's rotated-
    segment writer behind one lock (trace rows come from the collector
    thread AND from shedding submit threads).

    ``fsync=False`` by default — the access log is per-request, not
    log-cadence; a flush per line plus the reader's torn-tail tolerance is
    the crash-safety contract serving can afford. Readable by
    :func:`obs.journal.read_journal` (and ``tools/serve_doctor.py``).
    """

    def __init__(
        self,
        directory: str | Path,
        *,
        max_bytes: int = 8 * 1024 * 1024,
        keep: int = 16,
        fsync: bool = False,
    ):
        self._journal = RunJournal(
            directory, max_bytes=max_bytes, keep=keep, fsync=fsync
        )
        self._lock = threading.Lock()

    @property
    def path(self) -> Path:
        return self._journal.path

    def event(self, etype: str, **fields) -> dict:
        with self._lock:
            return self._journal.event(etype, **fields)

    def close(self) -> None:
        with self._lock:
            self._journal.close()

    def __enter__(self) -> "AccessLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _ms(seconds) -> float | None:
    return None if seconds is None else round(seconds * 1000.0, 3)


class RequestTracer:
    """Creates, advances, and finishes :class:`RequestTrace` objects.

    ``breakdown`` is a zero-arg callable returning the engine's per-call
    compute/fetch/bucket/pad breakdown for the current thread
    (:meth:`InferenceEngine.last_breakdown`) — invoked on the collector
    thread right after ``run_fn`` returns, so it sees exactly the predict
    the flushed batch ran. ``on_finish`` receives every finished trace
    (the SLO tracker's feed); ``access_log`` gets one ``request`` row per
    finished trace. All three are optional and independent.
    """

    def __init__(
        self,
        *,
        registry=None,
        access_log: AccessLog | None = None,
        breakdown: Callable[[], dict | None] | None = None,
        on_finish: Callable[[RequestTrace], None] | None = None,
    ):
        reg = registry if registry is not None else get_registry()
        self.access_log = access_log
        self._breakdown = breakdown
        self._on_finish = on_finish
        self._next_rid = itertools.count().__next__  # GIL-atomic
        self._m_latency = reg.histogram(
            "request_latency_seconds",
            "end-to-end request latency by terminal outcome",
            labels=("outcome",),
        )
        self._m_queue = reg.histogram(
            "request_queue_wait_seconds",
            "submit() start to batch admission (includes submit-side stalls)",
        )
        self._m_admission = reg.histogram(
            "request_admission_seconds",
            "batch admission to flush start (coalescing wait)",
        )
        self._m_compute = reg.histogram(
            "request_compute_seconds",
            "batched forward (dispatch + device execution) per request",
        )
        self._m_fetch = reg.histogram(
            "request_fetch_seconds", "device-to-host result fetch per request"
        )
        self._m_pad = reg.histogram(
            "request_pad_fraction",
            "padding rows / bucket for the chunk that served the request",
            buckets=RATIO_BUCKETS,
        )
        self._m_outcomes = reg.counter(
            "request_outcomes_total",
            "finished requests by terminal outcome",
            labels=("outcome",),
        )

    # ------------------------------------------------------------ lifecycle

    def begin(
        self,
        *,
        task: str = "",
        deadline_ms: float | None = None,
        tenant: str | None = None,
        tclass: str | None = None,
    ) -> RequestTrace:
        return RequestTrace(self._next_rid(), task, deadline_ms, tenant, tclass)

    def admitted(self, tr: RequestTrace) -> None:
        tr.t_admit = time.perf_counter()
        tr.queue_wait_s = tr.t_admit - tr.t0

    def flush_begin(self, traces) -> None:
        now = time.perf_counter()
        for tr in traces:
            tr.t_flush = now
            if tr.t_admit is not None:
                tr.admission_s = now - tr.t_admit

    def flush_end(self, traces, *, run_s: float, batch: int, breakdown=None) -> None:
        """Stamp the batch-level breakdown onto every trace in the flush.
        With an engine breakdown available, compute/fetch are the engine's
        own split; otherwise the whole ``run_fn`` wall time is compute.
        ``breakdown`` overrides the constructor callable for this flush —
        a replica set has one engine per replica, so the right
        ``last_breakdown`` is only known at the call site."""
        fn = breakdown if breakdown is not None else self._breakdown
        bd = fn() if fn is not None else None
        for tr in traces:
            tr.batch = batch
            if bd is not None:
                tr.compute_s = bd.get("compute_s")
                tr.fetch_s = bd.get("fetch_s")
                tr.bucket = bd.get("bucket")
                tr.pad_fraction = bd.get("pad_fraction")
            else:
                tr.compute_s = run_s

    def finish(self, tr: RequestTrace, outcome: str, *, error: str | None = None) -> None:
        tr.outcome = outcome
        tr.error = error
        now = time.perf_counter()
        tr.latency_s = now - tr.t0
        if tr.queue_wait_s is None:
            # never admitted (shed / deadline / shutdown): everything the
            # caller waited is pre-admission time
            tr.queue_wait_s = tr.latency_s
        self._m_latency.labels(outcome).observe(tr.latency_s)
        self._m_outcomes.labels(outcome).inc()
        self._m_queue.observe(tr.queue_wait_s)
        if tr.admission_s is not None:
            self._m_admission.observe(tr.admission_s)
        if tr.compute_s is not None:
            self._m_compute.observe(tr.compute_s)
        if tr.fetch_s is not None:
            self._m_fetch.observe(tr.fetch_s)
        if tr.pad_fraction is not None:
            self._m_pad.observe(tr.pad_fraction)
        if self.access_log is not None:
            row = {
                "rid": tr.rid,
                "outcome": outcome,
                "lat_ms": _ms(tr.latency_s),
                "queue_wait_ms": _ms(tr.queue_wait_s),
            }
            if tr.task:
                row["task"] = tr.task
            for key, val in (
                ("admission_ms", _ms(tr.admission_s)),
                ("compute_ms", _ms(tr.compute_s)),
                ("fetch_ms", _ms(tr.fetch_s)),
                ("batch", tr.batch),
                ("bucket", tr.bucket),
                ("pad", tr.pad_fraction),
                ("tokens", tr.tokens),
                ("device_ms", _ms(tr.device_s)),
                ("cost_flops", tr.cost_flops),
                ("deadline_ms", tr.deadline_ms),
                ("tenant", tr.tenant),
                ("class", tr.tclass),
                ("replica", tr.replica_id),
                ("retries", tr.retries or None),
                ("requeued_from", tr.requeued_from),
                ("err", error),
            ):
                if val is not None:
                    row[key] = val
            self.access_log.event("request", **row)
        if self._on_finish is not None:
            self._on_finish(tr)

    def event(self, etype: str, **fields) -> None:
        """Write a non-request event (e.g. an SLO summary) into the access
        log, when one is attached."""
        if self.access_log is not None:
            self.access_log.event(etype, **fields)

    def close(self) -> None:
        if self.access_log is not None:
            self.access_log.close()
