"""Retrace sentinel: zero-recompiles-after-warmup, enforced at runtime.

A steady-state training or serving loop must not compile. Every XLA
compile after warmup is either a bucket-config bug, a shape leak (a batch
that missed padding), or a weak-type/dtype drift — all of which silently
multiply step latency by 100-1000× when they land, and none of which the
test suite sees because tests run two steps and stop.

This sentinel hooks JAX's own compile telemetry
(``jax.monitoring`` event ``/jax/core/compile/backend_compile_duration``,
which fires on *every* backend compile, first trace and retrace alike —
and never in a compile-free steady state). Protocol:

* ``note(tag, tree)`` — record the abstract signature (leaf shapes +
  dtypes) of what is about to be dispatched; cheap, no device access.
* ``arm()`` — warmup is over: from here every compile is a violation
  unless inside an ``expected()`` block (checkpoint restore, a fault
  injection building its alternate executable, a one-off eval).
* on a violation the sentinel journals a ``retrace`` event carrying the
  most recent signature change it saw (tag, previous and new signature,
  the per-leaf diff) — the attribution that turns "something recompiled"
  into "batch 7 arrived as (96, 224, 224, 3) where warmup saw 128".

Metrics: ``retrace_compiles_total`` (every compile seen while active),
``retrace_events_total`` (violations), ``retrace_armed`` gauge.

JAX has no per-listener unregister, so one module-level listener is
installed on first use and dispatches to live sentinels via a WeakSet —
creating/dropping sentinels (tests do this a lot) never accumulates
listeners.
"""

from __future__ import annotations

import threading
import warnings
import weakref
from contextlib import contextmanager

__all__ = ["RetraceSentinel", "COMPILE_EVENT"]

COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_sentinels: "weakref.WeakSet[RetraceSentinel]" = weakref.WeakSet()
_listener_installed = False


def _dispatch(event: str, duration: float, **_kw) -> None:
    if event != COMPILE_EVENT:
        return
    for sentinel in list(_sentinels):
        sentinel._on_compile(duration)


def _ensure_listener() -> None:
    global _listener_installed
    if _listener_installed:
        return
    import jax.monitoring

    jax.monitoring.register_event_duration_secs_listener(_dispatch)
    _listener_installed = True


def _signature(tree) -> tuple:
    """Abstract signature of a pytree: ((shape, dtype), ...) per leaf."""
    import jax

    sig = []
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
        sig.append((shape, dtype))
    return tuple(sig)


def _sig_diff(prev: tuple, new: tuple) -> list[dict]:
    """Per-leaf differences between two signatures."""
    diff = []
    for i in range(max(len(prev), len(new))):
        p = prev[i] if i < len(prev) else None
        n = new[i] if i < len(new) else None
        if p != n:
            diff.append(
                {
                    "leaf": i,
                    "prev_shape": list(p[0]) if p else None,
                    "prev_dtype": p[1] if p else None,
                    "new_shape": list(n[0]) if n else None,
                    "new_dtype": n[1] if n else None,
                }
            )
    return diff


class RetraceSentinel:
    """One armed watcher over a loop's dispatch signatures."""

    def __init__(self, name: str = "train", *, journal=None, registry=None):
        from jumbo_mae_tpu_tpu.obs.metrics import get_registry

        self.name = name
        self._journal = journal
        self._lock = threading.Lock()
        self._armed = False
        self._expected_depth = 0
        self._sigs: dict[str, tuple] = {}
        self._last_change: dict | None = None
        self.compiles = 0          # every backend compile seen while live
        self.expected_compiles = 0
        self.violations: list[dict] = []
        reg = registry if registry is not None else get_registry()
        self._m_compiles = reg.counter(
            "retrace_compiles_total",
            "backend compiles observed by the retrace sentinel",
            labels=("loop",),
        )
        self._m_events = reg.counter(
            "retrace_events_total",
            "unexpected recompiles after warmup (each journals a "
            "`retrace` event)",
            labels=("loop",),
        )
        self._m_armed = reg.gauge(
            "retrace_armed",
            "1 once warmup ended and the zero-recompile contract is live",
            labels=("loop",),
        )
        self._m_armed.labels(loop=name).set(0)
        _ensure_listener()
        _sentinels.add(self)

    # -- protocol --------------------------------------------------------

    def note(self, tag: str, tree) -> None:
        """Record the signature about to be dispatched under ``tag``."""
        sig = _signature(tree)
        with self._lock:
            prev = self._sigs.get(tag)
            if prev is not None and prev != sig:
                self._last_change = {
                    "tag": tag,
                    "prev": prev,
                    "new": sig,
                    "diff": _sig_diff(prev, sig),
                }
            self._sigs[tag] = sig

    def arm(self) -> None:
        with self._lock:
            self._armed = True
            self._last_change = None
        self._m_armed.labels(loop=self.name).set(1)

    def disarm(self) -> None:
        with self._lock:
            self._armed = False
        self._m_armed.labels(loop=self.name).set(0)

    @contextmanager
    def expected(self, reason: str = ""):
        """Compiles inside this block are legitimate (fault-injection
        alternate executables, one-off evals, checkpoint paths)."""
        with self._lock:
            self._expected_depth += 1
        try:
            yield
        finally:
            with self._lock:
                self._expected_depth -= 1

    # -- listener side ---------------------------------------------------

    def _on_compile(self, duration: float) -> None:
        with self._lock:
            self.compiles += 1
            armed = self._armed and self._expected_depth == 0
            change = self._last_change
            self._last_change = None
            if armed:
                record = {
                    "loop": self.name,
                    "compile_seconds": round(float(duration), 4),
                    "tag": change["tag"] if change else None,
                    "prev_sig": (
                        [list(s) for s in change["prev"]] if change else None
                    ),
                    "new_sig": (
                        [list(s) for s in change["new"]] if change else None
                    ),
                    "diff": change["diff"] if change else None,
                }
                self.violations.append(record)
            elif not self._armed or self._expected_depth:
                self.expected_compiles += 1
        self._m_compiles.labels(loop=self.name).inc()
        if not armed:
            return
        self._m_events.labels(loop=self.name).inc()
        attribution = (
            f"last signature change: `{record['tag']}` {record['diff']}"
            if change
            else "no noted signature changed — host-side jit or weak-type "
            "promotion; check scalar dtypes"
        )
        warnings.warn(
            f"retrace sentinel[{self.name}]: unexpected XLA compile after "
            f"warmup ({record['compile_seconds']}s). {attribution}",
            RuntimeWarning,
            stacklevel=2,
        )
        journal = self._journal
        if journal is not None:
            try:
                journal.event("retrace", **record)
            except Exception:  # noqa: BLE001 — observability must not kill the loop
                pass

    # -- readout ---------------------------------------------------------

    def summary(self) -> dict:
        with self._lock:
            return {
                "loop": self.name,
                "compiles": self.compiles,
                "expected": self.expected_compiles,
                "violations": len(self.violations),
            }

    def assert_steady(self) -> None:
        """Raise if any unexpected recompile happened after ``arm()``."""
        if self.violations:
            first = self.violations[0]
            raise AssertionError(
                f"retrace sentinel[{self.name}]: "
                f"{len(self.violations)} unexpected recompile(s) after "
                f"warmup; first: tag={first['tag']} diff={first['diff']}"
            )

    def close(self) -> None:
        self.disarm()
        _sentinels.discard(self)
