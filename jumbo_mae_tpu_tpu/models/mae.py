"""MAE decoder and the end-to-end pretraining model.

Parity targets: ``MAEDecoder`` (``/root/reference/src/modeling.py:276-298``)
and ``PretrainModule`` (``/root/reference/src/pretraining.py:76-122``).

Differences by design (defect ledger fixes, SURVEY.md appendix):

- the number of mask tokens is ``num_patches - keep_len`` (the reference
  recomputes ``int(N·mask_ratio)`` which can disagree — ledger item, §7);
- CLS-token slicing uses ``cfg.num_cls_tokens`` everywhere (the reference
  hardcodes ``3`` in its pretrain module);
- loss is computed in float32 regardless of compute dtype.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
from flax.linen import initializers as init

from jumbo_mae_tpu_tpu.models.config import (
    DecoderConfig,
    JumboViTConfig,
    maybe_remat,
)
from jumbo_mae_tpu_tpu.models.layers import TRUNC_NORMAL, PlainBlock
from jumbo_mae_tpu_tpu.models.vit import JumboViT
from jumbo_mae_tpu_tpu.ops.masking import unshuffle_with_mask_tokens
from jumbo_mae_tpu_tpu.ops.patches import (
    extract_patches,
    patch_mse_loss_per_sample,
)
from jumbo_mae_tpu_tpu.ops.posemb import sincos2d_positional_embedding
from jumbo_mae_tpu_tpu.ops.preprocess import normalize_images


class MAEDecoder(nn.Module):
    """Lightweight ViT decoder over the unshuffled full sequence.

    Fixed sincos2d positional embeddings are added to the patch tokens
    (never to CLS), then ``cfg.layers`` plain pre-norm blocks and a final LN.
    """

    cfg: DecoderConfig
    grid: tuple[int, int]
    num_cls_tokens: int

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        deterministic: bool = True,
        *,
        blocks_override=None,
    ) -> jax.Array:
        """``blocks_override`` (optional callable ``tokens -> tokens``)
        replaces the sequential block chain — the same pipeline-parallel
        seam the encoder has (``JumboViT.__call__``), so the decoder stack
        can be depth-sharded over a ``pipe`` mesh axis too."""
        cfg = self.cfg
        k = self.num_cls_tokens
        pos = sincos2d_positional_embedding(*self.grid, cfg.dim).reshape(
            1, -1, cfg.dim
        )
        x = jnp.concatenate(
            [x[:, :k, :], x[:, k:, :] + jnp.asarray(pos, x.dtype)], axis=1
        )
        if blocks_override is not None:
            x = blocks_override(x)
        else:
            block_cls = maybe_remat(PlainBlock, cfg)
            for i in range(cfg.layers):
                x = block_cls(cfg, name=f"block_{i}")(x, deterministic)
        return nn.LayerNorm(dtype=cfg.compute_dtype, name="ln")(x)


class MAEPretrainModel(nn.Module):
    """uint8 images → masked-patch reconstruction loss.

    Pipeline: on-device normalize → JumboViT (MAE mode) → project to decoder
    width → insert learned mask tokens and unshuffle → MAEDecoder → per-patch
    pixel regression → masked MSE (optionally per-patch-normalized targets).
    """

    encoder_cfg: JumboViTConfig
    decoder_cfg: DecoderConfig
    norm_pix_loss: bool = False

    def setup(self):
        enc = self.encoder_cfg.replace(labels=None)
        if enc.mask_ratio is None:
            raise ValueError("encoder_cfg.mask_ratio is required for MAE pretraining")
        self.encoder = JumboViT(enc, name="encoder")
        self.mask_token = self.param(
            "mask_token", TRUNC_NORMAL, (1, 1, self.decoder_cfg.dim)
        )
        self.decoder_proj = nn.Dense(
            self.decoder_cfg.dim,
            kernel_init=TRUNC_NORMAL,
            dtype=self.decoder_cfg.compute_dtype,
            name="decoder_proj",
        )
        self.decoder = MAEDecoder(
            self.decoder_cfg,
            grid=enc.grid,
            num_cls_tokens=enc.num_cls_tokens,
            name="decoder",
        )
        self.pixel_proj = nn.Dense(
            self.encoder_cfg.patch_size**2 * 3,
            kernel_init=TRUNC_NORMAL,
            name="pixel_proj",
        )

    def __call__(
        self,
        images: jax.Array,
        deterministic: bool = True,
        return_reconstruction: bool = False,
        *,
        mask_noise: jax.Array | None = None,
        blocks_override=None,
        dec_blocks_override=None,
    ):
        enc_cfg = self.encoder_cfg
        k = enc_cfg.num_cls_tokens
        images = normalize_images(images, dtype=enc_cfg.compute_dtype)

        tokens, mask, ids_restore = self.encoder(
            images,
            deterministic,
            mask_noise=mask_noise,
            blocks_override=blocks_override,
        )
        tokens = self.decoder_proj(tokens)
        cls, visible = tokens[:, :k, :], tokens[:, k:, :]

        full = unshuffle_with_mask_tokens(
            visible, self.mask_token, ids_restore, impl=enc_cfg.gather_impl
        )
        decoded = self.decoder(
            jnp.concatenate([cls, full], axis=1),
            deterministic,
            blocks_override=dec_blocks_override,
        )
        pred = self.pixel_proj(decoded[:, k:, :].astype(jnp.float32))

        target = extract_patches(images.astype(jnp.float32), enc_cfg.patch_size)
        if self.norm_pix_loss:
            mean = target.mean(axis=-1, keepdims=True)
            var = target.var(axis=-1, keepdims=True)
            target = (target - mean) / jnp.sqrt(var + 1e-6)

        loss_per_sample = patch_mse_loss_per_sample(pred, target, mask)
        out = {"loss": loss_per_sample.mean(), "loss_per_sample": loss_per_sample}
        if return_reconstruction:
            out["reconstruction"] = pred
            out["mask"] = mask
        return out
