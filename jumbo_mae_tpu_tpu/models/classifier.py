"""Supervised classification model: finetune and linear probe.

Parity: ``FinetuneModule``, ``/root/reference/src/finetuning.py:78-106`` —
on-device normalization, one-hot + label smoothing, Mixup/CutMix in training,
CE/BCE criteria, and top-1/top-5 accuracy computed as membership of the
predicted classes in the label *set* (multi-label safe after mixup).
"""

from __future__ import annotations

from typing import Literal

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

from jumbo_mae_tpu_tpu.models.config import JumboViTConfig
from jumbo_mae_tpu_tpu.models.vit import JumboViT
from jumbo_mae_tpu_tpu.ops.mixup import mixup_cutmix
from jumbo_mae_tpu_tpu.ops.preprocess import normalize_images

Criterion = Literal["ce", "bce"]


def cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return optax.softmax_cross_entropy(logits, labels)


def binary_cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return optax.sigmoid_binary_cross_entropy(logits, labels > 0).mean(-1)


CRITERIA = {"ce": cross_entropy, "bce": binary_cross_entropy}


class ClassificationModel(nn.Module):
    """uint8 images + integer (or soft) labels → per-sample loss/acc metrics."""

    encoder_cfg: JumboViTConfig
    mixup_alpha: float = 0.0
    cutmix_alpha: float = 0.0
    label_smoothing: float = 0.0
    criterion: Criterion = "ce"

    def setup(self):
        if (self.encoder_cfg.labels or 0) <= 0:
            raise ValueError("ClassificationModel requires encoder_cfg.labels > 0")
        self.model = JumboViT(
            self.encoder_cfg.replace(mask_ratio=None), name="model"
        )

    def __call__(
        self,
        images: jax.Array,
        labels: jax.Array,
        deterministic: bool = True,
        *,
        blocks_override=None,
    ) -> dict[str, jax.Array]:
        cfg = self.encoder_cfg
        images = normalize_images(images, dtype=cfg.compute_dtype)

        if labels.ndim == 1:
            labels = nn.one_hot(labels, cfg.labels)
        labels = labels.astype(jnp.float32)

        if not deterministic:
            if self.criterion == "ce" and self.label_smoothing > 0:
                labels = optax.smooth_labels(labels, self.label_smoothing)
            if self.mixup_alpha > 0 or self.cutmix_alpha > 0:
                images, labels = mixup_cutmix(
                    self.make_rng("mixup"),
                    images,
                    labels,
                    self.mixup_alpha,
                    self.cutmix_alpha,
                )

        logits = self.model(
            images, deterministic, blocks_override=blocks_override
        ).astype(jnp.float32)
        loss = CRITERIA[self.criterion](logits, labels)

        # Top-k accuracy as membership in the per-sample label set — exact for
        # single-label data and meaningful after mixup (multi-label).
        label_set = labels == labels.max(-1, keepdims=True)
        top5 = jax.lax.top_k(logits, k=5)[1]
        hits = jnp.take_along_axis(label_set, top5, axis=-1)
        return {
            "loss": loss,
            "acc1": hits[:, 0].astype(jnp.float32),
            "acc5": hits.any(-1).astype(jnp.float32),
        }
