"""The Jumbo ViT encoder.

Parity: ``ViT``, ``/root/reference/src/modeling.py:221-274``. One module
serves three modes:

- **MAE mode** (``cfg.mask_ratio`` set, ``cfg.labels`` None/0): after patch
  embedding and CLS prepending, patch tokens are randomly masked and only the
  visible ones are encoded. Returns ``(tokens, mask, ids_restore)``.
- **classify mode** (``cfg.labels > 0``): full sequence encoded; the
  ``num_cls_tokens`` CLS embeddings are concatenated and fed to the linear
  head. ``cfg.linear_probing`` stops gradients into the trunk;
  ``cfg.batch_norm`` enables the probe-head BatchNorm.
- **feature mode** (``cfg.labels`` None and no mask_ratio): returns the
  normalized token sequence (useful for downstream / conversion tests).

The shared ``jumbo_mlp`` (width k·dim) is built once here and passed to every
block — the weight sharing is the defining property of the architecture.
Gradient checkpointing wraps each block with ``nn.remat`` (deterministic flag
static). The reference's ``pooling`` flag was parsed but ignored
(defect ledger #3); here ``pooling="gap"`` is actually implemented.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
from flax.linen import initializers as init

from jumbo_mae_tpu_tpu.models.config import JumboViTConfig, maybe_remat
from jumbo_mae_tpu_tpu.models.layers import (
    ClassifierHead,
    JumboBlock,
    PatchEmbed,
    make_jumbo_mlp,
    segment_attention_mask,
)
from jumbo_mae_tpu_tpu.ops.masking import random_masking


def pool_tokens(tokens: jax.Array, num_cls_tokens: int, pooling: str = "cls"):
    """The probe/head representation: ``"cls"`` concatenates the
    ``num_cls_tokens`` CLS embeddings (parity:
    ``/root/reference/src/modeling.py:269-274``); ``"gap"`` mean-pools the
    patch tokens. Shared by :class:`JumboViT` and
    ``tools/extract_features.py`` so the exported features can never drift
    from what the in-train heads consume."""
    if pooling == "gap":
        return tokens[:, num_cls_tokens:, :].mean(axis=1)
    return tokens[:, :num_cls_tokens, :].reshape(tokens.shape[0], -1)


class JumboViT(nn.Module):
    cfg: JumboViTConfig

    def setup(self):
        cfg = self.cfg
        self.embed = PatchEmbed(cfg, name="embed")
        self.cls_tokens = self.param(
            "cls_tokens", init.zeros, (1, cfg.num_cls_tokens, cfg.dim)
        )
        self.jumbo_mlp = make_jumbo_mlp(cfg)
        block_cls = maybe_remat(JumboBlock, cfg)
        self.blocks = [
            block_cls(cfg, self.jumbo_mlp, name=f"block_{i}")
            for i in range(cfg.layers)
        ]
        self.norm = nn.LayerNorm(dtype=cfg.compute_dtype, name="ln")
        self.drop = nn.Dropout(cfg.dropout)
        self.head = (
            ClassifierHead(cfg.labels, cfg.batch_norm, name="head")
            if (cfg.labels or 0) > 0
            else None
        )

    @property
    def mae_mode(self) -> bool:
        return self.head is None and self.cfg.mask_ratio is not None

    def __call__(
        self,
        images: jax.Array,
        deterministic: bool = True,
        *,
        mask_noise: jax.Array | None = None,
        blocks_override=None,
    ):
        """``blocks_override`` (optional callable ``tokens -> tokens``)
        replaces the sequential block chain — the seam the pipeline-parallel
        train step uses to run the same ``block_*`` parameters through the
        GPipe schedule (``parallel/pipeline.py``) instead of the Python
        loop. The override closes over the parameter tree at the step level,
        so gradients flow through it unchanged."""
        cfg = self.cfg
        k = cfg.num_cls_tokens
        x = self.embed(images)
        bs = x.shape[0]

        mask = ids_restore = None
        if self.mae_mode:
            rng = None if mask_noise is not None else self.make_rng("noise")
            x, mask, ids_restore = random_masking(
                x,
                rng,
                cfg.keep_len,
                mode=cfg.mask_mode,
                noise=mask_noise,
                gather_impl=cfg.gather_impl,
            )

        cls = jnp.broadcast_to(
            jnp.asarray(self.cls_tokens, x.dtype), (bs, k, cfg.dim)
        )
        x = jnp.concatenate([cls, x], axis=1)
        x = self.drop(x, deterministic)

        if blocks_override is not None:
            x = blocks_override(x)
        else:
            for block in self.blocks:
                x = block(x, deterministic)
        x = self.norm(x)

        if self.mae_mode:
            return x, mask, ids_restore

        if self.head is None:
            return x

        if cfg.linear_probing:
            x = jax.lax.stop_gradient(x)

        pooled = pool_tokens(x, k, cfg.pooling)
        return self.head(pooled.astype(jnp.float32), deterministic)

    # ------------------------------------------------- token-packed serving

    def patchify(self, images: jax.Array) -> jax.Array:
        """Patch embedding only (conv + posemb), (B, N, dim) — the packed
        serving path embeds each request at its own resolution, then packs
        the resulting token segments into one buffer. CLS tokens are NOT
        prepended here: the positional embedding applies to patches only
        in this architecture, so CLS injection can happen inside the packed
        executable (see :meth:`encode_packed`) with identical numerics."""
        return self.embed(images)

    def encode_packed(
        self,
        tokens: jax.Array,
        segment_ids: jax.Array,
        cls_pos: jax.Array,
        cls_index: jax.Array,
        deterministic: bool = True,
    ) -> jax.Array:
        """Run the block stack over a token-packed buffer.

        ``tokens`` is (rows, budget, dim) — already patch-embedded, zeros
        at CLS slots and padding. ``segment_ids``/``cls_pos``/``cls_index``
        are the :mod:`~jumbo_mae_tpu_tpu.infer.packing` plan arrays. The
        CLS parameter is injected at each segment's ``cls_pos`` slots;
        attention is block-diagonal per segment; every other op is
        per-token — so each segment computes exactly what its own unpacked
        batch row would."""
        cfg = self.cfg
        x = tokens.astype(cfg.compute_dtype)
        cls = jnp.asarray(self.cls_tokens, x.dtype)[0]  # (k, dim)
        x = jnp.where(cls_pos[..., None] >= 0, cls[jnp.clip(cls_pos, 0)], x)
        x = self.drop(x, deterministic)
        packed = {
            "mask": segment_attention_mask(segment_ids),
            "segment_ids": segment_ids,
            "cls_pos": cls_pos,
            "cls_index": cls_index,
        }
        for block in self.blocks:
            x = block(x, deterministic, packed)
        return self.norm(x)

    def pool_packed(
        self,
        tokens: jax.Array,
        segment_ids: jax.Array,
        cls_pos: jax.Array,
        cls_index: jax.Array,
        pooling: str = "cls",
    ) -> jax.Array:
        """Per-segment :func:`pool_tokens`: (rows, max_segments, k·dim)
        for ``"cls"``, (rows, max_segments, dim) for ``"gap"``. Unoccupied
        slots pool garbage (slot 0's tokens / zero counts clamped to 1) —
        callers slice results by the pack plan, so those never escape."""
        cfg = self.cfg
        k = cfg.num_cls_tokens
        rows, _, dim = tokens.shape
        smax = cls_index.shape[1]
        if pooling == "gap":
            slot = jnp.arange(1, smax + 1, dtype=segment_ids.dtype)
            own = (segment_ids[:, None, :] == slot[None, :, None]) & (
                cls_pos[:, None, :] < 0
            )
            w = own.astype(tokens.dtype)
            sums = jnp.einsum("rsl,rld->rsd", w, tokens)
            counts = jnp.maximum(w.sum(axis=-1), 1.0)
            return sums / counts[..., None]
        g = jnp.take_along_axis(
            tokens, cls_index.reshape(rows, smax * k)[..., None], axis=1
        )
        return g.reshape(rows, smax, k * dim)

    def serve_packed(
        self,
        tokens: jax.Array,
        segment_ids: jax.Array,
        cls_pos: jax.Array,
        cls_index: jax.Array,
        deterministic: bool = True,
        *,
        pooling: str = "cls",
    ) -> dict[str, jax.Array]:
        """The packed serving forward: encode, pool per segment, and (when
        the model has a head) classify — ``{"pooled": ..., "logits": ...}``
        so features and logits requests ride one executable."""
        x = self.encode_packed(
            tokens, segment_ids, cls_pos, cls_index, deterministic
        )
        pooled = self.pool_packed(x, segment_ids, cls_pos, cls_index, pooling)
        out = {"pooled": pooled.astype(jnp.float32)}
        if self.head is not None:
            head_in = (
                pooled
                if pooling == self.cfg.pooling
                else self.pool_packed(
                    x, segment_ids, cls_pos, cls_index, self.cfg.pooling
                )
            )
            out["logits"] = self.head(
                head_in.astype(jnp.float32), deterministic
            ).astype(jnp.float32)
        return out

    def serve_full(
        self,
        images: jax.Array,
        deterministic: bool = True,
        *,
        pooling: str = "cls",
    ) -> dict[str, jax.Array]:
        """Unpacked mirror of :meth:`serve_packed` — same output contract
        from a plain image batch. This is the packed path's per-request
        parity oracle (it also serves non-native resolutions, which the
        bucketed ``__call__`` path rejects)."""
        cfg = self.cfg
        k = cfg.num_cls_tokens
        x = self.embed(images)
        bs = x.shape[0]
        cls = jnp.broadcast_to(
            jnp.asarray(self.cls_tokens, x.dtype), (bs, k, cfg.dim)
        )
        x = jnp.concatenate([cls, x], axis=1)
        x = self.drop(x, deterministic)
        for block in self.blocks:
            x = block(x, deterministic)
        x = self.norm(x)
        out = {"pooled": pool_tokens(x, k, pooling).astype(jnp.float32)}
        if self.head is not None:
            head_in = pool_tokens(x, k, cfg.pooling)
            out["logits"] = self.head(
                head_in.astype(jnp.float32), deterministic
            ).astype(jnp.float32)
        return out
