from jumbo_mae_tpu_tpu.models.config import (
    DecoderConfig,
    JumboViTConfig,
    PRESETS,
    preset,
)
from jumbo_mae_tpu_tpu.models.vit import JumboViT, pool_tokens
from jumbo_mae_tpu_tpu.models.mae import MAEDecoder, MAEPretrainModel
from jumbo_mae_tpu_tpu.models.classifier import ClassificationModel

__all__ = [
    "DecoderConfig",
    "JumboViTConfig",
    "PRESETS",
    "preset",
    "JumboViT",
    "MAEDecoder",
    "MAEPretrainModel",
    "ClassificationModel",
    "pool_tokens",
]
