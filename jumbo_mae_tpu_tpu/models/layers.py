"""Transformer building blocks for the Jumbo ViT family.

Fresh flax.linen implementations with behavioral parity to
``/root/reference/src/modeling.py:106-219`` (PatchEmbed, Attention,
FeedForward, ViTLayer, JumboLayer, LinearCLS), designed TPU-first:

- compute in a configurable dtype (bfloat16 by default) with float32 params;
- attention scores accumulate in float32 on the MXU and softmax computes in
  float32, but the materialized score/prob tensors follow the compute dtype
  (halves the O(S²) HBM traffic under bf16; exact under f32 compute, which
  is what every parity test runs — see PERF.md);
- attention implementation switchable between a fused Pallas flash kernel and
  the plain einsum path (the einsum path is also the parity oracle in tests).

Parameter naming is semantic (q/k/v/out, fc1/fc2, ln1/ln2/ln3, ls1/ls2/ls3)
rather than the reference's wq/w1/norm1/scale1; ``tools/`` converters map
between layouts.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from flax.linen import initializers as init

from jumbo_mae_tpu_tpu.models.config import DecoderConfig, JumboViTConfig
from jumbo_mae_tpu_tpu.ops.posemb import sincos2d_positional_embedding

TRUNC_NORMAL = init.truncated_normal(0.02)

# attn_impl="auto" switches einsum → Pallas flash at this sequence length.
# The v5e-measured crossover sits between 199 (einsum 1.7× faster) and 787
# (flash 1.7× faster); 512 splits it conservatively. Env-overridable so a
# different TPU generation can re-pin it from tools/flash_microbench.py
# without a code change.
import os as _os

AUTO_FLASH_MIN_SEQ = int(_os.environ.get("JUMBO_AUTO_FLASH_MIN_SEQ", "512"))


def resolve_attn_impl(
    impl: str,
    *,
    backend: str,
    seq_len: int,
    dropout: float,
    deterministic: bool,
) -> str:
    """Resolve ``attn_impl="auto"`` to a concrete backend per call shape.

    Measured crossover on v5e (tools/flash_microbench.py, round 5,
    fwd+bwd ms): einsum wins at MAE-224 shapes (seq 199: 5.2 vs 8.7),
    the Pallas kernels win from long-context lengths up (seq 787: 9.0 vs
    15.3; seq 3139: 24.7 vs 45.8) now that they use bf16 MXU-rate
    operands and full-row blocks. dropout>0 training still needs
    einsum's materialized probs (flash has no probability dropout).
    Explicit impl choices pass through untouched.
    """
    if impl != "auto":
        return impl
    use_flash = (
        backend == "tpu"
        and seq_len >= AUTO_FLASH_MIN_SEQ
        and (dropout == 0.0 or deterministic)
    )
    return "flash" if use_flash else "einsum"

ConfigT = Any  # JumboViTConfig | DecoderConfig — same attribute surface


def segment_attention_mask(segment_ids: jax.Array) -> jax.Array:
    """Block-diagonal attention mask for token-packed sequences.

    ``segment_ids`` is (batch, seq) int32 — ``slot+1`` on tokens a packed
    segment owns, 0 on padding. A position attends only within its own
    segment (``same id AND id > 0``); the diagonal is OR'd in so all-pad
    positions softmax over themselves instead of an all(-inf) row whose
    NaN would pollute valid rows through the probs·V matmul. Returns
    (batch, 1, seq, seq) bool, broadcast over heads."""
    s = segment_ids
    same = (s[:, :, None] == s[:, None, :]) & (s[:, :, None] > 0)
    eye = jnp.eye(s.shape[-1], dtype=bool)[None]
    return (same | eye)[:, None, :, :]


class Attention(nn.Module):
    """Multi-head self-attention.

    Parity: ``/root/reference/src/modeling.py:127-138`` — separate q/k/v
    projections to (heads, head_dim), queries pre-scaled by head_dim**-0.5,
    dropout on the attention probabilities and on the output projection.

    The q/k/v projections stay ``nn.DenseGeneral`` deliberately: a
    flat-2-D-matmul variant with identical params won a standalone
    microbench (2.55 vs 2.8–3.8 ms at the H/14 encoder slice) but LOST
    7% step-level on H/14 (269–270 vs 292 img/s, two runs) — in the full
    graph XLA fuses the 4-D contraction's output layout straight into
    the attention einsums, which the reshape breaks. PERF.md §Round 5.
    """

    cfg: ConfigT

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        deterministic: bool = True,
        mask: jax.Array | None = None,
    ) -> jax.Array:
        cfg = self.cfg
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (cfg.heads, cfg.head_dim),
            kernel_init=TRUNC_NORMAL,
            dtype=cfg.compute_dtype,
            name=name,
        )
        q = dense("q")(x) * cfg.head_dim**-0.5
        k = dense("k")(x)
        v = dense("v")(x)

        # Masked attention (token-packed serving's block-diagonal segment
        # mask) exists only on the einsum path: the flash/ring kernels take
        # no mask operand, and silently dropping one would leak tokens
        # across segments.
        if mask is not None and cfg.attn_impl in ("flash", "ring"):
            raise ValueError(
                f"attn_impl={cfg.attn_impl!r} has no attention-mask "
                "support; packed/masked attention requires the einsum path "
                "(attn_impl='einsum' or 'auto')"
            )
        # The flash/ring paths have no attention-probability dropout; any
        # dropout>0 must take the einsum path so training semantics don't
        # silently change.
        if cfg.attn_impl in ("flash", "ring") and cfg.dropout > 0.0 and not deterministic:
            # Both are explicit requests — "ring" for sequence parallelism,
            # "flash" for O(S) score memory; silently degrading either to
            # the O(S²) einsum path would defeat the reason it was chosen.
            # Deterministic (inference) calls are fine: dropout is inactive,
            # so a model trained with einsum+dropout can still evaluate with
            # flash/ring.
            raise ValueError(
                f"attn_impl={cfg.attn_impl!r} has no attention-probability "
                "dropout; set dropout=0.0 to train (droppath regularization "
                "still applies)"
            )
        impl = resolve_attn_impl(
            cfg.attn_impl,
            backend=jax.default_backend(),
            seq_len=x.shape[1],
            dropout=cfg.dropout,
            deterministic=deterministic,
        )
        if mask is not None:
            impl = "einsum"  # auto: the only mask-capable path

        # z_head_major tracks each branch's output layout: (B,H,S,D) for the
        # einsum path, (B,S,H,D) for flash/ring — set alongside z so a new
        # branch can't silently mismatch the out-projection's axes.
        if impl == "ring":
            # Sequence parallelism: tokens shard over the ambient mesh's
            # "seq" axis, K/V ring-rotate over ICI (parallel/ring_attention).
            from jumbo_mae_tpu_tpu.parallel.ring_attention import (
                ring_self_attention,
            )

            z, z_head_major = (
                ring_self_attention(q, k, v, inner=cfg.ring_inner),
                False,
            )
        elif impl == "flash":
            from jumbo_mae_tpu_tpu.ops.flash_attention import flash_attention

            z, z_head_major = flash_attention(q, k, v), False
        else:
            # Scores materialize in the compute dtype; the MXU still
            # accumulates the dot in f32, and softmax still computes in f32
            # (the convert fuses into the softmax chain). Under bf16 compute
            # this halves the HBM traffic of the O(S²) score tensor — the
            # single largest bandwidth item in the profile: −27 ms/step on
            # the v5e bench workload's 8 decoder layers (PERF.md). Only the
            # materialized rounding is bf16; with float32 compute (all
            # parity tests/oracles) the path is exact and unchanged.
            logits = jnp.einsum("bqhd,bkhd->bhqk", q, k)
            scores = logits.astype(jnp.float32)
            if mask is not None:
                # -inf before softmax underflows to an exact 0 probability:
                # a masked key contributes exactly 0·v, so segment isolation
                # is bit-exact, not approximate (every query keeps at least
                # its diagonal, so no row is all -inf)
                scores = jnp.where(mask, scores, -jnp.inf)
            probs = jax.nn.softmax(scores, axis=-1).astype(
                cfg.compute_dtype
            )
            probs = nn.Dropout(cfg.dropout)(probs, deterministic)
            # Keep z head-major (B,H,S,D) — the layout the scores matmul
            # produces natively — and let the output projection contract
            # (h, d) from there: measured −17% attention fwd+bwd on v5e at
            # the encoder shape vs transposing back to (B,S,H,D) (PERF.md).
            z, z_head_major = jnp.einsum("bhqk,bkhd->bhqd", probs, v), True

        # kernel shape is (heads, head_dim, dim) for either axis choice, so
        # both paths share the same checkpoint layout
        out = nn.DenseGeneral(
            cfg.dim,
            axis=(1, 3) if z_head_major else (-2, -1),
            kernel_init=TRUNC_NORMAL,
            dtype=cfg.compute_dtype,
            name="out",
        )(z)
        return nn.Dropout(cfg.dropout)(out, deterministic)


class Mlp(nn.Module):
    """Dense(hidden) → GELU → Dense(out) with dropout after each dense.

    Parity: ``FeedForward``, ``/root/reference/src/modeling.py:141-148``.
    Also instantiated as the shared "jumbo MLP" with dim = k·encoder_dim.
    """

    dim: int
    hidden_dim: int
    dropout: float
    dtype: Any

    @nn.compact
    def __call__(self, x: jax.Array, deterministic: bool = True) -> jax.Array:
        x = nn.Dense(
            self.hidden_dim, kernel_init=TRUNC_NORMAL, dtype=self.dtype, name="fc1"
        )(x)
        x = nn.Dropout(self.dropout)(nn.gelu(x), deterministic)
        x = nn.Dense(
            self.dim, kernel_init=TRUNC_NORMAL, dtype=self.dtype, name="fc2"
        )(x)
        return nn.Dropout(self.dropout)(x, deterministic)


def make_jumbo_mlp(cfg: JumboViTConfig, name: str | None = "jumbo_mlp") -> Mlp:
    """The shared jumbo CLS MLP's one architectural definition — used by
    :class:`~jumbo_mae_tpu_tpu.models.vit.JumboViT` (owner of the shared
    params) and by the pipeline-parallel runtime, so the two can never
    diverge."""
    return Mlp(
        dim=cfg.num_cls_tokens * cfg.dim,
        hidden_dim=4 * cfg.num_cls_tokens * cfg.dim,
        dropout=cfg.dropout,
        dtype=cfg.compute_dtype,
        name=name,
    )


class DropPath(nn.Module):
    """Stochastic depth: drop the whole residual branch per sample, i.e. a
    Dropout broadcast over every non-batch axis (the reference's idiom,
    ``/root/reference/src/modeling.py:157,181-183``)."""

    rate: float

    @nn.compact
    def __call__(self, x: jax.Array, deterministic: bool = True) -> jax.Array:
        bcast = tuple(range(1, x.ndim))
        return nn.Dropout(self.rate, broadcast_dims=bcast)(x, deterministic)


class PlainBlock(nn.Module):
    """Pre-norm transformer block (used by the MAE decoder).

    Parity: ``ViTLayer``, ``/root/reference/src/modeling.py:150-167``.
    """

    cfg: ConfigT

    @nn.compact
    def __call__(self, x: jax.Array, deterministic: bool = True) -> jax.Array:
        cfg = self.cfg
        ls = (
            lambda name: self.param(name, init.constant(1e-4), (cfg.dim,))
            if cfg.layerscale
            else 1.0
        )
        h = Attention(cfg, name="attn")(
            nn.LayerNorm(dtype=cfg.compute_dtype, name="ln1")(x), deterministic
        )
        x = x + DropPath(cfg.droppath, name="dp1")(ls("ls1") * h, deterministic)
        h = Mlp(
            cfg.dim, cfg.hidden_dim, cfg.dropout, cfg.compute_dtype, name="mlp"
        )(nn.LayerNorm(dtype=cfg.compute_dtype, name="ln2")(x), deterministic)
        x = x + DropPath(cfg.droppath, name="dp2")(ls("ls2") * h, deterministic)
        return x


class JumboBlock(nn.Module):
    """The fork's signature block (parity: ``JumboLayer``,
    ``/root/reference/src/modeling.py:169-206``).

    Attention over the full sequence; then patch tokens get the usual MLP
    while the ``num_cls_tokens`` CLS tokens are concatenated to one
    (B, k·dim) vector, LayerNorm'd, and passed through a **shared** wide MLP
    (``jumbo_mlp``, owned by the encoder and passed in as an attribute).

    Quirk preserved on purpose (training dynamics depend on it): the CLS
    residual base is the *post-norm* vector — ``cc = ln3(concat);
    cc = cc + dp(ls3 · jumbo_mlp(cc))`` — not the pre-norm input.

    ``packed`` (positional, a traced pytree — stays past the remat
    wrapper's static ``deterministic`` slot) switches the block to
    token-packed layout: attention takes the block-diagonal segment mask,
    and the CLS tokens live at each segment's ``cls_index`` offsets
    instead of the sequence head. The per-segment math is identical —
    gather the k CLS tokens, same ln3/jumbo_mlp/residual, scatter back —
    so a packed segment computes exactly what its unpacked batch row
    would (the parity tests' contract).
    """

    cfg: JumboViTConfig
    jumbo_mlp: nn.Module

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        deterministic: bool = True,
        packed: dict | None = None,
    ) -> jax.Array:
        cfg = self.cfg
        k = cfg.num_cls_tokens
        ls = (
            lambda name, d: self.param(name, init.constant(1e-4), (d,))
            if cfg.layerscale
            else 1.0
        )

        h = Attention(cfg, name="attn")(
            nn.LayerNorm(dtype=cfg.compute_dtype, name="ln1")(x),
            deterministic,
            mask=None if packed is None else packed["mask"],
        )
        x = x + DropPath(cfg.droppath, name="dp1")(
            ls("ls1", cfg.dim) * h, deterministic
        )

        if packed is None:
            cls, patches = x[:, :k, :], x[:, k:, :]
            bs = cls.shape[0]

            cc = nn.LayerNorm(dtype=cfg.compute_dtype, name="ln3")(
                cls.reshape(bs, k * cfg.dim)
            )
            cc = cc + DropPath(cfg.droppath, name="dp3")(
                ls("ls3", k * cfg.dim) * self.jumbo_mlp(cc, deterministic),
                deterministic,
            )

            h = Mlp(
                cfg.dim, cfg.hidden_dim, cfg.dropout, cfg.compute_dtype, name="mlp"
            )(nn.LayerNorm(dtype=cfg.compute_dtype, name="ln2")(patches), deterministic)
            patches = patches + DropPath(cfg.droppath, name="dp2")(
                ls("ls2", cfg.dim) * h, deterministic
            )

            return jnp.concatenate([cc.reshape(bs, k, cfg.dim), patches], axis=1)

        # ---- packed layout: (rows, budget, dim) with per-segment CLS ----
        rows, seq, dim = x.shape
        cls_index = packed["cls_index"]  # (rows, max_segments, k)
        smax = cls_index.shape[1]
        # gather each slot's k CLS tokens -> the same (k·dim) concat the
        # unpacked branch builds from the sequence head
        g = jnp.take_along_axis(
            x, cls_index.reshape(rows, smax * k)[..., None], axis=1
        ).reshape(rows, smax, k * cfg.dim)
        cc = nn.LayerNorm(dtype=cfg.compute_dtype, name="ln3")(g)
        cc = cc + DropPath(cfg.droppath, name="dp3")(
            ls("ls3", k * cfg.dim) * self.jumbo_mlp(cc, deterministic),
            deterministic,
        )

        # patch MLP over ALL positions (it is per-token, so computing it on
        # CLS/pad positions is inert — CLS positions are overwritten below
        # and pads are never read through the masked attention)
        h = Mlp(
            cfg.dim, cfg.hidden_dim, cfg.dropout, cfg.compute_dtype, name="mlp"
        )(nn.LayerNorm(dtype=cfg.compute_dtype, name="ln2")(x), deterministic)
        patches = x + DropPath(cfg.droppath, name="dp2")(
            ls("ls2", cfg.dim) * h, deterministic
        )

        # scatter the updated CLS back to their in-row positions
        cc4 = cc.reshape(rows, smax, k, cfg.dim)
        slot0 = jnp.clip(packed["segment_ids"] - 1, 0)  # (rows, seq)
        pos0 = jnp.clip(packed["cls_pos"], 0)
        cls_vals = cc4[jnp.arange(rows)[:, None], slot0, pos0]
        return jnp.where(packed["cls_pos"][..., None] >= 0, cls_vals, patches)


class PatchEmbed(nn.Module):
    """Conv patchify + positional embedding added in 2-D grid shape.

    Parity: ``/root/reference/src/modeling.py:106-124``.
    """

    cfg: JumboViTConfig

    @nn.compact
    def __call__(self, images: jax.Array) -> jax.Array:
        cfg = self.cfg
        p = cfg.patch_size
        x = nn.Conv(
            cfg.dim,
            kernel_size=(p, p),
            strides=(p, p),
            padding="VALID",
            kernel_init=TRUNC_NORMAL,
            dtype=cfg.compute_dtype,
            name="proj",
        )(images)
        if cfg.posemb == "learnable":
            pos = self.param("pos_embed", TRUNC_NORMAL, (*cfg.grid, cfg.dim))
        else:
            pos = sincos2d_positional_embedding(*cfg.grid, cfg.dim)
        x = x + jnp.asarray(pos, x.dtype)
        return x.reshape(x.shape[0], -1, cfg.dim)


class ClassifierHead(nn.Module):
    """Linear head over concatenated CLS tokens, with an optional BatchNorm
    (linear-probe mode). Parity: ``LinearCLS``,
    ``/root/reference/src/modeling.py:209-219``.

    Under jit+GSPMD the batch axis is globally sharded, so BatchNorm's batch
    statistics are already computed over the *global* batch — no
    ``axis_name`` plumbing needed (the reference needed
    ``axis_name="batch"`` because of pmap).
    """

    labels: int
    batch_norm: bool

    @nn.compact
    def __call__(self, x: jax.Array, deterministic: bool = True) -> jax.Array:
        if self.batch_norm:
            x = nn.BatchNorm(use_running_average=deterministic, name="bn")(x)
        return nn.Dense(
            self.labels, kernel_init=TRUNC_NORMAL, name="fc"
        )(x)
