"""Model configuration dataclasses and presets.

Replaces the reference's ``ViTBase``/``MAEDecoderBase`` dataclass-mixin
pattern (``/root/reference/src/modeling.py:35-104``) with plain frozen config
objects passed to modules as a single attribute — hashable, serializable, and
independent of module inheritance.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Literal

import jax.numpy as jnp

Posemb = Literal["learnable", "sincos2d"]
Pooling = Literal["cls", "gap"]
AttnImpl = Literal["einsum", "flash", "ring", "auto"]
MaskModeT = Literal["shared", "per_sample"]
GatherImplT = Literal["take", "onehot"]
# rematerialization policy under grad_ckpt=True:
#   "none"          — save nothing, recompute the whole block (max memory win)
#   "dots"          — save every matmul output, recompute elementwise only
#   "dots_no_batch" — save param matmuls but not attention score matmuls
RematPolicy = Literal["none", "dots", "dots_no_batch"]


def checkpoint_policy(name: str):
    """Map a RematPolicy name to the jax.checkpoint policy callable (None =
    nothing saveable, jax.checkpoint's default)."""
    import jax

    if name == "none":
        return None
    if name == "dots":
        return jax.checkpoint_policies.dots_saveable
    if name == "dots_no_batch":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    raise ValueError(f"unknown remat policy {name!r}")


def maybe_remat(block_cls, cfg):
    """Wrap a transformer block class with ``nn.remat`` per the config's
    ``grad_ckpt``/``remat_policy`` knobs (the one place the remat wiring
    lives; used by both the encoder and the MAE decoder). The deterministic
    flag (arg 2) stays static."""
    import flax.linen as nn

    if not cfg.grad_ckpt:
        return block_cls
    return nn.remat(
        block_cls,
        static_argnums=(2,),
        policy=checkpoint_policy(cfg.remat_policy),
    )


@dataclass(frozen=True)
class JumboViTConfig:
    """Encoder configuration.

    Capability parity with ``ViTBase`` (``/root/reference/src/modeling.py:35``)
    plus TPU-first knobs: compute ``dtype`` (bfloat16 by default — MXU-native),
    ``attn_impl`` selection, and a per-sample masking mode option.
    """

    layers: int = 12
    dim: int = 768
    heads: int = 12
    num_cls_tokens: int = 3
    labels: int | None = 1000
    layerscale: bool = False

    patch_size: int = 16
    image_size: int = 224
    posemb: Posemb = "learnable"
    pooling: Pooling = "cls"

    dropout: float = 0.0
    droppath: float = 0.0
    grad_ckpt: bool = False
    remat_policy: RematPolicy = "none"

    # MAE
    mask_ratio: float | None = None
    mask_mode: MaskModeT = "shared"

    # classification-head behavior
    linear_probing: bool = False
    batch_norm: bool = False

    # TPU-first knobs
    dtype: str = "bfloat16"  # compute dtype; params always float32
    attn_impl: AttnImpl = "auto"
    # attn_impl="ring" only: per-hop lowering — "einsum" (O((S/n)²) local
    # scores) or "flash" (Pallas kernels + differentiable lse merge,
    # O(S/n) score memory; falls back to einsum off-TPU)
    ring_inner: str = "einsum"
    # masking shuffle/unshuffle lowering: "take" (XLA dynamic gather) or
    # "onehot" (0/1 MXU matmul, concat-free unshuffle) — bit-identical
    # numerics, pick by profile (ops/masking.py validates the value)
    gather_impl: GatherImplT = "take"

    def __post_init__(self):
        if self.heads <= 0 or self.dim % self.heads:
            # head_dim floors silently otherwise: heads=7 at dim=768 would
            # train a 763-wide attention with no warning (bench.py's
            # _parse_dec_heads already rejects this; the recipe/--set
            # surface lands here)
            raise ValueError(
                f"dim ({self.dim}) must be divisible by heads ({self.heads})"
            )

    @property
    def head_dim(self) -> int:
        return self.dim // self.heads

    @property
    def hidden_dim(self) -> int:
        return 4 * self.dim

    @property
    def grid(self) -> tuple[int, int]:
        return (self.image_size // self.patch_size,) * 2

    @property
    def num_patches(self) -> int:
        g = self.grid
        return g[0] * g[1]

    @property
    def keep_len(self) -> int:
        if self.mask_ratio is None:
            raise ValueError("keep_len undefined without mask_ratio")
        return int(self.num_patches * (1.0 - self.mask_ratio))

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)

    def replace(self, **kw) -> "JumboViTConfig":
        return dataclasses.replace(self, **kw)


@dataclass(frozen=True)
class DecoderConfig:
    """MAE decoder configuration (parity:
    ``MAEDecoderBase``, ``/root/reference/src/modeling.py:73-104``).
    Decoder positional embeddings are always fixed sincos2d — the reference's
    ``dec_posemb`` flag was parsed but ignored (defect ledger #3), so it does
    not exist here."""

    layers: int = 8
    dim: int = 512
    heads: int = 16
    layerscale: bool = False

    dropout: float = 0.0
    droppath: float = 0.0
    grad_ckpt: bool = False
    remat_policy: RematPolicy = "none"

    dtype: str = "bfloat16"
    attn_impl: AttnImpl = "auto"
    ring_inner: str = "einsum"

    def __post_init__(self):
        if self.heads <= 0 or self.dim % self.heads:
            raise ValueError(
                f"decoder dim ({self.dim}) must be divisible by heads "
                f"({self.heads})"
            )

    @property
    def head_dim(self) -> int:
        return self.dim // self.heads

    @property
    def hidden_dim(self) -> int:
        return 4 * self.dim

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)

    def replace(self, **kw) -> "DecoderConfig":
        return dataclasses.replace(self, **kw)


# Named presets matching the reference recipe matrix (config/*.sh) plus the
# BASELINE.json north-star ViT-H/14.
PRESETS: dict[str, dict] = {
    "vit_t16": dict(layers=2, dim=64, heads=4),  # test-sized
    "vit_s16": dict(layers=12, dim=384, heads=6),
    "vit_b16": dict(layers=12, dim=768, heads=12),
    "vit_l16": dict(layers=24, dim=1024, heads=16),
    "vit_h14": dict(layers=32, dim=1280, heads=16, patch_size=14),
}


def preset(name: str, **overrides) -> JumboViTConfig:
    if name not in PRESETS:
        raise ValueError(f"unknown preset {name!r}; have {sorted(PRESETS)}")
    return JumboViTConfig(**{**PRESETS[name], **overrides})
