"""Compat shim: FLOP counting / MFU reporting moved to
``jumbo_mae_tpu_tpu.obs.mfu`` (the train loop exports MFU through the
telemetry registry, so the math lives in the subsystem that publishes it)."""

from jumbo_mae_tpu_tpu.obs.mfu import (
    PEAK_TFLOPS,
    MfuReport,
    classify_flops_per_image,
    decoder_flops_per_image,
    detect_peak_tflops,
    encoder_flops_per_image,
    mfu_report,
    pretrain_flops_per_image,
)

__all__ = [
    "PEAK_TFLOPS",
    "MfuReport",
    "classify_flops_per_image",
    "decoder_flops_per_image",
    "detect_peak_tflops",
    "encoder_flops_per_image",
    "mfu_report",
    "pretrain_flops_per_image",
]
