"""Run logging: wandb when available, JSONL always, process-0 gated.

The reference logged through wandb only, on process 0 only
(``/root/reference/src/main_pretrain.py:56-57,67-74``); in this environment
wandb may not exist, so the logger degrades to a local JSONL metrics file
with the same record shape — nothing in the train loop branches on which
backend is live.
"""

from __future__ import annotations

import json
import time
from pathlib import Path


class MetricLogger:
    def __init__(
        self,
        output_dir: str | Path | None,
        *,
        name: str = "run",
        config: dict | None = None,
        enabled: bool = True,
        use_wandb: bool = True,
        wandb_project: str = "",
        wandb_entity: str = "",
        wandb_tags: tuple[str, ...] = (),
        wandb_id: str = "",
    ):
        self.enabled = enabled
        self._file = None
        self._wandb = None
        if not enabled:
            return
        if output_dir is not None:
            path = Path(output_dir)
            path.mkdir(parents=True, exist_ok=True)
            self._file = open(path / f"{name}-metrics.jsonl", "a", buffering=1)
            if config:
                (path / f"{name}-config.json").write_text(
                    json.dumps(config, indent=2, default=str)
                )
        if use_wandb:
            kwargs: dict = {"name": name, "config": config or {}}
            if wandb_project:
                kwargs["project"] = wandb_project
            if wandb_entity:
                kwargs["entity"] = wandb_entity
            if wandb_tags:
                kwargs["tags"] = list(wandb_tags)
            if wandb_id:
                # stable id → wandb resumes the run after a restart
                kwargs["id"] = wandb_id
                kwargs["resume"] = "allow"
            try:
                import wandb

                self._wandb = wandb.init(**kwargs)
            except ImportError:
                self._wandb = None  # JSONL-only environments are expected
            except Exception as e:  # noqa: BLE001
                print(f"[logging] wandb.init failed ({e}); JSONL only")
                self._wandb = None

    def log(self, metrics: dict, step: int | None = None):
        if not self.enabled:
            return
        record = {"_time": time.time(), **({"step": step} if step is not None else {}), **metrics}
        if self._file is not None:
            self._file.write(json.dumps(record, default=float) + "\n")
        if self._wandb is not None:  # pragma: no cover
            self._wandb.log(metrics, step=step)

    def close(self):
        if self._file is not None:
            self._file.close()
            self._file = None
        if self._wandb is not None:  # pragma: no cover
            self._wandb.finish()
            self._wandb = None


class StepTimer:
    """Wall-clock step timing with warmup exclusion; feeds MFU reporting."""

    def __init__(self, warmup_steps: int = 2):
        self.warmup_steps = warmup_steps
        self._seen = 0
        self._t0: float | None = None
        self._timed = 0

    def tick(self):
        """Call once per completed (blocked-on) step."""
        self._seen += 1
        if self._seen == self.warmup_steps:
            self._t0 = time.perf_counter()
            self._timed = 0
        elif self._seen > self.warmup_steps:
            self._timed += 1

    @property
    def steps_per_sec(self) -> float | None:
        if self._t0 is None or self._timed == 0:
            return None
        return self._timed / (time.perf_counter() - self._t0)
