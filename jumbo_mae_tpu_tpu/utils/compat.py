"""Version-portability shims for the jax/optax surface this framework uses.

The code targets the current jax API (``jax.shard_map`` with ``check_vma``,
``jax.sharding.set_mesh`` / ``get_abstract_mesh``, ``optax.safe_increment``);
deployment containers routinely ship one major step behind (jax 0.4.x /
older optax), where the same capabilities live under older names
(``jax.experimental.shard_map`` with ``check_rep``, the ``Mesh`` context
manager, ``optax.safe_int32_increment``). Every call site routes through
here so the framework runs unchanged on both — the round-6 seed triage
traced a third of the tier-1 failures to exactly these renames.
"""

from __future__ import annotations

import jax


def ambient_mesh():
    """The active ambient mesh: ``jax.sharding.get_abstract_mesh()`` where
    it exists, the thread-resource physical mesh on 0.4.x. Both expose the
    ``.shape`` mapping the callers use; both return an empty-shape mesh
    when none is active."""
    if hasattr(jax.sharding, "get_abstract_mesh"):
        return jax.sharding.get_abstract_mesh()
    from jax._src import mesh as mesh_lib

    return mesh_lib.thread_resources.env.physical_mesh


def set_mesh(mesh):
    """Context manager activating ``mesh`` as the ambient mesh:
    ``jax.sharding.set_mesh`` where it exists; on 0.4.x a ``Mesh`` is its
    own context manager."""
    if hasattr(jax.sharding, "set_mesh"):
        return jax.sharding.set_mesh(mesh)
    return mesh


def shard_map(fn, *, mesh=None, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` where it exists; ``jax.experimental.shard_map``
    on 0.4.x (``check_vma`` maps onto its ``check_rep``, and the ambient
    mesh is resolved explicitly because the experimental version requires
    one)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    if mesh is None:
        mesh = ambient_mesh()
    return _shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def axis_size(axis_name) -> int:
    """Static size of a bound mesh axis: ``jax.lax.axis_size`` where it
    exists, the core axis frame on 0.4.x."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    from jax.core import axis_frame

    frame = axis_frame(axis_name)
    # 0.4.x returns the size directly; earlier still, a frame object
    return getattr(frame, "size", frame)


def ensure_partitionable_rng():
    """Make random draws independent of the output sharding. Newer jax
    defaults ``jax_threefry_partitionable=True``; 0.4.x defaults False,
    where a ``jit(init, out_shardings=...)`` program can generate DIFFERENT
    values for a sharded array than the unsharded program would (observed:
    one fsdp-sharded kernel at init drew a wholly different tensor,
    breaking sharded-equals-single-device). The partitionable lowering
    computes the same function under every layout — flip it on once."""
    try:
        if not jax.config.jax_threefry_partitionable:
            jax.config.update("jax_threefry_partitionable", True)
    except AttributeError:  # the flag is gone once new jax drops it
        pass


def safe_increment(count):
    """``optax.safe_increment``, née ``safe_int32_increment``."""
    import optax

    fn = getattr(optax, "safe_increment", None)
    if fn is None:
        fn = optax.safe_int32_increment
    return fn(count)
