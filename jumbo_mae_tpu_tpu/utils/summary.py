"""Parameter-count summary printed at startup.

Parity: the reference printed ``module.tabulate(...)`` before training — a
human-checked parameter/shape table that was its main pre-flight QA
(``/root/reference/src/pretraining.py:214``, SURVEY §4). Flax's tabulate
re-runs module init abstractly; here the state is already materialized
(sharded init), so the summary walks the real param tree instead — no
second trace, and the numbers describe exactly what will train.
"""

from __future__ import annotations

import numpy as np


def _count(tree) -> tuple[int, int]:
    """(param count, bytes) of a pytree of arrays."""
    import jax

    leaves = jax.tree_util.tree_leaves(tree)
    n = sum(int(np.prod(x.shape)) for x in leaves)
    b = sum(int(np.prod(x.shape)) * x.dtype.itemsize for x in leaves)
    return n, b


def _fmt_count(n: int) -> str:
    for unit, div in (("B", 1e9), ("M", 1e6), ("K", 1e3)):
        if n >= div:
            return f"{n / div:,.2f}{unit}"
    return str(n)


def param_summary(params, *, depth: int = 2) -> str:
    """Render a per-subtree parameter table (down to ``depth`` path levels)
    plus totals, e.g.::

        encoder/block_0            12.60M
        ...
        total                     331.44M params (1.23 GiB)
    """
    from flax import serialization

    sd = serialization.to_state_dict(params)
    rows: list[tuple[str, int]] = []

    def walk(node, path):
        if not isinstance(node, dict) or len(path) >= depth:
            rows.append(("/".join(path), _count(node)[0]))
            return
        for key in node:
            walk(node[key], path + [key])

    walk(sd, [])
    total_n, total_b = _count(sd)
    width = max((len(name) for name, _ in rows), default=10) + 2
    lines = [f"{name:<{width}} {_fmt_count(n):>10}" for name, n in rows]
    lines.append(
        f"{'total':<{width}} {_fmt_count(total_n):>10} params "
        f"({total_b / 2**30:.2f} GiB)"
    )
    return "\n".join(lines)
