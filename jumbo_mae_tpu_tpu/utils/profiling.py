"""Compat shim: profiler capture moved to ``jumbo_mae_tpu_tpu.obs.trace``,
which adds host-side spans and chrome-trace export alongside the XLA
device-trace helpers that lived here."""

from jumbo_mae_tpu_tpu.obs.trace import annotate, trace

__all__ = ["annotate", "trace"]
