"""Profiler capture helpers (the reference had none — SURVEY §5).

``trace(dir)`` wraps ``jax.profiler`` trace capture so any train loop can be
profiled with one flag; traces open in XProf/TensorBoard and show the MXU
utilization and HBM traffic the Pallas work is judged against.
"""

from __future__ import annotations

from contextlib import contextmanager


@contextmanager
def trace(log_dir: str | None):
    """Capture a device trace into ``log_dir`` (no-op when None)."""
    if not log_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextmanager
def annotate(name: str):
    """Named region in the trace timeline (``jax.profiler.TraceAnnotation``)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
