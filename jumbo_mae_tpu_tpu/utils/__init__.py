from jumbo_mae_tpu_tpu.utils.logging import MetricLogger, StepTimer
from jumbo_mae_tpu_tpu.utils.meters import AverageMeter
from jumbo_mae_tpu_tpu.utils.mfu import (
    PEAK_TFLOPS,
    classify_flops_per_image,
    detect_peak_tflops,
    encoder_flops_per_image,
    mfu_report,
    pretrain_flops_per_image,
)
from jumbo_mae_tpu_tpu.utils.profiling import annotate, trace
from jumbo_mae_tpu_tpu.utils.summary import param_summary

__all__ = [
    "AverageMeter",
    "MetricLogger",
    "PEAK_TFLOPS",
    "StepTimer",
    "annotate",
    "classify_flops_per_image",
    "detect_peak_tflops",
    "encoder_flops_per_image",
    "mfu_report",
    "param_summary",
    "pretrain_flops_per_image",
    "trace",
]
