"""Compat shim: ``AverageMeter`` moved into the telemetry subsystem
(``jumbo_mae_tpu_tpu.obs.metrics``) so the log-window aggregation lives next
to the registry the train loop exports through."""

from jumbo_mae_tpu_tpu.obs.metrics import AverageMeter

__all__ = ["AverageMeter"]
