"""Host-side metric aggregation.

Equivalent of the reference's ``AverageMeter`` (``/root/reference/src/utils.py:36-52``):
buffer per-step metric dicts, then emit prefixed means — except keys marked
``use_latest`` (the live learning rate) which report their last value.
"""

from __future__ import annotations

import numpy as np


class AverageMeter:
    def __init__(self, *, use_latest: tuple[str, ...] = ("learning_rate",)):
        self.use_latest = set(use_latest)
        self.buffer: dict[str, list[float]] = {}

    def update(self, metrics: dict):
        for k, v in metrics.items():
            self.buffer.setdefault(k, []).append(float(np.asarray(v)))

    def summary(self, prefix: str = "") -> dict[str, float]:
        out = {}
        for k, vals in self.buffer.items():
            if not vals:
                continue
            value = vals[-1] if k in self.use_latest else float(np.mean(vals))
            out[prefix + k] = value
        self.buffer = {}
        return out
