"""Environment construction for CPU-only subprocesses.

One copy of the round-3 lesson: a wedged remote-accelerator tunnel can
block ANY process that lets the accelerator PJRT plugin register and then
touches ``jax.devices()`` — ``JAX_PLATFORMS=cpu`` in the env is NOT enough
on its own, because the plugin's backend hook intercepts device lookup
regardless of platform. CPU-only children (data workers, test
subprocesses, dryruns) must therefore strip the registration variable
entirely. ``__graft_entry__`` keeps a private copy of this logic on
purpose — it is a driver-facing standalone script that must not depend on
package imports in the calling process. (``bench.py`` is different: its
probe subprocess deliberately keeps the CURRENT env, because it is asking
whether the real accelerator answers.)
"""

from __future__ import annotations

import os


def host_fingerprint() -> str:
    """Short stable hash of this host's CPU identity, for keying the
    persistent XLA compile cache per machine.

    XLA:CPU AOT cache entries embed the compiling machine's CPU features;
    loading another machine's entries logs ``cpu_aot_loader.cc ... Machine
    type used for XLA:CPU compilation doesn't match`` per program and slows
    device-thread startup — which in round 4 pushed an 8-thread collective
    rendezvous past its 40 s abort window on a 1-core host. Keying the
    cache directory by this hash makes cross-machine reuse impossible.
    (``__graft_entry__._host_fingerprint`` is a deliberate private copy —
    that script must not import the package in the calling process.)
    """
    import hashlib
    import platform

    parts = [platform.machine()]
    wanted = {"model name", "flags", "Features", "CPU implementer"}
    seen: set[str] = set()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                key = line.split(":", 1)[0].strip()
                if key in wanted and key not in seen:
                    seen.add(key)
                    parts.append(line.strip())
                if seen == wanted:
                    break
    except OSError:
        pass
    return hashlib.sha256("\n".join(parts).encode()).hexdigest()[:12]


def host_cache_dir(repo_root: str | os.PathLike) -> str:
    """Host-keyed persistent-compile-cache path under ``repo_root``."""
    return os.path.join(
        str(repo_root), ".jax_cache", f"host-{host_fingerprint()}"
    )


def cpu_subprocess_env(
    n_devices: int | None = None,
    *,
    compile_cache: str | os.PathLike | None = None,
    base: dict | None = None,
) -> dict:
    """Env for a child process that must run on the CPU backend only.

    - strips the remote-accelerator PJRT registration (see module doc);
    - forces ``JAX_PLATFORMS=cpu``;
    - with ``n_devices``, pins ``--xla_force_host_platform_device_count``
      (replacing any inherited value);
    - with ``compile_cache``, wires the persistent compile cache with the
      same knobs as ``tests/conftest.py``.
    """
    env = dict(os.environ if base is None else base)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    if n_devices:
        flags = [
            f
            for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        flags.append(f"--xla_force_host_platform_device_count={n_devices}")
        env["XLA_FLAGS"] = " ".join(flags).strip()
    if compile_cache:
        env.setdefault("JAX_COMPILATION_CACHE_DIR", str(compile_cache))
        env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.25")
        env.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
    return env
