"""Environment construction for CPU-only subprocesses.

One copy of the round-3 lesson: a wedged remote-accelerator tunnel can
block ANY process that lets the accelerator PJRT plugin register and then
touches ``jax.devices()`` — ``JAX_PLATFORMS=cpu`` in the env is NOT enough
on its own, because the plugin's backend hook intercepts device lookup
regardless of platform. CPU-only children (data workers, test
subprocesses, dryruns) must therefore strip the registration variable
entirely. ``__graft_entry__`` keeps a private copy of this logic on
purpose — it is a driver-facing standalone script that must not depend on
package imports in the calling process. (``bench.py`` is different: its
probe subprocess deliberately keeps the CURRENT env, because it is asking
whether the real accelerator answers.)
"""

from __future__ import annotations

import os


def cpu_subprocess_env(
    n_devices: int | None = None,
    *,
    compile_cache: str | os.PathLike | None = None,
    base: dict | None = None,
) -> dict:
    """Env for a child process that must run on the CPU backend only.

    - strips the remote-accelerator PJRT registration (see module doc);
    - forces ``JAX_PLATFORMS=cpu``;
    - with ``n_devices``, pins ``--xla_force_host_platform_device_count``
      (replacing any inherited value);
    - with ``compile_cache``, wires the persistent compile cache with the
      same knobs as ``tests/conftest.py``.
    """
    env = dict(os.environ if base is None else base)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    if n_devices:
        flags = [
            f
            for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        flags.append(f"--xla_force_host_platform_device_count={n_devices}")
        env["XLA_FLAGS"] = " ".join(flags).strip()
    if compile_cache:
        env.setdefault("JAX_COMPILATION_CACHE_DIR", str(compile_cache))
        env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.25")
        env.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
    return env
