"""Environment construction for CPU-only subprocesses.

One copy of the round-3 lesson: a wedged remote-accelerator tunnel can
block ANY process that lets the accelerator PJRT plugin register and then
touches ``jax.devices()`` — ``JAX_PLATFORMS=cpu`` in the env is NOT enough
on its own, because the plugin's backend hook intercepts device lookup
regardless of platform. CPU-only children (data workers, test
subprocesses, dryruns) must therefore strip the registration variable
entirely. ``__graft_entry__`` keeps a private copy of this logic on
purpose — it is a driver-facing standalone script that must not depend on
package imports in the calling process. (``bench.py`` is different: its
probe subprocess deliberately keeps the CURRENT env, because it is asking
whether the real accelerator answers.)
"""

from __future__ import annotations

import atexit
import os
from pathlib import Path


def host_fingerprint() -> str:
    """Short stable hash of this host's CPU identity, for keying the
    persistent XLA compile cache per machine.

    XLA:CPU AOT cache entries embed the compiling machine's CPU features;
    loading another machine's entries logs ``cpu_aot_loader.cc ... Machine
    type used for XLA:CPU compilation doesn't match`` per program and slows
    device-thread startup — which in round 4 pushed an 8-thread collective
    rendezvous past its 40 s abort window on a 1-core host. Keying the
    cache directory by this hash makes cross-machine reuse impossible.
    (``__graft_entry__._host_fingerprint`` is a deliberate private copy —
    that script must not import the package in the calling process.)
    """
    import hashlib
    import platform

    parts = [platform.machine()]
    wanted = {"model name", "flags", "Features", "CPU implementer"}
    seen: set[str] = set()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                key = line.split(":", 1)[0].strip()
                if key in wanted and key not in seen:
                    seen.add(key)
                    parts.append(line.strip())
                if seen == wanted:
                    break
    except OSError:
        pass
    return hashlib.sha256("\n".join(parts).encode()).hexdigest()[:12]


def host_cache_dir(repo_root: str | os.PathLike) -> str:
    """Host-keyed persistent-compile-cache path under ``repo_root``."""
    return os.path.join(
        str(repo_root), ".jax_cache", f"host-{host_fingerprint()}"
    )


def default_warmcache_dir() -> str | None:
    """Default root for the serving warm-start executable cache
    (``infer/warmcache.py``) — the engine's ``warm_cache=True`` resolves
    through here. Resolution order:

    - ``JUMBO_WARMCACHE=0`` disables the default entirely (the test suite
      sets this: compile-count assertions need every compile to actually
      happen). An *explicit* ``warm_cache=<path>`` on the engine ignores
      this kill switch.
    - ``JUMBO_WARMCACHE_DIR`` overrides the location (CI points it at a
      scratch dir shared between the cold and warm probe processes).
    - otherwise ``~/.cache/jumbo_mae_tpu/warmcache/host-<fingerprint>`` —
      host-keyed for the same reason as :func:`host_cache_dir`: XLA:CPU
      executables embed the compiling machine's CPU features, so entries
      must never migrate between machines.
    """
    if os.environ.get("JUMBO_WARMCACHE", "1") == "0":
        return None
    override = os.environ.get("JUMBO_WARMCACHE_DIR")
    if override:
        return override
    return os.path.join(
        os.path.expanduser("~"),
        ".cache",
        "jumbo_mae_tpu",
        "warmcache",
        f"host-{host_fingerprint()}",
    )


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


def claim_compile_cache(cache_dir: str | os.PathLike) -> str:
    """Crash-safe claim of a persistent-compile-cache directory.

    jax's on-disk cache writes entries non-atomically (``LRUCache.put`` is a
    plain ``write_bytes``) and never overwrites an existing key — so a
    process killed mid-write (the tier-1 gate's own ``timeout -k``, a
    preempted pod) leaves a *permanently* truncated serialized executable,
    and XLA:CPU aborts the whole process deserializing it on every later
    run. Protocol: each process using the cache drops a pid sentinel in the
    directory and removes it on clean exit; a sentinel whose pid is dead at
    claim time means an unclean shutdown happened — every cache entry is
    purged (recompiling is cheap and bounded; a poisoned entry is a
    permanent crash). Returns the claimed directory path as a string."""
    path = Path(cache_dir)
    path.mkdir(parents=True, exist_ok=True)
    unclean = False
    for f in path.glob("_inuse-*"):
        try:
            pid = int(f.name.split("-", 1)[1])
        except ValueError:
            pid = -1
        if pid > 0 and _pid_alive(pid):
            continue  # a live process is using the cache; leave its claim
        unclean = True
        f.unlink(missing_ok=True)
    if unclean:
        for pattern in ("*-cache", "*-atime"):
            for f in path.glob(pattern):
                f.unlink(missing_ok=True)
    own = path / f"_inuse-{os.getpid()}"
    own.write_text("")

    def release(p=own):
        p.unlink(missing_ok=True)

    atexit.register(release)
    return str(path)


def enable_compile_cache(cache_dir: str | os.PathLike | None = None) -> str | None:
    """Wire jax's persistent compile cache for THIS process (the in-process
    counterpart of ``cpu_subprocess_env(compile_cache=...)``), claimed
    crash-safe via :func:`claim_compile_cache`. ``cache_dir`` defaults to
    ``$JAX_COMPILATION_CACHE_DIR``; returns None (no-op) when neither is
    set. Used by the inference engine and benches so AOT-lowered serving
    programs warm-start across processes."""
    target = cache_dir or os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if not target:
        return None
    import jax

    claimed = claim_compile_cache(target)
    jax.config.update("jax_compilation_cache_dir", claimed)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.25)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    return claimed


def cpu_subprocess_env(
    n_devices: int | None = None,
    *,
    compile_cache: str | os.PathLike | None = None,
    base: dict | None = None,
) -> dict:
    """Env for a child process that must run on the CPU backend only.

    - strips the remote-accelerator PJRT registration (see module doc);
    - forces ``JAX_PLATFORMS=cpu``;
    - with ``n_devices``, pins ``--xla_force_host_platform_device_count``
      (replacing any inherited value);
    - with ``compile_cache``, wires the persistent compile cache with the
      same knobs as ``tests/conftest.py``.
    """
    env = dict(os.environ if base is None else base)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    if n_devices:
        flags = [
            f
            for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        flags.append(f"--xla_force_host_platform_device_count={n_devices}")
        env["XLA_FLAGS"] = " ".join(flags).strip()
    if compile_cache:
        env.setdefault("JAX_COMPILATION_CACHE_DIR", str(compile_cache))
        env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.25")
        env.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
    return env
