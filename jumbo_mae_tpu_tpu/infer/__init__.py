from jumbo_mae_tpu_tpu.infer.batching import (
    DeadlineExceededError,
    MicroBatcher,
    QueueFullError,
    ShutdownError,
)
from jumbo_mae_tpu_tpu.infer.engine import (
    InferenceEngine,
    OversizedBatchError,
    bucket_for,
)
from jumbo_mae_tpu_tpu.infer.quant import (
    QuantizedTensor,
    parity_report,
    quantize_params,
)
from jumbo_mae_tpu_tpu.infer.replicaset import (
    PoolUnhealthyError,
    ReplicaSet,
    RetriesExhaustedError,
    WeightSwapController,
)
from jumbo_mae_tpu_tpu.infer.warmcache import WarmCache

__all__ = [
    "DeadlineExceededError",
    "InferenceEngine",
    "MicroBatcher",
    "OversizedBatchError",
    "PoolUnhealthyError",
    "QuantizedTensor",
    "QueueFullError",
    "ReplicaSet",
    "RetriesExhaustedError",
    "ShutdownError",
    "WarmCache",
    "WeightSwapController",
    "bucket_for",
    "parity_report",
    "quantize_params",
]
