from jumbo_mae_tpu_tpu.infer.batching import (
    DeadlineExceededError,
    MicroBatcher,
    QueueFullError,
    ShutdownError,
)
from jumbo_mae_tpu_tpu.infer.bucketing import (
    OversizedBatchError,
    bucket_for,
    floor_bucket,
    pow2_rungs,
)
from jumbo_mae_tpu_tpu.infer.engine import (
    InferenceEngine,
    ResolutionMismatchError,
)
from jumbo_mae_tpu_tpu.infer.packing import PackPlan, SegmentPlacement, pack_ffd
from jumbo_mae_tpu_tpu.infer.quant import (
    QuantizedTensor,
    parity_report,
    quantize_params,
)
from jumbo_mae_tpu_tpu.infer.replicaset import (
    PoolUnhealthyError,
    ReplicaSet,
    RetriesExhaustedError,
    WeightSwapController,
)
from jumbo_mae_tpu_tpu.infer.warmcache import WarmCache

__all__ = [
    "DeadlineExceededError",
    "InferenceEngine",
    "MicroBatcher",
    "OversizedBatchError",
    "PackPlan",
    "PoolUnhealthyError",
    "QuantizedTensor",
    "QueueFullError",
    "ReplicaSet",
    "ResolutionMismatchError",
    "RetriesExhaustedError",
    "SegmentPlacement",
    "ShutdownError",
    "WarmCache",
    "WeightSwapController",
    "bucket_for",
    "floor_bucket",
    "pack_ffd",
    "parity_report",
    "pow2_rungs",
    "quantize_params",
]
