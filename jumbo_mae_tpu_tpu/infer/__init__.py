from jumbo_mae_tpu_tpu.infer.batching import (
    DeadlineExceededError,
    MicroBatcher,
    QueueFullError,
    ShutdownError,
)
from jumbo_mae_tpu_tpu.infer.engine import (
    InferenceEngine,
    OversizedBatchError,
    bucket_for,
)
from jumbo_mae_tpu_tpu.infer.quant import (
    QuantizedTensor,
    parity_report,
    quantize_params,
)
from jumbo_mae_tpu_tpu.infer.warmcache import WarmCache

__all__ = [
    "DeadlineExceededError",
    "InferenceEngine",
    "MicroBatcher",
    "OversizedBatchError",
    "QuantizedTensor",
    "QueueFullError",
    "ShutdownError",
    "WarmCache",
    "bucket_for",
    "parity_report",
    "quantize_params",
]
