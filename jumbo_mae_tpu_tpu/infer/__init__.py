from jumbo_mae_tpu_tpu.infer.batching import MicroBatcher
from jumbo_mae_tpu_tpu.infer.engine import InferenceEngine, bucket_for

__all__ = ["InferenceEngine", "MicroBatcher", "bucket_for"]
