from jumbo_mae_tpu_tpu.infer.batching import (
    DeadlineExceededError,
    MicroBatcher,
    QueueFullError,
    ShutdownError,
)
from jumbo_mae_tpu_tpu.infer.engine import InferenceEngine, bucket_for

__all__ = [
    "DeadlineExceededError",
    "InferenceEngine",
    "MicroBatcher",
    "QueueFullError",
    "ShutdownError",
    "bucket_for",
]
