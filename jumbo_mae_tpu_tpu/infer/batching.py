"""Micro-batching queue: coalesce concurrent requests into MXU-size batches.

Serving traffic arrives as single images; the MXU (and even XLA:CPU's
dispatch overhead) wants batches. The :class:`MicroBatcher` sits between
them: callers ``submit()`` one image and get a future; a collector thread
drains the queue into a batch, waiting at most ``max_delay_ms`` from the
first queued request (the latency the operator is willing to trade for
throughput) and never exceeding ``max_batch`` (the engine's largest
bucket), then runs the whole batch through ``run_fn`` once and routes row
``i`` of the result back to request ``i``.

Ordering is a contract, not an accident: the queue is FIFO, a batch is the
next ``k`` requests in arrival order, and results are assigned by row
index — so responses can never cross between concurrent callers (pinned by
``tests/test_infer_engine.py`` under a thread storm). A ``run_fn`` failure
fails exactly the requests in that batch; later batches proceed.

The batcher is engine-agnostic — ``run_fn`` is any callable mapping a
stacked ``(k, ...)`` array to an array (or dict of arrays) with leading
dimension ``k`` — so tests drive it with plain numpy and the serving path
drives it with :meth:`InferenceEngine.features` et al.

Overload is **bounded, not buffered**: with ``max_queue`` set, a submit
against a full queue fails fast with :class:`QueueFullError` (shed load —
an unbounded queue turns overload into unbounded latency for everyone);
a per-request ``deadline_ms`` expires queued requests with
:class:`DeadlineExceededError` at batch-admission time instead of letting a
stale request occupy a batch slot — and is enforced *again* at resolution:
a request whose deadline passed while it waited for co-travelers or inside
``run_fn`` is failed with the same error (access-log outcome ``late``,
``infer_requests_late_total``) rather than resolving ``ok`` after the
caller gave up; and :meth:`close` resolves every pending future with
:class:`ShutdownError` — a ``submit()`` caller can never block forever on
a batcher that is shutting down.

With a :class:`~jumbo_mae_tpu_tpu.obs.reqtrace.RequestTracer` attached,
every request carries a trace context from the first line of ``submit()``
to its terminal outcome (``ok|shed|deadline|late|aborted|shutdown``) — per-
request queue wait, coalescing wait, compute/fetch split, batch/bucket/pad
— into ``request_*`` histograms and the JSONL access log. The trace begins
*before* the ``serve.submit`` fault point so injected submit stalls show up
as queue wait, exactly where the caller felt them. A trace is always
finished before its future resolves, so an access-log row exists for every
resolved future. Without a tracer every hook site is a ``None`` check.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Callable

import numpy as np

from jumbo_mae_tpu_tpu.faults.inject import fault_point
from jumbo_mae_tpu_tpu.obs import lockwatch
from jumbo_mae_tpu_tpu.obs.metrics import RATIO_BUCKETS, get_registry

_STOP = object()


class QueueFullError(RuntimeError):
    """Raised by ``submit()`` when the request queue is at ``max_queue`` —
    the caller should shed/retry elsewhere, not wait."""


class OccupancyWindow:
    """Windowed + exponentially-weighted batch-occupancy estimate.

    ``stats()["batch_occupancy"]`` used to report only the *last* flush: one
    straggler batch of 1 after a train of full batches read as near-zero
    occupancy, and that single-batch jitter fed straight into the
    ``slo_batch_occupancy`` probe and any autoscaler keyed on it. This keeps
    an EWMA (``alpha`` per flush) plus a bounded window of recent flush
    sizes, so snapshots reflect the recent *regime*, not the last batch.
    Shared by :class:`MicroBatcher`, the replica pool, and the continuous
    scheduler (``serve/scheduler.py``).
    """

    def __init__(self, max_batch: int, *, alpha: float = 0.2, window: int = 64):
        self.max_batch = max(int(max_batch), 1)
        self.alpha = float(alpha)
        self._recent: deque = deque(maxlen=int(window))
        self._ewma: float | None = None
        self._last = 0
        self._n = 0
        self._lock = lockwatch.lock("batcher.occupancy")

    def observe(self, size: int) -> None:
        occ = min(size / self.max_batch, 1.0)
        with self._lock:
            self._recent.append(occ)
            self._ewma = (
                occ
                if self._ewma is None
                else self.alpha * occ + (1.0 - self.alpha) * self._ewma
            )
            self._last = size
            self._n += 1

    def snapshot(self) -> dict:
        with self._lock:
            recent = list(self._recent)
            ewma = self._ewma
            last = self._last
            n = self._n
        return {
            "ewma": round(ewma, 4) if ewma is not None else 0.0,
            "window_mean": (
                round(sum(recent) / len(recent), 4) if recent else 0.0
            ),
            "last": round(last / self.max_batch, 4),
            "batches": n,
        }


class DeadlineExceededError(TimeoutError):
    """Set on a request future whose ``deadline_ms`` passed — either before
    the collector could admit it to a batch (outcome ``deadline``) or after
    admission, during coalescing/compute (outcome ``late``)."""


class ShutdownError(RuntimeError):
    """Set on pending request futures when the batcher closes."""


class MicroBatcher:
    """Thread-safe request coalescer in front of a batched ``run_fn``.

    ``max_delay_ms`` bounds the extra latency any request can pay waiting
    for co-travelers; ``max_batch`` bounds the batch handed to ``run_fn``;
    ``max_queue`` bounds how many submitted-but-unflushed requests may
    exist before ``submit`` sheds with :class:`QueueFullError` (``None`` =
    unbounded, the pre-backpressure behavior). ``batch_sizes`` records
    every flushed batch's size (bench/test observability). Use as a
    context manager or call :meth:`close`.
    """

    def __init__(
        self,
        run_fn: Callable[..., Any],
        *,
        max_batch: int = 32,
        max_delay_ms: float = 5.0,
        max_queue: int | None = None,
        pass_meta: bool = False,
        registry=None,
        tracer=None,
        task: str = "",
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.run_fn = run_fn
        self.max_batch = int(max_batch)
        self.max_delay = float(max_delay_ms) / 1000.0
        self.max_queue = max_queue
        # pass_meta: run_fn becomes run_fn(batch, metas) where metas[i] is
        # request i's submit(meta=...) value — the per-request side channel
        # a router needs to serve heterogeneous requests (e.g. per-request
        # reconstruction seeds, or cached-feature reuse hints) from one
        # coalesced batch without smuggling state through globals
        self.pass_meta = bool(pass_meta)
        self.batch_sizes: list[int] = []
        self._occ = OccupancyWindow(self.max_batch)
        self._tracer = tracer  # obs.reqtrace.RequestTracer | None
        self.task = task
        # serving telemetry (obs/metrics.py): submit→result latency is THE
        # operator number — it includes coalescing wait, queueing behind
        # in-flight batches, and the forward itself
        reg = registry if registry is not None else get_registry()
        self._m_latency = reg.histogram(
            "infer_request_latency_seconds",
            "request latency: submit() to resolved future",
        )
        self._m_occupancy = reg.histogram(
            "infer_batch_occupancy",
            "flushed batch size / max_batch",
            buckets=RATIO_BUCKETS,
        )
        self._m_depth = reg.gauge(
            "infer_queue_depth", "queued requests sampled at batch collect"
        )
        self._m_requests = reg.counter(
            "infer_requests_total", "requests collected into batches"
        )
        self._m_batches = reg.counter(
            "infer_batches_total", "batches flushed through run_fn"
        )
        self._m_failed = reg.counter(
            "infer_requests_failed_total", "requests failed by a run_fn error"
        )
        self._m_shed = reg.counter(
            "infer_requests_shed_total",
            "submits rejected with QueueFullError (queue at max_queue)",
        )
        self._m_expired = reg.counter(
            "infer_deadline_exceeded_total",
            "requests expired past their deadline before batch admission",
        )
        self._m_late = reg.counter(
            "infer_requests_late_total",
            "requests whose deadline passed after admission (during "
            "coalescing or compute) — failed at resolution, not resolved ok",
        )
        self._m_aborted = reg.counter(
            "infer_requests_aborted_total",
            "pending requests failed by close()",
        )
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._depth = 0               # submitted, not yet popped by the loop
        self._depth_bytes = 0         # payload bytes of those queued images
        self._submitted = 0           # lifetime submit attempts (incl. sheds)
        self._shed_n = 0              # lifetime QueueFullError sheds
        self._depth_lock = lockwatch.lock("batcher.depth")
        self._closed = False
        self._drain = True
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="microbatcher"
        )
        self._thread.start()

    # ------------------------------------------------------------- client

    def submit(
        self,
        image: np.ndarray,
        *,
        deadline_ms: float | None = None,
        meta=None,
    ) -> Future:
        """Enqueue one request (a single image, no batch dim); returns a
        future resolving to that request's row of the batched result.

        Raises :class:`QueueFullError` immediately when ``max_queue``
        requests are already pending (shed, don't buffer). With
        ``deadline_ms``, a request still queued that long after submit is
        failed with :class:`DeadlineExceededError` instead of occupying a
        slot in a batch. ``meta`` rides along to ``run_fn`` when the
        batcher was built with ``pass_meta=True``. With a tracer attached
        the returned future carries the request id as ``fut.rid``.
        """
        # trace begins before the fault point: an injected submit stall is
        # queue wait the caller experienced, and must be visible as such
        tr = (
            self._tracer.begin(task=self.task, deadline_ms=deadline_ms)
            if self._tracer is not None
            else None
        )
        arr = np.asarray(image)
        try:
            fault_point("serve.submit")
            if self._closed:
                raise RuntimeError("MicroBatcher is closed")
            with self._depth_lock:
                self._submitted += 1
                if self.max_queue is not None and self._depth >= self.max_queue:
                    self._m_shed.inc()
                    self._shed_n += 1
                    raise QueueFullError(
                        f"request queue full ({self._depth}/{self.max_queue})"
                    )
                self._depth += 1
                self._depth_bytes += arr.nbytes
        except BaseException as e:  # noqa: BLE001 — classify, trace, re-raise
            if tr is not None:
                if isinstance(e, QueueFullError):
                    self._tracer.finish(tr, "shed")
                elif self._closed:
                    self._tracer.finish(tr, "shutdown")
                else:
                    self._tracer.finish(
                        tr, "aborted", error=f"{type(e).__name__}: {e}"
                    )
            raise
        fut: Future = Future()
        if tr is not None:
            fut.rid = tr.rid
        deadline = (
            None
            if deadline_ms is None
            else time.monotonic() + float(deadline_ms) / 1000.0
        )
        # submit stays latency-metric-free (counted batch-at-a-time in
        # _flush): at CPU-smoke request rates even one observe per submit
        # is measurable; the depth lock above is one uncontended acquire
        self._q.put(
            (arr, fut, time.perf_counter(), deadline, tr, meta)
        )
        return fut

    def __call__(self, image: np.ndarray, *, deadline_ms: float | None = None):
        """Blocking convenience: submit and wait."""
        return self.submit(image, deadline_ms=deadline_ms).result()

    def stats(self) -> dict:
        """Live serving snapshot — the autoscaler inputs ROADMAP §2 names,
        shaped for ``HealthState.probe()`` / ``SLOTracker`` probes."""
        with self._depth_lock:
            depth = self._depth
            depth_bytes = self._depth_bytes
            submitted = self._submitted
            shed = self._shed_n
        sizes = self.batch_sizes
        mean = sum(sizes) / len(sizes) if sizes else 0.0
        occ = self._occ.snapshot()
        return {
            "queue_depth": depth,
            "queue_bytes": max(depth_bytes, 0),
            # EWMA over recent flushes — NOT the last flush alone, which fed
            # single-batch jitter into slo_batch_occupancy and the autoscaler
            "batch_occupancy": occ["ewma"],
            "last_batch_occupancy": occ["last"],
            "window_batch_occupancy": occ["window_mean"],
            "mean_batch_occupancy": round(mean / self.max_batch, 4),
            "requests_submitted": submitted,
            "requests_shed": shed,
            "shed_rate": round(shed / submitted, 4) if submitted else 0.0,
        }

    def close(self, drain: bool = True):
        """Stop the collector and resolve EVERY pending request — no caller
        can be left blocked on a future forever.

        ``drain=True`` (default): shed — pending (unflushed) requests fail
        fast with :class:`ShutdownError` without running ``run_fn`` again.
        ``drain=False``: graceful — requests queued before close still
        flush through ``run_fn``; only late racers are failed.
        """
        if not self._closed:
            self._drain = drain
            self._closed = True
            self._q.put(_STOP)
            self._thread.join()
            # sweep whatever the loop never popped (items enqueued behind
            # the stop sentinel by racing submits). An empty queue is NOT
            # proof we're done: a racing submit increments _depth before its
            # put(), so depth > 0 means an item is in — or about to enter —
            # the queue; keep sweeping until depth drains (bounded, so a
            # depth-accounting bug can't hang close forever).
            sweep_deadline = time.monotonic() + 5.0
            while True:
                try:
                    item = self._q.get_nowait()
                except queue.Empty:
                    with self._depth_lock:
                        depth = self._depth
                    if depth <= 0 or time.monotonic() > sweep_deadline:
                        break
                    time.sleep(0.001)
                    continue
                if item is _STOP:
                    continue
                self._dec(item)
                self._abort(item)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ---------------------------------------------------------- collector

    def _dec(self, item=None):
        with self._depth_lock:
            self._depth -= 1
            if item is not None:
                self._depth_bytes -= item[0].nbytes

    def _abort(self, item):
        self._m_aborted.inc()
        if item[4] is not None:
            # trace finishes before the future resolves: a caller that saw
            # its future done can rely on the access-log row existing
            self._tracer.finish(item[4], "shutdown")
        item[1].set_exception(ShutdownError("MicroBatcher closed"))

    def _admit(self, item, batch) -> None:
        """One popped request: shutdown-shed / deadline-expire / admit."""
        self._dec(item)
        if self._closed and self._drain:
            self._abort(item)
            return
        dl = item[3]
        if dl is not None and time.monotonic() > dl:
            self._m_expired.inc()
            if item[4] is not None:
                self._tracer.finish(item[4], "deadline")
            item[1].set_exception(
                DeadlineExceededError("request deadline passed while queued")
            )
            return
        if item[4] is not None:
            self._tracer.admitted(item[4])
        batch.append(item)

    def _loop(self):
        while True:
            item = self._q.get()
            if item is _STOP:
                return
            batch: list = []
            self._admit(item, batch)
            if not batch:
                continue
            self._m_depth.set(self._q.qsize() + 1)
            deadline = time.monotonic() + self.max_delay
            stop = False
            while len(batch) < self.max_batch:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is _STOP:
                    stop = True
                    break
                self._admit(nxt, batch)
            self._flush(batch)
            if stop:
                return

    def _flush(self, batch):
        self.batch_sizes.append(len(batch))
        self._occ.observe(len(batch))
        self._m_batches.inc()
        self._m_requests.inc(len(batch))
        self._m_occupancy.observe(len(batch) / self.max_batch)
        traces = [it[4] for it in batch if it[4] is not None]
        if traces:
            self._tracer.flush_begin(traces)
        t_run = time.perf_counter()
        try:
            stacked = np.stack([it[0] for it in batch])
            out = (
                self.run_fn(stacked, [it[5] for it in batch])
                if self.pass_meta
                else self.run_fn(stacked)
            )
        except BaseException as e:  # noqa: BLE001 — route to the waiters
            self._m_failed.inc(len(batch))
            err = f"{type(e).__name__}: {e}"
            for it in batch:
                if it[4] is not None:
                    self._tracer.finish(it[4], "aborted", error=err)
                it[1].set_exception(e)
            return
        done = time.perf_counter()
        if traces:
            # on the collector thread, right after run_fn: the engine's
            # thread-local breakdown still belongs to this batch's predict
            self._tracer.flush_end(traces, run_s=done - t_run, batch=len(batch))
        # one lock hand-off for the whole batch's latencies, before the
        # waiters wake (their submit→result time must not include it)
        self._m_latency.observe_many([done - it[2] for it in batch])
        if isinstance(out, dict):
            rows = [{k: v[i] for k, v in out.items()} for i in range(len(batch))]
        else:
            rows = out
        # deadline is re-checked at resolution: admission alone let a
        # request blow its budget inside the coalescing wait or run_fn and
        # still resolve ok — the caller had already given up on it
        now_mono = time.monotonic()
        for it, row in zip(batch, rows):
            dl = it[3]
            if dl is not None and now_mono > dl:
                self._m_late.inc()
                if it[4] is not None:
                    self._tracer.finish(it[4], "late")
                it[1].set_exception(
                    DeadlineExceededError(
                        "request deadline passed during batch coalescing/compute"
                    )
                )
            else:
                if it[4] is not None:
                    self._tracer.finish(it[4], "ok")
                it[1].set_result(row)
