"""The batched inference engine: shape-bucketed AOT executables.

Training drove the per-step roofline (PERF.md); this module is the serving
counterpart. The design moves every once-per-model cost out of the request
path:

- **Restore once.** The checkpoint is read a single time per process via
  :func:`~jumbo_mae_tpu_tpu.train.checkpoint.restore_inference_state`
  (params + BatchNorm stats only — the optimizer state's ~2x-params bytes
  are never read), then merged onto each task's serving module with the
  same overlap diagnostics the warm-start path prints.
- **Compile once per (task, bucket).** Request batches are padded up to a
  power-of-two bucket and run through an explicitly cached executable,
  lowered ahead-of-time with ``jax.jit(...).lower().compile()`` — the hot
  path never enters the jit tracing/cache machinery, and a compile can
  only happen where :meth:`InferenceEngine.warmup` or the first miss puts
  it. ``compile_counts`` / ``on_compile`` expose exactly when that was.
  The persistent compile cache (``JAX_COMPILATION_CACHE_DIR``, claimed
  crash-safe by ``utils/procenv.enable_compile_cache``) warm-starts the
  buckets across processes.
- **Padding is provably inert.** Every model op is row-independent in
  deterministic mode (per-token norms, within-sample attention, stored
  BatchNorm stats), so a padded row cannot perturb a valid row — the same
  ``valid``-mask convention the eval step uses, enforced bit-exactly by
  ``tests/test_infer_engine.py`` on the float32 path. The engine slices
  the valid rows out on the host; callers never see padding.

Three tasks cover the model zoo's heads:

- ``features`` — frozen-encoder embeddings (``pool`` ∈ cls/gap/tokens),
  the representation ``tools/extract_features.py`` / the kNN probe serve;
- ``logits``  — classification logits through the trained head
  (finetune or linear-probe checkpoints, BatchNorm stats grafted);
- ``reconstruct`` — MAE pixel reconstruction + mask (the demo-figure
  path), mask seed passed as a traced scalar so reseeding never recompiles.

Single-process by design: serving replicas scale horizontally; the mesh
machinery stays in the training stack.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from jumbo_mae_tpu_tpu.config import TrainConfig
from jumbo_mae_tpu_tpu.obs.metrics import RATIO_BUCKETS, get_registry
from jumbo_mae_tpu_tpu.models import (
    DecoderConfig,
    JumboViT,
    MAEPretrainModel,
    pool_tokens,
    preset,
)
from jumbo_mae_tpu_tpu.ops.preprocess import normalize_images
from jumbo_mae_tpu_tpu.train.checkpoint import (
    _ENCODER_KEYS,
    merge_pretrained_params,
    require_loaded,
    restore_inference_state,
)
from jumbo_mae_tpu_tpu.utils.procenv import enable_compile_cache

POOLS = ("cls", "gap", "tokens")


def bucket_for(n: int, max_batch: int) -> int:
    """Smallest power-of-two >= n, clamped to ``max_batch`` (so the number
    of distinct compiled programs is log2(max_batch)+1, not one per
    request size)."""
    if n <= 0:
        raise ValueError(f"need a positive batch, got {n}")
    if n >= max_batch:
        return max_batch
    b = 1
    while b < n:
        b <<= 1
    return b


def _to_state_dict(tree) -> dict:
    from flax import serialization

    return serialization.to_state_dict(tree)


class InferenceEngine:
    """Restore a checkpoint once; serve bucket-batched forwards forever.

    ``cfg`` is the training recipe (`TrainConfig`) whose model section
    defines the encoder/decoder; ``ckpt`` any
    :func:`restore_inference_state` carrier (omit for random init —
    benchmarking only, a loaded checkpoint is enforced through the same
    ``require_loaded`` guard the export tools use).

    ``dtype`` overrides the serving compute dtype (default: the recipe's
    encoder dtype — bf16 on the chip; pass ``"float32"`` for the exact
    path). ``max_batch`` caps the largest bucket; requests larger than it
    are chunked. All public predict methods are thread-safe (compiles are
    serialized behind a lock; dispatches run concurrently).
    """

    def __init__(
        self,
        cfg: TrainConfig,
        *,
        ckpt: str = "",
        dtype: str | None = None,
        max_batch: int = 64,
        labels: int | None = None,
        batch_norm: bool | None = None,
        on_compile: Callable[[str, int], None] | None = None,
        compile_cache: str | None = None,
        registry=None,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        enable_compile_cache(compile_cache)
        # telemetry handles resolved once (obs/metrics.py): the hot path only
        # ever pays a counter inc / histogram observe, and a NullRegistry
        # default turns every site into a no-op with no branches here
        reg = registry if registry is not None else get_registry()
        self._m_predict = reg.histogram(
            "infer_predict_seconds",
            "engine predict() wall time per batched call",
            labels=("task",),
        )
        self._m_images = reg.counter(
            "infer_images_total", "images served", labels=("task",)
        )
        self._m_hits = reg.counter(
            "infer_bucket_cache_hits_total",
            "bucket-executable cache hits",
            labels=("task",),
        )
        self._m_misses = reg.counter(
            "infer_bucket_cache_misses_total",
            "bucket-executable cache misses (each one is a compile)",
            labels=("task",),
        )
        self._m_compile = reg.histogram(
            "infer_compile_seconds",
            "AOT lower+compile time per (task, bucket) executable",
            labels=("task",),
        )
        self._m_pad = reg.histogram(
            "infer_pad_fraction",
            "padding rows / bucket size per dispatched chunk",
            buckets=RATIO_BUCKETS,
        )
        self.cfg = cfg
        self.max_batch = int(max_batch)
        self.on_compile = on_compile
        m = cfg.model
        overrides = dict(m.overrides)
        if dtype is not None:
            overrides["dtype"] = dtype
        # serving is always deterministic — stochastic knobs forced off,
        # LAST, so recipe overrides can't re-enable them
        self._enc = preset(
            m.preset,
            **{
                **overrides,
                "labels": None,
                "mask_ratio": None,
                "dropout": 0.0,
                "droppath": 0.0,
            },
        )
        self._labels = labels if labels is not None else overrides.get("labels")
        self._batch_norm = (
            batch_norm if batch_norm is not None else cfg.run.mode == "linear"
        )
        self._dec = DecoderConfig(
            **{
                "layers": m.dec_layers,
                "dim": m.dec_dim,
                "heads": m.dec_heads,
                "dtype": m.dec_overrides.get("dtype", m.dec_dtype)
                if dtype is None
                else dtype,
                **{
                    k: v
                    for k, v in m.dec_overrides.items()
                    if k not in ("dtype", "dropout", "droppath")
                },
            }
        )
        self.image_size = self._enc.image_size

        self._ckpt = str(ckpt)
        self._ckpt_tree: dict | None = None
        self._ckpt_stats: dict | None = None
        if self._ckpt:
            tree, stats = restore_inference_state(self._ckpt)
            self._ckpt_tree = _to_state_dict(tree)
            self._ckpt_stats = (
                _to_state_dict(stats) if stats is not None else None
            )

        self.load_stats: dict[str, dict] = {}
        self._tasks: dict[str, dict] = {}  # task -> {model, params, ...}
        self._exec: dict[tuple[str, int], Any] = {}
        self.compile_counts: dict[tuple[str, int], int] = {}
        self._lock = threading.Lock()
        # per-thread breakdown of the most recent predict on that thread
        # (compute/fetch split, bucket, pad rows) — read back by
        # last_breakdown() for request tracing. Thread-local because
        # predicts run concurrently; a shared dict would interleave.
        self._tls = threading.local()

    # ---------------------------------------------------------------- tasks

    def _graft(self, task: str, init_params, *, subtree: str, whole: bool):
        """Merge the restored checkpoint tree onto a task's fresh init.
        ``whole=True`` merges the full tree (reconstruct needs the decoder);
        otherwise the checkpoint's encoder subtree (``encoder`` for
        pretrain trees, ``model`` for classification trees, else the bare
        root) lands on ``subtree`` of the init."""
        if self._ckpt_tree is None:
            return init_params
        from flax import serialization

        init_sd = _to_state_dict(init_params)
        stats: dict = {}
        if whole:
            merged = merge_pretrained_params(
                self._ckpt_tree, init_sd, stats=stats
            )
        else:
            src_key = next(
                (k for k in _ENCODER_KEYS if k in self._ckpt_tree), None
            )
            src = self._ckpt_tree[src_key] if src_key else self._ckpt_tree
            dst = init_sd[subtree] if subtree else init_sd
            sub_merged = merge_pretrained_params(src, dst, stats=stats)
            merged = (
                {**init_sd, subtree: sub_merged} if subtree else sub_merged
            )
        require_loaded(stats, self._ckpt, f"the {task} serving model")
        self.load_stats[task] = stats
        return serialization.from_state_dict(init_params, merged)

    def _build_task(self, task: str) -> dict:
        size = self.image_size
        example = jnp.zeros((1, size, size, 3), jnp.uint8)
        rngs = {"params": jax.random.key(self.cfg.run.init_seed)}
        if task == "features":
            model = JumboViT(self._enc)
            variables = model.init(
                rngs, normalize_images(example, dtype=self._enc.compute_dtype), True
            )
            params = self._graft(task, variables["params"], subtree="", whole=False)
            return {"model": model, "params": params, "batch_stats": None}
        if task == "logits":
            if not self._labels:
                raise ValueError(
                    "the logits task needs a label count — set "
                    "model.overrides.labels in the recipe or pass labels="
                )
            enc = self._enc.replace(
                labels=int(self._labels), batch_norm=self._batch_norm
            )
            model = JumboViT(enc)
            variables = model.init(
                rngs, normalize_images(example, dtype=enc.compute_dtype), True
            )
            params = self._graft(task, variables["params"], subtree="", whole=False)
            batch_stats = variables.get("batch_stats")
            if batch_stats is not None and self._ckpt_stats is not None:
                from flax import serialization

                saved = self._ckpt_stats
                # classification trees keep the head's stats under "model"
                saved = saved.get("model", saved)
                batch_stats = serialization.from_state_dict(batch_stats, saved)
            return {"model": model, "params": params, "batch_stats": batch_stats}
        if task == "reconstruct":
            enc = self._enc.replace(
                mask_ratio=self.cfg.model.overrides.get("mask_ratio", 0.75)
            )
            model = MAEPretrainModel(
                enc, self._dec, norm_pix_loss=self.cfg.model.norm_pix_loss
            )
            variables = model.init(
                {**rngs, "noise": jax.random.key(0)}, example
            )
            params = self._graft(task, variables["params"], subtree="", whole=True)
            return {"model": model, "params": params, "batch_stats": None}
        raise ValueError(f"unknown task {task!r}")

    def _task(self, task: str) -> dict:
        t = self._tasks.get(task)
        if t is None:
            with self._lock:
                t = self._tasks.get(task)
                if t is None:
                    t = self._build_task(task)
                    self._tasks[task] = t
        return t

    # ---------------------------------------------------- executable cache

    def _task_key(self, task: str, pool: str | None) -> str:
        return f"{task}:{pool}" if pool else task

    def _fn(self, task: str, pool: str | None):
        t = self._task(task)
        model, batch_stats = t["model"], t["batch_stats"]
        if task == "features":
            k = self._enc.num_cls_tokens

            def fn(params, images):
                x = normalize_images(images, dtype=self._enc.compute_dtype)
                tokens = model.apply({"params": params}, x, True)
                out = (
                    tokens if pool == "tokens" else pool_tokens(tokens, k, pool)
                )
                return out.astype(jnp.float32)

            return fn
        if task == "logits":

            def fn(params, images):
                variables = {"params": params}
                if batch_stats is not None:
                    variables["batch_stats"] = batch_stats
                x = normalize_images(images, dtype=self._enc.compute_dtype)
                return model.apply(variables, x, True).astype(jnp.float32)

            return fn

        def fn(params, images, seed):
            out = model.apply(
                {"params": params},
                images,
                True,
                True,
                rngs={"noise": jax.random.key(seed)},
            )
            return {
                "reconstruction": out["reconstruction"].astype(jnp.float32),
                "mask": out["mask"].astype(jnp.float32),
            }

        return fn

    def _executable(self, task: str, pool: str | None, bucket: int):
        key = (self._task_key(task, pool), bucket)
        ex = self._exec.get(key)
        if ex is not None:
            self._m_hits.labels(key[0]).inc()
            return ex
        # build the task OUTSIDE the compile lock: _task takes the same
        # non-reentrant lock on first build, so calling it under _lock
        # deadlocks when the compile is the first touch (warmup-first)
        t = self._task(task)
        with self._lock:
            ex = self._exec.get(key)
            if ex is not None:
                self._m_hits.labels(key[0]).inc()
                return ex
            self._m_misses.labels(key[0]).inc()
            t_compile = time.perf_counter()
            size = self.image_size
            images = jax.ShapeDtypeStruct((bucket, size, size, 3), jnp.uint8)
            # donate the request buffer: its HBM is recycled for
            # intermediates the moment normalize reads it (no-op on CPU,
            # where jax would warn per program)
            donate = (1,) if jax.default_backend() != "cpu" else ()
            args = [t["params"], images]
            if task == "reconstruct":
                args.append(jax.ShapeDtypeStruct((), jnp.int32))
            ex = (
                jax.jit(self._fn(task, pool), donate_argnums=donate)
                .lower(*args)
                .compile()
            )
            self._exec[key] = ex
            self.compile_counts[key] = self.compile_counts.get(key, 0) + 1
            self._m_compile.labels(key[0]).observe(
                time.perf_counter() - t_compile
            )
            if self.on_compile is not None:
                self.on_compile(key[0], bucket)
            return ex

    def warmup(
        self,
        tasks: tuple[str, ...] = ("features",),
        *,
        pool: str = "cls",
        buckets: tuple[int, ...] | None = None,
    ) -> int:
        """Pre-compile every (task, bucket) executable the workload will
        hit — afterwards the request path never compiles (asserted by the
        bench's zero-recompiles-after-warmup report). Default buckets:
        every power of two up to ``max_batch``."""
        if buckets is None:
            buckets = tuple(
                b for b in (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)
                if b <= self.max_batch
            )
        n = 0
        for task in tasks:
            p = pool if task == "features" else None
            for b in buckets:
                before = self.compile_counts.get((self._task_key(task, p), b), 0)
                self._executable(task, p, b)
                n += self.compile_counts[(self._task_key(task, p), b)] - before
        return n

    # -------------------------------------------------------------- predict

    def _run(self, task: str, pool: str | None, images: np.ndarray, extra=()):
        """Bucket-pad one chunk (len <= max_batch), run, slice valid rows."""
        n = images.shape[0]
        bucket = bucket_for(n, self.max_batch)
        self._m_pad.observe((bucket - n) / bucket)
        if n < bucket:
            pad = np.zeros((bucket - n, *images.shape[1:]), images.dtype)
            images = np.concatenate([images, pad])
        t = self._task(task)
        t_compute = time.perf_counter()
        out = self._executable(task, pool, bucket)(t["params"], images, *extra)
        # block here so compute vs fetch split cleanly: dispatch+execution
        # ends at block_until_ready; what follows is device→host copy
        jax.block_until_ready(out)
        t_fetch = time.perf_counter()
        out = jax.tree_util.tree_map(lambda a: np.asarray(a)[:n], out)
        bd = self._tls.bd
        bd["compute_s"] += t_fetch - t_compute
        bd["fetch_s"] += time.perf_counter() - t_fetch
        bd["bucket"] = max(bd["bucket"], bucket)
        bd["pad_rows"] += bucket - n
        bd["bucket_rows"] += bucket
        return out

    def last_breakdown(self) -> dict | None:
        """The compute/fetch/bucket/pad breakdown of the most recent predict
        *on the calling thread* (``None`` before any). This is the
        ``RequestTracer(breakdown=...)`` feed: the micro-batcher's collector
        thread calls predict and reads this right after, so the value can't
        be clobbered by a concurrent caller."""
        bd = getattr(self._tls, "bd", None)
        if bd is None:
            return None
        rows = bd["bucket_rows"]
        return {
            "compute_s": bd["compute_s"],
            "fetch_s": bd["fetch_s"],
            "bucket": bd["bucket"],
            "pad_fraction": (bd["pad_rows"] / rows) if rows else 0.0,
        }

    def _predict(self, task: str, images, *, pool=None, extra=()):
        t0 = time.perf_counter()
        self._tls.bd = {
            "compute_s": 0.0, "fetch_s": 0.0,
            "bucket": 0, "pad_rows": 0, "bucket_rows": 0,
        }
        images = np.asarray(images)
        if images.ndim == 3:
            images = images[None]
        if images.ndim != 4 or images.shape[-1] != 3:
            raise ValueError(f"expected (n, H, W, 3) uint8 images, got {images.shape}")
        if images.shape[1] != self.image_size or images.shape[2] != self.image_size:
            raise ValueError(
                f"engine is compiled for {self.image_size}px inputs, got "
                f"{images.shape[1]}x{images.shape[2]} — resize upstream"
            )
        images = images.astype(np.uint8, copy=False)
        chunks = [
            self._run(task, pool, images[i : i + self.max_batch], extra)
            for i in range(0, images.shape[0], self.max_batch)
        ]
        out = (
            chunks[0]
            if len(chunks) == 1
            else jax.tree_util.tree_map(lambda *xs: np.concatenate(xs), *chunks)
        )
        self._m_predict.labels(task).observe(time.perf_counter() - t0)
        self._m_images.labels(task).inc(images.shape[0])
        return out

    def features(self, images, *, pool: str = "cls") -> np.ndarray:
        """Pooled (or full-token) float32 encoder features, one row per
        input image."""
        if pool not in POOLS:
            raise ValueError(f"pool must be one of {POOLS}, got {pool!r}")
        return self._predict("features", images, pool=pool)

    def logits(self, images) -> np.ndarray:
        """Float32 classification logits through the trained head."""
        return self._predict("logits", images)

    def reconstruct(self, images, *, seed: int = 0) -> dict[str, np.ndarray]:
        """MAE reconstruction: ``{"reconstruction": (n, N, p*p*3), "mask":
        (n, N)}`` in (possibly norm-pix) patch space — same contract as
        ``tools/reconstruct.py``. ``seed`` varies the mask without
        recompiling (traced scalar)."""
        return self._predict(
            "reconstruct", images, extra=(jnp.asarray(seed, jnp.int32),)
        )

    def predict(self, images, task: str = "features", **kw):
        if task == "features":
            return self.features(images, **kw)
        if task == "logits":
            return self.logits(images, **kw)
        if task == "reconstruct":
            return self.reconstruct(images, **kw)
        raise ValueError(f"unknown task {task!r}")
