"""The batched inference engine: shape-bucketed AOT executables.

Training drove the per-step roofline (PERF.md); this module is the serving
counterpart. The design moves every once-per-model cost out of the request
path:

- **Restore once.** The checkpoint is read a single time per process via
  :func:`~jumbo_mae_tpu_tpu.train.checkpoint.restore_inference_state`
  (params + BatchNorm stats only — the optimizer state's ~2x-params bytes
  are never read), then merged onto each task's serving module with the
  same overlap diagnostics the warm-start path prints.
- **Compile once per (task, bucket) — per HOST, not per process.** Request
  batches are padded up to a power-of-two bucket and run through an
  explicitly cached executable, lowered ahead-of-time with
  ``jax.jit(...).lower().compile()`` — the hot path never enters the jit
  tracing/cache machinery, and a compile can only happen where
  :meth:`InferenceEngine.warmup` or the first miss puts it.
  ``compile_counts`` / ``on_compile`` expose exactly when that was, and
  ``warm_hits`` counts the executables that were *loaded* instead: by
  default every compile is published to the persistent
  :class:`~jumbo_mae_tpu_tpu.infer.warmcache.WarmCache` and a restarted
  replica's warmup deserializes the ladder instead of recompiling it
  (``warm_cache=False`` opts out; the ``JUMBO_WARMCACHE*`` env knobs are
  documented on ``utils/procenv.default_warmcache_dir``). Warmup runs the
  ladder from a small thread pool — XLA compiles release the GIL.
- **Weights can be int8.** ``quant="int8"`` quantizes each task's params
  tree (``infer/quant.py``: per-output-channel weight-only PTQ) and the
  jitted forward dequantizes on use — the executable's HBM-resident
  argument is the int8 tree, which halves the weight traffic that
  dominates small-batch serving. Parity is measured, not assumed
  (``quant.parity_report``); padding-inertness is preserved because
  dequantization is an exact per-weight ``q * scale``.
- **Padding is provably inert.** Every model op is row-independent in
  deterministic mode (per-token norms, within-sample attention, stored
  BatchNorm stats), so a padded row cannot perturb a valid row — the same
  ``valid``-mask convention the eval step uses, enforced bit-exactly by
  ``tests/test_infer_engine.py`` on the float32 path. The engine slices
  the valid rows out on the host; callers never see padding.

Three tasks cover the model zoo's heads:

- ``features`` — frozen-encoder embeddings (``pool`` ∈ cls/gap/tokens),
  the representation ``tools/extract_features.py`` / the kNN probe serve;
- ``logits``  — classification logits through the trained head
  (finetune or linear-probe checkpoints, BatchNorm stats grafted);
- ``reconstruct`` — MAE pixel reconstruction + mask (the demo-figure
  path), mask seed passed as a traced scalar so reseeding never recompiles.
  With ``encoder_cache=N`` the task splits into an encode executable
  (normalize → masked encoder → decoder projection) and a decode
  executable, with an N-entry LRU of encoder outputs keyed by
  (image bytes, seed) in between — repeated reconstructions of the same
  image run the deep encoder once and only the light decoder per request.

Single-process by design: serving replicas scale horizontally; the mesh
machinery stays in the training stack.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import threading
import time
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from jumbo_mae_tpu_tpu.config import TrainConfig
from jumbo_mae_tpu_tpu.infer import packing
from jumbo_mae_tpu_tpu.infer import warmcache as wc
from jumbo_mae_tpu_tpu.infer.bucketing import ceil_pow2
from jumbo_mae_tpu_tpu.infer.quant import dequantize_tree, quantize_params
from jumbo_mae_tpu_tpu.obs import lockwatch
from jumbo_mae_tpu_tpu.obs.metrics import RATIO_BUCKETS, get_registry
from jumbo_mae_tpu_tpu.models import (
    DecoderConfig,
    JumboViT,
    MAEPretrainModel,
    pool_tokens,
    preset,
)
from jumbo_mae_tpu_tpu.ops.masking import unshuffle_with_mask_tokens
from jumbo_mae_tpu_tpu.ops.preprocess import normalize_images
from jumbo_mae_tpu_tpu.train.checkpoint import (
    _ENCODER_KEYS,
    merge_pretrained_params,
    require_loaded,
    restore_inference_state,
)
from jumbo_mae_tpu_tpu.utils.procenv import (
    default_warmcache_dir,
    enable_compile_cache,
    host_fingerprint,
)

POOLS = ("cls", "gap", "tokens")

# bucket math lives in infer/bucketing.py (one definition, property-tested);
# re-exported here because this module was its historical home
from jumbo_mae_tpu_tpu.infer.bucketing import (  # noqa: E402,F401
    OversizedBatchError,
    bucket_for,
    pow2_rungs,
)


class ResolutionMismatchError(ValueError):
    """Input resolution differs from what the engine's image-bucket
    executables were compiled for. Typed (rather than a bare ValueError)
    so a scheduler/router can catch it and route the request to the
    token-packed path — which accepts any patch-aligned resolution —
    instead of failing the request. ``expected`` is the engine's native
    square size; ``got`` the offending (H, W)."""

    def __init__(self, expected: int, got: tuple[int, int]):
        self.expected = int(expected)
        self.got = (int(got[0]), int(got[1]))
        super().__init__(
            f"engine is compiled for {expected}px inputs, got "
            f"{got[0]}x{got[1]} — resize upstream or route to the "
            f"token-packed path (predict_packed)"
        )


def _to_state_dict(tree) -> dict:
    from flax import serialization

    return serialization.to_state_dict(tree)


# Encoder-once/decode-many split of MAEPretrainModel.__call__ (models/mae.py):
# the two halves, bound via ``apply(..., method=...)``, cover between them
# exactly the ops of the fused reconstruction forward — same modules, same
# order, same PRNG consumption — so the mask is bit-identical to the fused
# path and the reconstruction matches to fusion-level float tolerance.


def _mae_encode(mdl, images, deterministic: bool = True):
    """normalize → masked encoder → decoder projection. Everything that
    depends only on (image, mask seed) — the cacheable prefix."""
    x = normalize_images(images, dtype=mdl.encoder_cfg.compute_dtype)
    tokens, mask, ids_restore = mdl.encoder(x, deterministic)
    return mdl.decoder_proj(tokens), mask, ids_restore


def _mae_decode(mdl, tokens, mask, ids_restore, deterministic: bool = True):
    """mask-token unshuffle → decoder stack → pixel head. Row-independent
    throughout (per-token norms, within-sample attention, per-sample
    gather), so zero-padded rows stay provably inert — the same contract
    the fused executable has."""
    enc_cfg = mdl.encoder_cfg
    k = enc_cfg.num_cls_tokens
    cls, visible = tokens[:, :k, :], tokens[:, k:, :]
    full = unshuffle_with_mask_tokens(
        visible, mdl.mask_token, ids_restore, impl=enc_cfg.gather_impl
    )
    decoded = mdl.decoder(jnp.concatenate([cls, full], axis=1), deterministic)
    pred = mdl.pixel_proj(decoded[:, k:, :].astype(jnp.float32))
    return {"reconstruction": pred, "mask": mask}


class InferenceEngine:
    """Restore a checkpoint once; serve bucket-batched forwards forever.

    ``cfg`` is the training recipe (`TrainConfig`) whose model section
    defines the encoder/decoder; ``ckpt`` any
    :func:`restore_inference_state` carrier (omit for random init —
    benchmarking only, a loaded checkpoint is enforced through the same
    ``require_loaded`` guard the export tools use).

    ``dtype`` overrides the serving compute dtype (default: the recipe's
    encoder dtype — bf16 on the chip; pass ``"float32"`` for the exact
    path). ``max_batch`` caps the largest bucket; requests larger than it
    are chunked. All public predict methods are thread-safe (compiles are
    serialized behind per-executable locks; dispatches run concurrently).

    ``quant="int8"`` serves the weight-only-quantized forward
    (``infer/quant.py`` — measure parity with ``quant.parity_report``
    before rollout). ``warm_cache`` controls the persistent executable
    cache: ``True`` (default) resolves via
    ``procenv.default_warmcache_dir()`` (env-disableable), a path uses that
    directory unconditionally, ``False``/``None`` disables.
    ``encoder_cache=N`` keeps an N-entry LRU of reconstruction encoder
    outputs so repeated reconstructions of one image pay the encoder once.
    """

    def __init__(
        self,
        cfg: TrainConfig,
        *,
        ckpt: str = "",
        dtype: str | None = None,
        max_batch: int = 64,
        max_tokens: int = 4096,
        labels: int | None = None,
        batch_norm: bool | None = None,
        quant: str | None = None,
        warm_cache: str | os.PathLike | bool | None = True,
        encoder_cache: int = 0,
        encoder_cache_bytes: int = 0,
        on_compile: Callable[[str, int], None] | None = None,
        compile_cache: str | None = None,
        registry=None,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if quant not in (None, "int8"):
            raise ValueError(f"quant must be None or 'int8', got {quant!r}")
        enable_compile_cache(compile_cache)
        # telemetry handles resolved once (obs/metrics.py): the hot path only
        # ever pays a counter inc / histogram observe, and a NullRegistry
        # default turns every site into a no-op with no branches here
        reg = registry if registry is not None else get_registry()
        self._m_predict = reg.histogram(
            "infer_predict_seconds",
            "engine predict() wall time per batched call",
            labels=("task",),
        )
        self._m_images = reg.counter(
            "infer_images_total", "images served", labels=("task",)
        )
        self._m_hits = reg.counter(
            "infer_bucket_cache_hits_total",
            "bucket-executable cache hits",
            labels=("task",),
        )
        self._m_misses = reg.counter(
            "infer_bucket_cache_misses_total",
            "bucket-executable cache misses (each one is a compile)",
            labels=("task",),
        )
        self._m_compile = reg.histogram(
            "infer_compile_seconds",
            "AOT lower+compile time per (task, bucket) executable",
            labels=("task",),
        )
        self._m_pad = reg.histogram(
            "infer_pad_fraction",
            "padding rows / bucket size per dispatched chunk",
            buckets=RATIO_BUCKETS,
        )
        self._m_warm_start = reg.gauge(
            "infer_warm_start_seconds",
            "wall time of the last warmup() ladder (compiles + cache loads)",
        )
        self._m_enc_cache = reg.counter(
            "infer_encoder_cache_events_total",
            "reconstruction encoder-output LRU events",
            labels=("event",),
        )
        self._m_enc_cache_bytes = reg.gauge(
            "infer_encoder_cache_bytes",
            "resident bytes of cached encoder-output rows (tokens+mask+ids)",
        )
        self._m_quant = reg.gauge(
            "infer_quant_compression",
            "params bytes_before / bytes_after per quantized task",
            labels=("task",),
        )
        self._m_bucket_compile = reg.gauge(
            "infer_bucket_compile_seconds",
            "lower+compile wall time of each (task, bucket) executable",
            labels=("task", "bucket"),
        )
        self._m_exec_bytes = reg.gauge(
            "infer_executable_bytes",
            "serialized executable size per (task, bucket)",
            labels=("task", "bucket"),
        )
        self._m_warm_saved = reg.counter(
            "infer_warmcache_saved_seconds_total",
            "compile seconds avoided by warmcache hits (from entry metadata)",
            labels=("task",),
        )
        self._m_pred_s = reg.gauge(
            "perf_predicted_step_seconds",
            "roofline-predicted execution seconds",
            labels=("program",),
        )
        self._m_drift = reg.gauge(
            "perf_predict_vs_measured",
            "measured / roofline-predicted execution time",
            labels=("program",),
        )
        # token-packed serving observability (see predict_packed)
        self._m_pack_pad = reg.histogram(
            "serve_pack_token_pad_fraction",
            "padding tokens / device tokens per packed dispatch "
            "(row bucketing included)",
            buckets=RATIO_BUCKETS,
        )
        self._m_pack_segments = reg.histogram(
            "serve_pack_segments_per_dispatch",
            "request segments packed into one dispatch",
            buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0),
        )
        self._m_pack_occ = reg.histogram(
            "serve_pack_budget_occupancy",
            "occupied tokens / (rows x token budget) per packed dispatch",
            buckets=RATIO_BUCKETS,
        )
        self._m_pack_dispatches = reg.counter(
            "serve_pack_dispatches_total",
            "token-packed dispatches served",
            labels=("task",),
        )
        self._m_pack_parity = reg.gauge(
            "serve_pack_parity_min",
            "min packed-vs-unpacked feature cosine of the last parity gate",
        )
        self._m_pack_parity_fail = reg.counter(
            "serve_pack_parity_failures_total",
            "packed-parity gate failures (cosine or top-1 below threshold)",
        )
        self._registry = reg
        self.cfg = cfg
        self.max_batch = int(max_batch)
        # packed-path token budget ceiling: the rung ladder tops out here
        # (4096 covers 896px/patch16 = 3136 patch tokens + CLS)
        self.max_tokens = int(max_tokens)
        self.on_compile = on_compile
        m = cfg.model
        overrides = dict(m.overrides)
        if dtype is not None:
            overrides["dtype"] = dtype
        # serving is always deterministic — stochastic knobs forced off,
        # LAST, so recipe overrides can't re-enable them. grad_ckpt too:
        # there are no gradients to checkpoint for, and the packed forward
        # passes a traced pytree positionally past the remat wrapper's
        # static deterministic flag.
        self._enc = preset(
            m.preset,
            **{
                **overrides,
                "labels": None,
                "mask_ratio": None,
                "dropout": 0.0,
                "droppath": 0.0,
                "grad_ckpt": False,
            },
        )
        self._labels = labels if labels is not None else overrides.get("labels")
        self._batch_norm = (
            batch_norm if batch_norm is not None else cfg.run.mode == "linear"
        )
        self._dec = DecoderConfig(
            **{
                "layers": m.dec_layers,
                "dim": m.dec_dim,
                "heads": m.dec_heads,
                "dtype": m.dec_overrides.get("dtype", m.dec_dtype)
                if dtype is None
                else dtype,
                **{
                    k: v
                    for k, v in m.dec_overrides.items()
                    if k not in ("dtype", "dropout", "droppath")
                },
            }
        )
        self.image_size = self._enc.image_size

        self._ckpt = str(ckpt)
        self._ckpt_tree: dict | None = None
        self._ckpt_stats: dict | None = None
        if self._ckpt:
            from jumbo_mae_tpu_tpu.serve.publisher import is_publish_artifact

            if is_publish_artifact(self._ckpt):
                # a published train→serve artifact (serve/publisher.py):
                # verify the manifest, resolve its delta chain to a full
                # host tree — a pool can cold-start straight from the
                # newest publish and absorb later ones via hot-swap
                from jumbo_mae_tpu_tpu.serve.publisher import resolve_chain

                tree, stats = resolve_chain(self._ckpt)[:2]
            else:
                # to_device: leaves land on device one at a time, host
                # buffers dropped as they go — replica-density restore
                # (peak one tree, not host + device copies of a full model)
                tree, stats = restore_inference_state(
                    self._ckpt, to_device=True
                )
            self._ckpt_tree = _to_state_dict(tree)
            self._ckpt_stats = (
                _to_state_dict(stats) if stats is not None else None
            )

        self.quant = quant
        if encoder_cache and self._enc.mask_mode != "shared":
            # per-sample masking draws (batch, length) noise: a row's mask
            # depends on its batch position, so a cached encoder output
            # would silently change results across batch compositions.
            # Shared mode draws (length,) noise — position-independent.
            raise ValueError(
                "encoder_cache requires mask_mode='shared' (per-sample "
                "masks are batch-position-dependent and cannot be cached "
                "per image)"
            )
        self._enc_cache_size = int(encoder_cache)
        # optional byte bound on top of the entry bound: whichever trips
        # first evicts. 0 = entries-only (historical behaviour). Only
        # meaningful when encoder_cache > 0 enables the cache at all.
        self._enc_cache_bytes_cap = int(encoder_cache_bytes)
        self._enc_cache_nbytes = 0
        self._enc_cache: OrderedDict[str, tuple] = OrderedDict()
        self._enc_cache_lock = lockwatch.lock("engine.enc_cache")
        self.encoder_cache_hits = 0
        self.encoder_cache_misses = 0

        if warm_cache is True:
            wc_root = default_warmcache_dir()
        elif warm_cache:
            wc_root = str(warm_cache)
        else:
            wc_root = None
        self.warmcache = (
            wc.WarmCache(wc_root, registry=reg) if wc_root else None
        )
        # executables loaded from the warmcache instead of compiled —
        # deliberately NOT folded into compile_counts: "restart performs
        # zero compiles" is asserted against compile_counts staying flat
        self.warm_hits: dict[tuple[str, int], int] = {}
        self._fingerprint = self._model_fingerprint()

        self.load_stats: dict[str, dict] = {}
        self._tasks: dict[str, dict] = {}  # task -> {model, variables, ...}
        self._exec: dict[tuple[str, int], Any] = {}
        # serialized size per resident executable (where known) — summed by
        # executable_cache_bytes() for the memory accountant
        self._exec_nbytes: dict[tuple[str, int], int] = {}
        self.compile_counts: dict[tuple[str, int], int] = {}
        # XLA cost analysis per (task_key, bucket) + its roofline-predicted
        # execution seconds — filled at compile/warm-load time, read by the
        # per-dispatch drift gauge and bench_infer's ledger row
        self.cost_reports: dict[tuple[str, int], Any] = {}
        self._pred_s: dict[tuple[str, int], float] = {}
        self._lock = lockwatch.lock("engine.master")
        # one lock per (task, bucket): warmup threads compile distinct
        # executables concurrently (XLA releases the GIL) while two racers
        # for the SAME key still serialize
        self._key_locks: dict[tuple[str, int], threading.Lock] = {}
        # per-thread breakdown of the most recent predict on that thread
        # (compute/fetch split, bucket, pad rows) — read back by
        # last_breakdown() for request tracing. Thread-local because
        # predicts run concurrently; a shared dict would interleave.
        self._tls = threading.local()

    # ---------------------------------------------------------------- tasks

    def _graft(self, task: str, init_params, *, subtree: str, whole: bool):
        """Merge the restored checkpoint tree onto a task's fresh init.
        ``whole=True`` merges the full tree (reconstruct needs the decoder);
        otherwise the checkpoint's encoder subtree (``encoder`` for
        pretrain trees, ``model`` for classification trees, else the bare
        root) lands on ``subtree`` of the init."""
        if self._ckpt_tree is None:
            return init_params
        from flax import serialization

        init_sd = _to_state_dict(init_params)
        stats: dict = {}
        if whole:
            merged = merge_pretrained_params(
                self._ckpt_tree, init_sd, stats=stats
            )
        else:
            src_key = next(
                (k for k in _ENCODER_KEYS if k in self._ckpt_tree), None
            )
            src = self._ckpt_tree[src_key] if src_key else self._ckpt_tree
            dst = init_sd[subtree] if subtree else init_sd
            sub_merged = merge_pretrained_params(src, dst, stats=stats)
            merged = (
                {**init_sd, subtree: sub_merged} if subtree else sub_merged
            )
        require_loaded(stats, self._ckpt, f"the {task} serving model")
        self.load_stats[task] = stats
        return serialization.from_state_dict(init_params, merged)

    def _finish_task(self, task: str, t: dict) -> dict:
        """Shared tail of task construction: weight-only quantization of
        the params subtree (BatchNorm statistics stay f32 — they are not
        matmul weights and the executable takes them as arguments, never
        as baked-in constants, so warmcache entries stay checkpoint-
        independent)."""
        if self.quant == "int8":
            qtree, report = quantize_params(t["variables"]["params"])
            t["variables"] = {**t["variables"], "params": qtree}
            t["quant_report"] = report
            self._m_quant.labels(task).set(report["compression"])
        return t

    def _build_task(self, task: str) -> dict:
        size = self.image_size
        example = jnp.zeros((1, size, size, 3), jnp.uint8)
        rngs = {"params": jax.random.key(self.cfg.run.init_seed)}
        if task == "features":
            model = JumboViT(self._enc)
            variables = model.init(
                rngs, normalize_images(example, dtype=self._enc.compute_dtype), True
            )
            params = self._graft(task, variables["params"], subtree="", whole=False)
            return self._finish_task(
                task, {"model": model, "variables": {"params": params}}
            )
        if task == "logits":
            if not self._labels:
                raise ValueError(
                    "the logits task needs a label count — set "
                    "model.overrides.labels in the recipe or pass labels="
                )
            enc = self._enc.replace(
                labels=int(self._labels), batch_norm=self._batch_norm
            )
            model = JumboViT(enc)
            variables = model.init(
                rngs, normalize_images(example, dtype=enc.compute_dtype), True
            )
            params = self._graft(task, variables["params"], subtree="", whole=False)
            batch_stats = variables.get("batch_stats")
            if batch_stats is not None and self._ckpt_stats is not None:
                from flax import serialization

                saved = self._ckpt_stats
                # classification trees keep the head's stats under "model"
                saved = saved.get("model", saved)
                batch_stats = serialization.from_state_dict(batch_stats, saved)
            v = {"params": params}
            if batch_stats is not None:
                v["batch_stats"] = batch_stats
            return self._finish_task(task, {"model": model, "variables": v})
        if task == "reconstruct":
            enc = self._enc.replace(
                mask_ratio=self.cfg.model.overrides.get("mask_ratio", 0.75)
            )
            model = MAEPretrainModel(
                enc, self._dec, norm_pix_loss=self.cfg.model.norm_pix_loss
            )
            variables = model.init(
                {**rngs, "noise": jax.random.key(0)}, example
            )
            params = self._graft(task, variables["params"], subtree="", whole=True)
            return self._finish_task(
                task,
                {
                    "model": model,
                    "variables": {"params": params},
                    "enc_cfg": enc,
                },
            )
        raise ValueError(f"unknown task {task!r}")

    def _task(self, task: str) -> dict:
        t = self._tasks.get(task)
        if t is None:
            with self._lock:
                t = self._tasks.get(task)
                if t is None:
                    t = self._build_task(task)
                    self._tasks[task] = t
        return t

    # ------------------------------------------------------------ hot swap

    def swap_weights(self, params, batch_stats=None, *, ckpt: str = "") -> dict:
        """Replace the live weights with a newly restored tree — zero
        compiles. Params/batch_stats are executable *arguments*, so every
        cached AOT executable serves the new weights unchanged; only the
        task variable trees are rebuilt (fresh init + graft + quant).

        Returns an opaque snapshot of the previous weights for
        :meth:`restore_snapshot` — the double buffer a hot-swap rollback
        needs. Raises (leaving the previous weights live) when the new tree
        does not graft onto this architecture; the swap controller treats
        that as a failed swap. In-flight predicts are per-request atomic:
        each dispatch reads one task dict, so a request serves entirely old
        or entirely new weights, never a mix.
        """
        new_tree = _to_state_dict(params)
        new_stats = (
            _to_state_dict(batch_stats) if batch_stats is not None else None
        )
        with self._lock:
            snap = {
                "ckpt": self._ckpt,
                "tree": self._ckpt_tree,
                "stats": self._ckpt_stats,
                "tasks": dict(self._tasks),
            }
            built = sorted(self._tasks)
            self._ckpt = str(ckpt)
            self._ckpt_tree = new_tree
            self._ckpt_stats = new_stats
        try:
            rebuilt = {task: self._build_task(task) for task in built}
        except BaseException:
            with self._lock:
                self._ckpt = snap["ckpt"]
                self._ckpt_tree = snap["tree"]
                self._ckpt_stats = snap["stats"]
            raise
        with self._lock:
            self._tasks.update(rebuilt)
        with self._enc_cache_lock:
            # cached encoder outputs are weight-dependent
            self._enc_cache.clear()
            self._enc_cache_nbytes = 0
            self._m_enc_cache_bytes.set(0)
        return snap

    def restore_snapshot(self, snap: dict) -> None:
        """Reinstate a :meth:`swap_weights` snapshot (rollback). Tasks
        first built *after* the swap are dropped so they lazily rebuild
        from the restored tree instead of keeping the rolled-back weights."""
        with self._lock:
            self._ckpt = snap["ckpt"]
            self._ckpt_tree = snap["tree"]
            self._ckpt_stats = snap["stats"]
            for task in list(self._tasks):
                if task in snap["tasks"]:
                    self._tasks[task] = snap["tasks"][task]
                else:
                    del self._tasks[task]
        with self._enc_cache_lock:
            self._enc_cache.clear()
            self._enc_cache_nbytes = 0
            self._m_enc_cache_bytes.set(0)

    # ---------------------------------------------------- executable cache

    def _task_key(self, task: str, pool: str | None) -> str:
        return f"{task}:{pool}" if pool else task

    @staticmethod
    def _base_task(task: str) -> str:
        """'reconstruct.enc' / 'reconstruct.dec' share the 'reconstruct'
        task state (model + grafted variables); everything else is 1:1."""
        return task.split(".", 1)[0]

    def _model_fingerprint(self) -> str:
        """Everything the traced serving programs depend on besides their
        runtime arguments. Params and BatchNorm stats are arguments, so
        checkpoints of one architecture share warmcache entries; jax/jaxlib
        versions and the host CPU fingerprint are included because XLA:CPU
        executables embed machine features and PjRt serialization is not
        stable across versions."""
        import jaxlib

        def cfg_dict(c):
            return dataclasses.asdict(c) if dataclasses.is_dataclass(c) else str(c)

        return wc.fingerprint(
            {
                "enc": cfg_dict(self._enc),
                "dec": cfg_dict(self._dec),
                "labels": self._labels,
                "batch_norm": self._batch_norm,
                "norm_pix_loss": self.cfg.model.norm_pix_loss,
                "mask_ratio": self.cfg.model.overrides.get("mask_ratio", 0.75),
                "image_size": self.image_size,
                "jax": jax.__version__,
                "jaxlib": jaxlib.__version__,
                "backend": jax.default_backend(),
                "host": host_fingerprint(),
            }
        )

    def _entry_name(self, task_key: str, bucket: int) -> str:
        return wc.entry_name(
            self._fingerprint, task_key, bucket, str(self._enc.dtype), self.quant
        )

    def _task_cfg(self, base: str):
        """The encoder config a base task's model was built with — what the
        packed path's per-resolution variants must replicate (same params
        tree, different image_size)."""
        if base == "logits":
            return self._enc.replace(
                labels=int(self._labels), batch_norm=self._batch_norm
            )
        return self._enc

    @staticmethod
    def _packed_dims(task: str) -> tuple[int, int]:
        """Parse (rows, max_segments) out of a packed task key
        (``<base>.packed:<pool>@r<rows>s<smax>``)."""
        spec = task.rsplit("@", 1)[1]
        r, s = spec[1:].split("s", 1)
        return int(r), int(s)

    def _fn(self, task: str, pool: str | None):
        t = self._task(self._base_task(task))
        model = t["model"]
        quantized = self.quant is not None

        def prep(variables):
            # dequant-on-use: the executable's argument stays int8; the f32
            # view is an on-chip intermediate fused into the consumers
            return dequantize_tree(variables) if quantized else variables

        if ".embed@" in task:
            # per-resolution patch embedding: the packed pipeline's stage 1.
            # Same variables tree as the base task — only the (traced)
            # image_size differs, and with sincos2d posemb the params are
            # resolution-independent, so the graft/quant state is shared.
            res = int(task.rsplit("@", 1)[1])
            model_r = JumboViT(
                self._task_cfg(self._base_task(task)).replace(image_size=res)
            )

            def fn(variables, images):
                v = prep(variables)
                x = normalize_images(images, dtype=self._enc.compute_dtype)
                toks = model_r.apply(
                    {"params": v["params"]}, x, method=JumboViT.patchify
                )
                return toks.astype(jnp.float32)

            return fn
        if ".full:" in task:
            # unpacked full forward at an arbitrary resolution — the packed
            # path's per-request parity oracle (same output contract as
            # serve_packed: {"pooled", "logits"?})
            pool_name = task.split(".full:", 1)[1].rsplit("@", 1)[0]
            res = int(task.rsplit("@", 1)[1])
            model_r = JumboViT(
                self._task_cfg(self._base_task(task)).replace(image_size=res)
            )

            def fn(variables, images):
                v = prep(variables)
                x = normalize_images(images, dtype=self._enc.compute_dtype)
                return model_r.apply(
                    v, x, True, pooling=pool_name, method=JumboViT.serve_full
                )

            return fn
        if ".packed:" in task:
            # token-packed forward: consumes pre-embedded token segments,
            # so one executable serves every resolution in the mix (and
            # both features + logits when the base task has a head)
            pool_name = task.split(".packed:", 1)[1].rsplit("@", 1)[0]

            def fn(variables, tokens, seg, cls_pos, cls_index):
                v = prep(variables)
                return model.apply(
                    v,
                    tokens,
                    seg,
                    cls_pos,
                    cls_index,
                    True,
                    pooling=pool_name,
                    method=JumboViT.serve_packed,
                )

            return fn
        if task == "features":
            k = self._enc.num_cls_tokens

            def fn(variables, images):
                v = prep(variables)
                x = normalize_images(images, dtype=self._enc.compute_dtype)
                tokens = model.apply({"params": v["params"]}, x, True)
                out = (
                    tokens if pool == "tokens" else pool_tokens(tokens, k, pool)
                )
                return out.astype(jnp.float32)

            return fn
        if task == "logits":

            def fn(variables, images):
                x = normalize_images(images, dtype=self._enc.compute_dtype)
                return model.apply(prep(variables), x, True).astype(jnp.float32)

            return fn
        if task == "reconstruct.enc":

            def fn(variables, images, seed):
                v = prep(variables)
                tokens, mask, ids = model.apply(
                    {"params": v["params"]},
                    images,
                    True,
                    method=_mae_encode,
                    rngs={"noise": jax.random.key(seed)},
                )
                if ids.ndim == 1:
                    # shared-mode ids_restore is one permutation for the
                    # whole batch; materialize it per row so cached rows
                    # are self-contained (the 2-D decode gather is exact)
                    ids = jnp.broadcast_to(ids, (images.shape[0], ids.shape[0]))
                return tokens, mask.astype(jnp.float32), ids.astype(jnp.int32)

            return fn
        if task == "reconstruct.dec":

            def fn(variables, tokens, mask, ids):
                v = prep(variables)
                out = model.apply(
                    {"params": v["params"]},
                    tokens,
                    mask,
                    ids,
                    True,
                    method=_mae_decode,
                )
                return {
                    "reconstruction": out["reconstruction"].astype(jnp.float32),
                    "mask": out["mask"].astype(jnp.float32),
                }

            return fn

        def fn(variables, images, seed):
            v = prep(variables)
            out = model.apply(
                {"params": v["params"]},
                images,
                True,
                True,
                rngs={"noise": jax.random.key(seed)},
            )
            return {
                "reconstruction": out["reconstruction"].astype(jnp.float32),
                "mask": out["mask"].astype(jnp.float32),
            }

        return fn

    def _abstract_args(self, task: str, bucket: int, t: dict) -> list:
        """Lowering arguments for one executable: the task's (possibly
        quantized) variables tree plus shape-only stand-ins for the data."""
        size = self.image_size
        if ".embed@" in task or ".full:" in task:
            res = int(task.rsplit("@", 1)[1])
            return [
                t["variables"],
                jax.ShapeDtypeStruct((bucket, res, res, 3), jnp.uint8),
            ]
        if ".packed:" in task:
            # packed executables key rows/segment-slots into the task name;
            # ``bucket`` is the token budget
            rows, smax = self._packed_dims(task)
            k = self._enc.num_cls_tokens
            return [
                t["variables"],
                jax.ShapeDtypeStruct((rows, bucket, self._enc.dim), jnp.float32),
                jax.ShapeDtypeStruct((rows, bucket), jnp.int32),
                jax.ShapeDtypeStruct((rows, bucket), jnp.int32),
                jax.ShapeDtypeStruct((rows, smax, k), jnp.int32),
            ]
        if task == "reconstruct.dec":
            enc = t["enc_cfg"]
            seq = enc.num_cls_tokens + enc.keep_len
            return [
                t["variables"],
                jax.ShapeDtypeStruct(
                    (bucket, seq, self._dec.dim), self._dec.compute_dtype
                ),
                jax.ShapeDtypeStruct((bucket, enc.num_patches), jnp.float32),
                jax.ShapeDtypeStruct((bucket, enc.num_patches), jnp.int32),
            ]
        args = [
            t["variables"],
            jax.ShapeDtypeStruct((bucket, size, size, 3), jnp.uint8),
        ]
        if task in ("reconstruct", "reconstruct.enc"):
            args.append(jax.ShapeDtypeStruct((), jnp.int32))
        return args

    def _compile_lock(self, key: tuple[str, int]) -> threading.Lock:
        with self._lock:
            lk = self._key_locks.get(key)
            if lk is None:
                lk = self._key_locks[key] = lockwatch.lock(
                    f"engine.compile[{key[0]}/{key[1]}]"
                )
            return lk

    def _executable(self, task: str, pool: str | None, bucket: int):
        key = (self._task_key(task, pool), bucket)
        ex = self._exec.get(key)
        if ex is not None:
            self._m_hits.labels(key[0]).inc()
            return ex
        # build the task OUTSIDE any compile lock: _task takes the master
        # lock on first build, so calling it under a held lock deadlocks
        # when the compile is the first touch (warmup-first)
        t = self._task(self._base_task(task))
        with self._compile_lock(key):
            ex = self._exec.get(key)
            if ex is not None:
                self._m_hits.labels(key[0]).inc()
                return ex
            if self.warmcache is not None:
                name = self._entry_name(key[0], bucket)
                ex = self.warmcache.get(name)
                if ex is not None:
                    # a warm-start load, not a compile: compile_counts must
                    # stay flat so "restart performs zero compiles" is a
                    # checkable invariant, and miss keeps meaning compile
                    self._exec[key] = ex
                    self.warm_hits[key] = self.warm_hits.get(key, 0) + 1
                    self._publish_cost(key, ex)
                    meta = self.warmcache.entry_meta(name)
                    if meta:
                        # quantify what the hit was worth: the compile
                        # seconds the first process paid for this entry
                        saved = float(meta.get("compile_seconds") or 0.0)
                        if saved > 0:
                            self._m_warm_saved.labels(key[0]).inc(saved)
                        size = float(meta.get("executable_bytes") or 0.0)
                        if size > 0:
                            self._m_exec_bytes.labels(*map(str, key)).set(size)
                            self._exec_nbytes[key] = int(size)
                    return ex
            self._m_misses.labels(key[0]).inc()
            t_compile = time.perf_counter()
            # donate the request buffers: their HBM is recycled for
            # intermediates the moment the first op reads them (no-op on
            # CPU, where jax would warn per program)
            if jax.default_backend() == "cpu":
                donate: tuple[int, ...] = ()
            elif task == "reconstruct.dec":
                donate = (1, 2, 3)
            else:
                donate = (1,)
            ex = (
                jax.jit(self._fn(task, pool), donate_argnums=donate)
                .lower(*self._abstract_args(task, bucket, t))
                .compile()
            )
            self._exec[key] = ex
            self.compile_counts[key] = self.compile_counts.get(key, 0) + 1
            compile_s = time.perf_counter() - t_compile
            self._m_compile.labels(key[0]).observe(compile_s)
            self._m_bucket_compile.labels(*map(str, key)).set(compile_s)
            if self.on_compile is not None:
                self.on_compile(key[0], bucket)
            cost = self._publish_cost(key, ex)
            if self.warmcache is not None:
                meta = {"compile_seconds": round(compile_s, 4)}
                if cost is not None:
                    from jumbo_mae_tpu_tpu.obs.costmodel import cost_asdict

                    meta["cost"] = cost_asdict(cost)
                size = self.warmcache.put(
                    self._entry_name(key[0], bucket), ex, meta=meta
                )
                if size:
                    self._m_exec_bytes.labels(*map(str, key)).set(size)
                    self._exec_nbytes[key] = int(size)
            return ex

    def _publish_cost(self, key: tuple[str, int], ex):
        """Extract + publish XLA's cost analysis for one executable (at
        compile or warm-load time, never per dispatch) and precompute its
        roofline prediction for the drift gauge. Best-effort throughout."""
        try:
            from jumbo_mae_tpu_tpu.obs.costmodel import extract_cost, publish_cost
            from jumbo_mae_tpu_tpu.obs.perfmodel import detect_chip, roofline

            cost = extract_cost(ex, key[0])
            if cost is None:
                return None
            dtype = str(self._enc.dtype) + (f"+{self.quant}" if self.quant else "")
            publish_cost(
                cost, bucket=str(key[1]), dtype=dtype, registry=self._registry
            )
            self.cost_reports[key] = cost
            pred = roofline(
                cost.flops,
                cost.bytes_accessed,
                detect_chip(),
                batch=key[1],
                peak_hbm_bytes=cost.peak_bytes,
            )
            self._pred_s[key] = pred.step_time_s
            self._m_pred_s.labels(f"{key[0]}/b{key[1]}").set(pred.step_time_s)
            return cost
        except Exception:  # noqa: BLE001 — observability must not fail serving
            return None

    def warmup(
        self,
        tasks: tuple[str, ...] = ("features",),
        *,
        pool: str = "cls",
        buckets: tuple[int, ...] | None = None,
        workers: int | None = None,
    ) -> int:
        """Pre-build every (task, bucket) executable the workload will hit
        — afterwards the request path never compiles (asserted by the
        bench's zero-recompiles-after-warmup report). Default buckets:
        every power of two up to ``max_batch``, plus ``max_batch`` itself
        when it is not one. Returns the number of executables *compiled* —
        warmcache loads are free and counted in ``warm_hits`` instead.

        The ladder runs on a small thread pool (XLA compiles release the
        GIL; per-executable locks keep same-key racers serialized), each
        compile's wall time observed into ``infer_compile_seconds`` and the
        whole ladder into ``infer_warm_start_seconds``."""
        if buckets is None:
            buckets = tuple(
                b for b in (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)
                if b <= self.max_batch
            )
            if self.max_batch not in buckets:
                buckets += (self.max_batch,)
        else:
            bad = [b for b in buckets if b > self.max_batch]
            if bad:
                raise OversizedBatchError(
                    f"warmup buckets {bad} exceed max_batch={self.max_batch}"
                )
        jobs: list[tuple[str, str | None, int]] = []
        for task in tasks:
            p = pool if task == "features" else None
            execs = (
                ("reconstruct.enc", "reconstruct.dec")
                if task == "reconstruct" and self._enc_cache_size > 0
                else (task,)
            )
            for name in execs:
                jobs.extend((name, p, b) for b in buckets)
        before = sum(self.compile_counts.values())
        t0 = time.perf_counter()
        if workers is None:
            workers = min(4, len(jobs))
        if workers > 1 and len(jobs) > 1:
            with ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix="warmup"
            ) as px:
                list(px.map(lambda j: self._executable(*j), jobs))
        else:
            for j in jobs:
                self._executable(*j)
        self._m_warm_start.set(time.perf_counter() - t0)
        return sum(self.compile_counts.values()) - before

    # -------------------------------------------------------------- predict

    def _dispatch(self, task: str, pool: str | None, bucket: int, args, n: int):
        """Run one padded bucket through its executable; slice valid rows
        and fold the compute/fetch split into the thread-local breakdown."""
        t = self._task(self._base_task(task))
        t_compute = time.perf_counter()
        out = self._executable(task, pool, bucket)(t["variables"], *args)
        # block here so compute vs fetch split cleanly: dispatch+execution
        # ends at block_until_ready; what follows is device→host copy
        jax.block_until_ready(out)
        t_fetch = time.perf_counter()
        out = jax.tree_util.tree_map(lambda a: np.asarray(a)[:n], out)
        bd = self._tls.bd
        bd["compute_s"] += t_fetch - t_compute
        bd["fetch_s"] += time.perf_counter() - t_fetch
        bd["bucket"] = max(bd["bucket"], bucket)
        bd["pad_rows"] += bucket - n
        bd["bucket_rows"] += bucket
        # predicted-vs-measured drift: prediction precomputed at compile
        # time, so the hot path pays one dict lookup + one gauge set
        pred = self._pred_s.get((self._task_key(task, pool), bucket))
        if pred:
            self._m_drift.labels(f"{self._task_key(task, pool)}/b{bucket}").set(
                (t_fetch - t_compute) / pred
            )
        return out

    @staticmethod
    def _pad_rows(arr: np.ndarray, bucket: int) -> np.ndarray:
        n = arr.shape[0]
        if n == bucket:
            return arr
        pad = np.zeros((bucket - n, *arr.shape[1:]), arr.dtype)
        return np.concatenate([arr, pad])

    def _run(self, task: str, pool: str | None, images: np.ndarray, extra=()):
        """Bucket-pad one image chunk (len <= max_batch), run, slice."""
        n = images.shape[0]
        bucket = bucket_for(n, self.max_batch)
        self._m_pad.observe((bucket - n) / bucket)
        return self._dispatch(
            task, pool, bucket, (self._pad_rows(images, bucket), *extra), n
        )

    def _run_decode(self, tokens, mask, ids):
        """Bucket-pad one decode chunk (cached encoder outputs) and run the
        decode executable. Zero-padded rows are inert: every decode op is
        row-independent (see ``_mae_decode``)."""
        n = tokens.shape[0]
        bucket = bucket_for(n, self.max_batch)
        self._m_pad.observe((bucket - n) / bucket)
        args = (
            self._pad_rows(tokens, bucket),
            self._pad_rows(mask, bucket),
            self._pad_rows(ids, bucket),
        )
        return self._dispatch("reconstruct.dec", None, bucket, args, n)

    def last_breakdown(self) -> dict | None:
        """The compute/fetch/bucket/pad breakdown of the most recent predict
        *on the calling thread* (``None`` before any). This is the
        ``RequestTracer(breakdown=...)`` feed: the micro-batcher's collector
        thread calls predict and reads this right after, so the value can't
        be clobbered by a concurrent caller."""
        bd = getattr(self._tls, "bd", None)
        if bd is None:
            return None
        rows = bd["bucket_rows"]
        return {
            "compute_s": bd["compute_s"],
            "fetch_s": bd["fetch_s"],
            "bucket": bd["bucket"],
            "pad_fraction": (bd["pad_rows"] / rows) if rows else 0.0,
        }

    def _check_images(self, images) -> np.ndarray:
        images = np.asarray(images)
        if images.ndim == 3:
            images = images[None]
        if images.ndim != 4 or images.shape[-1] != 3:
            raise ValueError(f"expected (n, H, W, 3) uint8 images, got {images.shape}")
        if images.shape[1] != self.image_size or images.shape[2] != self.image_size:
            raise ResolutionMismatchError(
                self.image_size, (images.shape[1], images.shape[2])
            )
        return images.astype(np.uint8, copy=False)

    def _reset_breakdown(self):
        self._tls.bd = {
            "compute_s": 0.0, "fetch_s": 0.0,
            "bucket": 0, "pad_rows": 0, "bucket_rows": 0,
        }

    def _predict(self, task: str, images, *, pool=None, extra=()):
        t0 = time.perf_counter()
        self._reset_breakdown()
        images = self._check_images(images)
        chunks = [
            self._run(task, pool, images[i : i + self.max_batch], extra)
            for i in range(0, images.shape[0], self.max_batch)
        ]
        out = (
            chunks[0]
            if len(chunks) == 1
            else jax.tree_util.tree_map(lambda *xs: np.concatenate(xs), *chunks)
        )
        self._m_predict.labels(task).observe(time.perf_counter() - t0)
        self._m_images.labels(task).inc(images.shape[0])
        return out

    def features(self, images, *, pool: str = "cls") -> np.ndarray:
        """Pooled (or full-token) float32 encoder features, one row per
        input image."""
        if pool not in POOLS:
            raise ValueError(f"pool must be one of {POOLS}, got {pool!r}")
        return self._predict("features", images, pool=pool)

    def logits(self, images) -> np.ndarray:
        """Float32 classification logits through the trained head."""
        return self._predict("logits", images)

    def reconstruct(self, images, *, seed: int = 0) -> dict[str, np.ndarray]:
        """MAE reconstruction: ``{"reconstruction": (n, N, p*p*3), "mask":
        (n, N)}`` in (possibly norm-pix) patch space — same contract as
        ``tools/reconstruct.py``. ``seed`` varies the mask without
        recompiling (traced scalar). With ``encoder_cache`` enabled the
        encoder runs once per distinct (image, seed); repeats pay only the
        light decoder."""
        if self._enc_cache_size > 0:
            return self._reconstruct_cached(images, int(seed))
        return self._predict(
            "reconstruct", images, extra=(jnp.asarray(seed, jnp.int32),)
        )

    @staticmethod
    def _row_nbytes(row: tuple) -> int:
        """Payload bytes of one cached (tokens, mask, ids) row."""
        return sum(int(getattr(a, "nbytes", 0)) for a in row)

    def encoder_cache_stats(self) -> dict:
        with self._enc_cache_lock:
            size = len(self._enc_cache)
            nbytes = self._enc_cache_nbytes
        return {
            "capacity": self._enc_cache_size,
            "capacity_bytes": self._enc_cache_bytes_cap,
            "size": size,
            "bytes": nbytes,
            "hits": self.encoder_cache_hits,
            "misses": self.encoder_cache_misses,
        }

    def encoder_cache_bytes(self) -> int:
        """Resident payload bytes of the encoder-output LRU — the memory
        accountant's ``engine_enc_cache`` component probe."""
        with self._enc_cache_lock:
            return self._enc_cache_nbytes

    def executable_cache_bytes(self) -> int:
        """Sum of known serialized sizes of resident executables — the
        accountant's ``engine_exec_cache`` probe. Sizes come from warmcache
        serialization; a compiled-but-never-serialized executable (warmcache
        off) contributes 0 rather than guessing."""
        return sum(self._exec_nbytes.values())

    def predicted_peak_hbm(self) -> dict[str, float]:
        """XLA-predicted peak HBM bytes per compiled program
        (``task/b<bucket>`` keys) — feeds the serving-side
        ``mem_hbm_predict_vs_measured`` drift gauge via
        ``MemoryWatcher.record_predicted_peak``."""
        return {
            f"{k[0]}/b{k[1]}": float(c.peak_bytes)
            for k, c in self.cost_reports.items()
            if getattr(c, "peak_bytes", 0)
        }

    def _reconstruct_cached(self, images, seed: int) -> dict[str, np.ndarray]:
        """Encoder-once/decode-many reconstruction. The LRU key is the raw
        image bytes + mask seed: the mask draw depends on exactly (seed,
        position-in-batch-independent PRNG), so a cached encoder output is
        bit-identical to recomputing it — the cache can never change a
        result, only skip work."""
        t0 = time.perf_counter()
        self._reset_breakdown()
        images = self._check_images(images)
        n = images.shape[0]
        keys = [
            hashlib.sha1(images[i].tobytes()).hexdigest() + f":{seed}"
            for i in range(n)
        ]
        rows: list[tuple | None] = [None] * n
        miss_idx: dict[str, list[int]] = {}
        with self._enc_cache_lock:
            for i, k in enumerate(keys):
                hit = self._enc_cache.get(k)
                if hit is not None:
                    self._enc_cache.move_to_end(k)
                    rows[i] = hit
                else:
                    # dedupe within the batch: one encode per distinct image
                    miss_idx.setdefault(k, []).append(i)
        hits = n - sum(len(v) for v in miss_idx.values())
        self.encoder_cache_hits += hits
        self.encoder_cache_misses += len(miss_idx)
        if hits:
            self._m_enc_cache.labels("hit").inc(hits)
        if miss_idx:
            self._m_enc_cache.labels("miss").inc(len(miss_idx))
            miss_images = np.stack(
                [images[idxs[0]] for idxs in miss_idx.values()]
            )
            extra = (jnp.asarray(seed, jnp.int32),)
            parts = [
                self._run(
                    "reconstruct.enc",
                    None,
                    miss_images[i : i + self.max_batch],
                    extra,
                )
                for i in range(0, miss_images.shape[0], self.max_batch)
            ]
            tokens, mask, ids = (
                parts[0]
                if len(parts) == 1
                else tuple(
                    np.concatenate([p[j] for p in parts]) for j in range(3)
                )
            )
            with self._enc_cache_lock:
                for j, (k, idxs) in enumerate(miss_idx.items()):
                    row = (tokens[j], mask[j], ids[j])
                    for i in idxs:
                        rows[i] = row
                    if k not in self._enc_cache:
                        self._enc_cache_nbytes += self._row_nbytes(row)
                    self._enc_cache[k] = row
                    self._enc_cache.move_to_end(k)
                # two bounds, one loop: entry count (historical) and, when
                # configured, resident bytes — whichever trips first evicts
                while self._enc_cache and (
                    len(self._enc_cache) > self._enc_cache_size
                    or (
                        self._enc_cache_bytes_cap > 0
                        and self._enc_cache_nbytes > self._enc_cache_bytes_cap
                    )
                ):
                    _, old = self._enc_cache.popitem(last=False)
                    self._enc_cache_nbytes -= self._row_nbytes(old)
                    self._m_enc_cache.labels("evict").inc()
                self._m_enc_cache_bytes.set(self._enc_cache_nbytes)
        tokens = np.stack([r[0] for r in rows])
        mask = np.stack([r[1] for r in rows])
        ids = np.stack([r[2] for r in rows])
        chunks = [
            self._run_decode(
                tokens[i : i + self.max_batch],
                mask[i : i + self.max_batch],
                ids[i : i + self.max_batch],
            )
            for i in range(0, n, self.max_batch)
        ]
        out = (
            chunks[0]
            if len(chunks) == 1
            else jax.tree_util.tree_map(lambda *xs: np.concatenate(xs), *chunks)
        )
        self._m_predict.labels("reconstruct").observe(time.perf_counter() - t0)
        self._m_images.labels("reconstruct").inc(n)
        return out

    def predict(self, images, task: str = "features", **kw):
        if task == "features":
            return self.features(images, **kw)
        if task == "logits":
            return self.logits(images, **kw)
        if task == "reconstruct":
            return self.reconstruct(images, **kw)
        raise ValueError(f"unknown task {task!r}")

    # ------------------------------------------------- token-packed serving

    def seq_len(self, size: int) -> int:
        """Token count of one packed request at a square resolution:
        ``num_cls_tokens + (size/patch)²``. Raises on non-patch-aligned
        sizes — packing plans in whole patch tokens."""
        p = self._enc.patch_size
        size = int(size)
        if size < p or size % p:
            raise ValueError(
                f"image size {size} is not a positive multiple of "
                f"patch_size={p} — packed serving needs patch-aligned inputs"
            )
        return self._enc.num_cls_tokens + (size // p) ** 2

    def _check_packed_request(self, imgs: list, task_list: list) -> list[int]:
        """Validate a packed request mix; returns per-request token counts."""
        lengths = []
        for i, im in enumerate(imgs):
            if im.ndim != 3 or im.shape[-1] != 3:
                raise ValueError(
                    f"packed request {i}: expected one (H, W, 3) uint8 "
                    f"image, got {im.shape}"
                )
            h, w = int(im.shape[0]), int(im.shape[1])
            if h != w:
                raise ValueError(
                    f"packed request {i}: expected a square image, got "
                    f"{h}x{w}"
                )
            if h != self.image_size and self._enc.posemb != "sincos2d":
                raise ValueError(
                    f"packed request {i} is {h}px but the engine's native "
                    f"size is {self.image_size}px and posemb="
                    f"{self._enc.posemb!r} is resolution-locked — serve "
                    f"mixed resolutions with posemb='sincos2d'"
                )
            lengths.append(self.seq_len(h))
        bad = sorted({t for t in task_list if t not in ("features", "logits")})
        if bad:
            raise ValueError(
                f"packed serving covers the encoder-sharing tasks "
                f"features/logits; got {bad}"
            )
        return lengths

    def _embed_requests(
        self, imgs: list, tree_task: str
    ) -> list[np.ndarray]:
        """Stage 1 of the packed pipeline: per-resolution patch embedding
        (image-count-bucketed executables), one (n_patches, dim) float32
        token array per request."""
        patch_tokens: list = [None] * len(imgs)
        by_res: dict[int, list[int]] = {}
        for i, im in enumerate(imgs):
            by_res.setdefault(int(im.shape[0]), []).append(i)
        for res, idxs in sorted(by_res.items()):
            stack = np.stack([imgs[i] for i in idxs]).astype(np.uint8, copy=False)
            for off in range(0, len(idxs), self.max_batch):
                out = self._run(
                    f"{tree_task}.embed@{res}",
                    None,
                    stack[off : off + self.max_batch],
                )
                for j, i_req in enumerate(idxs[off : off + self.max_batch]):
                    patch_tokens[i_req] = out[j]
        return patch_tokens

    def predict_packed(
        self,
        images,
        tasks="features",
        *,
        pool: str = "cls",
        max_tokens: int | None = None,
    ) -> list[np.ndarray]:
        """Serve a mixed-resolution, mixed-task request list through ONE
        token-packed dispatch instead of one padded image bucket per
        ``(task, shape)``.

        ``images`` is a list of square, patch-aligned ``(H, W, 3)`` uint8
        arrays (224–896px etc. — any patch multiple; non-native sizes need
        ``posemb='sincos2d'``). ``tasks`` is one task name or one per
        request, from ``features``/``logits`` — the encoder-sharing pair
        that can ride one executable (when any request wants logits, the
        whole pack runs on the logits task's tree, whose encoder is the
        same grafted checkpoint). Returns one float32 row per request, in
        request order.

        Pipeline: per-resolution patch embedding (stage 1, image-count
        buckets) → deterministic FFD pack of the token segments into a
        power-of-2 token-budget rung (``infer/packing.py``) → one packed
        executable keyed by (rows, max_segments, budget). Pad tokens are
        provably inert (block-diagonal segment attention), and
        ``last_breakdown().pad_fraction`` reports the *token*-level pad of
        the packed dispatch — the costmeter bills waste from it.
        """
        if pool not in ("cls", "gap"):
            raise ValueError(
                f"packed serving pools per segment: pool must be 'cls' or "
                f"'gap', got {pool!r}"
            )
        imgs = [np.asarray(im) for im in images]
        n = len(imgs)
        if n == 0:
            return []
        task_list = [tasks] * n if isinstance(tasks, str) else list(tasks)
        if len(task_list) != n:
            raise ValueError(
                f"{n} images but {len(task_list)} tasks — pass one task "
                f"name or one per request"
            )
        lengths = self._check_packed_request(imgs, task_list)
        tree_task = (
            "logits" if any(t == "logits" for t in task_list) else "features"
        )

        t0 = time.perf_counter()
        self._reset_breakdown()
        patch_tokens = self._embed_requests(imgs, tree_task)
        # stage-1 image buckets are tiny next to the packed dispatch; reset
        # the pad accounting so last_breakdown() reports the packed
        # dispatch's TOKEN pad fraction (compute/fetch keep accumulating)
        self._tls.bd["pad_rows"] = 0
        self._tls.bd["bucket_rows"] = 0

        k = self._enc.num_cls_tokens
        rungs = packing.budget_rungs(int(max_tokens or self.max_tokens))
        budget, plan = packing.choose_budget(lengths, rungs)
        rows_b = ceil_pow2(plan.rows)
        smax_b = ceil_pow2(plan.max_segments)
        arrays = packing.build_arrays(plan, k, rows=rows_b, max_segments=smax_b)
        buf = packing.place_tokens(plan, patch_tokens, k, rows=rows_b)

        task_key = f"{tree_task}.packed:{pool}@r{rows_b}s{smax_b}"
        ex = self._executable(task_key, None, budget)
        t = self._task(tree_task)
        t_compute = time.perf_counter()
        out = ex(
            t["variables"],
            buf,
            arrays["segment_ids"],
            arrays["cls_pos"],
            arrays["cls_index"],
        )
        jax.block_until_ready(out)
        t_fetch = time.perf_counter()
        out = jax.tree_util.tree_map(np.asarray, out)
        bd = self._tls.bd
        bd["compute_s"] += t_fetch - t_compute
        bd["fetch_s"] += time.perf_counter() - t_fetch
        device_tokens = rows_b * budget
        total_tokens = plan.total_tokens
        bd["bucket"] = max(bd["bucket"], budget)
        bd["pad_rows"] += device_tokens - total_tokens
        bd["bucket_rows"] += device_tokens
        pred = self._pred_s.get((task_key, budget))
        if pred:
            self._m_drift.labels(f"{task_key}/b{budget}").set(
                (t_fetch - t_compute) / pred
            )

        self._m_pack_pad.observe((device_tokens - total_tokens) / device_tokens)
        self._m_pack_segments.observe(len(plan.segments))
        self._m_pack_occ.observe(total_tokens / device_tokens)
        self._m_pack_dispatches.labels(tree_task).inc()
        self._m_predict.labels("packed").observe(time.perf_counter() - t0)
        self._m_images.labels("packed").inc(n)

        pooled = packing.unpack_rows(plan, out["pooled"])
        logits = (
            packing.unpack_rows(plan, out["logits"]) if "logits" in out else None
        )
        return [
            logits[i] if task_list[i] == "logits" else pooled[i]
            for i in range(n)
        ]

    def packed_parity(
        self,
        images,
        tasks="features",
        *,
        pool: str = "cls",
        max_tokens: int | None = None,
        feature_cos_min: float = 0.999,
        logits_top1_min: float = 0.98,
    ) -> dict:
        """Per-request numeric parity of the packed path against the
        unpacked forward on the SAME task tree — the packed rollout's
        correctness gate (same thresholds as the int8 quant gate:
        feature cosine >= 0.999, logits top-1 agreement >= 0.98)."""
        imgs = [np.asarray(im) for im in images]
        n = len(imgs)
        task_list = [tasks] * n if isinstance(tasks, str) else list(tasks)
        packed = self.predict_packed(
            imgs, task_list, pool=pool, max_tokens=max_tokens
        )
        tree_task = (
            "logits" if any(t == "logits" for t in task_list) else "features"
        )
        ref_pooled: list = [None] * n
        ref_logits: list = [None] * n
        by_res: dict[int, list[int]] = {}
        for i, im in enumerate(imgs):
            by_res.setdefault(int(im.shape[0]), []).append(i)
        self._reset_breakdown()
        for res, idxs in sorted(by_res.items()):
            stack = np.stack([imgs[i] for i in idxs]).astype(np.uint8, copy=False)
            for off in range(0, len(idxs), self.max_batch):
                out = self._run(
                    f"{tree_task}.full:{pool}@{res}",
                    None,
                    stack[off : off + self.max_batch],
                )
                for j, i_req in enumerate(idxs[off : off + self.max_batch]):
                    ref_pooled[i_req] = out["pooled"][j]
                    if "logits" in out:
                        ref_logits[i_req] = out["logits"][j]
        cosines: list[float] = []
        top1: list[int] = []
        rows = []
        for i in range(n):
            if task_list[i] == "logits":
                agree = int(np.argmax(packed[i]) == np.argmax(ref_logits[i]))
                top1.append(agree)
                rows.append({"task": "logits", "top1_agree": agree})
            else:
                a = packed[i].ravel().astype(np.float64)
                b = ref_pooled[i].ravel().astype(np.float64)
                denom = np.linalg.norm(a) * np.linalg.norm(b)
                cos = float(a @ b / denom) if denom else 1.0
                cosines.append(cos)
                rows.append({"task": "features", "cosine": round(cos, 6)})
        cos_min = min(cosines) if cosines else None
        top1_agree = float(np.mean(top1)) if top1 else None
        ok = (cos_min is None or cos_min >= feature_cos_min) and (
            top1_agree is None or top1_agree >= logits_top1_min
        )
        self._m_pack_parity.set(cos_min if cos_min is not None else 1.0)
        if not ok:
            self._m_pack_parity_fail.inc()
        return {
            "n": n,
            "pool": pool,
            "feature_cosine_min": cos_min,
            "logits_top1_agree": top1_agree,
            "feature_cos_threshold": feature_cos_min,
            "logits_top1_threshold": logits_top1_min,
            "pass": ok,
            "requests": rows,
        }

    def warmup_packed(
        self,
        resolutions,
        tasks: tuple[str, ...] = ("features",),
        *,
        pool: str = "cls",
        max_tokens: int | None = None,
    ) -> int:
        """Precompile the packed path for a representative resolution mix:
        each resolution's embed executable plus the packed executable the
        mix's FFD plan lands on. Returns compiles performed (warmcache
        loads are free, same contract as :meth:`warmup`)."""
        resolutions = [int(r) for r in resolutions]
        if not resolutions:
            return 0
        tree_task = "logits" if "logits" in tuple(tasks) else "features"
        lengths = [self.seq_len(r) for r in resolutions]
        rungs = packing.budget_rungs(int(max_tokens or self.max_tokens))
        budget, plan = packing.choose_budget(lengths, rungs)
        rows_b = ceil_pow2(plan.rows)
        smax_b = ceil_pow2(plan.max_segments)
        before = sum(self.compile_counts.values())
        t0 = time.perf_counter()
        counts: dict[int, int] = {}
        for r in resolutions:
            counts[r] = counts.get(r, 0) + 1
        for res, cnt in sorted(counts.items()):
            self._executable(
                f"{tree_task}.embed@{res}",
                None,
                bucket_for(min(cnt, self.max_batch), self.max_batch),
            )
        self._executable(
            f"{tree_task}.packed:{pool}@r{rows_b}s{smax_b}", None, budget
        )
        self._m_warm_start.set(time.perf_counter() - t0)
        return sum(self.compile_counts.values()) - before
