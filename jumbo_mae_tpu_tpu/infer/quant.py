"""Int8 weight-only post-training quantization for the serving forward.

PERF.md's per-op accounting puts the serving-relevant shapes in the
weight-HBM-bandwidth-bound regime at small batch: every request streams the
full parameter set through the MXU once, so halving parameter bytes halves
the dominant term. This module converts a restored f32 params tree into
int8 matmul kernels with per-output-channel f32 scales:

- **What quantizes.** Leaves named ``kernel`` with ndim >= 2 — the patch
  embedding conv, q/k/v/out attention projections, MLP fc1/fc2, the head,
  and the decoder stack. Everything else (positional embeddings, CLS/mask
  tokens, LayerNorm scales, biases, BatchNorm statistics) stays f32: those
  are a rounding error of the byte budget and quantizing them buys nothing.
- **How.** Symmetric per-output-channel scaling: ``scale = max|w| / 127``
  over the reduction axes (the axes the matmul contracts away), so each
  output channel keeps its own dynamic range and a single outlier channel
  cannot crush the resolution of the rest. Zero-max channels get scale 1
  (they dequantize to exact zeros).
- **Dequant-on-use.** :class:`QuantizedTensor` is a registered pytree node,
  so the quantized tree is passed straight into the jitted forward as an
  argument — int8 weights are what lives in HBM and what the executable
  reads; the ``int8 -> f32 multiply`` runs on-chip where it fuses into the
  consumer. Dequantization reproduces ``q * scale`` exactly in f32, so the
  quantized forward is as deterministic (and as row-independent — the
  padding-inertness contract survives) as the f32 one.

Parity is measured, never assumed: :func:`parity_report` runs the same
images through a reference and a quantized engine and reports feature
cosine / logits top-1 agreement against the stated tolerances below —
``tools/bench_infer.py`` embeds the report in its JSON and CI gates on it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# Stated parity tolerances (README "Quantized serving"): measured on the
# CPU smoke model (bench_infer --quant-leg) and asserted by CI; chip-side
# recipes re-measure with the same report before a quantized rollout.
FEATURE_COSINE_MIN = 0.999
TOP1_AGREEMENT_MIN = 0.98

_QKV = ("q", "k", "v")


@jax.tree_util.register_pytree_node_class
class QuantizedTensor:
    """An int8 weight plus its per-output-channel f32 scale.

    Registered as a pytree node so jit/AOT treat it as two leaves — the
    int8 payload is the device-resident form; nothing f32-sized survives
    quantization. ``scale`` keeps reduced axes as size-1 dims so
    ``q * scale`` broadcasts back to the weight's shape.
    """

    __slots__ = ("q", "scale")

    def __init__(self, q, scale):
        self.q = q
        self.scale = scale

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    def dequantize(self, dtype=jnp.float32):
        """Exact ``q * scale`` in f32, then cast — inside a jitted forward
        the multiply fuses into the consuming matmul's operand read."""
        w = self.q.astype(jnp.float32) * self.scale
        return w.astype(dtype)

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        del aux
        return cls(*children)

    def __repr__(self):
        return f"QuantizedTensor(shape={tuple(self.q.shape)}, int8+f32scale)"


def is_quantized(x) -> bool:
    return isinstance(x, QuantizedTensor)


def _key_name(entry) -> str:
    # DictKey(.key) for dicts, GetAttrKey(.name) for dataclasses/modules
    return str(getattr(entry, "key", getattr(entry, "name", entry)))


def _reduction_axes(names: list[str], ndim: int) -> tuple[int, ...]:
    """The axes a matmul contracts away, i.e. everything except the output
    channels. DenseGeneral q/k/v kernels are (dim, heads, head_dim) — the
    output is the trailing (heads, head_dim) pair; every other kernel
    (Dense 2-D, attention out 3-D, Conv 4-D) has output as the last axis."""
    if ndim >= 3 and len(names) >= 2 and names[-2] in _QKV:
        return tuple(range(ndim - 2))
    return tuple(range(ndim - 1))


def quantize_tensor(w, axes: tuple[int, ...]) -> QuantizedTensor:
    """Symmetric int8 quantization of one weight over ``axes``."""
    w = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=axes, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return QuantizedTensor(q, scale)


def quantize_params(params) -> tuple[dict, dict]:
    """Walk a params tree; return ``(quantized_tree, report)``.

    The tree keeps its structure — matmul kernels become
    :class:`QuantizedTensor` leaves, everything else passes through
    untouched. ``report`` accounts for what happened: leaf counts, byte
    totals before/after, and the compression ratio (the number the
    bandwidth model converts into step-time)."""
    report = {
        "n_quantized": 0,
        "n_kept": 0,
        "bytes_before": 0,
        "bytes_after": 0,
    }

    def visit(path, leaf):
        names = [_key_name(p) for p in path]
        if is_quantized(leaf):
            raise ValueError(
                f"{'/'.join(names)} is already quantized — quantize_params "
                "expects an f32 params tree, not its own output"
            )
        arr = np.asarray(leaf)
        nbytes = int(arr.size * arr.dtype.itemsize)
        report["bytes_before"] += nbytes
        if names and names[-1] == "kernel" and arr.ndim >= 2:
            qt = quantize_tensor(leaf, _reduction_axes(names, arr.ndim))
            report["n_quantized"] += 1
            report["bytes_after"] += int(
                qt.q.size * 1 + qt.scale.size * qt.scale.dtype.itemsize
            )
            return qt
        report["n_kept"] += 1
        report["bytes_after"] += nbytes
        return leaf

    qtree = jax.tree_util.tree_map_with_path(visit, params, is_leaf=is_quantized)
    report["compression"] = round(
        report["bytes_before"] / max(report["bytes_after"], 1), 3
    )
    return qtree, report


def dequantize_tree(tree, dtype=jnp.float32):
    """Map :meth:`QuantizedTensor.dequantize` over a (possibly mixed) tree.
    Called at the top of the jitted forward: the executable's *arguments*
    stay int8; the f32 view exists only as fused intermediates."""
    return jax.tree_util.tree_map(
        lambda x: x.dequantize(dtype) if is_quantized(x) else x,
        tree,
        is_leaf=is_quantized,
    )


# ------------------------------------------------------------------ parity


def feature_cosine(a, b) -> np.ndarray:
    """Per-row cosine similarity between two feature matrices."""
    a = np.asarray(a, np.float64).reshape(len(a), -1)
    b = np.asarray(b, np.float64).reshape(len(b), -1)
    num = (a * b).sum(axis=1)
    den = np.linalg.norm(a, axis=1) * np.linalg.norm(b, axis=1)
    return num / np.maximum(den, 1e-12)


def top1_agreement(logits_a, logits_b) -> float:
    a = np.asarray(logits_a)
    b = np.asarray(logits_b)
    return float((a.argmax(-1) == b.argmax(-1)).mean())


def parity_report(
    reference,
    quantized,
    images,
    *,
    task: str = "features",
    pool: str = "cls",
    registry=None,
) -> dict:
    """Measure quantization parity on real traffic: the same images through
    a reference engine and a quantized engine.

    ``features``: per-image cosine between pooled embeddings (min and mean)
    against :data:`FEATURE_COSINE_MIN`. ``logits``: top-1 agreement against
    :data:`TOP1_AGREEMENT_MIN`, plus the max absolute logit delta for
    context. The verdict lands in ``within_tolerance`` and, when a metrics
    registry is live, in the ``infer_quant_parity`` gauge family.
    """
    if task not in ("features", "logits"):
        raise ValueError(f"parity is defined for features/logits, got {task!r}")
    rep: dict = {"task": task, "images": int(np.asarray(images).shape[0])}
    if task == "features":
        ref = reference.features(images, pool=pool)
        q = quantized.features(images, pool=pool)
        cos = feature_cosine(ref, q)
        rep.update(
            cosine_min=round(float(cos.min()), 6),
            cosine_mean=round(float(cos.mean()), 6),
            tolerance={"cosine_min": FEATURE_COSINE_MIN},
        )
        rep["within_tolerance"] = rep["cosine_min"] >= FEATURE_COSINE_MIN
    else:
        ref = reference.logits(images)
        q = quantized.logits(images)
        rep.update(
            top1_agreement=round(top1_agreement(ref, q), 6),
            max_abs_logit_delta=round(float(np.abs(ref - q).max()), 6),
            tolerance={"top1_agreement": TOP1_AGREEMENT_MIN},
        )
        rep["within_tolerance"] = rep["top1_agreement"] >= TOP1_AGREEMENT_MIN
    if registry is None:
        from jumbo_mae_tpu_tpu.obs.metrics import get_registry

        registry = get_registry()
    gauge = registry.gauge(
        "infer_quant_parity",
        "quantized-vs-reference parity measurements",
        labels=("metric",),
    )
    for name in ("cosine_min", "cosine_mean", "top1_agreement"):
        if name in rep:
            gauge.labels(name).set(rep[name])
    gauge.labels("within_tolerance").set(1.0 if rep["within_tolerance"] else 0.0)
    return rep
