"""Persistent, crash-safe cache of AOT-compiled serving executables.

A serving replica's startup cost is the per-(task, bucket) compile ladder —
seconds on CPU smoke, minutes for a real encoder at a full bucket set. The
engine already guarantees the *request path* never compiles; this module
makes the *warmup* free after the first process on a host: compiled
executables are serialized (``jax.experimental.serialize_executable``) to a
versioned on-disk cache and restarted replicas load them instead of
compiling.

Design constraints, in order:

- **A corrupt entry must never crash the process.** The seed's history
  documents XLA:CPU aborting the whole process deserializing a truncated
  cache entry (jax's internal compilation cache writes non-atomically; a
  ``timeout -k``'d test run poisoned it permanently — see
  ``utils/procenv.claim_compile_cache``). Here a sha256 digest over the
  payload is verified *before* any bytes reach XLA, writes are atomic
  (unique tmp + ``os.replace``), and any entry that fails the header,
  digest, unpickle, or XLA load is moved to ``quarantine/`` — kept for a
  postmortem, never retried.
- **Keyed so reuse is provably safe.** The entry name carries the model
  fingerprint (every architecture/config field the traced program depends
  on, plus jax/jaxlib versions, backend, and the host CPU fingerprint —
  XLA:CPU executables embed machine features), the task, the bucket, the
  compute dtype, and the quant mode. Parameters are executable *arguments*,
  not constants, so different checkpoints of the same architecture share
  entries by construction — the engine keeps anything value-dependent
  (BatchNorm stats included) out of closure constants.
- **Concurrent processes race safely.** Writers use per-process unique tmp
  names; ``os.replace`` is atomic, last-writer-wins, and readers see either
  a complete old entry or a complete new one, never a partial write.

``python -m jumbo_mae_tpu_tpu.infer.warmcache`` is the restart probe: it
builds an engine against a cache dir, warms it, runs a hot-path batch, and
prints one JSON line with compile/hit counts and timings — bench_infer's
cold/warm A/B and CI's restart-reuses-warmcache assertion both drive it.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import re
import sys
import time
import uuid
from pathlib import Path

from jumbo_mae_tpu_tpu.obs.journal import fsync_dir

# format version is part of MAGIC: bump it and every older entry misses
# cleanly (no attempt to parse an incompatible layout)
MAGIC = b"JWC1"
_DIGEST_LEN = 32  # sha256


def fingerprint(spec: dict) -> str:
    """Stable short hash of a JSON-able spec dict (the engine feeds every
    compile-relevant config field through this)."""
    blob = json.dumps(spec, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def entry_name(
    fp: str, task_key: str, bucket: int, dtype: str, quant: str | None
) -> str:
    """Filesystem-safe cache entry name — the (fingerprint, task, bucket,
    dtype, quant) key schema README documents."""
    safe = lambda s: re.sub(r"[^A-Za-z0-9_.-]", "_", str(s))  # noqa: E731
    return (
        f"{safe(fp)}-{safe(task_key)}-b{int(bucket)}"
        f"-{safe(dtype)}-{safe(quant or 'none')}.exe"
    )


class WarmCache:
    """One directory of serialized executables, with quarantine semantics.

    All failure paths degrade to a miss: the caller compiles as if the
    cache were cold. ``stats()`` plus the ``infer_warmcache_*`` counters
    expose what actually happened.

    ``quarantine/`` is bounded: entries beyond ``quarantine_keep`` (newest
    kept) or older than ``quarantine_max_age_s`` are deleted when the cache
    directory is claimed (construction) and after each new quarantine — a
    crash-looping replica that corrupts an entry per restart must not fill
    the disk with postmortem copies.
    """

    def __init__(
        self,
        root: str | os.PathLike,
        *,
        registry=None,
        quarantine_keep: int = 32,
        quarantine_max_age_s: float = 7 * 24 * 3600.0,
        cache_max_bytes: int = 0,
    ):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        if registry is None:
            from jumbo_mae_tpu_tpu.obs.metrics import get_registry

            registry = get_registry()
        self._m = registry.counter(
            "infer_warmcache_events_total",
            "warm-start executable cache events",
            labels=("event",),
        )
        self._m_pruned = registry.counter(
            "infer_warmcache_quarantine_pruned_total",
            "quarantined entries deleted by the count/age cap",
        )
        self._m_disk = registry.gauge(
            "infer_warmcache_disk_bytes",
            "on-disk bytes of main-dir cache entries + sidecars",
        )
        self.quarantine_keep = int(quarantine_keep)
        self.quarantine_max_age_s = float(quarantine_max_age_s)
        # optional byte bound on the MAIN dir (quarantine has its own
        # count/age cap): oldest-mtime entries and their sidecars are
        # deleted until the footprint fits. 0 = unbounded (historical).
        self.cache_max_bytes = int(cache_max_bytes)
        self.hits = 0
        self.misses = 0
        self.puts = 0
        self.put_errors = 0
        self.quarantined = 0
        self.quarantine_pruned = 0
        self.main_pruned = 0
        # claim-time sweep: whoever opens the cache dir pays the prune, so
        # the bound holds even if every previous process crashed mid-flight
        self._prune_quarantine()
        self._prune_main()

    # ------------------------------------------------------------------ io

    def get(self, name: str):
        """Load one executable, or None (miss / quarantined corrupt entry)."""
        path = self.root / name
        try:
            blob = path.read_bytes()
        except OSError:
            self.misses += 1
            self._m.labels("miss").inc()
            return None
        try:
            if len(blob) < len(MAGIC) + _DIGEST_LEN or blob[: len(MAGIC)] != MAGIC:
                raise ValueError("bad magic/header")
            digest = blob[len(MAGIC) : len(MAGIC) + _DIGEST_LEN]
            payload = blob[len(MAGIC) + _DIGEST_LEN :]
            if hashlib.sha256(payload).digest() != digest:
                raise ValueError("payload digest mismatch (truncated write?)")
            # the pickled in/out treedefs may reference QuantizedTensor;
            # importing quant registers the pytree node before unpickling
            from jumbo_mae_tpu_tpu.infer import quant as _quant  # noqa: F401
            from jax.experimental.serialize_executable import (
                deserialize_and_load,
            )

            serialized, in_tree, out_tree = pickle.loads(payload)
            ex = deserialize_and_load(serialized, in_tree, out_tree)
        except Exception as e:  # noqa: BLE001 — any corruption is a miss
            self._quarantine(path, e)
            self.misses += 1
            self._m.labels("miss").inc()
            return None
        self.hits += 1
        self._m.labels("hit").inc()
        return ex

    def put(self, name: str, compiled, meta: dict | None = None) -> int:
        """Serialize + atomically publish one executable; best-effort (a
        full disk or an unserializable program must not fail serving).

        Returns the serialized blob size in bytes (0 on failure — callers
        that only care whether the put landed keep working, callers that
        gauge executable size get it for free). ``meta`` lands in a
        ``<name>.meta.json`` sidecar (compile wall time, cost analysis) so
        a warm-started process can credit what the hit saved it."""
        path = self.root / name
        tmp = None
        try:
            from jax.experimental.serialize_executable import serialize

            payload = pickle.dumps(serialize(compiled))
            blob = MAGIC + hashlib.sha256(payload).digest() + payload
            tmp = path.with_name(
                f".{name}.{os.getpid()}.{uuid.uuid4().hex[:8]}.tmp"
            )
            tmp.write_bytes(blob)
            os.replace(tmp, path)
            fsync_dir(self.root)  # rename alone is not durable over power loss
        except Exception as e:  # noqa: BLE001
            if tmp is not None:
                Path(tmp).unlink(missing_ok=True)
            self.put_errors += 1
            self._m.labels("put_error").inc()
            print(f"[warmcache] put({name}) failed: {e}", file=sys.stderr)
            return 0
        self.puts += 1
        self._m.labels("put").inc()
        self._put_meta(name, {"executable_bytes": len(blob), **(meta or {})})
        # re-enforce the byte bound (and refresh the disk gauge) after every
        # publish — the writer pays for its own growth
        self._prune_main()
        return len(blob)

    def _put_meta(self, name: str, meta: dict) -> None:
        """Atomic best-effort sidecar write; a corrupt/missing sidecar only
        loses metadata, never the executable."""
        path = self.root / f"{name}.meta.json"
        tmp = path.with_name(f".{path.name}.{os.getpid()}.{uuid.uuid4().hex[:8]}.tmp")
        try:
            tmp.write_text(json.dumps(meta, sort_keys=True, default=str))
            os.replace(tmp, path)
            fsync_dir(self.root)
        except Exception:  # noqa: BLE001
            Path(tmp).unlink(missing_ok=True)

    def entry_meta(self, name: str) -> dict | None:
        """The ``put()`` metadata sidecar for one entry, or None."""
        try:
            return json.loads((self.root / f"{name}.meta.json").read_text())
        except Exception:  # noqa: BLE001 — metadata is advisory
            return None

    def _quarantine(self, path: Path, err: Exception):
        """Move a bad entry aside — kept for postmortem, never re-read."""
        qdir = self.root / "quarantine"
        dst = qdir / f"{path.name}.{os.getpid()}.{uuid.uuid4().hex[:8]}"
        try:
            qdir.mkdir(exist_ok=True)
            os.replace(path, dst)
            # both directories changed; sync both or a crash can resurrect
            # the corrupt entry under its servable name
            fsync_dir(qdir)
            fsync_dir(self.root)
        except OSError:
            path.unlink(missing_ok=True)
        self.quarantined += 1
        self._m.labels("quarantined").inc()
        print(
            f"[warmcache] quarantined corrupt entry {path.name}: {err}",
            file=sys.stderr,
        )
        self._prune_quarantine()

    def _prune_quarantine(self) -> int:
        """Enforce the quarantine count/age cap; returns entries deleted.
        Newest entries win the count cap — the freshest corruption is the
        one a postmortem wants."""
        qdir = self.root / "quarantine"
        try:
            entries = sorted(
                ((p.stat().st_mtime, p) for p in qdir.iterdir() if p.is_file()),
                reverse=True,
            )
        except OSError:
            return 0
        now = time.time()
        pruned = 0
        for rank, (mtime, path) in enumerate(entries):
            if rank < self.quarantine_keep and now - mtime <= self.quarantine_max_age_s:
                continue
            try:
                path.unlink()
            except OSError:
                continue
            pruned += 1
        if pruned:
            self.quarantine_pruned += pruned
            self._m_pruned.inc(pruned)
        return pruned

    def disk_bytes(self) -> int:
        """Main-dir footprint in bytes (entries + sidecars + in-flight
        tmps; ``quarantine/`` excluded — it has its own count/age bound).
        The memory accountant's ``warmcache_disk`` component probe."""
        total = 0
        try:
            for p in self.root.iterdir():
                if not p.is_file():
                    continue
                try:
                    total += p.stat().st_size
                except OSError:
                    continue
        except OSError:
            return 0
        return total

    def _prune_main(self) -> int:
        """Enforce ``cache_max_bytes`` over the main dir, LRU by mtime:
        oldest entries (and their sidecars) are deleted until the footprint
        fits. Always refreshes ``infer_warmcache_disk_bytes``. Returns
        entries deleted."""
        pruned = 0
        if self.cache_max_bytes > 0:
            try:
                entries = sorted(
                    (p.stat().st_mtime, p) for p in self.root.glob("*.exe")
                )
            except OSError:
                entries = []
            total = self.disk_bytes()
            for _mtime, path in entries:
                if total <= self.cache_max_bytes:
                    break
                for victim in (path, self.root / f"{path.name}.meta.json"):
                    try:
                        size = victim.stat().st_size
                        victim.unlink()
                    except OSError:
                        continue
                    total -= size
                pruned += 1
                self._m.labels("pruned").inc()
            if pruned:
                self.main_pruned += pruned
        self._m_disk.set(self.disk_bytes())
        return pruned

    def stats(self) -> dict:
        return {
            "root": str(self.root),
            "entries": len(list(self.root.glob("*.exe"))),
            "disk_bytes": self.disk_bytes(),
            "hits": self.hits,
            "misses": self.misses,
            "puts": self.puts,
            "put_errors": self.put_errors,
            "quarantined": self.quarantined,
            "quarantine_pruned": self.quarantine_pruned,
            "main_pruned": self.main_pruned,
        }


# ------------------------------------------------------------- restart probe


def _probe_main(argv: list[str] | None = None) -> dict:
    """Restart probe: engine up against ``--dir``, warm, serve one hot batch,
    print a JSON line. Run twice against the same dir to measure cold vs
    warm start; the second run must report ``"compiles": 0``."""
    import argparse
    import time

    p = argparse.ArgumentParser(description=_probe_main.__doc__)
    p.add_argument("--dir", required=True, help="warmcache directory")
    p.add_argument("--recipe", default=None, help="YAML recipe (default: CPU smoke)")
    p.add_argument(
        "--task", choices=("features", "logits", "reconstruct"), default="features"
    )
    p.add_argument("--pool", choices=("cls", "gap", "tokens"), default="cls")
    p.add_argument("--ckpt", default="")
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--buckets", type=int, nargs="*", default=None)
    p.add_argument("--quant", choices=("int8",), default=None)
    p.add_argument("--dtype", default=None)
    p.add_argument("--probe-images", type=int, default=3)
    p.add_argument("--out", default="", help="also write the JSON here")
    p.add_argument(
        "--set", dest="overrides", metavar="KEY.PATH=VALUE",
        nargs="*", action="extend", default=[],
    )
    args = p.parse_args(argv)

    import numpy as np

    from jumbo_mae_tpu_tpu.config import load_config
    from jumbo_mae_tpu_tpu.infer import InferenceEngine

    recipe = args.recipe
    if recipe is None:
        recipe = str(
            Path(__file__).resolve().parents[2] / "recipes" / "smoke_cpu.yaml"
        )
    cfg = load_config(recipe, args.overrides)

    t0 = time.perf_counter()
    engine = InferenceEngine(
        cfg,
        ckpt=args.ckpt,
        dtype=args.dtype,
        max_batch=args.max_batch,
        quant=args.quant,
        warm_cache=args.dir,
    )
    init_s = time.perf_counter() - t0
    t1 = time.perf_counter()
    compiles = engine.warmup(
        (args.task,),
        pool=args.pool,
        buckets=tuple(args.buckets) if args.buckets else None,
    )
    warmup_s = time.perf_counter() - t1
    after_warm = sum(engine.compile_counts.values())
    images = (
        np.random.RandomState(0)
        .randint(0, 256, (args.probe_images, engine.image_size, engine.image_size, 3))
        .astype(np.uint8)
    )
    kw = {"pool": args.pool} if args.task == "features" else {}
    engine.predict(images, task=args.task, **kw)
    report = {
        "probe": "warmcache",
        "dir": args.dir,
        "task": args.task,
        "quant": args.quant,
        "init_s": round(init_s, 3),
        "warmup_s": round(warmup_s, 3),
        "compiles": compiles,
        "warm_hits": sum(engine.warm_hits.values()),
        "hot_path_compiles": sum(engine.compile_counts.values()) - after_warm,
        "executables": len(engine._exec),
        "warmcache": engine.warmcache.stats() if engine.warmcache else None,
    }
    line = json.dumps(report)
    print(line)
    if args.out:
        Path(args.out).write_text(line + "\n")
    return report


if __name__ == "__main__":
    _probe_main()
