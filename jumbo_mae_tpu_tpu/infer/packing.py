"""Token-packing plans: many variable-length requests, one executable.

The image-bucketed serving path pads every dispatch up to a per-``(task,
shape)`` power-of-2 *image* bucket, so mixed-resolution traffic pads each
resolution to its own bucket and the pad rows burn MXU cycles
(`infer_pad_fraction` tells the story per shape; the costmeter bills the
waste). NaViT-style sequence packing (arXiv:2307.06304) recovers that
waste: each request becomes a variable-length *token segment* (its CLS
slots + patch tokens) and segments from different requests — different
resolutions, different tasks sharing the encoder — are packed into the
rows of one fixed ``(rows, token_budget)`` buffer served by one AOT
executable.

This module is the host-side planner; it is pure numpy and fully
deterministic (sorted first-fit-decreasing, ties broken by request index
— same requests, same plan, every time; asserted by
``tests/test_packing.py``). The device-side contract it plans for:

- ``segment_ids`` (rows, budget) int32: ``slot+1`` on every token a
  segment owns, 0 on padding — the block-diagonal attention mask is
  ``same-id AND id>0`` (plus the diagonal, so all-pad rows softmax over
  themselves instead of NaN-ing);
- ``cls_pos`` (rows, budget) int32: ``0..k-1`` on the segment's k leading
  CLS slots, -1 elsewhere — where the encoder injects its ``cls_tokens``
  parameter (exact: this architecture adds posemb to patches only);
- ``cls_index`` (rows, max_segments, k) int32: each slot's CLS token
  coordinates, for the per-segment jumbo-MLP gather/scatter and pooling.

Padding is provably inert in both directions: pad tokens attend only to
themselves (they never read a real token) and real tokens never attend to
pads (mask), so perturbing one segment cannot move any other segment's
output — ``tests/test_packed_model.py`` asserts this bit-exactly.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from jumbo_mae_tpu_tpu.infer.bucketing import bucket_for, pow2_rungs


@dataclass(frozen=True)
class SegmentPlacement:
    """One request's segment inside a pack: ``request`` is the index into
    the caller's request list; ``length`` the segment's token count
    (k CLS slots + patch tokens); ``row``/``slot`` its row and per-row
    segment slot; ``offset`` the row position of its first token."""

    request: int
    length: int
    row: int
    slot: int
    offset: int


@dataclass(frozen=True)
class PackPlan:
    """A deterministic packing of segments into ``rows`` rows of
    ``budget`` tokens. ``max_segments`` is the largest per-row segment
    count (the executable's slot dimension)."""

    budget: int
    rows: int
    max_segments: int
    segments: tuple[SegmentPlacement, ...]

    @property
    def total_tokens(self) -> int:
        return sum(s.length for s in self.segments)

    def pad_fraction(self, rows: int | None = None) -> float:
        """Token pad fraction of the dispatched buffer: padded tokens /
        device tokens, over ``rows`` rows (default: the plan's own —
        pass the row-bucketed count for what the device actually ran)."""
        r = self.rows if rows is None else int(rows)
        dev = r * self.budget
        return (dev - self.total_tokens) / dev if dev else 0.0


def pack_ffd(lengths, budget: int) -> PackPlan:
    """First-fit-decreasing pack of ``lengths`` token segments into rows
    of ``budget`` tokens. Deterministic: segments are placed longest
    first (ties by request index), each into the first row with room.
    A segment longer than the budget is a planning error, not a truncate.
    """
    budget = int(budget)
    if budget < 1:
        raise ValueError(f"need a positive token budget, got {budget}")
    lengths = [int(n) for n in lengths]
    if not lengths:
        return PackPlan(budget=budget, rows=0, max_segments=0, segments=())
    for i, n in enumerate(lengths):
        if n < 1:
            raise ValueError(f"segment {i} has non-positive length {n}")
        if n > budget:
            raise ValueError(
                f"segment {i} needs {n} tokens > budget {budget} — pick a "
                f"larger rung (choose_budget does this automatically)"
            )
    order = sorted(range(len(lengths)), key=lambda i: (-lengths[i], i))
    row_fill: list[int] = []
    row_slots: list[int] = []
    placed: list[SegmentPlacement] = []
    for i in order:
        n = lengths[i]
        for r in range(len(row_fill)):
            if row_fill[r] + n <= budget:
                placed.append(
                    SegmentPlacement(
                        request=i, length=n, row=r,
                        slot=row_slots[r], offset=row_fill[r],
                    )
                )
                row_fill[r] += n
                row_slots[r] += 1
                break
        else:
            placed.append(
                SegmentPlacement(request=i, length=n, row=len(row_fill),
                                 slot=0, offset=0)
            )
            row_fill.append(n)
            row_slots.append(1)
    placed.sort(key=lambda s: s.request)
    return PackPlan(
        budget=budget,
        rows=len(row_fill),
        max_segments=max(row_slots),
        segments=tuple(placed),
    )


def choose_budget(
    lengths, rungs, *, max_rows: int | None = None
) -> tuple[int, PackPlan]:
    """Pick the rung minimizing total device tokens — ``row-bucketed rows
    × budget`` (rows pad to a power of two the same way image batches do,
    so a small budget that fragments into many rows loses to a larger one
    that packs tight). Ties break toward the smaller budget; fully
    deterministic. Returns ``(budget, plan)``."""
    need = max(int(n) for n in lengths)
    usable = [b for b in rungs if b >= need]
    if not usable:
        raise ValueError(
            f"largest segment needs {need} tokens but the rung ladder tops "
            f"out at {max(rungs)} — raise the packed token budget"
        )
    best = None
    for b in sorted(usable):
        plan = pack_ffd(lengths, b)
        rows_cap = max_rows if max_rows is not None else max(plan.rows, 1)
        rows_b = bucket_for(plan.rows, max(rows_cap, plan.rows))
        total = rows_b * b
        if best is None or total < best[0]:
            best = (total, b, plan)
    return best[1], best[2]


def budget_rungs(max_budget: int, *, min_budget: int = 64) -> tuple[int, ...]:
    """The packed executable ladder's budget rungs: powers of two from
    ``min_budget`` up to ``max_budget`` (plus ``max_budget`` itself when
    it is not one) — same shape as the engine's image-bucket ladder."""
    return tuple(b for b in pow2_rungs(max_budget) if b >= min_budget) or (
        max_budget,
    )


def build_arrays(
    plan: PackPlan,
    num_cls_tokens: int,
    *,
    rows: int | None = None,
    max_segments: int | None = None,
) -> dict[str, np.ndarray]:
    """Materialize the device-side plan arrays (see module docstring).
    ``rows``/``max_segments`` may be rounded up past the plan's own values
    (executable-shape bucketing); the extra rows/slots are all-pad and
    inert."""
    k = int(num_cls_tokens)
    r = plan.rows if rows is None else int(rows)
    smax = plan.max_segments if max_segments is None else int(max_segments)
    if r < plan.rows or smax < plan.max_segments:
        raise ValueError(
            f"plan needs rows>={plan.rows}, max_segments>="
            f"{plan.max_segments}; got rows={r}, max_segments={smax}"
        )
    seg = np.zeros((r, plan.budget), np.int32)
    cls_pos = np.full((r, plan.budget), -1, np.int32)
    cls_index = np.zeros((r, smax, k), np.int32)
    for s in plan.segments:
        seg[s.row, s.offset : s.offset + s.length] = s.slot + 1
        cls_pos[s.row, s.offset : s.offset + k] = np.arange(k, dtype=np.int32)
        cls_index[s.row, s.slot] = s.offset + np.arange(k, dtype=np.int32)
    return {"segment_ids": seg, "cls_pos": cls_pos, "cls_index": cls_index}


def place_tokens(
    plan: PackPlan,
    patch_tokens,
    num_cls_tokens: int,
    *,
    rows: int | None = None,
    dtype=np.float32,
) -> np.ndarray:
    """Scatter per-request patch-token arrays (``patch_tokens[i]`` is
    request i's ``(length - k, dim)`` array) into the packed ``(rows,
    budget, dim)`` host buffer. CLS slots and padding stay zero — the
    encoder injects its CLS parameter on device; pad values are masked
    out of every cross-token op."""
    k = int(num_cls_tokens)
    r = plan.rows if rows is None else int(rows)
    dim = int(np.shape(patch_tokens[0])[-1])
    buf = np.zeros((r, plan.budget, dim), dtype)
    for s in plan.segments:
        toks = np.asarray(patch_tokens[s.request], dtype)
        if toks.shape[0] != s.length - k:
            raise ValueError(
                f"request {s.request}: planned {s.length - k} patch tokens, "
                f"got {toks.shape[0]}"
            )
        buf[s.row, s.offset + k : s.offset + s.length] = toks
    return buf


def unpack_rows(plan: PackPlan, packed_out: np.ndarray) -> list[np.ndarray]:
    """Gather each request's per-segment output from a ``(rows,
    max_segments, ...)`` device result, back in request order."""
    out: list = [None] * len(plan.segments)
    for s in plan.segments:
        out[s.request] = np.asarray(packed_out[s.row, s.slot])
    return out
