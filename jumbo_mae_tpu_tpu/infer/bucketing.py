"""The one definition of the serving bucket ladder.

Three call sites grew their own copies of the power-of-two bucket walk —
the engine's ceil (``bucket_for``), the continuous scheduler's floor
(``floor_bucket``), and the load generator's report-side ceil — and the
invariant that keeps the whole tier honest lives *between* them:

    floor_bucket(k) <= k <= bucket_for(k)            (k <= max_batch)
    bucket_for(floor_bucket(k)) == floor_bucket(k)   (a floor is pad-free)

A drifted copy breaks that silently: the scheduler would "align" partial
batches to a size the engine then pads anyway, and the loadgen report
would account pad rows the device never ran. Both functions live here and
everywhere else imports them; ``tests/test_bucketing.py`` property-checks
the pair against each other across the (n, max_batch) lattice so the
invariant is enforced at the definition, not per call site.
"""

from __future__ import annotations


class OversizedBatchError(ValueError):
    """A single dispatch larger than the engine's ``max_batch`` — there is
    no planned executable for that shape, and compiling one on the hot path
    is exactly the latency cliff the bucket ladder exists to prevent.
    ``InferenceEngine.predict`` never raises this (it chunks oversized
    requests); direct ``bucket_for``/``warmup`` callers get it instead of a
    silent unplanned compile."""


def bucket_for(n: int, max_batch: int) -> int:
    """Smallest power-of-two >= n, clamped to ``max_batch`` (so the number
    of distinct compiled programs is log2(max_batch)+1, not one per
    request size; a non-power-of-two ``max_batch`` is itself the last rung
    of the ladder). ``n > max_batch`` raises :class:`OversizedBatchError` —
    historically this silently returned a too-small (or, for non-pow2
    ``max_batch``, a too-LARGE unplanned) bucket."""
    if n <= 0:
        raise ValueError(f"need a positive batch, got {n}")
    if n > max_batch:
        raise OversizedBatchError(
            f"batch of {n} exceeds max_batch={max_batch} — split the "
            f"request upstream (engine.predict chunks automatically) or "
            f"raise max_batch"
        )
    b = 1
    while b < n:
        b <<= 1
    return min(b, max_batch)


def floor_bucket(k: int, max_batch: int) -> int:
    """Largest engine pad-bucket size <= k: the engine pads every flush up
    to a power-of-2 bucket (capped at max_batch, itself the top rung), so
    a batch of exactly this size runs with zero pad rows."""
    if k >= max_batch:
        return max_batch
    b = 1
    while b * 2 <= k:
        b *= 2
    return b


def ceil_pow2(n: int) -> int:
    """Smallest power of two >= n (no ladder cap — the packed executable's
    row/segment-slot dimensions bucket this way so the number of distinct
    compiled shapes stays logarithmic)."""
    if n <= 0:
        raise ValueError(f"need a positive count, got {n}")
    b = 1
    while b < n:
        b <<= 1
    return b


def pow2_rungs(max_value: int) -> tuple[int, ...]:
    """Every power of two <= ``max_value``, plus ``max_value`` itself when
    it is not one — the engine's warmup ladder and the token-budget rung
    set for packed serving share this shape."""
    if max_value < 1:
        raise ValueError(f"need a positive max, got {max_value}")
    rungs = []
    b = 1
    while b <= max_value:
        rungs.append(b)
        b <<= 1
    if rungs[-1] != max_value:
        rungs.append(max_value)
    return tuple(rungs)
