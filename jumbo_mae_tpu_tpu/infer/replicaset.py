"""Replicated serving tier: a supervised pool of inference engine workers.

One ``InferenceEngine`` behind one queue means one wedged predict or one
bad checkpoint load takes the whole serving path down. The
:class:`ReplicaSet` turns that single point of failure into a supervised
pool: N replicas, each an engine plus its own micro-batching worker thread
(same ``max_batch``/``max_delay_ms``/``max_queue``/deadline semantics as
:class:`~jumbo_mae_tpu_tpu.infer.batching.MicroBatcher`), behind a router
that assigns each request to the least-loaded healthy replica.

**Crash isolation.** A replica whose predict raises (or is fault-injected
via the ``serve.replica`` site — ``key`` is the replica name) is marked
down; its in-flight and queued requests are *requeued onto surviving
replicas* with the failed replica in the request's excluded set, so a
retry can never land back on the replica that just failed it. A replica
whose predict hangs past ``hang_timeout_s`` is declared hung by the
supervisor, its slot replaced, and its requests requeued the same way —
the zombie thread's eventual late result loses the per-request settle
race, so **every future still resolves exactly once** (ok / ok-with-retry
attribution / typed error), and every resolution writes exactly one
access-log row carrying ``replica``/``retries``/``requeued_from``.

**Self-healing.** The supervisor restarts down replicas with capped
exponential backoff (engine construction goes back through the provider,
so a warm cache makes the restart compile-free), beats per-replica
heartbeats into an attached :class:`~jumbo_mae_tpu_tpu.obs.exporter.
HealthState`, and opens a circuit breaker when healthy replicas drop
below ``quorum`` — surfaced as the *soft* degraded flag in ``/healthz``
(the pool still serves whatever capacity survives; degraded must not
flip the 503 or an autoscaler would amplify the outage).

**Zero-downtime weight hot-swap.** The :class:`WeightSwapController`
double-buffer-restores a new checkpoint (``restore_inference_state``; the
``ckpt.load`` fault site fires here with the restored tree as payload),
then promotes it through three gates, rolling back to the previous
weights at the first failure:

1. **parity** — the canary replica is paused, drained, and flipped via
   ``InferenceEngine.swap_weights`` (zero compiles: params are executable
   arguments); feature cosine vs the live weights' outputs on a fixed
   probe batch (the ``infer/quant.py`` parity machinery) must clear
   ``parity_min_cosine``. A corrupt or wrong-architecture push dies here
   without ever serving traffic.
2. **canary** — the flipped replica rejoins the pool and serves live
   traffic; a dedicated ``obs/slo.py`` burn-rate tracker watches only its
   outcomes for ``canary_requests`` requests (bounded by
   ``canary_timeout_s``). A breach — or the canary crashing outright —
   rolls the replica back to the buffered previous weights.
3. **promote** — surviving replicas are flipped one at a time
   (pause → drain → swap → resume), so the pool never stops serving; the
   provider is then repointed so future restarts build the new weights.

``serve_replica_*`` / ``serve_swap_*`` metrics and ``replica_*`` /
``swap_*`` access-log events make every transition observable offline
(``tools/serve_doctor.py``) and live (``/metrics``).
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable

import numpy as np

from jumbo_mae_tpu_tpu.faults.inject import fault_point
from jumbo_mae_tpu_tpu.obs import lockwatch
from jumbo_mae_tpu_tpu.infer.batching import (
    DeadlineExceededError,
    OccupancyWindow,
    QueueFullError,
    ShutdownError,
)
from jumbo_mae_tpu_tpu.obs.metrics import (
    NULL_REGISTRY,
    RATIO_BUCKETS,
    get_registry,
)

_STOP = object()


class PoolUnhealthyError(RuntimeError):
    """No healthy replica can take (or retry) a request — the pool is at
    or below zero routable capacity for this request's excluded set."""


class RetriesExhaustedError(RuntimeError):
    """A request was requeued off failing replicas more than
    ``max_retries`` times; the last replica error is in the message."""


class _Request:
    """One routed request: the payload, its future, and the settle latch
    that makes resolution exactly-once under requeue/zombie races."""

    __slots__ = (
        "image", "meta", "deadline", "fut", "tr", "excluded",
        "retries", "t0", "gid", "_settled", "_lock",
    )

    def __init__(self, image, meta, deadline, fut, tr, gid=None):
        self.image = image
        self.meta = meta
        self.deadline = deadline
        self.fut = fut
        self.tr = tr
        self.excluded: set[str] = set()
        self.retries = 0
        self.t0 = time.perf_counter()
        # dispatch-group id (submit_group): the worker only coalesces
        # requests sharing a gid, so a group the scheduler shaped flushes
        # exactly as shaped — never merged with a neighboring group
        self.gid = gid
        self._settled = False
        self._lock = lockwatch.lock("replicaset.request")

    def settle(self) -> bool:
        """Claim the exclusive right to resolve this request. Exactly one
        caller ever wins — the requeue path, a surviving replica, a zombie
        (hung-then-woken) replica, and the close() sweep all race through
        here."""
        with self._lock:
            if self._settled:
                return False
            self._settled = True
            return True

    @property
    def settled(self) -> bool:
        return self._settled


class _Replica:
    """One pool slot incarnation: an engine, an inbound queue, a worker
    thread, and the supervisor-visible state."""

    __slots__ = (
        "idx", "name", "gen", "engine", "q", "thread", "state",
        "busy_since", "pending", "served",
    )

    def __init__(self, idx: int, gen: int, engine):
        self.idx = idx
        self.name = f"r{idx}"
        self.gen = gen
        self.engine = engine
        self.q: queue.SimpleQueue = queue.SimpleQueue()
        self.thread: threading.Thread | None = None
        self.state = "up"          # up | paused | down
        self.busy_since: float | None = None
        self.pending: tuple = ()   # records in the in-flight batch
        self.served = 0


class ReplicaSet:
    """Supervised pool of N engine workers with MicroBatcher semantics.

    ``engine_provider(idx)`` builds replica ``idx``'s engine — called at
    construction and again on every restart (route it through a warm
    cache and restarts are compile-free). ``run(engine, batch, metas)``
    is the batched predict. Both are plain callables so tests drive the
    pool with stub engines and the CLI drives it with
    :class:`InferenceEngine`.

    Use as a context manager or call :meth:`close` — every pending future
    is resolved within a bounded sweep even if a worker is wedged.
    """

    def __init__(
        self,
        engine_provider: Callable[[int], Any],
        run: Callable[[Any, np.ndarray, list], Any],
        *,
        replicas: int = 2,
        max_batch: int = 32,
        max_delay_ms: float = 5.0,
        max_queue: int | None = None,
        max_retries: int = 2,
        hang_timeout_s: float = 30.0,
        restart_backoff_s: float = 0.25,
        restart_backoff_max_s: float = 8.0,
        quorum: int | None = None,
        supervise_interval_s: float = 0.05,
        tracer=None,
        task: str = "",
        registry=None,
        health=None,
        breakdown: Callable[[Any], dict | None] | None = None,
        costmeter=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if quorum is not None and not 1 <= quorum <= replicas:
            raise ValueError(f"quorum must be in [1, {replicas}], got {quorum}")
        self._provider = engine_provider
        self._run = run
        self.n = int(replicas)
        self.max_batch = int(max_batch)
        self.max_delay = float(max_delay_ms) / 1000.0
        self.max_queue = max_queue
        self.max_retries = int(max_retries)
        self.hang_timeout_s = float(hang_timeout_s)
        self.restart_backoff_s = float(restart_backoff_s)
        self.restart_backoff_max_s = float(restart_backoff_max_s)
        # default quorum: majority — the smallest pool that can still
        # claim it is "the" serving tier rather than a stray survivor.
        # An explicit quorum is pinned; the default majority is recomputed
        # when scale_to() resizes the pool (a 4-replica quorum of 3 would
        # latch the breaker open forever on a pool scaled down to 2).
        self._explicit_quorum = quorum is not None
        self.quorum = quorum if quorum is not None else self.n // 2 + 1
        self._interval = float(supervise_interval_s)
        self._tracer = tracer
        self.task = task
        self._health = health
        self._breakdown = breakdown
        self._costmeter = costmeter
        self._clock = clock
        self._observers: list[Callable] = []

        reg = registry if registry is not None else get_registry()
        # pool-tier metrics (serve_replica_*) ...
        self._m_up = reg.gauge(
            "serve_replica_up", "replica is up and routable (1) or not (0)",
            labels=("replica",),
        )
        self._m_restarts = reg.counter(
            "serve_replica_restarts_total",
            "replica restarts completed by the supervisor",
            labels=("replica",),
        )
        self._m_crashes = reg.counter(
            "serve_replica_crashes_total",
            "replica predict failures by kind (crash|hang|restart_error)",
            labels=("replica", "kind"),
        )
        self._m_served = reg.counter(
            "serve_replica_requests_total",
            "requests resolved ok, by serving replica",
            labels=("replica",),
        )
        self._m_requeued = reg.counter(
            "serve_replica_requeued_total",
            "in-flight/queued requests requeued off a failed replica, "
            "attributed to the replica that failed them",
            labels=("replica",),
        )
        self._m_preempted = reg.counter(
            "serve_replica_preempted_total",
            "replicas drained and retired by preemption notice "
            "(pause -> idle -> down -> supervisor restart); never drops "
            "in-flight work",
            labels=("replica",),
        )
        self._m_healthy = reg.gauge(
            "serve_replica_healthy_count", "replicas currently up or paused"
        )
        self._m_quorum = reg.gauge(
            "serve_replica_quorum", "healthy-replica floor for the breaker"
        )
        self._m_breaker = reg.gauge(
            "serve_replica_breaker_open",
            "1 while healthy replicas < quorum (degraded in /healthz)",
        )
        self._m_breaker_trips = reg.counter(
            "serve_replica_breaker_trips_total",
            "times the pool dropped below quorum",
        )
        # ... and the same request-tier families MicroBatcher publishes,
        # so existing dashboards/doctors read the replicated tier unchanged
        self._m_latency = reg.histogram(
            "infer_request_latency_seconds",
            "request latency: submit() to resolved future",
        )
        self._m_requests = reg.counter(
            "infer_requests_total", "requests collected into batches"
        )
        self._m_batches = reg.counter(
            "infer_batches_total", "batches flushed through run_fn"
        )
        self._m_shed = reg.counter(
            "infer_requests_shed_total",
            "submits rejected with QueueFullError (queue at max_queue)",
        )
        self._m_expired = reg.counter(
            "infer_deadline_exceeded_total",
            "requests expired past their deadline before batch admission",
        )
        self._m_late = reg.counter(
            "infer_requests_late_total",
            "requests whose deadline passed after admission (during "
            "coalescing or compute) — failed at resolution, not resolved ok",
        )
        self._m_aborted = reg.counter(
            "infer_requests_aborted_total",
            "pending requests failed by close()",
        )
        self._m_occupancy = reg.histogram(
            "infer_batch_occupancy",
            "flushed batch size / max_batch",
            buckets=RATIO_BUCKETS,
        )
        self._occ = OccupancyWindow(self.max_batch)
        self._m_quorum.set(self.quorum)

        self._depth = 0
        self._submitted = 0
        self._shed_n = 0
        self._gid = itertools.count(1)  # dispatch-group ids (submit_group)
        self._depth_lock = lockwatch.lock("replicaset.depth")
        self._live: set[_Request] = set()
        self._live_lock = lockwatch.lock("replicaset.live")
        self._closed = False
        self._drain = True
        self._breaker_open = False
        self._canary_pref: str | None = None
        self._state_lock = lockwatch.lock("replicaset.state")
        self._scale_lock = lockwatch.lock("replicaset.scale")
        # slots removed by scale_to(): the supervisor keeps rescuing their
        # queues so a submit that raced the removal is requeued, not lost
        self._retired: list[_Replica] = []

        self._slots: list[_Replica] = []
        self._fails = [0] * self.n
        self._restart_at = [0.0] * self.n
        self._restarting = [False] * self.n
        for idx in range(self.n):
            rep = _Replica(idx, gen=0, engine=self._provider(idx))
            self._slots.append(rep)
            self._start_worker(rep)
            self._m_up.labels(rep.name).set(1)
            # eager child: the preemption counter scrapes as 0 from boot,
            # not from the first preemption (PR 15 registration pattern)
            self._m_preempted.labels(rep.name)
            if self._health is not None:
                self._health.beat(f"replica.{rep.name}")
        self._update_health()
        if self._health is not None:
            self._health.probe("replicas", self.stats)
        self._supervisor = threading.Thread(
            target=self._supervise, daemon=True, name="replicaset-supervisor"
        )
        self._supervisor.start()

    # ------------------------------------------------------------- client

    def submit(
        self,
        image: np.ndarray,
        *,
        deadline_ms: float | None = None,
        meta=None,
        tenant: str | None = None,
        tclass: str | None = None,
    ) -> Future:
        """Route one request to a healthy replica; returns a future for
        its row of the batched result. Shed/deadline/shutdown semantics
        match :meth:`MicroBatcher.submit`; additionally raises
        :class:`PoolUnhealthyError` when no replica is routable.
        ``tenant``/``tclass`` ride into the trace row (admission tier
        attribution) — they do not change routing here."""
        tr = (
            self._tracer.begin(
                task=self.task, deadline_ms=deadline_ms,
                tenant=tenant, tclass=tclass,
            )
            if self._tracer is not None
            else None
        )
        try:
            fault_point("serve.submit")
            if self._closed:
                raise ShutdownError("ReplicaSet is closed")
            with self._depth_lock:
                self._submitted += 1
                if self.max_queue is not None and self._depth >= self.max_queue:
                    self._m_shed.inc()
                    self._shed_n += 1
                    raise QueueFullError(
                        f"request queue full ({self._depth}/{self.max_queue})"
                    )
                self._depth += 1
            target = self._pick(frozenset())
            if target is None:
                with self._depth_lock:
                    self._depth -= 1
                raise PoolUnhealthyError(
                    f"no healthy replica (healthy={self._healthy_count()}, "
                    f"quorum={self.quorum})"
                )
        except BaseException as e:  # noqa: BLE001 — classify, trace, re-raise
            if tr is not None:
                if isinstance(e, QueueFullError):
                    self._tracer.finish(tr, "shed")
                elif isinstance(e, ShutdownError) or self._closed:
                    self._tracer.finish(tr, "shutdown")
                else:
                    self._tracer.finish(
                        tr, "aborted", error=f"{type(e).__name__}: {e}"
                    )
            raise
        fut: Future = Future()
        if tr is not None:
            fut.rid = tr.rid
        deadline = (
            None
            if deadline_ms is None
            else time.monotonic() + float(deadline_ms) / 1000.0
        )
        rec = _Request(np.asarray(image), meta, deadline, fut, tr)
        with self._live_lock:
            self._live.add(rec)
        target.q.put(rec)
        return rec.fut

    def submit_group(self, items) -> list[Future]:
        """Route a pre-coalesced group of requests to ONE replica as a
        unit — the continuous scheduler's dispatch path. ``items`` is a
        list of ``(image, deadline, meta, tr)`` tuples where ``deadline``
        is an absolute ``time.monotonic()`` instant (or ``None``) and
        ``tr`` is a trace the *caller* already began (or ``None``). The
        group lands consecutively on the least-loaded replica's queue, so
        (for ``len(items) <= max_batch``) it flushes as one batch — the
        occupancy the scheduler assembled is the occupancy the replica
        runs.

        Exception contract: on shed/shutdown/unroutable the group fails
        as a unit — every trace in it is finished (``shed`` /
        ``shutdown`` / ``aborted``) before the typed error is raised, and
        the caller owns failing its own futures.
        """
        k = len(items)
        if k == 0:
            return []
        traces = [it[3] for it in items if it[3] is not None]
        try:
            fault_point("serve.submit")
            if self._closed:
                raise ShutdownError("ReplicaSet is closed")
            with self._depth_lock:
                self._submitted += k
                if (
                    self.max_queue is not None
                    and self._depth + k > self.max_queue
                ):
                    self._m_shed.inc(k)
                    self._shed_n += k
                    raise QueueFullError(
                        f"request queue full "
                        f"({self._depth}+{k}/{self.max_queue})"
                    )
                self._depth += k
            target = self._pick(frozenset())
            if target is None:
                with self._depth_lock:
                    self._depth -= k
                raise PoolUnhealthyError(
                    f"no healthy replica (healthy={self._healthy_count()}, "
                    f"quorum={self.quorum})"
                )
        except BaseException as e:  # noqa: BLE001 — classify, trace, re-raise
            if self._tracer is not None:
                if isinstance(e, QueueFullError):
                    outcome, err = "shed", None
                elif isinstance(e, ShutdownError) or self._closed:
                    outcome, err = "shutdown", None
                else:
                    outcome, err = "aborted", f"{type(e).__name__}: {e}"
                for tr in traces:
                    self._tracer.finish(tr, outcome, error=err)
            raise
        recs = []
        gid = next(self._gid)
        for image, deadline, meta, tr in items:
            fut: Future = Future()
            if tr is not None:
                fut.rid = tr.rid
            recs.append(
                _Request(np.asarray(image), meta, deadline, fut, tr, gid=gid)
            )
        with self._live_lock:
            self._live.update(recs)
        for rec in recs:
            target.q.put(rec)
        return [rec.fut for rec in recs]

    def __call__(self, image, *, deadline_ms: float | None = None):
        return self.submit(image, deadline_ms=deadline_ms).result()

    def add_observer(self, fn: Callable) -> None:
        """``fn(replica_name, outcome, latency_s, retries)`` on every
        resolved request — the canary SLO feed."""
        self._observers.append(fn)

    def remove_observer(self, fn: Callable) -> None:
        try:
            self._observers.remove(fn)
        except ValueError:
            pass

    # ----------------------------------------------------------- lifecycle

    def replica(self, idx: int) -> _Replica:
        return self._slots[idx]

    def generation(self, idx: int) -> int:
        return self._slots[idx].gen

    def first_routable(self) -> _Replica | None:
        return self._pick(frozenset())

    def _healthy_count(self) -> int:
        return sum(1 for rep in self._slots if rep.state in ("up", "paused"))

    def degraded(self) -> bool:
        """Breaker state, shaped for :meth:`HealthState.degraded_when`."""
        return self._breaker_open

    def pause(self, idx: int) -> None:
        """Take a replica out of routing (it drains what it already has);
        the swap controller's flip window."""
        with self._state_lock:
            rep = self._slots[idx]
            if rep.state == "up":
                rep.state = "paused"

    def resume(self, idx: int) -> None:
        with self._state_lock:
            rep = self._slots[idx]
            if rep.state == "paused":
                rep.state = "up"

    def wait_idle(self, idx: int, timeout_s: float = 10.0) -> bool:
        """Block until replica ``idx`` has nothing queued or in flight
        (True) or the timeout passes (False)."""
        deadline = self._clock() + timeout_s
        while self._clock() < deadline:
            rep = self._slots[idx]
            if rep.q.empty() and rep.busy_since is None and not rep.pending:
                return True
            time.sleep(0.005)
        return False

    def set_engine_provider(self, fn: Callable[[int], Any]) -> None:
        """Repoint restarts at a new engine source (a promoted swap must
        survive a later replica restart)."""
        self._provider = fn

    def set_costmeter(self, meter) -> None:
        """Late-bind the tenant cost meter — it is usually built after the
        pool, next to the admission controller it feeds. Every flushed
        batch then reports ``(run_s, traces, engine)`` to
        :meth:`CostMeter.observe_batch`."""
        self._costmeter = meter

    # ------------------------------------------------------------- scaling

    def scale_to(self, n: int, *, drain_timeout_s: float = 10.0) -> dict:
        """Resize the pool to ``n`` replicas, one slot at a time — the
        autoscaler's actuator. Growth builds engines through the current
        provider (warm cache → compile-free); shrink always removes the
        *last* slot and only after pausing it and draining its queued and
        in-flight work onto survivors' capacity (``wait_idle``), so
        scale-down never kills an in-flight request. A slot that is down,
        restarting, or won't drain within ``drain_timeout_s`` stops the
        shrink for this round (the next reconcile retries). The default
        majority quorum is recomputed per size; an explicit quorum is
        pinned. Returns ``{"from", "to", "added", "removed", "stopped"}``.
        """
        if n < 1:
            raise ValueError(f"scale target must be >= 1, got {n}")
        with self._scale_lock:
            start = len(self._slots)
            added: list[str] = []
            removed: list[str] = []
            stopped: str | None = None
            while len(self._slots) < n and not self._closed:
                name = self._add_slot()
                if name is None:
                    stopped = "engine provider failed"
                    break
                added.append(name)
            while len(self._slots) > n and not self._closed:
                name = self._remove_last_slot(drain_timeout_s)
                if name is None:
                    stopped = "last slot not removable (down/restarting/undrained)"
                    break
                removed.append(name)
            return {
                "from": start,
                "to": len(self._slots),
                "added": added,
                "removed": removed,
                "stopped": stopped,
            }

    def _add_slot(self) -> str | None:
        """Append one replica slot; returns its name, or ``None`` when the
        engine provider failed (the pool is unchanged)."""
        idx = len(self._slots)
        try:
            # engine construction OUTSIDE the state lock: a cold build can
            # compile for seconds and serving must not stall behind it
            engine = self._provider(idx)
        except BaseException as e:  # noqa: BLE001 — a provider error is a failed scale step
            self._event(
                "replica_restart_failed", replica=f"r{idx}",
                err=f"{type(e).__name__}: {e}",
            )
            return None
        rep = _Replica(idx, gen=0, engine=engine)
        with self._state_lock:
            self._slots.append(rep)
            self._fails.append(0)
            self._restart_at.append(0.0)
            self._restarting.append(False)
            self.n = len(self._slots)
            if not self._explicit_quorum:
                self.quorum = self.n // 2 + 1
                self._m_quorum.set(self.quorum)
            self._update_health()
        self._start_worker(rep)
        self._m_up.labels(rep.name).set(1)
        if self._health is not None:
            self._health.beat(f"replica.{rep.name}")
        self._event("replica_added", replica=rep.name, pool=self.n)
        return rep.name

    def _remove_last_slot(self, drain_timeout_s: float) -> str | None:
        """Drain and retire the last slot; returns its name, or ``None``
        when it cannot be removed right now (pool of one, slot down or
        restarting, or the drain timed out — in which case routing is
        restored)."""
        with self._state_lock:
            if len(self._slots) <= 1:
                return None
            rep = self._slots[-1]
            if self._restarting[rep.idx] or rep.state == "down":
                return None
            we_paused = rep.state == "up"
            if we_paused:
                rep.state = "paused"  # out of routing; drains what it has
        if not self.wait_idle(rep.idx, drain_timeout_s):
            with self._state_lock:
                if (
                    we_paused
                    and not self._stale(rep)
                    and rep.state == "paused"
                ):
                    rep.state = "up"
            return None
        with self._state_lock:
            # re-verify under the lock: a hang/crash during the drain
            # (or a racing restart) means this incarnation no longer owns
            # the slot — leave it to the supervisor
            if (
                self._stale(rep)
                or rep.idx != len(self._slots) - 1
                or rep.state != "paused"
            ):
                return None
            self._slots.pop()
            self._fails.pop()
            self._restart_at.pop()
            self._restarting.pop()
            self.n = len(self._slots)
            if not self._explicit_quorum:
                self.quorum = self.n // 2 + 1
                self._m_quorum.set(self.quorum)
            self._update_health()
            # the supervisor keeps rescuing this queue: a submit that
            # picked the slot before the pop lands here after it
            self._retired.append(rep)
        rep.q.put(_STOP)
        self._m_up.labels(rep.name).set(0)
        self._event("replica_removed", replica=rep.name, gen=rep.gen, pool=self.n)
        self._drain_slot(rep, "replica removed")
        rep.engine = None  # drop the engine's memory with the slot
        return rep.name

    def preempt(self, idx: int, *, drain_timeout_s: float = 10.0) -> bool:
        """Preemption notice for replica ``idx`` (TPU maintenance, spot
        reclaim, or the ``serve.preempt`` fault site): take it out of
        routing, let it finish everything it already holds, then retire
        the incarnation — zero in-flight requests dropped. The slot goes
        ``down`` WITHOUT a failure count (preemption is not a crash), so
        the supervisor restarts it after one plain backoff and the
        capacity returns. False when the slot is not preemptible right
        now (down/restarting/closed) or the drain timed out (routing is
        restored and the caller may retry)."""
        with self._state_lock:
            if self._closed or idx >= len(self._slots):
                return False
            rep = self._slots[idx]
            if rep.state not in ("up", "paused") or self._restarting[idx]:
                return False
            we_paused = rep.state == "up"
            rep.state = "paused"  # out of routing; drains what it holds
        if not self.wait_idle(idx, drain_timeout_s):
            with self._state_lock:
                if we_paused and not self._stale(rep) and rep.state == "paused":
                    rep.state = "up"
            return False
        with self._state_lock:
            # re-verify: a crash/hang during the drain means this
            # incarnation is no longer ours to retire
            if self._stale(rep) or rep.state != "paused":
                return False
            rep.state = "down"
            self._m_up.labels(rep.name).set(0)
            # plain backoff, no fails increment: the replacement should
            # come back at base speed, not on the crash penalty curve
            self._restart_at[idx] = self._clock() + self.restart_backoff_s
            self._update_health()
        self._m_preempted.labels(rep.name).inc()
        self._event("replica_preempted", replica=rep.name, gen=rep.gen)
        rep.q.put(_STOP)
        self._drain_slot(rep, "replica preempted")
        return True

    def pressure(self) -> float:
        """Pending depth / max_queue in [0, ~] — cheap enough to call per
        admission decision (one counter read, no slot snapshot). Unbounded
        queue → always 0."""
        if not self.max_queue:
            return 0.0
        with self._depth_lock:
            return self._depth / self.max_queue

    def stats(self) -> dict:
        with self._depth_lock:
            depth, submitted, shed = self._depth, self._submitted, self._shed_n
        occ = self._occ.snapshot()
        with self._state_lock:
            slots = list(self._slots)
            fails = list(self._fails)
        return {
            "replicas": {
                rep.name: {
                    "state": rep.state,
                    "gen": rep.gen,
                    "queued": rep.q.qsize(),
                    "served": rep.served,
                    "restarts": fails[i] if i < len(fails) else 0,
                }
                for i, rep in enumerate(slots)
            },
            "healthy": self._healthy_count(),
            "quorum": self.quorum,
            "breaker_open": self._breaker_open,
            "queue_depth": depth,
            "requests_submitted": submitted,
            "requests_shed": shed,
            # EWMA/windowed flush occupancy (autoscaler + SLO probe input)
            "batch_occupancy": occ["ewma"],
            "window_batch_occupancy": occ["window_mean"],
            "batches_flushed": occ["batches"],
        }

    def close(self, drain: bool = True, timeout_s: float = 10.0):
        """Stop everything and resolve EVERY pending request. Joins are
        bounded — a hung worker cannot hang close(); its requests are
        swept with :class:`ShutdownError` (the settle latch keeps a
        late zombie result from double-resolving them)."""
        if self._closed:
            return
        self._drain = drain
        # latch shutdown under the state lock: _restart_slot re-checks
        # _closed under the same lock before installing a new incarnation,
        # so a restart that raced close() can never respawn a slot after
        # the close sweep has run
        with self._state_lock:
            self._closed = True
        self._supervisor.join(timeout=max(1.0, self._interval * 4))
        for rep in self._slots:
            rep.q.put(_STOP)
        deadline = time.monotonic() + timeout_s
        for rep in self._slots:
            if rep.thread is not None:
                rep.thread.join(timeout=max(0.0, deadline - time.monotonic()))
        # sweep: anything still unresolved (queued behind a sentinel,
        # stranded on a down slot, in a wedged worker) fails typed now
        with self._live_lock:
            leftovers = list(self._live)
        for rec in leftovers:
            if rec.settle():
                self._m_aborted.inc()
                self._finish(
                    rec, "shutdown", exc=ShutdownError("ReplicaSet closed")
                )

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------- router

    def _pick(self, excluded) -> _Replica | None:
        best, best_sz = None, None
        pref, pref_sz = None, None
        for rep in self._slots:
            if rep.state != "up" or rep.name in excluded:
                continue
            sz = rep.q.qsize()
            if best is None or sz < best_sz:
                best, best_sz = rep, sz
            if rep.name == self._canary_pref:
                pref, pref_sz = rep, sz
        # a canary under evaluation takes ties: least-loaded tie-breaking
        # would otherwise starve any slot but the first on an idle pool,
        # and the canary window needs live traffic to judge
        if pref is not None and pref_sz <= best_sz:
            return pref
        return best

    def set_canary_preference(self, name: str | None) -> None:
        """Route queue-size ties to this replica (the swap controller's
        canary window); ``None`` restores pure least-loaded routing."""
        self._canary_pref = name

    def _event(self, etype: str, **fields) -> None:
        if self._tracer is not None:
            self._tracer.event(etype, **fields)

    def _finish(
        self, rec: _Request, outcome: str, *,
        result=None, exc=None, replica: str | None = None,
        error: str | None = None,
    ) -> None:
        """Resolve a settled record: trace row first, then the future —
        callers that see the future done can rely on the row existing."""
        with self._live_lock:
            self._live.discard(rec)
        if rec.tr is not None:
            rec.tr.replica_id = replica
            rec.tr.retries = rec.retries
            if rec.excluded:
                rec.tr.requeued_from = ",".join(sorted(rec.excluded))
            self._tracer.finish(rec.tr, outcome, error=error)
        if exc is not None:
            rec.fut.set_exception(exc)
        else:
            rec.fut.set_result(result)
        lat = time.perf_counter() - rec.t0
        for fn in list(self._observers):
            try:
                fn(replica, outcome, lat, rec.retries)
            except Exception:  # noqa: BLE001 — observers must not kill serving
                pass

    def _requeue(self, rec: _Request, from_rep: _Replica, err: str) -> None:
        """Move one request off a failed replica: excluded-set + retry
        budget + survivor routing; terminal failures settle typed."""
        if rec.settled:
            return
        rec.excluded.add(from_rep.name)
        rec.retries += 1
        self._m_requeued.labels(from_rep.name).inc()
        if self._closed and self._drain:
            if rec.settle():
                self._m_aborted.inc()
                self._finish(
                    rec, "shutdown", exc=ShutdownError("ReplicaSet closed")
                )
            return
        if rec.retries > self.max_retries:
            if rec.settle():
                self._finish(
                    rec, "aborted",
                    exc=RetriesExhaustedError(
                        f"retries exhausted after {rec.retries} attempts; "
                        f"last error on {from_rep.name}: {err}"
                    ),
                    error=f"RetriesExhaustedError: last error on "
                          f"{from_rep.name}: {err}",
                )
            return
        target = self._pick(rec.excluded)
        if target is None:
            if rec.settle():
                self._finish(
                    rec, "aborted",
                    exc=PoolUnhealthyError(
                        f"no surviving replica outside {sorted(rec.excluded)} "
                        f"to retry on (last error: {err})"
                    ),
                    error="PoolUnhealthyError: no surviving replica",
                )
            return
        with self._depth_lock:
            self._depth += 1
        target.q.put(rec)

    def _drain_slot(self, rep: _Replica, err: str) -> None:
        """Requeue everything queued on a down slot."""
        while True:
            try:
                item = rep.q.get_nowait()
            except queue.Empty:
                return
            if item is _STOP:
                continue
            with self._depth_lock:
                self._depth -= 1
            self._requeue(item, rep, err)

    # ------------------------------------------------------------- worker

    def _start_worker(self, rep: _Replica) -> None:
        rep.thread = threading.Thread(
            target=self._worker, args=(rep,), daemon=True,
            name=f"replica-{rep.name}-g{rep.gen}",
        )
        rep.thread.start()

    def _stale(self, rep: _Replica) -> bool:
        # the idx bound matters post-scale_to(): a removed slot's worker
        # (or zombie) must read as stale, not IndexError
        return rep.idx >= len(self._slots) or self._slots[rep.idx] is not rep

    def _worker(self, rep: _Replica) -> None:
        carry: _Request | None = None  # lookahead from a different group
        try:
            while not self._stale(rep):
                if carry is not None:
                    item, carry = carry, None
                else:
                    try:
                        item = rep.q.get(timeout=0.05)
                    except queue.Empty:
                        if self._closed:
                            return
                        continue
                    if item is _STOP:
                        return
                batch: list[_Request] = []
                self._admit(item, batch)
                coalesce_deadline = time.monotonic() + self.max_delay
                stop = False
                while len(batch) < self.max_batch:
                    remaining = coalesce_deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    try:
                        nxt = rep.q.get(timeout=remaining)
                    except queue.Empty:
                        break
                    if nxt is _STOP:
                        stop = True
                        break
                    # dispatch groups flush exactly as shaped: never
                    # coalesce across a group boundary (the scheduler
                    # already sized the group to its image or token
                    # budget — merging would blow past it and, with mixed
                    # shapes, break batch stacking). batch may be empty
                    # here when _admit dropped the opener.
                    if batch and nxt.gid != batch[0].gid:
                        carry = nxt
                        break
                    self._admit(nxt, batch)
                if batch and not self._flush(rep, batch):
                    return  # crashed: restart is the supervisor's job now
                if stop:
                    return
        finally:
            if carry is not None:
                # the worker is exiting (crash/stop/stale) while holding a
                # lookahead record that was never admitted: route it back
                # through the requeue path so it can't hang until close()
                with self._depth_lock:
                    self._depth -= 1
                self._requeue(carry, rep, "worker exited holding lookahead")

    def _admit(self, rec: _Request, batch: list) -> None:
        with self._depth_lock:
            self._depth -= 1
        if rec.settled:
            return  # already resolved elsewhere (requeue/zombie race)
        if self._closed and self._drain:
            if rec.settle():
                self._m_aborted.inc()
                self._finish(
                    rec, "shutdown", exc=ShutdownError("ReplicaSet closed")
                )
            return
        if rec.deadline is not None and time.monotonic() > rec.deadline:
            if rec.settle():
                self._m_expired.inc()
                self._finish(
                    rec, "deadline",
                    exc=DeadlineExceededError(
                        "request deadline passed while queued"
                    ),
                )
            return
        if rec.tr is not None:
            self._tracer.admitted(rec.tr)
        batch.append(rec)

    def _flush(self, rep: _Replica, batch: list[_Request]) -> bool:
        """Run one batch on this replica. Returns False when the replica
        crashed (worker must exit)."""
        self._m_batches.inc()
        self._m_requests.inc(len(batch))
        self._m_occupancy.observe(len(batch) / self.max_batch)
        self._occ.observe(len(batch))
        traces = [rec.tr for rec in batch if rec.tr is not None]
        if traces:
            self._tracer.flush_begin(traces)
        # pending + busy_since go up BEFORE the fault point: an injected
        # delay (the hang model) must be visible to the supervisor, and a
        # hang's in-flight records must be findable for requeue
        rep.pending = tuple(batch)
        rep.busy_since = self._clock()
        t_run = time.perf_counter()
        try:
            fault_point("serve.replica", key=rep.name)
            # a token-packed group mixes resolutions: no common stack shape,
            # so the run_fn gets the raw image list (predict_packed takes
            # per-request arrays; the homogeneous fast path keeps the stack)
            if len({rec.image.shape for rec in batch}) == 1:
                images = np.stack([rec.image for rec in batch])
            else:
                images = [rec.image for rec in batch]
            out = self._run(rep.engine, images, [rec.meta for rec in batch])
        except BaseException as e:  # noqa: BLE001 — crash-isolate the replica
            rep.busy_since = None
            rep.pending = ()
            self._on_failure(rep, batch, e, kind="crash")
            return False
        done = time.perf_counter()
        rep.busy_since = None
        rep.pending = ()
        if traces:
            bd = (
                (lambda: self._breakdown(rep.engine))
                if self._breakdown is not None
                else None
            )
            self._tracer.flush_end(
                traces, run_s=done - t_run, batch=len(batch), breakdown=bd
            )
        if traces and self._costmeter is not None:
            # before the _finish loop: the stamped device_s/cost_flops
            # must land on every access-log row this batch produces
            self._costmeter.observe_batch(
                run_s=done - t_run,
                traces=traces,
                batch=len(batch),
                engine=rep.engine,
            )
        self._m_latency.observe_many([done - rec.t0 for rec in batch])
        if isinstance(out, dict):
            rows = [
                {k: v[i] for k, v in out.items()} for i in range(len(batch))
            ]
        else:
            rows = out
        now_mono = time.monotonic()
        for rec, row in zip(batch, rows):
            if rec.deadline is not None and now_mono > rec.deadline:
                if rec.settle():
                    self._m_late.inc()
                    self._finish(
                        rec, "late", replica=rep.name,
                        exc=DeadlineExceededError(
                            "request deadline passed during batch "
                            "coalescing/compute"
                        ),
                    )
            elif rec.settle():
                rep.served += 1
                self._m_served.labels(rep.name).inc()
                self._finish(rec, "ok", result=row, replica=rep.name)
        # a whole good batch resets the slot's backoff ladder — unless this
        # is a zombie incarnation that already lost its slot to a restart
        if not self._stale(rep):
            self._fails[rep.idx] = 0
            if self._health is not None:
                self._health.beat(f"replica.{rep.name}")
        return True

    # --------------------------------------------------------- supervisor

    def _on_failure(self, rep: _Replica, batch, exc, *, kind: str) -> None:
        err = f"{type(exc).__name__}: {exc}"
        self._m_crashes.labels(rep.name, kind).inc()
        self._event(
            "replica_crash", replica=rep.name, kind=kind, gen=rep.gen, err=err
        )
        self._mark_down(rep)
        for rec in batch:
            self._requeue(rec, rep, err)
        self._drain_slot(rep, err)

    def _mark_down(self, rep: _Replica) -> None:
        with self._state_lock:
            if self._stale(rep) or rep.state == "down":
                return
            rep.state = "down"
            self._m_up.labels(rep.name).set(0)
            self._fails[rep.idx] += 1
            backoff = min(
                self.restart_backoff_s * 2 ** (self._fails[rep.idx] - 1),
                self.restart_backoff_max_s,
            )
            self._restart_at[rep.idx] = self._clock() + backoff
            self._update_health()

    def _update_health(self) -> None:
        healthy = self._healthy_count()
        self._m_healthy.set(healthy)
        open_now = healthy < self.quorum
        if open_now and not self._breaker_open:
            self._breaker_open = True
            self._m_breaker.set(1)
            self._m_breaker_trips.inc()
            self._event(
                "breaker_open", healthy=healthy, quorum=self.quorum
            )
        elif not open_now and self._breaker_open:
            self._breaker_open = False
            self._m_breaker.set(0)
            self._event(
                "breaker_close", healthy=healthy, quorum=self.quorum
            )

    def _supervise(self) -> None:
        while not self._closed:
            now = self._clock()
            # retired slots keep getting rescued: a submit that raced a
            # scale-down removal may still land on a retired queue
            for rep in list(self._retired):
                self._drain_slot(rep, "replica removed")
            for rep in list(self._slots):
                if rep.state in ("up", "paused"):
                    try:
                        # preemption notice: ticked once per routable
                        # replica per supervisor pass (key = replica name)
                        fault_point("serve.preempt", key=rep.name)
                    except Exception:
                        # drain in a thread — a 10s drain must not stall
                        # hang detection for every other replica
                        threading.Thread(
                            target=self.preempt, args=(rep.idx,),
                            daemon=True, name=f"replica-preempt-{rep.name}",
                        ).start()
                        continue
                    busy = rep.busy_since
                    if busy is not None and now - busy > self.hang_timeout_s:
                        # hung predict: abandon the thread, rescue the work
                        self._m_crashes.labels(rep.name, "hang").inc()
                        self._event(
                            "replica_hang", replica=rep.name, gen=rep.gen,
                            busy_s=round(now - busy, 3),
                        )
                        self._mark_down(rep)
                        for rec in list(rep.pending):
                            self._requeue(rec, rep, "hung predict")
                        self._drain_slot(rep, "hung predict")
                elif rep.state == "down":
                    idx = rep.idx
                    # racing submits may still land on a dead queue; keep
                    # rescuing them every tick until the slot restarts
                    self._drain_slot(rep, "replica down")
                    if (
                        now >= self._restart_at[idx]
                        and not self._restarting[idx]
                    ):
                        self._restarting[idx] = True
                        threading.Thread(
                            target=self._restart_slot, args=(idx,),
                            daemon=True, name=f"replica-restart-{rep.name}",
                        ).start()
            time.sleep(self._interval)

    def _restart_slot(self, idx: int) -> None:
        old = self._slots[idx]
        try:
            try:
                engine = self._provider(idx)
            except BaseException as e:  # noqa: BLE001 — a provider error is a failed restart
                self._m_crashes.labels(old.name, "restart_error").inc()
                self._event(
                    "replica_restart_failed", replica=old.name,
                    err=f"{type(e).__name__}: {e}",
                )
                with self._state_lock:
                    self._fails[idx] += 1
                    backoff = min(
                        self.restart_backoff_s * 2 ** (self._fails[idx] - 1),
                        self.restart_backoff_max_s,
                    )
                    self._restart_at[idx] = self._clock() + backoff
                return
            if self._closed:
                return
            rep = _Replica(idx, gen=old.gen + 1, engine=engine)
            with self._state_lock:
                # the shutdown latch: close() sets _closed under this lock
                # before sweeping, so checking here (not just above, where
                # the slow provider build races close) guarantees a new
                # incarnation is never installed after close began
                if self._closed:
                    return
                self._slots[idx] = rep
            self._start_worker(rep)
            self._m_up.labels(rep.name).set(1)
            self._m_restarts.labels(rep.name).inc()
            self._event("replica_restart", replica=rep.name, gen=rep.gen)
            if self._health is not None:
                self._health.beat(f"replica.{rep.name}")
            with self._state_lock:
                self._update_health()
            # anything stranded on the old incarnation's queue rides over
            self._drain_slot(old, "superseded incarnation")
        finally:
            self._restarting[idx] = False


class WeightSwapController:
    """Parity- and canary-gated zero-downtime weight hot-swap over a
    :class:`ReplicaSet` (state machine in the module docstring).

    ``restore_fn(path) -> (params, batch_stats)`` defaults to
    ``train.checkpoint.restore_inference_state`` (host-side restore — the
    double buffer lives in host memory, one extra tree, not N).
    ``features_fn(engine, images)`` defaults to ``engine.features`` — the
    probe both parity legs run. ``on_promote(ckpt)`` lets the owner
    repoint the replica provider (and its own bookkeeping) at the newly
    shipped checkpoint.
    """

    def __init__(
        self,
        replicaset: ReplicaSet,
        *,
        restore_fn=None,
        features_fn=None,
        parity_images=None,
        parity_min_cosine: float = 0.98,
        canary_slo: str = "success_rate>=0.99",
        canary_requests: int = 16,
        canary_timeout_s: float = 30.0,
        drain_timeout_s: float = 10.0,
        on_promote=None,
        headroom_fn=None,
        registry=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.rs = replicaset
        self._restore_fn = restore_fn
        self._features_fn = features_fn or (
            lambda engine, images: engine.features(images)
        )
        self.parity_images = parity_images
        self.parity_min_cosine = float(parity_min_cosine)
        self.canary_slo = canary_slo
        self.canary_requests = int(canary_requests)
        self.canary_timeout_s = float(canary_timeout_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self._on_promote = on_promote
        # optional memory gate (obs/memwatch.MemoryWatcher.headroom_check):
        # called with the swap's double-buffer byte need (new tree + the
        # rollback snapshot of the old one) AFTER restore succeeds, BEFORE
        # any replica is touched — returns None (fits) or a reason string
        self._headroom_fn = headroom_fn
        self._clock = clock
        self._swap_lock = lockwatch.lock("replicaset.swap")
        self.last_report: dict | None = None
        reg = registry if registry is not None else get_registry()
        self._m_attempts = reg.counter(
            "serve_swap_attempts_total", "weight hot-swap attempts"
        )
        self._m_promoted = reg.counter(
            "serve_swap_promoted_total", "hot-swaps promoted to the full pool"
        )
        self._m_rollbacks = reg.counter(
            "serve_swap_rollbacks_total",
            "hot-swaps rolled back (parity gate, canary breach, canary "
            "crash, or promote failure)",
        )
        self._m_rejected = reg.counter(
            "serve_swap_rejected_total",
            "hot-swaps rejected before any replica was flipped "
            "(restore/graft failure, memory headroom, no routable canary)",
        )
        self._m_active = reg.gauge(
            "serve_swap_active", "1 while a swap is in flight"
        )
        self._m_parity = reg.gauge(
            "serve_swap_parity_cosine",
            "min feature cosine of the last swap's parity gate",
        )
        self._m_canary_burn = reg.gauge(
            "serve_swap_canary_burn",
            "worst slow-window burn rate of the last canary evaluation",
        )

    # ------------------------------------------------------------ plumbing

    def _restore(self, ckpt: str):
        if self._restore_fn is not None:
            params, stats = self._restore_fn(ckpt)
        else:
            from jumbo_mae_tpu_tpu.train.checkpoint import (
                restore_inference_state,
            )

            params, stats = restore_inference_state(ckpt, to_device=False)
        from jumbo_mae_tpu_tpu.infer.engine import _to_state_dict

        params = _to_state_dict(params)
        # the bad-push chaos site: `corrupt` diverges the tree (parity must
        # catch it), `raise` models an unreadable checkpoint
        params = fault_point("ckpt.load", key=str(ckpt), data=params)
        return params, stats

    def _event(self, etype: str, **fields) -> None:
        self.rs._event(etype, **fields)

    def _parity(self, engine, reference_feats) -> dict:
        from jumbo_mae_tpu_tpu.infer.quant import feature_cosine

        cand = np.asarray(self._features_fn(engine, self.parity_images))
        cos = feature_cosine(np.asarray(reference_feats), cand)
        cos_min = float(np.min(cos))
        self._m_parity.set(cos_min)
        return {
            "cosine_min": cos_min,
            "cosine_mean": float(np.mean(cos)),
            "tolerance": self.parity_min_cosine,
            "within_tolerance": cos_min >= self.parity_min_cosine,
        }

    def _reject(self, report: dict, stage: str, err: str) -> dict:
        report.update(verdict="rejected", stage=stage, error=err)
        self._m_rejected.inc()
        self._event("swap_rejected", ckpt=report["ckpt"], stage=stage, err=err)
        return report

    def _rollback(self, report: dict, stage: str, detail: str) -> dict:
        report.update(verdict="rolled_back", stage=stage, detail=detail)
        self._m_rollbacks.inc()
        self._event(
            "swap_rollback", ckpt=report["ckpt"], stage=stage, detail=detail
        )
        return report

    # ---------------------------------------------------------------- swap

    def swap(self, ckpt: str) -> dict:
        """Run one checkpoint through restore → parity → canary → promote;
        returns the report dict (``verdict``: promoted | rolled_back |
        rejected). One swap at a time; a second caller blocks."""
        with self._swap_lock:
            report = self._swap(str(ckpt))
            self.last_report = report
            return report

    def _swap(self, ckpt: str) -> dict:
        report: dict = {"ckpt": ckpt, "verdict": None, "stage": None}
        self._m_attempts.inc()
        self._m_active.set(1)
        self._event("swap_start", ckpt=ckpt)
        try:
            try:
                params, stats = self._restore(ckpt)
            except BaseException as e:  # noqa: BLE001 — an unreadable push is a verdict
                return self._reject(
                    report, "restore", f"{type(e).__name__}: {e}"
                )
            if self._headroom_fn is not None:
                from jumbo_mae_tpu_tpu.obs.memwatch import tree_nbytes

                # double buffer: the restored tree plus the snapshot of the
                # old one both live until promote/rollback resolves
                need = 2 * tree_nbytes(params)
                try:
                    shortfall = self._headroom_fn(need)
                except Exception:  # noqa: BLE001 — a broken probe must not block swaps
                    shortfall = None
                if shortfall:
                    return self._reject(report, "headroom", str(shortfall))
            canary = self.rs.first_routable()
            if canary is None:
                return self._reject(report, "canary_pick", "no routable replica")
            report["canary"] = canary.name
            canary_gen = canary.gen
            self.rs.pause(canary.idx)
            try:
                self.rs.wait_idle(canary.idx, self.drain_timeout_s)
                if self.parity_images is None:
                    self.parity_images = self._default_probe(canary.engine)
                ref = np.asarray(
                    self._features_fn(canary.engine, self.parity_images)
                )
                try:
                    snap = canary.engine.swap_weights(
                        params, stats, ckpt=ckpt
                    )
                except BaseException as e:  # noqa: BLE001 — graft failure leaves old weights live
                    return self._reject(
                        report, "graft", f"{type(e).__name__}: {e}"
                    )
                try:
                    parity = self._parity(canary.engine, ref)
                except BaseException as e:  # noqa: BLE001 — a probe crash is a failed gate
                    canary.engine.restore_snapshot(snap)
                    return self._rollback(
                        report, "parity", f"probe error: {type(e).__name__}: {e}"
                    )
                report["parity"] = parity
                if not parity["within_tolerance"]:
                    canary.engine.restore_snapshot(snap)
                    return self._rollback(
                        report, "parity",
                        f"cosine_min {parity['cosine_min']:.4f} < "
                        f"{self.parity_min_cosine}",
                    )
            finally:
                self.rs.resume(canary.idx)
            self._event(
                "swap_canary", ckpt=ckpt, replica=canary.name,
                cosine_min=report.get("parity", {}).get("cosine_min"),
            )
            breach, canary_report = self._canary_window(canary, canary_gen)
            report["canary_eval"] = canary_report
            if breach:
                if self.rs.generation(canary.idx) == canary_gen:
                    self.rs.pause(canary.idx)
                    self.rs.wait_idle(canary.idx, self.drain_timeout_s)
                    canary.engine.restore_snapshot(snap)
                    self.rs.resume(canary.idx)
                # else: the canary crashed and its replacement was rebuilt
                # by the provider — which still serves the old weights
                return self._rollback(report, "canary", canary_report["why"])
            # promote: flip the survivors one at a time, never all at once
            flipped = [(canary.idx, canary_gen, snap)]
            for rep in list(self.rs._slots):
                if rep.idx == canary.idx or rep.state != "up":
                    continue
                self.rs.pause(rep.idx)
                self.rs.wait_idle(rep.idx, self.drain_timeout_s)
                try:
                    s = rep.engine.swap_weights(params, stats, ckpt=ckpt)
                    flipped.append((rep.idx, rep.gen, s))
                except BaseException as e:  # noqa: BLE001 — undo the partial promote
                    self.rs.resume(rep.idx)
                    for idx, gen, s2 in flipped:
                        if self.rs.generation(idx) == gen:
                            self.rs.pause(idx)
                            self.rs.wait_idle(idx, self.drain_timeout_s)
                            self.rs.replica(idx).engine.restore_snapshot(s2)
                            self.rs.resume(idx)
                    return self._rollback(
                        report, "promote",
                        f"{rep.name}: {type(e).__name__}: {e}",
                    )
                self.rs.resume(rep.idx)
            if self._on_promote is not None:
                try:
                    self._on_promote(ckpt)
                except Exception:  # noqa: BLE001 — bookkeeping must not fail a shipped swap
                    pass
            self._m_promoted.inc()
            self._event("swap_promoted", ckpt=ckpt)
            report.update(verdict="promoted", stage="promote")
            return report
        finally:
            self._m_active.set(0)

    def _default_probe(self, engine) -> np.ndarray:
        size = getattr(engine, "image_size", 32)
        return (
            np.random.RandomState(0)
            .randint(0, 256, (4, size, size, 3))
            .astype(np.uint8)
        )

    def _canary_window(self, canary, canary_gen: int) -> tuple[bool, dict]:
        """Watch only the canary replica's live outcomes through a
        dedicated burn-rate tracker; returns (breached, report)."""
        from jumbo_mae_tpu_tpu.obs.slo import SLOTracker, parse_slo

        tracker = SLOTracker(
            parse_slo(self.canary_slo),
            window_s=max(self.canary_timeout_s, 1.0),
            registry=NULL_REGISTRY,
        )
        seen = {"n": 0}

        def feed(replica, outcome, latency_s, retries):
            if replica == canary.name:
                seen["n"] += 1
                tracker.observe(latency_s, outcome)

        self.rs.add_observer(feed)
        self.rs.set_canary_preference(canary.name)
        try:
            deadline = self._clock() + self.canary_timeout_s
            while self._clock() < deadline:
                if seen["n"] >= self.canary_requests:
                    break
                if self.rs.generation(canary.idx) != canary_gen:
                    return True, {
                        "requests": seen["n"],
                        "why": "canary replica crashed during the window",
                    }
                time.sleep(0.01)
        finally:
            self.rs.set_canary_preference(None)
            self.rs.remove_observer(feed)
        if self.rs.generation(canary.idx) != canary_gen:
            return True, {
                "requests": seen["n"],
                "why": "canary replica crashed during the window",
            }
        ev = tracker.evaluate()
        worst = max(
            (o["burn_slow"] for o in ev["objectives"]), default=0.0
        )
        self._m_canary_burn.set(worst)
        breached = bool(ev["degraded"]) or any(
            o["breached"] for o in ev["objectives"]
        )
        why = (
            "canary SLO breached: "
            + "; ".join(
                f"{o['name']}={o['value']} (burn {o['burn_slow']})"
                for o in ev["objectives"]
                if o["breached"]
            )
            if breached
            else "ok"
        )
        return breached, {
            "requests": seen["n"],
            "burn_worst": worst,
            "objectives": ev["objectives"],
            "why": why,
        }
