from jumbo_mae_tpu_tpu.train.checkpoint import (
    CheckpointConfig,
    Checkpointer,
    export_params_msgpack,
    import_params_msgpack,
    load_pretrained_params,
)
from jumbo_mae_tpu_tpu.train.elastic import ElasticSupervisor
from jumbo_mae_tpu_tpu.train.engine import (
    EXIT_ELASTIC,
    EXIT_FATAL,
    EXIT_HANG,
    EXIT_OK,
    CheckpointEvent,
    exit_code_for,
    LogWindow,
    RunEngine,
    StepEvent,
)
from jumbo_mae_tpu_tpu.train.optim import OptimConfig, make_optimizer, make_schedule
from jumbo_mae_tpu_tpu.train.state import TrainState
from jumbo_mae_tpu_tpu.train.steps import (
    create_sharded_state,
    make_eval_step,
    make_train_step,
)

__all__ = [
    "CheckpointConfig",
    "Checkpointer",
    "export_params_msgpack",
    "import_params_msgpack",
    "load_pretrained_params",
    "CheckpointEvent",
    "ElasticSupervisor",
    "EXIT_ELASTIC",
    "EXIT_FATAL",
    "EXIT_HANG",
    "EXIT_OK",
    "exit_code_for",
    "LogWindow",
    "RunEngine",
    "StepEvent",
    "OptimConfig",
    "make_optimizer",
    "make_schedule",
    "TrainState",
    "create_sharded_state",
    "make_eval_step",
    "make_train_step",
]
