from jumbo_mae_tpu_tpu.train.optim import OptimConfig, make_optimizer, make_schedule
from jumbo_mae_tpu_tpu.train.state import TrainState
from jumbo_mae_tpu_tpu.train.steps import (
    create_sharded_state,
    make_eval_step,
    make_train_step,
)

__all__ = [
    "OptimConfig",
    "make_optimizer",
    "make_schedule",
    "TrainState",
    "create_sharded_state",
    "make_eval_step",
    "make_train_step",
]
