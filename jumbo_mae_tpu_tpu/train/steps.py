"""jit + GSPMD train/eval steps and sharded state creation.

This is the runtime the reference delegated to ``jax.pmap``
(``/root/reference/src/pretraining.py:125-167``,
``/root/reference/src/finetuning.py:109-165``), rebuilt mesh-native:

- ONE ``jax.jit`` program per step over an explicit mesh; the batch is
  sharded over (data, fsdp), parameters/optimizer state over fsdp (ZeRO-3
  rule in ``parallel/sharding.py``). GSPMD inserts the gradient
  reduce-scatter/all-gather the reference expressed as ``lax.pmean``.
- Gradient accumulation is a ``lax.scan`` over a leading micro-batch axis
  *inside* the step — one device dispatch per optimizer update — instead of
  the reference's host-visible micro-step counter + ``lax.cond`` state
  machine.
- Metrics come back as global scalars (the mean over a globally-sharded
  batch IS the cross-replica mean; no explicit collective needed).
- Eval aggregates per-sample metrics against an explicit ``valid`` mask,
  fixing the reference's mis-normalized pretrain val loss
  (``/root/reference/src/main_pretrain.py:43-45``, SURVEY defect #2) and its
  count-the-padding ``num_samples`` quirk.

State creation initializes parameters *already sharded* via
``jax.jit(init, out_shardings=...)`` — no host-resident full copy, which is
what makes ViT-H-scale FSDP init feasible on small hosts.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Literal

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh

from jumbo_mae_tpu_tpu.faults.sentinel import guarded_apply_gradients
from jumbo_mae_tpu_tpu.obs.modelstats import group_stats
from jumbo_mae_tpu_tpu.parallel.sharding import (
    batch_sharding,
    infer_state_sharding,
)
from jumbo_mae_tpu_tpu.train.state import (
    EVAL_DOMAIN,
    STREAMS,
    TrainState,
    make_base_rng,
)

Mode = Literal["pretrain", "classify"]

# Folded into the "dropout" stream before it enters the gpipe key
# derivation ("pipe" in ASCII) — keeps pipeline keys out of any integer
# range flax's path-folding could produce for the sequential blocks.
PIPE_RNG_DOMAIN = 0x70697065


def _tree_add(a, b):
    return jax.tree_util.tree_map(jnp.add, a, b)


def _tree_scale(a, s):
    return jax.tree_util.tree_map(lambda x: x * s, a)


def _model_inputs(mode: Mode, batch: dict) -> tuple:
    if mode == "pretrain":
        return (batch["images"],)
    return (batch["images"], batch["labels"])


def create_sharded_state(
    module,
    tx: optax.GradientTransformation,
    example_batch: dict,
    mesh: Mesh,
    *,
    mode: Mode,
    init_seed: int = 0,
    rng_seed: int = 0,
    min_shard_size: int = 2**16,
    param_dtype: str | None = None,
) -> tuple[TrainState, Any]:
    """Initialize a TrainState directly into its mesh sharding.

    Returns ``(state, state_sharding)``; the sharding tree is reused by the
    step factories and the checkpoint manager.

    ``param_dtype`` casts the stored params after init (e.g. "bfloat16" for
    half weight-read HBM traffic); pair it with ``optim.param_dtype`` so the
    optimizer keeps a float32 master copy (``with_master_weights``).
    """
    from jumbo_mae_tpu_tpu.utils.compat import ensure_partitionable_rng

    # init draws must not depend on the mesh layout (jax 0.4.x defaults
    # non-partitionable threefry, where they do)
    ensure_partitionable_rng()
    inputs = _model_inputs(mode, example_batch)
    init_rngs = {
        "params": jax.random.key(init_seed),
        **{
            name: jax.random.fold_in(jax.random.key(init_seed), sid + 1)
            for name, sid in STREAMS.items()
        },
    }

    def init_fn():
        variables = module.init(init_rngs, *inputs)
        params = variables["params"]
        if param_dtype is not None:
            dt = jnp.dtype(param_dtype)
            params = jax.tree_util.tree_map(lambda p: p.astype(dt), params)
        return TrainState.create(
            apply_fn=module.apply,
            params=params,
            tx=tx,
            batch_stats=variables.get("batch_stats"),
            rng=make_base_rng(rng_seed),
        )

    shapes = jax.eval_shape(init_fn)
    sharding = infer_state_sharding(shapes, mesh, min_shard_size=min_shard_size)
    state = jax.jit(init_fn, out_shardings=sharding)()
    return state, sharding


def make_train_step(
    mesh: Mesh,
    state_sharding: Any,
    *,
    mode: Mode,
    grad_accum: int = 1,
    pipe_microbatches: int = 0,
    encoder_cfg: Any = None,
    decoder_cfg: Any = None,
    guard_nonfinite: bool = False,
    diag: bool = False,
) -> Callable[[TrainState, dict], tuple[TrainState, dict]]:
    """Build the jitted train step.

    ``grad_accum == 1``: batch leaves are (batch, ...).
    ``grad_accum > 1``: batch leaves are (accum, micro, ...) and a
    ``lax.scan`` accumulates gradients before the single optimizer update.

    ``guard_nonfinite=True`` compiles the divergence guard into the step
    (``faults/sentinel.py``): non-finite loss or grad-norm steps skip the
    optimizer update via ``lax.cond`` — state passes through untouched
    except ``step + 1`` — and the metrics gain ``grad_norm`` and
    ``skipped``. Same program either way batch-to-batch: no recompile.

    The returned callable accepts an optional third argument ``inject`` —
    a ``(2,)`` float32 host array ``[loss_mult, grad_mult]`` (defaults to
    ones) multiplied into the differentiated loss and the gradients. It is
    a *traced* input, so the fault-injection harness can turn a chosen
    step's loss/grads NaN (``train.loss`` / ``train.grad`` sites) without
    triggering a compile; a multiply by exactly 1.0 is bit-exact in every
    float dtype, so un-injected runs are numerically identical.

    ``diag=True`` (a STATIC flag — the ``diag=False`` program is untouched)
    additionally compiles per-layer-group diagnostics into the step
    (``obs/modelstats.py``): the metrics gain ``diag``, a ``(groups, 3)``
    float32 array of (grad_norm, param_norm, update_ratio) per layer group
    in :func:`~jumbo_mae_tpu_tpu.obs.modelstats.group_layout` order, and
    ``finite_frac``, the finite fraction of the per-sample loss batch. The
    host decides the fetch cadence (``run.diag_every``).

    ``pipe_microbatches > 0`` (requires ``encoder_cfg`` and a mesh with a
    ``pipe`` axis): the encoder's block chain runs through the GPipe
    schedule (``parallel/pipeline.py``) via the model's ``blocks_override``
    seam — same parameters, pipelined execution. Works for BOTH modes
    (pretrain and classify/finetune — the classifier shares the JumboViT
    encoder). With ``decoder_cfg`` additionally set (pretrain only), the
    MAE decoder stack is depth-sharded through the same schedule via its
    own seam (``dec_blocks_override``).
    """
    if pipe_microbatches:
        if encoder_cfg is None:
            raise ValueError("pipe_microbatches requires encoder_cfg")
        if "pipe" not in mesh.shape:
            raise ValueError("pipe_microbatches requires a mesh with a 'pipe' axis")
        if decoder_cfg is not None and mode != "pretrain":
            raise ValueError("decoder pipelining applies to pretrain only")
        from jumbo_mae_tpu_tpu.parallel.pipeline import (
            make_jumbo_pipeline_apply,
            make_plain_pipeline_apply,
        )

        pipeline_apply = make_jumbo_pipeline_apply(
            encoder_cfg, mesh=mesh, microbatches=pipe_microbatches
        )
        # the encoder subtree lives under "encoder" in MAEPretrainModel
        # trees and "model" in ClassificationModel trees
        enc_key = "encoder" if mode == "pretrain" else "model"
        # dropout/droppath ride gpipe's per-(shard, block, microbatch)
        # key derivation (parallel/pipeline.py); deterministic configs
        # skip the rng plumbing entirely
        pipe_stochastic = (encoder_cfg.dropout or 0) > 0 or (
            encoder_cfg.droppath or 0
        ) > 0
        dec_pipeline_apply = None
        if decoder_cfg is not None:
            dec_pipeline_apply = make_plain_pipeline_apply(
                decoder_cfg, mesh=mesh, microbatches=pipe_microbatches
            )
            dec_stochastic = (decoder_cfg.dropout or 0) > 0 or (
                decoder_cfg.droppath or 0
            ) > 0

    def loss_fn(params, batch_stats, micro_idx, batch, state, loss_mult):
        rngs = state.step_rngs(micro=micro_idx)
        variables = {"params": params}
        extra = {}
        if pipe_microbatches:
            enc_params = params[enc_key]
            # domain-separated from flax's own path-folded "dropout" use so
            # the pipeline's integer folds can't collide with module
            # streams; encoder and decoder pipelines get disjoint folds
            pipe_base = jax.random.fold_in(rngs["dropout"], PIPE_RNG_DOMAIN)
            pipe_rng = (
                jax.random.fold_in(pipe_base, 0) if pipe_stochastic else None
            )
            extra["blocks_override"] = lambda x: pipeline_apply(
                enc_params, x, pipe_rng
            )
            if dec_pipeline_apply is not None:
                dec_params = params["decoder"]
                dec_rng = (
                    jax.random.fold_in(pipe_base, 1)
                    if dec_stochastic
                    else None
                )
                extra["dec_blocks_override"] = lambda x: dec_pipeline_apply(
                    dec_params, x, dec_rng
                )
        new_stats = None
        if batch_stats is not None:
            variables["batch_stats"] = batch_stats
            out, updated = state.apply_fn(
                variables,
                *_model_inputs(mode, batch),
                deterministic=False,
                rngs=rngs,
                mutable=["batch_stats"],
                **extra,
            )
            new_stats = updated["batch_stats"]
        else:
            out = state.apply_fn(
                variables,
                *_model_inputs(mode, batch),
                deterministic=False,
                rngs=rngs,
                **extra,
            )
        metrics = {
            k: v.mean() if v.ndim else v
            for k, v in out.items()
            if not k.endswith("_per_sample")
        }
        if diag:
            # finite fraction of the loss batch: per-sample where the model
            # exposes it (pretrain loss_per_sample, classify per-sample
            # loss), else the scalar's own finiteness
            ps = out.get("loss_per_sample", out["loss"])
            fin = jnp.isfinite(ps).astype(jnp.float32)
            metrics["finite_frac"] = fin.mean() if fin.ndim else fin
        return metrics["loss"] * loss_mult, (metrics, new_stats)

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    @partial(
        jax.jit,
        donate_argnums=(0,),
        in_shardings=(
            state_sharding,
            batch_sharding(mesh, accum=grad_accum > 1),
            None,
        ),
        out_shardings=(state_sharding, None),
    )
    def _train_step(state: TrainState, batch: dict, inject):
        loss_mult, grad_mult = inject[0], inject[1]
        if grad_accum == 1:
            (_, (metrics, new_stats)), grads = grad_fn(
                state.params, state.batch_stats, 0, batch, state, loss_mult
            )
        else:
            metrics_shape = jax.eval_shape(
                lambda: loss_fn(
                    state.params,
                    state.batch_stats,
                    0,
                    jax.tree_util.tree_map(lambda x: x[0], batch),
                    state,
                    loss_mult,
                )[1][0]
            )
            # Accumulate in float32 even when params (and so grads) are
            # bf16-stored: micro-grad sums lose mantissa fast in bf16.
            init = (
                jax.tree_util.tree_map(
                    lambda p: jnp.zeros(p.shape, jnp.float32), state.params
                ),
                jax.tree_util.tree_map(
                    lambda s: jnp.zeros(s.shape, s.dtype), metrics_shape
                ),
                state.batch_stats,
            )

            def micro(carry, xs):
                grads_acc, metrics_acc, stats = carry
                idx, micro_batch = xs
                (_, (metrics, new_stats)), grads = grad_fn(
                    state.params, stats, idx, micro_batch, state, loss_mult
                )
                grads = jax.tree_util.tree_map(
                    lambda g: g.astype(jnp.float32), grads
                )
                return (
                    _tree_add(grads_acc, grads),
                    _tree_add(metrics_acc, metrics),
                    new_stats if new_stats is not None else stats,
                ), None

            (grads, metrics, new_stats), _ = jax.lax.scan(
                micro, init, (jnp.arange(grad_accum), batch)
            )
            grads = _tree_scale(grads, 1.0 / grad_accum)
            metrics = _tree_scale(metrics, 1.0 / grad_accum)

        grads = jax.tree_util.tree_map(
            lambda g: g * grad_mult.astype(g.dtype), grads
        )
        prev_params = state.params if diag else None
        if guard_nonfinite:
            # the guard must see the INJECTED loss (metrics keep the raw
            # one): raw_loss x loss_mult is exactly the differentiated value
            loss_val = metrics["loss"] * loss_mult
            state, grad_norm, finite = guarded_apply_gradients(
                state, grads, loss_val
            )
            if new_stats is not None:
                # BatchNorm stats from a non-finite forward are tainted too
                state = state.replace(
                    batch_stats=jax.tree_util.tree_map(
                        lambda new, old: jnp.where(finite, new, old),
                        new_stats,
                        state.batch_stats,
                    )
                )
            metrics = metrics | {
                "grad_norm": grad_norm,
                "skipped": 1.0 - finite.astype(jnp.float32),
            }
        else:
            state = state.apply_gradients(grads=grads)
            if new_stats is not None:
                state = state.replace(batch_stats=new_stats)
        if diag:
            # one stacked (groups, 3) array — a single small host fetch per
            # diagnostic step instead of a tree of scalars
            metrics = metrics | {
                "diag": group_stats(prev_params, grads, state.params)
            }
        hyper = getattr(state.opt_state, "hyperparams", None)
        if hyper is not None:
            metrics = metrics | {"learning_rate": hyper["learning_rate"]}
        return state, metrics

    no_inject = np.ones(2, np.float32)

    # Dispatch through an AOT-compiled executable (lower().compile(), keyed
    # by batch shapes) instead of the tracing jit wrapper. Two reasons:
    # (1) cost observability — ``Compiled.cost_analysis()`` needs the
    # executable in hand, and jax's AOT path is NOT deduped against the C++
    # jit cache, so a post-hoc ``lower().compile()`` on an already-traced
    # jit function would compile the whole program a second time;
    # (2) it makes the train loop's compile point explicit, matching the
    # serving engine's idiom. Any AOT failure degrades permanently to the
    # plain jit path (``Compiled.__call__`` validates avals/shardings before
    # buffers are donated, so falling back after a raise is safe).
    aot: dict[tuple, Any] = {}
    state_fallback = {"plain": False}

    def _batch_key(batch: dict) -> tuple:
        return tuple(
            (k, tuple(v.shape), str(getattr(v, "dtype", type(v))))
            for k, v in sorted(batch.items())
        )

    def train_step(state: TrainState, batch: dict, inject=None):
        inj = no_inject if inject is None else np.asarray(inject, np.float32)
        if not state_fallback["plain"]:
            key = _batch_key(batch)
            compiled = aot.get(key)
            if compiled is None:
                try:
                    compiled = _train_step.lower(state, batch, inj).compile()
                    aot[key] = compiled
                except Exception:  # noqa: BLE001 - AOT is an optimization
                    state_fallback["plain"] = True
            if compiled is not None:
                try:
                    return compiled(state, batch, inj)
                except Exception:  # noqa: BLE001 - pre-execution validation
                    state_fallback["plain"] = True
        return _train_step(state, batch, inj)

    train_step.executables = aot  # read by cli/train's cost extraction
    return train_step


def make_eval_step(
    mesh: Mesh, state_sharding: Any, *, mode: Mode
) -> Callable[[TrainState, dict], dict]:
    """Jitted eval step returning SUMS over valid samples + the valid count;
    the host-side loop divides at the end (exact weighted mean even with
    ragged final batches). ``batch_idx`` varies the eval RNG (MAE masking)
    across the eval loop's batches; derivation is domain-separated from
    training so no (step, micro) coordinate can collide."""

    @partial(
        jax.jit,
        in_shardings=(state_sharding, batch_sharding(mesh, accum=False), None),
        out_shardings=None,
    )
    def _eval_step(state: TrainState, batch: dict, batch_idx):
        rngs = state.step_rngs(micro=batch_idx, domain=EVAL_DOMAIN)
        variables = {"params": state.params}
        if state.batch_stats is not None:
            variables["batch_stats"] = state.batch_stats
        valid = batch.get("valid")
        if valid is None:
            valid = jnp.ones(batch["images"].shape[0], jnp.float32)
        else:
            valid = valid.astype(jnp.float32)

        if mode == "pretrain":
            out = state.apply_fn(
                variables, batch["images"], deterministic=True, rngs=rngs
            )
            per_sample = {"loss": out["loss_per_sample"]}
        else:
            labels = jnp.where(batch["labels"] >= 0, batch["labels"], 0)
            out = state.apply_fn(
                variables, batch["images"], labels, deterministic=True
            )
            per_sample = {k: out[k] for k in ("loss", "acc1", "acc5")}

        sums = {k: jnp.sum(v * valid) for k, v in per_sample.items()}
        sums["num_samples"] = valid.sum()
        return sums

    def eval_step(state: TrainState, batch: dict, batch_idx: int = 0):
        return _eval_step(state, batch, jnp.asarray(batch_idx, jnp.int32))

    return eval_step
