"""Train state: parameters, optimizer state, BatchNorm stats, and ONE rng key.

Replaces the reference's three threaded RNG streams + split-per-step design
(``/root/reference/src/pretraining.py:50-73``) with stateless derivation:
every step folds (base_key, step, stream_id) — reproducible from the seed
alone, immune to the stream-advancement bug the reference has in finetuning
(``/root/reference/src/finetuning.py:136-154``, SURVEY defect #1), and free
of per-device key plumbing (GSPMD gives every device the same program; where
per-position randomness matters, jax generates it from the same key sharded
consistently).
"""

from __future__ import annotations

from typing import Any

import jax
from flax import struct
from flax.training import train_state

# Stable stream ids for fold_in derivation.
STREAMS = {"dropout": 0, "noise": 1, "mixup": 2}

# Domain separators so train and eval derivations can never collide even at
# the same (step, micro) coordinates.
TRAIN_DOMAIN = 0
EVAL_DOMAIN = 1


class TrainState(train_state.TrainState):
    """flax TrainState + BatchNorm running stats + base rng key."""

    batch_stats: Any = None
    rng: jax.Array = struct.field(default=None)

    def step_rngs(
        self, *, micro: jax.Array | int = 0, domain: int = TRAIN_DOMAIN
    ) -> dict[str, jax.Array]:
        """Per-step, per-microbatch named rng streams."""
        base = jax.random.fold_in(self.rng, self.step)
        base = jax.random.fold_in(base, domain)
        base = jax.random.fold_in(base, micro)
        return {
            name: jax.random.fold_in(base, sid) for name, sid in STREAMS.items()
        }


def make_base_rng(seed: int, process_index: int | None = None) -> jax.Array:
    """Base key decorrelated across hosts (parity intent:
    ``/root/reference/src/pretraining.py:264-266`` — but folded, not added,
    so distinct seeds can't collide across processes)."""
    if process_index is None:
        process_index = jax.process_index()
    return jax.random.fold_in(jax.random.key(seed), process_index)
