"""RunEngine: the extracted step driver behind ``cli/train.py``.

The training loop used to be a ~400-line ``while`` block owning every
concern at once — data wait, dispatch, metric buffering, sentinel
verdicts, journaling, beacons, memory sampling, rollback, checkpointing,
preemption. The engine keeps only the *driver* logic:

- the step counter and the ``while step < training_steps`` loop,
- log-boundary batching of device metrics (sync ONLY at log boundaries —
  a per-step ``device_get`` would serialize host dispatch against device
  compute),
- eval/checkpoint boundary arithmetic,
- rollback control flow (a hook requests it; the registered rollback
  hooks perform the restore and return the resumed step),
- the preemption agreement point and the crash/shutdown ladder.

Everything else registers as a component through lifecycle hooks, in the
order the hooks should run:

- ``pre_step(engine, step)`` — before the data wait (beacon writes).
- ``on_step(engine, StepEvent)`` — after dispatch, metrics still on
  device. Hooks may mutate ``ev.metrics`` (e.g. strip non-scalar legs
  out of the pending buffer).
- ``on_log_window(engine, LogWindow)`` — at log boundaries with the
  window's fetched host metrics. Hooks share scratch via attributes on
  the window (``bad_steps`` etc.) and may call
  :meth:`RunEngine.request_rollback`.
- ``on_rollback(engine, step, window) -> int | None`` — perform the
  restore; the last non-``None`` return becomes the resumed step.
- ``on_eval(engine, step, state) -> dict | None`` — eval-boundary work;
  returned dicts merge into the checkpoint event's metrics.
- ``on_checkpoint(engine, CheckpointEvent)`` — the save itself plus
  anything riding the save (the weights publisher registers here).
- ``on_crash(engine, exc)`` — the run is dying; dump black boxes. Hooks
  may override ``engine.exit_reason``.
- ``on_shutdown(engine, reason, step)`` — the ``finally`` ladder, run in
  registration order however the loop exits.

``train``/``eval``/``publish`` loops share this one tested core; the
equivalence contract (same journal event stream as the monolithic loop)
is pinned by ``tests/test_engine.py``'s golden and the chaos suite.
"""

from __future__ import annotations

from typing import Callable

# -- exit-code protocol ---------------------------------------------------
# The ElasticSupervisor (train/elastic.py) classifies a dead child by its
# exit code. Codes are chosen outside the shell/signal ranges (1, 126-128,
# 128+N) so a supervisor can tell "the run asked to be restarted" from "the
# run tripped over a bug" from "the kernel killed it".
EXIT_OK = 0  # completed (or clean preemption checkpoint + exit)
EXIT_ELASTIC = 42  # host lost / membership change: restart me at new world
EXIT_FATAL = 43  # unrecoverable (diverged, config error): do NOT restart
EXIT_HANG = 44  # hang watchdog fired: a collective is wedged, restart me

#: exit reasons that must NOT be retried by a supervisor.
FATAL_REASONS = frozenset({"diverged"})


def exit_code_for(reason: str) -> int:
    """Map an engine ``exit_reason`` to the supervisor exit-code protocol.

    ``completed``/``preempted``/``stopped`` are clean exits; ``host_lost``
    asks for an elastic restart; reasons in :data:`FATAL_REASONS` (and any
    ``exception:*``) are fatal — the supervisor gives up rather than loop
    on a deterministic crash.
    """
    if reason in ("completed", "preempted", "stopped"):
        return EXIT_OK
    if reason == "host_lost":
        return EXIT_ELASTIC
    if reason == "hang":
        return EXIT_HANG
    return EXIT_FATAL


class StepEvent:
    """One dispatched step; ``metrics`` may still live on device and is
    mutable so hooks can strip non-scalar legs before buffering."""

    __slots__ = ("step", "metrics")

    def __init__(self, step: int, metrics):
        self.step = step
        self.metrics = metrics


class LogWindow:
    """One log boundary: ``fetched`` is ``[(step, host_metrics), ...]``
    for every step dispatched since the previous boundary. Hooks share
    derived scratch (``bad_steps``, ``summary``, ...) as attributes."""

    def __init__(self, step: int, fetched: list):
        self.step = step
        self.fetched = fetched
        self.bad_steps: list[int] = []


class CheckpointEvent:
    """One checkpoint boundary. ``reason`` is ``"interval"`` (periodic /
    final-step save), ``"preemption"`` (stop-flag save on the way out).
    ``metrics`` holds the merged ``on_eval`` results (``None`` when no
    eval ran). Hooks may attach attributes for later hooks in the chain
    (the saver stamps ``save_seconds``; the publisher reads it)."""

    def __init__(self, step: int, metrics: dict | None, reason: str):
        self.step = step
        self.metrics = metrics
        self.reason = reason


class RunEngine:
    """Hook-driven step driver (see module docstring).

    ``next_batch(step)`` produces the step's batch (host wait accounting
    belongs to the caller's closure); ``dispatch(state, batch, step) ->
    (state, metrics)`` issues the device step. ``should_stop()`` is the
    preemption agreement probe, evaluated at stop-safe boundaries only
    (multi-host agreement needs an allgather — per-step would serialize
    dispatch). ``fetch`` maps a list of device metric trees to host
    (default ``jax.device_get``); injectable so the driver itself is
    testable without a device.
    """

    def __init__(
        self,
        *,
        training_steps: int,
        start_step: int = 0,
        log_interval: int = 1,
        eval_interval: int = 0,
        ckpt_interval: int = 0,
        process_count: int = 1,
        next_batch: Callable[[int], object],
        dispatch: Callable,
        should_stop: Callable[[], bool] | None = None,
        fetch: Callable | None = None,
    ):
        self.training_steps = int(training_steps)
        self.start_step = int(start_step)
        self.log_interval = max(1, int(log_interval))
        self.eval_interval = int(eval_interval)
        self.ckpt_interval = int(ckpt_interval)
        self.process_count = int(process_count)
        self._next_batch = next_batch
        self._dispatch = dispatch
        self._should_stop = should_stop
        if fetch is None:
            import jax

            fetch = jax.device_get
        self._fetch = fetch

        self.state = None
        self.step = self.start_step
        self.exit_reason = "completed"
        self._pending: list = []  # [(step, device metrics)] → log boundary
        self._rollback_wanted = False
        self._stop_reason: str | None = None
        self._pre_step: list = []
        self._on_step: list = []
        self._on_log_window: list = []
        self._on_rollback: list = []
        self._on_eval: list = []
        self._on_checkpoint: list = []
        self._on_crash: list = []
        self._on_shutdown: list = []
        self._on_host_lost: list = []
        self.host_lost_info: dict | None = None

    # -- hook registration (usable as decorators; registration order is
    # -- execution order) ------------------------------------------------
    def pre_step(self, fn):
        self._pre_step.append(fn)
        return fn

    def on_step(self, fn):
        self._on_step.append(fn)
        return fn

    def on_log_window(self, fn):
        self._on_log_window.append(fn)
        return fn

    def on_rollback(self, fn):
        self._on_rollback.append(fn)
        return fn

    def on_eval(self, fn):
        self._on_eval.append(fn)
        return fn

    def on_checkpoint(self, fn):
        self._on_checkpoint.append(fn)
        return fn

    def on_crash(self, fn):
        self._on_crash.append(fn)
        return fn

    def on_shutdown(self, fn):
        self._on_shutdown.append(fn)
        return fn

    def on_host_lost(self, fn):
        """``fn(engine, info)`` — fired once at the stop-safe boundary
        after :meth:`notify_host_lost`, BEFORE the preemption checkpoint,
        so journal/flightrec/beacon hooks can record the membership change
        while the step context still exists."""
        self._on_host_lost.append(fn)
        return fn

    # -- control requests (called from hooks) ----------------------------
    def request_rollback(self) -> None:
        """Ask the driver to run the rollback chain after the current log
        window's hooks finish (the window must complete first: its
        metrics/black-box records describe the divergence)."""
        self._rollback_wanted = True

    def request_stop(self, reason: str = "stopped") -> None:
        """Ask the driver to exit at the next stop-safe boundary with
        ``exit_reason=reason`` (checkpointing first, like preemption)."""
        self._stop_reason = reason

    def notify_host_lost(self, info: dict | None = None) -> None:
        """A fleet peer is gone (dead beacon / supervisor signal). Records
        ``info`` (e.g. ``{"hosts": [1], "detected_by": "beacon"}``), fires
        the ``on_host_lost`` chain at the next stop-safe boundary, then
        exits with ``exit_reason="host_lost"`` → :data:`EXIT_ELASTIC`.

        The loop cannot keep stepping: the next collective would block on
        the dead peer forever. First notification wins."""
        if self._stop_reason != "host_lost":
            self.host_lost_info = dict(info or {})
            self.request_stop("host_lost")

    # -- boundaries ------------------------------------------------------
    def at_log_boundary(self, step: int) -> bool:
        return step % self.log_interval == 0 or step == self.training_steps

    def at_eval_boundary(self, step: int) -> bool:
        return step == self.training_steps or (
            self.eval_interval > 0 and step % self.eval_interval == 0
        )

    def at_ckpt_boundary(self, step: int) -> bool:
        """Checkpoint-only cadence (``run.ckpt_every``), decoupled from
        eval so the save interval can track failure rate, not eval cost.
        0 keeps the legacy coupling: saves ride eval boundaries only."""
        return self.ckpt_interval > 0 and step % self.ckpt_interval == 0

    # -- the driver ------------------------------------------------------
    def run(self, state):
        """Drive ``state`` from ``start_step`` to ``training_steps``.
        Returns the final state; ``exit_reason`` records how the loop
        ended (``completed`` / ``preempted`` / hook-assigned)."""
        self.state = state
        step = self.start_step
        self.step = step
        try:
            while step < self.training_steps:
                step += 1
                self.step = step
                for fn in self._pre_step:
                    fn(self, step)
                batch = self._next_batch(step)
                self.state, metrics = self._dispatch(self.state, batch, step)
                ev = StepEvent(step, metrics)
                for fn in self._on_step:
                    fn(self, ev)
                self._pending.append((ev.step, ev.metrics))

                if self.at_log_boundary(step):
                    # sync ONLY at log boundaries — one fetch for the
                    # whole window's device scalars
                    fetched = list(
                        zip(
                            [s for s, _ in self._pending],
                            self._fetch([m for _, m in self._pending]),
                        )
                    )
                    self._pending.clear()
                    win = LogWindow(step, fetched)
                    for fn in self._on_log_window:
                        fn(self, win)
                    if self._rollback_wanted:
                        self._rollback_wanted = False
                        new_step = None
                        for fn in self._on_rollback:
                            r = fn(self, step, win)
                            if r is not None:
                                new_step = r
                        if new_step is None:
                            raise RuntimeError(
                                "rollback requested but no on_rollback hook "
                                "returned the resumed step"
                            )
                        step = int(new_step)
                        self.step = step
                        continue

                saved_this_step = False
                run_eval = self.at_eval_boundary(step)
                if run_eval or self.at_ckpt_boundary(step):
                    evals: dict | None = None
                    if run_eval:
                        for fn in self._on_eval:
                            r = fn(self, step, self.state)
                            if r:
                                evals = {**(evals or {}), **r}
                    cev = CheckpointEvent(step, evals, reason="interval")
                    for fn in self._on_checkpoint:
                        fn(self, cev)
                    saved_this_step = True

                # Stop-safe boundary: single-host checks the flag every
                # step; multi-host only at log/eval boundaries (agreement
                # needs a host allgather), well inside any grace window.
                boundary = (
                    self.process_count == 1
                    or saved_this_step
                    or step % self.log_interval == 0
                )
                if boundary and (
                    self._stop_reason is not None
                    or (self._should_stop is not None and self._should_stop())
                ):
                    if self._stop_reason == "host_lost":
                        for fn in self._on_host_lost:
                            fn(self, self.host_lost_info or {})
                        # no preemption save: a checkpoint is collective and
                        # the lost peer can never join it — the last
                        # COMMITTED checkpoint is the elastic resume point
                        print(
                            f"[train] host lost at step {step}; exiting "
                            "for elastic restart"
                        )
                    else:
                        if not saved_this_step:
                            cev = CheckpointEvent(
                                step, None, reason="preemption"
                            )
                            for fn in self._on_checkpoint:
                                fn(self, cev)
                        print(
                            f"[train] preemption checkpoint at step {step}; "
                            "exiting"
                        )
                    self.exit_reason = self._stop_reason or "preempted"
                    break
        except BaseException as e:
            # default classification; on_crash hooks may refine it (the
            # train CLI maps DivergenceError → "diverged" and dumps the
            # flight recorder exactly here, while the ring still exists)
            self.exit_reason = f"exception:{type(e).__name__}"
            for fn in self._on_crash:
                try:
                    fn(self, e)
                except Exception:  # noqa: BLE001 - never mask the real failure
                    pass
            raise
        finally:
            for fn in self._on_shutdown:
                fn(self, self.exit_reason, self.step)
        return self.state
