"""Checkpointing: Orbax full-state async save/restore with true resume.

The reference persisted *params only*, via a fire-and-forget msgpack thread
(``/root/reference/src/utils.py:55-63``) — no optimizer state, no RNG, no step
counter, so a restart silently lost Adam moments and schedule position
(SURVEY §5 "no true resume", defect #6 un-joined writer thread). This module
is the TPU-native replacement:

- **Full state**: params + optimizer state + BatchNorm stats + base RNG +
  step counter, saved with Orbax (async by default, multi-host aware,
  sharding-preserving) — restart == continue.
- **best/last policy**: ``last/`` keeps a rolling window; ``best/`` keeps the
  single best checkpoint by a chosen metric (min val loss for pretrain, max
  val acc1 for finetune — parity with
  ``/root/reference/src/main_pretrain.py:88-90`` /
  ``src/main_finetune.py:88-90``).
- **Warm start**: :func:`load_pretrained_params` merges a pretrained encoder
  into a fresh param tree with key-overlap diagnostics and *working*
  positional-embedding resize (the reference shipped this commented out,
  ``/root/reference/src/utils.py:160-200``, defect #5).
- **Interop**: msgpack export/import for reference-style params files, with a
  joined background-writer registry (no truncation on exit).
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp
from flax import serialization

from jumbo_mae_tpu_tpu.data.tario import open_url
from jumbo_mae_tpu_tpu.obs.journal import fsync_dir


def is_remote_path(path) -> bool:
    """True for URL-scheme paths that must NOT go through ``pathlib.Path``
    (which would mangle ``gs://b/x`` into the local path ``gs:/b/x``).
    These route through ``open_url`` for stream IO and ``checkpoint_root``
    for directory handles."""
    return str(path).startswith(
        ("pipe:", "gs://", "http://", "https://", "file://")
    )


def _strip_file_scheme(path) -> str:
    """``file:///x/y`` → ``/x/y``; everything else unchanged."""
    s = str(path)
    if s.startswith("file://"):
        from urllib.parse import urlparse

        return urlparse(s).path
    return s


def checkpoint_root(directory: str):
    """Map a checkpoint directory string to the path object handed to Orbax.

    Local paths (incl. ``file://``) become absolute ``pathlib.Path``;
    URL-scheme paths (``gs://`` etc.) become ``etils.epath.Path`` — Orbax's
    own path type — so the scheme survives verbatim (parity with the
    reference writing checkpoints straight to GCS URLs,
    ``/root/reference/src/utils.py:55-63``). ``pipe:`` is stream-only and
    rejected: it can carry a msgpack params file but not a managed
    checkpoint directory.
    """
    s = str(directory)
    if s.startswith("pipe:"):
        raise ValueError(
            "pipe: URLs are stream-only — usable for msgpack params "
            "export/import, not as a checkpoint directory"
        )
    if s.startswith("file://"):
        return Path(_strip_file_scheme(s)).absolute()
    if is_remote_path(s):
        from etils import epath

        return epath.Path(s)
    return Path(directory).absolute()

# --------------------------------------------------------------------------
# RNG-key plumbing: typed PRNG keys are stored as their uint32 key data.
# --------------------------------------------------------------------------


def _is_typed_key(x) -> bool:
    return isinstance(x, jax.Array) and jnp.issubdtype(x.dtype, jax.dtypes.prng_key)


def split_rng_for_save(state):
    """Return (state_without_rng_types, rng_key_data or None)."""
    rng = getattr(state, "rng", None)
    if rng is not None and _is_typed_key(rng):
        return state.replace(rng=jax.random.key_data(rng)), True
    return state, False


def rejoin_rng(state, was_typed: bool):
    if was_typed and state.rng is not None and not _is_typed_key(state.rng):
        return state.replace(rng=jax.random.wrap_key_data(state.rng))
    return state


def abstract_state(state_or_shapes, sharding: Any = None):
    """ShapeDtypeStruct tree (rng as key-data) for Orbax restore, with
    shardings attached when given so arrays restore directly into the mesh."""
    shapes = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(
            jax.random.key_data(x).shape
            if _is_typed_key(x)
            else x.shape,
            jnp.uint32 if _is_typed_key(x) else x.dtype,
        ),
        state_or_shapes,
    )
    if sharding is None:
        return shapes
    return jax.tree_util.tree_map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        shapes,
        sharding,
    )


# --------------------------------------------------------------------------
# Checkpointer: best/last full-state policy over two Orbax managers
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class CheckpointConfig:
    directory: str
    max_keep_last: int = 2
    async_save: bool = True
    best_mode: str = "min"  # "min" (val loss) or "max" (val acc)
    metric_key: str = "val/loss"


class Checkpointer:
    """Full-train-state checkpoint manager with a best/last policy.

    ``save(step, state, metrics)`` always updates ``last/`` and additionally
    ``best/`` when ``metrics[metric_key]`` improves. ``restore`` rebuilds the
    state *into its mesh sharding* from a template. ``extra`` carries
    host-side state (data-iterator cursor, config echo) as JSON.
    """

    def __init__(self, cfg: CheckpointConfig):
        self.cfg = cfg
        root = checkpoint_root(cfg.directory)
        opts = dict(enable_async_checkpointing=cfg.async_save)
        # the explicit handler registry lets item_metadata work in FRESH
        # processes (resume), which the dtype-cast warning depends on —
        # without it orbax returns None metadata and the check degrades
        handlers = dict(
            state=ocp.StandardCheckpointHandler(),
            extra=ocp.JsonCheckpointHandler(),
        )
        self._last = ocp.CheckpointManager(
            root / "last",
            options=ocp.CheckpointManagerOptions(
                max_to_keep=cfg.max_keep_last, **opts
            ),
            item_handlers=handlers,
        )
        self._best = ocp.CheckpointManager(
            root / "best",
            options=ocp.CheckpointManagerOptions(max_to_keep=1, **opts),
            item_handlers=dict(
                state=ocp.StandardCheckpointHandler(),
                extra=ocp.JsonCheckpointHandler(),
            ),
        )
        self._best_metric = self._read_best_metric()
        # measured wall-clock of the most recent save/restore (synchronous
        # portion) — the goodput ledger charges these to its checkpoint
        # buckets, and the interval advisor reads the save cost.
        self.last_save_s: float | None = None
        self.last_restore_s: float | None = None

    def _read_best_metric(self) -> float | None:
        step = self._best.latest_step()
        if step is None:
            return None
        try:
            meta = self._best.restore(
                step, args=ocp.args.Composite(extra=ocp.args.JsonRestore())
            )["extra"]
            return meta.get("_best_metric")
        except Exception:
            return None

    @property
    def best_metric(self) -> float | None:
        return self._best_metric

    def _improved(self, value: float) -> bool:
        if self._best_metric is None:
            return True
        if self.cfg.best_mode == "min":
            return value < self._best_metric
        return value > self._best_metric

    def save(
        self,
        step: int,
        state,
        metrics: dict[str, float] | None = None,
        extra: dict[str, Any] | None = None,
    ) -> bool:
        """Save ``last``; promote to ``best`` on metric improvement.
        Returns True if this step became the new best."""
        # chaos hook: a wedged/failing checkpoint store is a classic
        # pod-scale failure — injectable without a real flaky filesystem
        from jumbo_mae_tpu_tpu.faults.inject import fault_point

        t0 = time.perf_counter()
        fault_point("ckpt.save", key=str(step))
        extra = dict(extra or {})
        state, was_typed = split_rng_for_save(state)
        extra["_rng_typed"] = was_typed
        args = ocp.args.Composite(
            state=ocp.args.StandardSave(state),
            extra=ocp.args.JsonSave(extra),
        )
        self._last.save(step, args=args)
        value = None if metrics is None else metrics.get(self.cfg.metric_key)
        is_best = value is not None and self._improved(float(value))
        if is_best:
            self._best_metric = float(value)
            best_extra = extra | {"_best_metric": self._best_metric}
            self._best.save(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardSave(state),
                    extra=ocp.args.JsonSave(best_extra),
                ),
            )
        self.last_save_s = time.perf_counter() - t0
        return is_best

    def latest_step(self, which: str = "last") -> int | None:
        return (self._last if which == "last" else self._best).latest_step()

    def _resolve(self, which: str, step: int | None):
        """(manager, concrete step) for ``which`` in {"last", "best"};
        raises FileNotFoundError when nothing is saved."""
        mgr = self._last if which == "last" else self._best
        if step is None:
            step = mgr.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no '{which}' checkpoint under {self.cfg.directory}"
            )
        return mgr, step

    def restore(
        self,
        template,
        *,
        sharding: Any = None,
        step: int | None = None,
        which: str = "last",
        fallback_steps: int = 0,
        on_fallback=None,
    ):
        """Restore ``(state, extra)``. ``template`` is a live state or
        eval_shape tree defining structure/dtypes; ``sharding`` (same tree of
        NamedShardings) places arrays directly on the mesh.

        ``fallback_steps > 0`` makes the restore survivable: when the
        resolved step fails to load (torn/corrupt save — e.g. the writing
        host was SIGKILLed mid-commit), the restore walks back through up
        to ``fallback_steps`` earlier committed steps instead of crashing
        the relaunch. ``on_fallback(from_step, to_step, error)`` fires per
        hop (the train CLI journals it as ``ckpt_fallback``). The walk is
        bounded — a store where every step is bad still raises. The
        ``ckpt.load`` fault site fires per attempt with the step as key.
        """
        t0 = time.perf_counter()
        mgr, step = self._resolve(which, step)
        tmpl, _ = split_rng_for_save(template)
        abstract = abstract_state(tmpl, sharding)
        steps = [step]
        if fallback_steps > 0:
            older = sorted(
                (s for s in mgr.all_steps() if s < step), reverse=True
            )
            steps += older[: max(0, int(fallback_steps))]
        from jumbo_mae_tpu_tpu.faults.inject import fault_point

        last_err: Exception | None = None
        for i, s in enumerate(steps):
            if i > 0 and on_fallback is not None:
                on_fallback(steps[i - 1], s, last_err)
            try:
                fault_point("ckpt.load", key=str(s))
                _warn_on_dtype_casts(mgr, s, abstract)
                out = mgr.restore(
                    s,
                    args=ocp.args.Composite(
                        state=ocp.args.StandardRestore(abstract),
                        extra=ocp.args.JsonRestore(),
                    ),
                )
            except Exception as e:  # noqa: BLE001 - each step gets one shot
                if not steps[i + 1 :]:
                    raise
                last_err = e
                print(
                    f"[ckpt] restore of step {s} failed ({type(e).__name__}:"
                    f" {e}); walking back"
                )
                continue
            extra = out["extra"] or {}
            state = rejoin_rng(out["state"], extra.get("_rng_typed", False))
            self.last_restore_s = time.perf_counter() - t0
            return state, extra
        raise last_err  # pragma: no cover - loop always raises or returns

    def restore_eval(
        self, template, *, sharding: Any = None, step: int | None = None,
        which: str = "last",
    ):
        """Restore only what evaluation needs — params, batch_stats, rng and
        step — grafted into ``template`` (a live TrainState). The
        checkpoint's optimizer-state bytes are never read (Orbax partial
        restore) and arrays restore *directly into their mesh shardings*
        (no single-device staging), so an eval-only process
        (``run.eval_only``) never pays AdamW's ~2x-params footprint in
        device memory, host memory, or restore I/O. Pair with a no-op
        ``tx`` (the template's opt_state is left as-is)."""
        _, step = self._resolve(which, step)
        # a dedicated PyTree-handler manager: partial restore needs PyTree
        # args (the main managers register Standard handlers — mixing the
        # two raises a handler-registry conflict), and its metadata feeds
        # the dtype-cast warning below
        mgr = ocp.CheckpointManager(
            checkpoint_root(self.cfg.directory) / which,
            item_handlers=dict(
                state=ocp.PyTreeCheckpointHandler(),
                extra=ocp.JsonCheckpointHandler(),
            ),
        )
        try:
            return self._restore_eval_impl(mgr, step, template, sharding)
        finally:
            mgr.close()

    def _restore_eval_impl(self, mgr, step, template, sharding):
        # one abstract (shape/dtype) walk per subtree feeds BOTH the restore
        # item and the same silent-downcast warning restore() emits (e.g.
        # f32 checkpoint into an optim.param_dtype=bfloat16 eval config)
        abstract = {
            attr: abstract_state(getattr(template, attr))
            for attr in ("params", "batch_stats")
            if getattr(template, attr) is not None
        }
        _warn_on_dtype_casts(mgr, step, abstract)

        def arr_args(attr):
            shard_tree = getattr(sharding, attr, None)
            if shard_tree is not None:
                return jax.tree_util.tree_map(
                    lambda t, sh: ocp.ArrayRestoreArgs(
                        sharding=sh, dtype=t.dtype
                    ),
                    abstract[attr],
                    shard_tree,
                )
            return jax.tree_util.tree_map(
                lambda t: ocp.RestoreArgs(restore_type=np.ndarray),
                abstract[attr],
            )

        item: dict[str, Any] = {
            attr: arr_args(attr) for attr in abstract
        }
        item["step"] = ocp.RestoreArgs(restore_type=np.ndarray)
        item["rng"] = ocp.RestoreArgs(restore_type=np.ndarray)
        try:
            out = mgr.restore(
                step,
                args=ocp.args.Composite(
                    state=_partial_pytree_restore(item),
                    extra=ocp.args.JsonRestore(),
                ),
            )
        except (TypeError, ValueError) as e:
            # structural divergence surfaces as an opaque Orbax tree error —
            # re-raise with the actionable diagnosis (mismatches that Orbax
            # instead silently fills are caught in graft() below)
            raise ValueError(
                "eval config's state does not match the checkpoint — check "
                "model.preset/overrides and run.mode against the run that "
                f"produced it (orbax: {e})"
            ) from e
        raw = out["state"]
        extra = out["extra"] or {}

        def graft(attr):
            tmpl = getattr(template, attr)
            saved = raw.get(attr) if isinstance(raw, dict) else None
            if tmpl is None or saved is None:
                return tmpl
            # partial_restore fills template paths ABSENT from the
            # checkpoint with the RestoreArgs leaves themselves — surface a
            # readable model/checkpoint mismatch instead of letting those
            # objects reach jit (restore() raises a clear structure error
            # on the same mismatch; restore_eval must not be weaker)
            missing = [
                jax.tree_util.keystr(path)
                for path, leaf in jax.tree_util.tree_flatten_with_path(saved)[0]
                if isinstance(leaf, ocp.RestoreArgs)
            ]
            if missing:
                head = ", ".join(missing[:5])
                raise ValueError(
                    f"eval config's {attr} does not match the checkpoint — "
                    f"{len(missing)} paths missing from the saved tree "
                    f"(first: {head}); check model.preset/overrides against "
                    "the run that produced the checkpoint"
                )
            if getattr(sharding, attr, None) is not None:
                return saved  # already mesh-sharded + template-dtype
            # host-side dtype cast (no device staging); placement is jit's
            return jax.tree_util.tree_map(
                lambda t, r: np.asarray(r).astype(t.dtype), tmpl, saved
            )

        rng = template.rng
        saved_rng = raw.get("rng") if isinstance(raw, dict) else None
        if saved_rng is not None:
            rng = (
                jax.random.wrap_key_data(jnp.asarray(saved_rng))
                if extra.get("_rng_typed", False)
                else jnp.asarray(saved_rng)
            )
            rng_sharding = getattr(sharding, "rng", None)
            if rng_sharding is not None:
                rng = jax.device_put(rng, rng_sharding)
        new_step = template.step
        if isinstance(raw, dict) and raw.get("step") is not None:
            new_step = jnp.asarray(
                raw["step"], getattr(template.step, "dtype", jnp.int32)
            )
            step_sharding = getattr(sharding, "step", None)
            if step_sharding is not None:
                new_step = jax.device_put(new_step, step_sharding)
        state = template.replace(
            step=new_step,
            params=graft("params"),
            batch_stats=graft("batch_stats"),
            rng=rng,
        )
        return state, extra

    def wait(self):
        self._last.wait_until_finished()
        self._best.wait_until_finished()

    def close(self):
        self.wait()
        self._last.close()
        self._best.close()


def _partial_pytree_restore(item) -> "ocp.args.PyTreeRestore":
    """Version-portable partial ``PyTreeRestore``. ``item`` is a tree with
    ``RestoreArgs`` leaves naming exactly the paths to read; checkpoint
    paths outside it are never touched, and item paths ABSENT from the
    checkpoint come back as the ``RestoreArgs`` leaves themselves (the
    callers' mismatch detection keys on that). Newer orbax spells this
    ``partial_restore=True``; 0.7.x spells it ``restore_args`` + a non-None
    ``transforms`` (the RestoreArgs leaves double as their own structure
    placeholders — verified semantics-identical, incl. the missing-path
    behavior). The seed pinned the newer spelling only, which is why every
    ``restore_eval`` path failed under the installed 0.7.0 (seed-test
    triage, round 6)."""
    import inspect

    params = inspect.signature(ocp.args.PyTreeRestore.__init__).parameters
    if "partial_restore" in params:
        return ocp.args.PyTreeRestore(item=item, partial_restore=True)
    return ocp.args.PyTreeRestore(item=item, restore_args=item, transforms={})


def _leaf_dtype_map(tree) -> dict[str, Any]:
    """Flatten a pytree to {"a/b/c": dtype} keyed by path *names* only, so a
    flax-struct state and Orbax's dict-shaped metadata compare likewise."""
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        names = []
        for k in path:
            name = getattr(k, "key", None)
            if name is None:
                name = getattr(k, "name", None)
            if name is None:
                name = getattr(k, "idx", None)
            names.append(str(name) if name is not None else str(k))
        dt = getattr(leaf, "dtype", None)
        if dt is not None:
            out["/".join(names)] = jnp.dtype(dt)
    return out


def _warn_on_dtype_casts(mgr, step, abstract):
    """Abstract-template restore silently casts any saved array whose dtype
    differs from the template (e.g. resuming an f32-moment checkpoint with an
    ``optim.nu_dtype=bfloat16`` recipe changes numerics mid-run). Surface
    that, best-effort — metadata layouts vary across Orbax versions."""
    try:
        meta = mgr.item_metadata(step)["state"]
        if meta is None:
            # happens on managers without a handler registry — land in the
            # except below rather than comparing against an empty map
            raise ValueError("no state metadata (handler registry missing)")
        saved = _leaf_dtype_map(meta)
        want = _leaf_dtype_map(abstract)
        casts = {
            p: (saved[p], want[p])
            for p in want
            if p in saved and saved[p] != want[p]
        }
        if casts:
            shown = sorted(casts)[:8]
            detail = ", ".join(
                f"{p}: {casts[p][0]}→{casts[p][1]}" for p in shown
            )
            more = len(casts) - len(shown)
            print(
                f"[checkpoint] WARNING: restore is casting {len(casts)} "
                f"array(s) to the template dtype ({detail}"
                + (f", +{more} more" if more > 0 else "")
                + ") — numerics change mid-run; align the recipe's "
                "mu/nu/param dtypes with the checkpoint if unintended"
            )
    except Exception as e:
        # Never block a restore on the diagnostic — but don't degrade
        # silently either: an Orbax metadata-layout change lands here.
        print(
            "[checkpoint] note: dtype-cast check unavailable "
            f"({type(e).__name__}: {e})"
        )


# --------------------------------------------------------------------------
# Warm start: pretrained-encoder merge with diagnostics + posemb resize
# --------------------------------------------------------------------------


def _flatten(tree, prefix=()):
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, prefix + (k,)))
    else:
        out[prefix] = tree
    return out


def _unflatten(flat):
    tree: dict = {}
    for path, v in flat.items():
        node = tree
        for k in path[:-1]:
            node = node.setdefault(k, {})
        node[path[-1]] = v
    return tree


def resize_posemb(posemb: np.ndarray, target_shape: tuple[int, ...]) -> np.ndarray:
    """Bilinearly resize an (H, W, D) or (1, H, W, D) positional-embedding
    grid to a new grid size (image-size / patch-size change between pretrain
    and finetune). The reference's equivalent surgery was commented out
    (``/root/reference/src/utils.py:168-179``); here it works. This
    framework's learnable posemb is the 3-D ``pos_embed`` grid
    (``models/layers.py``)."""
    if posemb.shape == tuple(target_shape):
        return posemb
    if posemb.ndim != len(target_shape) or posemb.ndim not in (3, 4):
        raise ValueError(
            f"posemb resize expects (H,W,D) or (1,H,W,D) grids, got "
            f"{posemb.shape} → {target_shape}"
        )
    hw = slice(1, 3) if posemb.ndim == 4 else slice(0, 2)
    out_shape = list(posemb.shape)
    out_shape[hw] = list(target_shape[hw])
    resized = jax.image.resize(
        jnp.asarray(posemb, jnp.float32), out_shape, method="bilinear"
    )
    return np.asarray(resized, dtype=posemb.dtype)


def merge_pretrained_params(
    pretrained: dict,
    init_params: dict,
    *,
    verbose: bool = True,
    stats: dict | None = None,
) -> dict:
    """Merge ``pretrained`` into ``init_params`` by key path.

    - matching path + shape → pretrained value;
    - posemb grids with mismatched H/W → bilinear resize;
    - other shape mismatches (e.g. a head for a different label count) →
      keep the fresh init;
    - paths only in ``init_params`` (decoder dropped, new head) → fresh init.

    Prints the overlap diagnostics the reference printed
    (``/root/reference/src/utils.py:154-158``). Pass a dict as ``stats`` to
    receive the ``loaded``/``resized``/``skipped``/``unused`` path lists —
    callers that must fail on an empty merge (e.g.
    ``tools/extract_features.py``) check ``stats["loaded"]``.
    """
    src = _flatten(pretrained)
    dst = _flatten(init_params)
    merged, loaded, resized, skipped = {}, [], [], []
    for path, init_val in dst.items():
        if path not in src:
            merged[path] = init_val
            continue
        val = src[path]
        if tuple(np.shape(val)) == tuple(np.shape(init_val)):
            merged[path] = jnp.asarray(val, init_val.dtype)
            loaded.append(path)
        elif path[-1] in ("pos_embed", "posemb", "wpe") and np.ndim(val) in (3, 4):
            merged[path] = jnp.asarray(
                resize_posemb(np.asarray(val), np.shape(init_val)),
                init_val.dtype,
            )
            resized.append(path)
        else:
            merged[path] = init_val
            skipped.append(path)
    unused = [p for p in src if p not in dst]
    if stats is not None:
        stats.update(
            loaded=loaded, resized=resized, skipped=skipped, unused=unused
        )
    if verbose:
        def fmt(paths):
            return sorted("/".join(p) for p in paths)

        print(
            f"[checkpoint] pretrained merge: {len(loaded)} loaded, "
            f"{len(resized)} resized, {len(skipped)} shape-mismatch (fresh), "
            f"{len(unused)} unused"
        )
        for name, paths in (("resized", resized), ("fresh", skipped)):
            for p in fmt(paths):
                print(f"[checkpoint]   {name}: {p}")
        for p in fmt(unused)[:20]:
            print(f"[checkpoint]   unused: {p}")
    return _unflatten(merged)


def require_loaded(stats: dict, source, target_desc: str):
    """CLI-tool guard: exit unless a ``merge_pretrained_params`` call (via
    its ``stats`` out-param) actually loaded something — writing
    plausible-looking random-init artifacts is worse than failing. Shared
    by ``tools/extract_features.py`` and ``tools/reconstruct.py``."""
    if not (stats.get("loaded") or stats.get("resized")):
        raise SystemExit(
            f"--ckpt {source} loaded 0 params into {target_desc} — "
            "wrong preset/shape or an unrelated params tree"
        )


# the encoder lives under "encoder" in MAEPretrainModel trees and "model"
# in ClassificationModel trees; warm starts cross that boundary.
_ENCODER_KEYS = ("encoder", "model")


def load_params_tree(path: str) -> dict:
    """Load a raw params tree from any supported checkpoint carrier: an
    Orbax checkpoint dir (local or ``gs://``), a local ``.msgpack`` file, or
    a stream URL (``pipe:``, ``http(s)://``, or a remote ``.msgpack``)."""
    s = str(path)
    if s.startswith(("pipe:", "http://", "https://")) or (
        is_remote_path(s) and s.endswith(".msgpack")
    ):
        return import_params_msgpack(s)
    p = checkpoint_root(s)
    if p.is_dir():
        return restore_params_any(p)
    return import_params_msgpack(s)


def load_pretrained_params(
    path: str,
    init_params: dict,
    *,
    subtree: str | None = "auto",
    verbose: bool = True,
    stats: dict | None = None,
) -> dict:
    """Load pretrained params from an Orbax checkpoint dir or a ``.msgpack``
    file and merge into ``init_params`` (parity:
    ``/root/reference/src/utils.py:150-202``, with the surgery un-commented).

    ``subtree="auto"``: the encoder subtree is located on both sides
    (``encoder`` for pretrain trees, ``model`` for classification trees) and
    merged across the rename — a pretrain checkpoint's decoder params are
    dropped for finetune. Pass an explicit key or ``None`` for whole-tree
    merge.

    ``path`` may be an Orbax checkpoint dir (local or ``gs://``), a local
    ``.msgpack`` file, or a stream URL (``pipe:``, ``http(s)://``, or any
    remote path ending in ``.msgpack``) carrying a msgpack params file.
    """
    tree = serialization.to_state_dict(load_params_tree(path))
    init_sd = serialization.to_state_dict(init_params)

    def find_encoder(sd):
        for k in _ENCODER_KEYS:
            if k in sd:
                return k
        return None

    if subtree == "auto":
        src_key, dst_key = find_encoder(tree), find_encoder(init_sd)
    else:
        src_key = dst_key = subtree

    if src_key is not None and dst_key is not None:
        merged = dict(init_sd)
        merged[dst_key] = merge_pretrained_params(
            tree[src_key], init_sd[dst_key], verbose=verbose, stats=stats
        )
    else:
        merged = merge_pretrained_params(
            tree, init_sd, verbose=verbose, stats=stats
        )
    return serialization.from_state_dict(init_params, merged)


def _restore_subtrees(mgr, step, names: tuple[str, ...]) -> dict | None:
    """Partial restore of the named top-level state subtrees — everything
    else (the optimizer state's ~2x-params bytes above all) is never read.
    Needs the saved tree's structure, taken from the checkpoint metadata;
    returns None when the layout doesn't expose it or ``params`` is absent
    (caller falls back to a whole-tree restore)."""
    try:
        meta = mgr.item_metadata(step)
        state_meta = None if meta is None else meta.get("state")
        tree = getattr(state_meta, "tree", state_meta)
        if not isinstance(tree, dict) or "params" not in tree:
            return None
        item = {
            name: jax.tree_util.tree_map(
                lambda _: ocp.RestoreArgs(restore_type=np.ndarray),
                tree[name],
            )
            for name in names
            if isinstance(tree.get(name), dict)
        }
        out = mgr.restore(
            step, args=ocp.args.Composite(state=_partial_pytree_restore(item))
        )
        return out["state"]
    except Exception:
        return None


def _restore_params_only(mgr, step) -> dict | None:
    out = _restore_subtrees(mgr, step, ("params",))
    return None if out is None else out.get("params")


def _device_put_incremental(tree):
    """Per-leaf host→device transfer that releases each host buffer as its
    device copy lands: the recursion REBINDS every dict slot in place, so
    after a leaf is transferred nothing references the numpy array anymore
    and it is freed before the next leaf stages. Peak restore memory is one
    full tree plus one leaf — not the host tree and the device tree side by
    side, which is what caps serving-replica density on small hosts."""
    if isinstance(tree, dict):
        for k in tree:
            tree[k] = _device_put_incremental(tree[k])
        return tree
    if tree is None:
        return None
    return jax.device_put(tree)


def restore_inference_state(path, *, to_device: bool = False) -> tuple[dict, dict | None]:
    """Restore ``(params, batch_stats)`` for serving — the checkpoint's
    optimizer-state bytes are never read or staged (same partial-restore
    machinery as :meth:`Checkpointer.restore_eval`, without needing a live
    TrainState template). ``batch_stats`` is None when the checkpoint has
    none (pretrain/finetune trees; linear-probe trees carry the probe
    head's BatchNorm statistics, which deterministic serving needs).

    ``to_device=True`` transfers the restored leaves to the default device
    incrementally (:func:`_device_put_incremental`), dropping host buffers
    as device copies land — the inference engine passes this so restore
    peaks at ~one params tree instead of two.

    ``path`` accepts every :func:`load_params_tree` carrier: a Checkpointer
    run directory (``best``/``last`` layout, local or ``gs://``), a direct
    manager dir, a ``.msgpack`` params file, or a stream URL — the stream
    forms carry params only."""

    def _restore() -> tuple[dict, dict | None]:
        s = str(path)
        if s.startswith(("pipe:", "http://", "https://")) or (
            is_remote_path(s) and s.endswith(".msgpack")
        ):
            return import_params_msgpack(s), None
        p = checkpoint_root(s)
        if not p.is_dir():
            return import_params_msgpack(s), None
        for sub in ("best", "last", "."):
            root = p if sub == "." else p / sub
            if not root.is_dir():
                continue
            with ocp.CheckpointManager(
                root,
                item_handlers={
                    "state": ocp.PyTreeCheckpointHandler(),
                    "extra": ocp.JsonCheckpointHandler(),
                },
            ) as mgr:
                step = mgr.latest_step()
                if step is None:
                    continue
                out = _restore_subtrees(mgr, step, ("params", "batch_stats"))
                if out is not None and out.get("params") is not None:
                    return out["params"], out.get("batch_stats")
        # legacy layouts without usable metadata: whole-tree restore
        return restore_params_any(p), None

    params, batch_stats = _restore()
    if to_device:
        params = _device_put_incremental(params)
        batch_stats = _device_put_incremental(batch_stats)
    return params, batch_stats


def restore_params_any(directory) -> dict:
    """Restore just the params tree from a Checkpointer layout (best/ or
    last/ subdirs, or a direct manager dir). ``directory`` may be local or a
    ``gs://`` URL (routed through :func:`checkpoint_root`). TrainState
    layouts restore the params subtree only (optimizer bytes skipped);
    other layouts fall back to a whole-tree restore."""
    directory = checkpoint_root(directory)
    for sub in ("best", "last", "."):
        root = directory if sub == "." else directory / sub
        if not root.is_dir():
            continue
        # params-only partial restore needs the saved tree structure, which
        # item_metadata only exposes with an explicit handler registry
        with ocp.CheckpointManager(
            root,
            item_handlers={
                "state": ocp.PyTreeCheckpointHandler(),
                "extra": ocp.JsonCheckpointHandler(),
            },
        ) as mgr:
            step = mgr.latest_step()
            if step is None:
                continue
            params = _restore_params_only(mgr, step)
            if params is not None:
                return params
        # fallback: whole-tree restore on a plain manager (legacy layouts)
        with ocp.CheckpointManager(root) as mgr:
            out = mgr.restore(
                step, args=ocp.args.Composite(state=ocp.args.StandardRestore())
            )
            state = out["state"]
            params = (
                state.get("params") if isinstance(state, dict) else state.params
            )
            if params is not None:
                return params
    raise FileNotFoundError(f"no restorable checkpoint under {directory}")


# --------------------------------------------------------------------------
# msgpack interop (+ joined background writer — defect #6 fixed)
# --------------------------------------------------------------------------

_background_writers: list[threading.Thread] = []


def export_params_msgpack(params, path: str, *, background: bool = False):
    """Write a reference-compatible params msgpack — to a local path or any
    ``open_url`` write target (``gs://``, ``pipe:CMD``), matching the
    reference's gopen-based URL writes (``/root/reference/src/utils.py:55-63``).
    With ``background=True`` the write happens on a tracked thread that is
    joined at interpreter exit (the reference's thread was fire-and-forget →
    truncation risk, ``/root/reference/src/utils.py:58-63``)."""
    host_params = jax.tree_util.tree_map(np.asarray, params)
    payload = serialization.msgpack_serialize(
        serialization.to_state_dict(host_params)
    )

    def write():
        if is_remote_path(path) and not str(path).startswith("file://"):
            # remote stores commit on stream close; no tmp-rename dance
            with open_url(path, "wb") as s:
                s.write(payload)
            return
        # local (incl. file://): parent mkdir + atomic tmp-rename commit
        target = Path(_strip_file_scheme(path))
        target.parent.mkdir(parents=True, exist_ok=True)
        tmp = target.with_suffix(target.suffix + ".tmp")
        tmp.write_bytes(payload)
        fd = os.open(str(tmp), os.O_RDONLY)
        try:
            os.fsync(fd)  # data durable before the rename can expose it
        finally:
            os.close(fd)
        tmp.replace(target)  # atomic: readers never see a partial file
        fsync_dir(target.parent)  # rename durable over power loss

    if background:
        t = threading.Thread(target=write, daemon=False)
        t.start()
        _background_writers.append(t)
    else:
        write()


def import_params_msgpack(path: str) -> dict:
    """Read a params msgpack from a local path or any ``open_url`` read
    source (``gs://``, ``pipe:``, ``http(s)://`` — parity with the reference
    reading pretrained files via gopen, ``/root/reference/src/utils.py:150-152``)."""
    if is_remote_path(path):
        with open_url(path, "rb") as s:
            return serialization.msgpack_restore(s.read())
    return serialization.msgpack_restore(Path(path).read_bytes())


@atexit.register
def _join_background_writers():
    for t in _background_writers:
        t.join()


def save_metadata_json(directory: str, payload: dict):
    p = checkpoint_root(directory)  # epath for gs:// etc., Path locally
    p.mkdir(parents=True, exist_ok=True)
    (p / "metadata.json").write_text(json.dumps(payload, indent=2, default=str))
