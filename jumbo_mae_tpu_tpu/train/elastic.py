"""ElasticSupervisor: spawn, watch, and relaunch a multi-process fleet.

PR 11 gave the fleet senses (beacons, lost/straggler detection,
fleet_doctor); this module is the reflexes. The supervisor owns the N
training processes of a local fleet (``cli/train.py --elastic N``) and
makes host loss a survivable, journaled, budgeted event:

- a child that dies by signal (SIGKILL'd host), exits ``EXIT_HANG`` (its
  hang watchdog converted a wedged collective into a death), or exits
  ``EXIT_ELASTIC`` (it observed a peer's beacon go stale) triggers a fleet
  restart: survivors are drained (SIGTERM → grace → SIGKILL — a process
  blocked in a dead collective cannot run its preemption checkpoint, the
  last *committed* checkpoint is the resume point), world size is
  recomputed without the bad slots (for ``EXIT_ELASTIC`` the exiting
  children are the healthy detectors, so the fleet restarts at their
  count), and the fleet relaunches from the last committed checkpoint;
- restarts are budgeted: ``max_restarts`` with exponential backoff
  (``backoff_s`` doubling to ``backoff_cap_s``); exhaustion journals
  ``elastic_exhausted`` with a verdict and exits nonzero;
- ``EXIT_FATAL`` (diverged, config error) is never retried — restarting a
  deterministic crash just burns the budget proving it again;
- after a down-size, the supervisor attempts a *rejoin* every
  ``rejoin_after_s``: graceful teardown (children checkpoint and exit
  clean) and relaunch at full world size, journaled ``elastic_rejoin``;
- with ``wedge_after_s > 0`` the supervisor also reads the fleet beacon
  dir itself and treats an alive child whose beacon is stale as wedged —
  the backstop for a hang the in-process watchdog cannot see (e.g. the
  watchdog thread itself starved).

The supervisor shares the run's journal *directory* with host 0 but owns
its own segment file (``RunJournal`` writers always open a fresh
max+1-indexed segment), so ``read_merged_journal`` interleaves supervisor
events (``role="supervisor"``) with the hosts' without coordination.

The journaled generation boundaries (``elastic_restart`` with
``generation``/``backoff_s``, plus the ``GRAFT_GENERATION`` the launch
callback stamps into each child) are what ``obs/goodput.py``'s
``stitch_generations`` prices offline: inter-generation gaps become
hang-latency + restart-downtime buckets, and lost work is steps executed
minus steps committed when each generation died (``tools/goodput_doctor``
renders the per-restart cost table).

Everything time-related is injectable (``clock``/``sleep_fn``) so the
restart/backoff/rejoin state machine is unit-testable without subprocesses
(the launch callback is just a factory returning ``Popen``-shaped
objects).
"""

from __future__ import annotations

import signal
import time
from pathlib import Path
from typing import Callable

from jumbo_mae_tpu_tpu.obs.metrics import get_registry
from jumbo_mae_tpu_tpu.train.engine import (
    EXIT_ELASTIC,
    EXIT_FATAL,
    EXIT_HANG,
)

#: teardown reasons where the FAILED slots are the bad machines, removed
#: from the next world size (presumed bad until the rejoin timer says
#: otherwise). ``host_lost`` is handled separately: there the exiting
#: children are the healthy DETECTORS and the next world is their count.
_DOWNSIZE_REASONS = frozenset({"host_dead", "hang", "wedged"})


class ElasticSupervisor:
    """Budgeted restart supervisor for a local training fleet.

    ``launch(world_size, gen)`` spawns the fleet's processes and returns
    them as a list indexed by process id — each needs only the ``Popen``
    surface (``poll``, ``send_signal``, ``kill``, ``wait``,
    ``returncode``, ``pid``). A fresh coordinator port per generation is
    the factory's job. ``run_dir`` locates the fleet beacon dir and the
    shared journal.
    """

    def __init__(
        self,
        *,
        run_dir: str | Path,
        world_size: int,
        launch: Callable[[int, int], list],
        max_restarts: int = 8,
        backoff_s: float = 1.0,
        backoff_cap_s: float = 60.0,
        rejoin_after_s: float = 30.0,
        wedge_after_s: float = 0.0,
        grace_s: float = 15.0,
        poll_s: float = 0.2,
        world_ok: Callable[[int], bool] | None = None,
        journal=None,
        clock: Callable[[], float] = time.monotonic,
        sleep_fn: Callable[[float], None] = time.sleep,
    ):
        self.run_dir = Path(run_dir)
        self.world_size = int(world_size)
        self._launch = launch
        self.max_restarts = int(max_restarts)
        self.backoff_s = float(backoff_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.rejoin_after_s = float(rejoin_after_s)
        self.wedge_after_s = float(wedge_after_s)
        self.grace_s = float(grace_s)
        self.poll_s = float(poll_s)
        #: optional divisibility predicate for candidate world sizes (e.g.
        #: "divides run.train_batch_size"). A downsize is clamped to the
        #: largest valid world at or below the candidate — relaunching at
        #: an invalid world would crash every child with a config error and
        #: burn the whole restart budget re-proving it.
        self.world_ok = world_ok
        self.journal = journal
        self._clock = clock
        self._sleep = sleep_fn
        self.restarts_used = 0
        self.generation = 0
        self._stopping = False
        reg = get_registry()
        self._m_restarts = reg.counter(
            "fleet_restarts_total",
            "fleet relaunches by the elastic supervisor",
            labels=("reason",),
        )
        self._m_rejoins = reg.counter(
            "fleet_rejoins_total",
            "graceful restarts back to full world size",
        )
        self._g_world = reg.gauge(
            "fleet_world_size", "world size of the current fleet generation"
        )

    # -- journal helper --------------------------------------------------
    def _emit(self, etype: str, **fields) -> None:
        if self.journal is not None:
            try:
                self.journal.event(etype, role="supervisor", **fields)
            except Exception:  # noqa: BLE001 - journaling must not kill the loop
                pass

    def request_stop(self) -> None:
        """SIGTERM-from-outside: drain the fleet and return cleanly."""
        self._stopping = True

    # -- process plumbing ------------------------------------------------
    def _clean_beacons(self) -> None:
        """Drop stale beacon files before a relaunch: the fleet dir
        persists across generations, and a dead slot's old beacon would
        read as a perpetually-lost host to the new generation's
        aggregator (and to this supervisor's own wedge scan)."""
        fleet = self.run_dir / "fleet"
        if not fleet.is_dir():
            return
        for p in fleet.glob("host-*.json"):
            try:
                p.unlink()
            except OSError:
                pass

    def _teardown(self, procs: list, *, skip: set[int] = frozenset()) -> None:
        """SIGTERM the fleet, grace, then SIGKILL stragglers. A child at a
        stop-safe boundary checkpoints and exits clean; one blocked in a
        dead collective cannot, and is killed — its progress since the
        last committed checkpoint is the (bounded) replay cost."""
        alive = [
            (i, p)
            for i, p in enumerate(procs)
            if i not in skip and p.poll() is None
        ]
        for _, p in alive:
            try:
                p.send_signal(signal.SIGTERM)
            except OSError:
                pass
        deadline = self._clock() + self.grace_s
        for _, p in alive:
            while p.poll() is None and self._clock() < deadline:
                self._sleep(self.poll_s)
        for _, p in alive:
            if p.poll() is None:
                try:
                    p.kill()
                except OSError:
                    pass
                try:
                    p.wait(timeout=10)
                except Exception:  # noqa: BLE001  # pragma: no cover
                    pass

    def _stale_hosts(self, procs: list) -> list[int]:
        """Alive children whose beacon heartbeat is older than
        ``wedge_after_s`` — the supervisor-side wedge detector."""
        if self.wedge_after_s <= 0:
            return []
        from jumbo_mae_tpu_tpu.obs.fleet import read_beacons

        beacons = read_beacons(self.run_dir / "fleet")
        now = time.time()
        out = []
        for i, p in enumerate(procs):
            if p.poll() is not None:
                continue
            b = beacons.get(i)
            if b is None:
                continue  # not started stepping yet — compile, restore
            if now - float(b.get("heartbeat", now)) > self.wedge_after_s:
                out.append(i)
        return out

    @staticmethod
    def _classify(dead: dict[int, int]) -> tuple[str, list[int]]:
        """(reason, failed slots) from the self-dead children's exit codes
        — the children that died on their own, before any teardown. Signal
        deaths dominate (a SIGKILL'd host often takes survivors down with
        collective errors in the same poll window), then the protocol
        codes, then generic crashes."""
        if any(c == EXIT_FATAL for c in dead.values()):
            return "fatal", [i for i, c in dead.items() if c == EXIT_FATAL]
        sig = [i for i, c in dead.items() if c < 0]
        if sig:
            return "host_dead", sig
        hang = [i for i, c in dead.items() if c == EXIT_HANG]
        if hang:
            return "hang", hang
        lost = [i for i, c in dead.items() if c == EXIT_ELASTIC]
        if lost:
            # the exiting children are the healthy *detectors*; the lost
            # peers are the slots that did NOT exit EXIT_ELASTIC. The run
            # loop restarts at the detector count (the surviving hosts),
            # not world minus the detectors.
            return "host_lost", lost
        return "crash", list(dead)

    # -- the supervision loop --------------------------------------------
    def run(self) -> int:
        """Supervise until the run completes (0), a fatal exit (no retry),
        or the restart budget is exhausted. Returns the supervisor's exit
        code."""
        backoff = self.backoff_s
        world = self.world_size
        downsized_at: float | None = None
        self._g_world.set(world)
        self._clean_beacons()
        procs = self._launch(world, self.generation)
        while True:
            self._sleep(self.poll_s)
            if self._stopping:
                self._teardown(procs)
                self._emit("shutdown", reason="supervisor_stop", world=world)
                return 0

            # ---- collect self-dead children ----------------------------
            dead = {
                i: p.returncode
                for i, p in enumerate(procs)
                if p.poll() is not None
            }
            if len(dead) == len(procs) and all(
                c == 0 for c in dead.values()
            ):
                return 0  # run complete
            abnormal = {i: c for i, c in dead.items() if c != 0}

            # ---- supervisor-side wedge detection -----------------------
            wedged = [] if abnormal else self._stale_hosts(procs)
            if wedged:
                for i in wedged:
                    try:
                        procs[i].kill()
                        procs[i].wait(timeout=10)
                    except Exception:  # noqa: BLE001  # pragma: no cover
                        pass
                reason, failed = "wedged", wedged
            elif abnormal:
                reason, failed = self._classify(abnormal)
            else:
                # ---- healthy; is a rejoin due? -------------------------
                if (
                    world < self.world_size
                    and downsized_at is not None
                    and self._clock() - downsized_at >= self.rejoin_after_s
                ):
                    self._teardown(procs)
                    self.generation += 1
                    self._emit(
                        "elastic_rejoin",
                        old_world=world,
                        new_world=self.world_size,
                        generation=self.generation,
                    )
                    self._m_rejoins.inc()
                    world = self.world_size
                    downsized_at = None
                    self._g_world.set(world)
                    self._clean_beacons()
                    procs = self._launch(world, self.generation)
                continue

            # ---- a restartable (or fatal) failure ----------------------
            self._teardown(procs, skip=set(dead))
            if reason == "fatal":
                self._emit(
                    "elastic_exhausted",
                    verdict="fatal child exit — not retryable",
                    reason=reason,
                    failed_hosts=failed,
                    exit_codes={str(i): c for i, c in abnormal.items()},
                    restarts_used=self.restarts_used,
                )
                return EXIT_FATAL
            if self.restarts_used >= self.max_restarts:
                self._emit(
                    "elastic_exhausted",
                    verdict=(
                        f"restart budget exhausted after {self.restarts_used}"
                        f" restarts (max {self.max_restarts})"
                    ),
                    reason=reason,
                    failed_hosts=failed,
                    restarts_used=self.restarts_used,
                )
                return EXIT_FATAL
            self.restarts_used += 1
            new_world = world
            if reason == "host_lost":
                # the EXIT_ELASTIC children are the healthy detectors that
                # saw a peer's beacon go stale — the lost hosts are the
                # slots that did NOT exit, so the surviving world is the
                # detector count (world - len(failed) would idle healthy
                # hosts until rejoin)
                new_world = max(1, len(failed))
            elif reason in _DOWNSIZE_REASONS:
                new_world = max(1, world - len(failed))
            requested = new_world
            if new_world < world and self.world_ok is not None:
                while new_world > 1 and not self.world_ok(new_world):
                    new_world -= 1
            slept = backoff
            self._sleep(slept)
            backoff = min(self.backoff_cap_s, backoff * 2)
            self.generation += 1
            extra = (
                {"requested_world": requested}
                if new_world != requested
                else {}
            )
            self._emit(
                "elastic_restart",
                reason=reason,
                failed_hosts=failed,
                exit_codes={str(i): c for i, c in abnormal.items()},
                old_world=world,
                new_world=new_world,
                generation=self.generation,
                restarts_used=self.restarts_used,
                # the delay actually slept before THIS relaunch (the
                # doubled value applies to the next restart)
                backoff_s=round(slept, 3),
                **extra,
            )
            self._m_restarts.labels(reason).inc()
            if new_world < world:
                downsized_at = self._clock()
            world = new_world
            self._g_world.set(world)
            self._clean_beacons()
            procs = self._launch(world, self.generation)
