"""Optimizers, schedules, layer-wise LR decay.

Parity targets:

- optimizer set {adamw, lamb(modified), lars, sgd} with the reference's
  hyperparameter wiring (``/root/reference/src/pretraining.py:223-259``,
  ``/root/reference/src/finetuning.py:218-265``);
- modified LAMB: adam scaling → decoupled weight decay → trust ratio applied
  ONLY to weight-decayed (kernel) params (``/root/reference/src/utils.py:124-139``);
- weight-decay mask = parameters literally named "kernel";
- layer-wise LR decay via ``optax.multi_transform`` keyed by encoder depth
  (``/root/reference/src/utils.py:142-147``);
- warmup+cosine schedule (init 1e-6 → peak → end), MAE linear LR scaling
  peak = lr · global_batch/256;
- live LR exposed through ``optax.inject_hyperparams`` for logging.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from functools import partial
from typing import Any, Literal, NamedTuple

import jax
import jax.numpy as jnp
import optax

from jumbo_mae_tpu_tpu.utils import compat
from jax.tree_util import tree_map_with_path

OptimizerName = Literal["adamw", "lamb", "lars", "sgd"]
LrScaling = Literal["batch", "none"]


@dataclass(frozen=True)
class OptimConfig:
    name: OptimizerName = "adamw"
    learning_rate: float = 1.5e-4  # base LR (pre-scaling)
    lr_scaling: LrScaling = "batch"
    b1: float = 0.9
    b2: float = 0.95
    eps: float = 1e-8
    weight_decay: float = 0.05
    momentum: float = 0.9
    clip_grad: float = 0.0
    layer_decay: float = 1.0  # <1 enables layer-wise decay
    warmup_steps: int = 0
    training_steps: int = 1
    init_lr: float = 1e-6
    end_lr: float = 1e-5
    # dtype for the Adam first moment (optax mu_dtype). "bfloat16" halves the
    # first-moment HBM traffic in the (bandwidth-bound) optimizer update; the
    # second moment and params stay float32.
    mu_dtype: str | None = None
    # dtype for the Adam second moment. The EMA itself always computes in
    # float32 (only the *stored* moment is cast), but bf16's 8-bit mantissa
    # quantizes the stored EMA between steps — an explicit opt-in perf knob
    # for bandwidth-bound large models (PERF.md §ViT-H/14), never a silent
    # default.
    nu_dtype: str | None = None
    # Storage dtype for the *parameters* (forward/backward weight reads).
    # "bfloat16" halves weight HBM traffic — the lever that matters when the
    # same weights are re-read many times per step (the shared jumbo MLP, the
    # constant-size decoder). The optimizer keeps a float32 master copy in
    # its state and computes the update in float32; the bf16 params are an
    # exact cast of the master after every step, so optimizer numerics are
    # full-precision and only the forward sees rounded weights. Opt-in.
    param_dtype: str | None = None

    def peak_lr(self, global_batch_size: int) -> float:
        if self.lr_scaling == "batch":
            return self.learning_rate * global_batch_size / 256
        return self.learning_rate


def kernel_mask(params):
    """True for every param whose final path key is "kernel"."""
    return tree_map_with_path(lambda kp, _: kp[-1].key == "kernel", params)


def layer_index(path, _unused=None, *, num_layers: int) -> int:
    """Param path → encoder depth for layer-wise LR decay.

    Layout-specific to this framework's trees: the encoder lives under a
    top-level "model" (finetune) with blocks named ``block_i``. embed → 0,
    block_i → i+1, everything else (head, final norm, cls_tokens,
    jumbo_mlp) → num_layers.
    """
    keys = [getattr(k, "key", str(k)) for k in path]
    if keys and keys[0] == "model":
        if len(keys) > 1 and keys[1] == "embed":
            return 0
        if len(keys) > 1 and (m := re.fullmatch(r"block_(\d+)", keys[1])):
            return int(m.group(1)) + 1
    return num_layers


def make_schedule(cfg: OptimConfig, global_batch_size: int) -> optax.Schedule:
    return optax.warmup_cosine_decay_schedule(
        init_value=cfg.init_lr,
        peak_value=cfg.peak_lr(global_batch_size),
        warmup_steps=cfg.warmup_steps,
        decay_steps=cfg.training_steps,
        end_value=cfg.end_lr,
    )


def scale_by_adam_dtyped(
    b1, b2, eps, mu_dtype=None, nu_dtype=None
) -> optax.GradientTransformation:
    """``optax.scale_by_adam`` with independently castable stored moments.

    optax only exposes ``mu_dtype``; this adds ``nu_dtype`` with the same
    contract: the EMAs and the update are computed in float32 (cast up from
    whatever is stored), and only the moment written back to the optimizer
    state is cast down. With both dtypes ``None`` the math is identical to
    ``optax.scale_by_adam`` (covered by a bit-parity test)."""
    mu_dtype = jnp.dtype(mu_dtype) if mu_dtype else None
    nu_dtype = jnp.dtype(nu_dtype) if nu_dtype else None

    def init_fn(params):
        mu = jax.tree.map(
            lambda p: jnp.zeros_like(p, dtype=mu_dtype or p.dtype), params
        )
        nu = jax.tree.map(
            lambda p: jnp.zeros_like(p, dtype=nu_dtype or p.dtype), params
        )
        return optax.ScaleByAdamState(
            count=jnp.zeros([], jnp.int32), mu=mu, nu=nu
        )

    def update_fn(updates, state, params=None):
        del params
        count = compat.safe_increment(state.count)
        f32 = jnp.float32
        mu_f = jax.tree.map(
            lambda g, m: b1 * m.astype(f32) + (1 - b1) * g.astype(f32),
            updates,
            state.mu,
        )
        nu_f = jax.tree.map(
            lambda g, n: b2 * n.astype(f32)
            + (1 - b2) * jnp.square(g.astype(f32)),
            updates,
            state.nu,
        )
        c1 = 1 - jnp.asarray(b1, f32) ** count.astype(f32)
        c2 = 1 - jnp.asarray(b2, f32) ** count.astype(f32)
        out = jax.tree.map(
            lambda g, m, n: ((m / c1) / (jnp.sqrt(n / c2) + eps)).astype(
                g.dtype
            ),
            updates,
            mu_f,
            nu_f,
        )
        mu_s = jax.tree.map(
            lambda m: m.astype(mu_dtype) if mu_dtype else m, mu_f
        )
        nu_s = jax.tree.map(
            lambda n: n.astype(nu_dtype) if nu_dtype else n, nu_f
        )
        return out, optax.ScaleByAdamState(count=count, mu=mu_s, nu=nu_s)

    return optax.GradientTransformation(init_fn, update_fn)


class MasterWeightsState(NamedTuple):
    """float32 master copy of the params + the wrapped optimizer's state."""

    master: Any
    inner: Any


def with_master_weights(
    inner: optax.GradientTransformation, master_dtype=jnp.float32
) -> optax.GradientTransformation:
    """Run ``inner`` against a float32 master copy of low-precision params.

    The returned transformation's update is ``new_master - params`` computed
    in ``master_dtype``; ``optax.apply_updates`` promotes ``params`` to the
    update dtype before adding, so the stored low-precision params are an
    EXACT downcast of the master after every step (covered by a test). The
    sharding rules in ``parallel/sharding.py`` match on trailing path names,
    so the master tree inherits the params' FSDP/TP layout automatically.
    """
    master_dtype = jnp.dtype(master_dtype)

    def init_fn(params):
        master = jax.tree.map(lambda p: p.astype(master_dtype), params)
        return MasterWeightsState(master=master, inner=inner.init(master))

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("with_master_weights requires params")
        grads = jax.tree.map(lambda g: g.astype(master_dtype), updates)
        inner_updates, inner_state = inner.update(
            grads, state.inner, state.master
        )
        new_master = optax.apply_updates(state.master, inner_updates)
        out = jax.tree.map(
            lambda m, p: m - p.astype(master_dtype), new_master, params
        )
        return out, MasterWeightsState(master=new_master, inner=inner_state)

    return optax.GradientTransformation(init_fn, update_fn)


def _scale_by_adam(b1, b2, eps, mu_dtype=None, nu_dtype=None):
    """Stock optax unless ``nu_dtype`` forces the dtyped variant."""
    if nu_dtype:
        return scale_by_adam_dtyped(
            b1, b2, eps, mu_dtype=mu_dtype, nu_dtype=nu_dtype
        )
    return optax.scale_by_adam(b1=b1, b2=b2, eps=eps, mu_dtype=mu_dtype)


def modified_lamb(
    learning_rate, b1, b2, eps, weight_decay, mask, mu_dtype=None, nu_dtype=None
) -> optax.GradientTransformation:
    """LAMB with the trust ratio restricted to weight-decayed params."""
    return optax.chain(
        _scale_by_adam(b1, b2, eps, mu_dtype=mu_dtype, nu_dtype=nu_dtype),
        optax.add_decayed_weights(weight_decay=weight_decay, mask=mask),
        optax.masked(optax.scale_by_trust_ratio(), mask=mask),
        optax.scale_by_learning_rate(learning_rate),
    )


def make_optimizer(
    cfg: OptimConfig,
    global_batch_size: int,
    *,
    num_layers: int | None = None,
) -> optax.GradientTransformation:
    """Build the full transformation chain, LR exposed in
    ``opt_state.hyperparams["learning_rate"]``."""

    @optax.inject_hyperparams
    def build(learning_rate):
        wd_mask = kernel_mask
        if cfg.name == "adamw":
            # optax.adamw's own chain, with the dtyped core swapped in when
            # nu_dtype asks for it (optax exposes no nu_dtype).
            tx = optax.chain(
                _scale_by_adam(
                    cfg.b1,
                    cfg.b2,
                    cfg.eps,
                    mu_dtype=cfg.mu_dtype,
                    nu_dtype=cfg.nu_dtype,
                ),
                optax.add_decayed_weights(
                    weight_decay=cfg.weight_decay, mask=wd_mask
                ),
                optax.scale_by_learning_rate(learning_rate),
            )
        elif cfg.name == "lamb":
            tx = modified_lamb(
                learning_rate,
                cfg.b1,
                cfg.b2,
                cfg.eps,
                cfg.weight_decay,
                wd_mask,
                mu_dtype=cfg.mu_dtype,
                nu_dtype=cfg.nu_dtype,
            )
        elif cfg.name == "lars":
            tx = optax.lars(learning_rate, momentum=cfg.momentum)
        elif cfg.name == "sgd":
            tx = optax.sgd(learning_rate, momentum=cfg.momentum)
        else:
            raise ValueError(f"unknown optimizer {cfg.name!r}")

        if cfg.layer_decay < 1.0:
            if num_layers is None:
                raise ValueError("layer_decay requires num_layers")
            scales = {
                i: optax.scale(cfg.layer_decay ** (num_layers - i))
                for i in range(num_layers + 1)
            }
            label_fn = partial(
                tree_map_with_path, partial(layer_index, num_layers=num_layers)
            )
            tx = optax.chain(tx, optax.multi_transform(scales, label_fn))
        if cfg.clip_grad > 0:
            tx = optax.chain(optax.clip_by_global_norm(cfg.clip_grad), tx)
        if cfg.param_dtype and jnp.dtype(cfg.param_dtype) != jnp.float32:
            tx = with_master_weights(tx)
        return tx

    return build(make_schedule(cfg, global_batch_size))
